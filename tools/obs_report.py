#!/usr/bin/env python
"""Render a run's telemetry into per-pass tables, SLO verdicts, and a
merged cross-rank trace.

The obs plane (docs/OBSERVABILITY.md) writes three artifact kinds:
rank-tagged metric-series JSONL (MetricsWriter), per-rank chrome traces
(Profiler.export_chrome_trace), and incident bundles (FlightRecorder).
This CLI is the read side for all three:

  # per-pass table + SLO verdicts over a metrics dir (ckpt/<root>/obs)
  python tools/obs_report.py <obs_dir> [--rank R]
      [--slo serve.latency_ms:p99<=50 ...] [--json]

  # fuse N ranks' chrome traces into ONE timeline (one process row per
  # rank; cross-rank sends share a trace_id via the PBTX frame extension)
  python tools/obs_report.py --merge-traces out.json rank0.json rank1.json ...

  # self-contained smoke of histogram/series/recorder/merge (verify drive)
  python tools/obs_report.py --selfcheck

Exit code: 0 on success AND every SLO verdict PASS; 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import tempfile
from typing import Dict, List, Optional, Sequence, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# metric series: per-pass tables
# ---------------------------------------------------------------------------


def load_series(obs_dir: str, rank: Optional[int] = None) -> List[dict]:
    """All parsed series records under ``obs_dir`` (one writer per rank),
    ordered by (rank, seq). ``rank`` filters to a single writer."""
    from paddlebox_tpu.obs.metrics_writer import read_series, series_ranks

    ranks = [rank] if rank is not None else series_ranks(obs_dir)
    out: List[dict] = []
    for r in ranks:
        out.extend(read_series(obs_dir, rank=r))
    out.sort(key=lambda rec: (rec.get("rank", 0), rec.get("seq", 0)))
    return out


def _pass_records(records: Sequence[dict]) -> List[dict]:
    return [r for r in records if str(r.get("label", "")).startswith("pass")]


def _table_columns(passes: Sequence[dict], max_cols: int = 6) -> List[str]:
    """The most interesting delta counters across the pass records: ranked
    by peak magnitude so the table stays readable on any workload."""
    peak: Dict[str, float] = {}
    for rec in passes:
        for name, v in (rec.get("deltas") or {}).items():
            peak[name] = max(peak.get(name, 0.0), abs(float(v)))
    ranked = sorted(peak, key=lambda n: (-peak[n], n))
    return sorted(ranked[:max_cols])


def render_pass_table(records: Sequence[dict]) -> str:
    """Fixed-width per-pass table: one row per pass snapshot, columns are
    the top delta counters plus wall time between snapshots."""
    passes = _pass_records(records)
    if not passes:
        return "(no pass-boundary snapshots found)"
    cols = _table_columns(passes)
    header = ["rank", "seq", "label", "dt_s"] + cols
    rows: List[List[str]] = []
    prev_t: Dict[int, float] = {}
    for rec in passes:
        rk = int(rec.get("rank", 0))
        t = float(rec.get("t", 0.0))
        dt = t - prev_t[rk] if rk in prev_t else 0.0
        prev_t[rk] = t
        deltas = rec.get("deltas") or {}
        rows.append(
            [str(rk), str(rec.get("seq", "")), str(rec.get("label", "")),
             f"{dt:.2f}"]
            + [_fmt_num(deltas.get(c)) for c in cols]
        )
    widths = [
        max(len(header[i]), *(len(r[i]) for r in rows))
        for i in range(len(header))
    ]
    lines = [
        "  ".join(h.ljust(widths[i]) for i, h in enumerate(header)),
        "  ".join("-" * w for w in widths),
    ]
    lines += ["  ".join(c.ljust(widths[i]) for i, c in enumerate(r))
              for r in rows]
    return "\n".join(lines)


def _fmt_num(v) -> str:
    if v is None:
        return "-"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return f"{f:.4g}"


def summarize_histograms(records: Sequence[dict]) -> Dict[str, dict]:
    """Final (cumulative) histogram summaries per rank-merged name: the
    LAST record per rank carries the run's full distribution, so merge
    across ranks by re-accumulating the per-rank summaries' counts."""
    last_per_rank: Dict[int, dict] = {}
    for rec in records:
        last_per_rank[int(rec.get("rank", 0))] = rec
    merged: Dict[str, dict] = {}
    for rec in last_per_rank.values():
        for name, summ in (rec.get("histograms") or {}).items():
            cur = merged.get(name)
            if cur is None or summ.get("count", 0) >= cur.get("count", 0):
                # per-name: keep the widest view (quantiles are not
                # mergeable from summaries; ranks report independently)
                merged[name] = dict(summ, rank=rec.get("rank", 0))
    return merged


# ---------------------------------------------------------------------------
# SLO verdicts
# ---------------------------------------------------------------------------

_SLO_RE = re.compile(
    r"^(?P<name>[a-z0-9_.]+):(?P<field>[a-z0-9_]+)"
    r"(?P<op><=|>=)(?P<bound>[-+0-9.eE]+)$"
)


def parse_slo(spec: str) -> Tuple[str, str, str, float]:
    """'serve.latency_ms:p99<=50' -> (name, field, op, bound)."""
    m = _SLO_RE.match(spec.strip())
    if m is None:
        raise ValueError(
            f"bad --slo spec {spec!r} (want name:field<=bound or >=)"
        )
    return (m["name"], m["field"], m["op"], float(m["bound"]))


def slo_verdicts(
    hists: Dict[str, dict], specs: Sequence[str]
) -> List[dict]:
    """Evaluate each SLO spec against the final histogram summaries."""
    out = []
    for spec in specs:
        name, field, op, bound = parse_slo(spec)
        summ = hists.get(name)
        value = None if summ is None else summ.get(field)
        if value is None:
            verdict = "NODATA"
        elif op == "<=":
            verdict = "PASS" if float(value) <= bound else "FAIL"
        else:
            verdict = "PASS" if float(value) >= bound else "FAIL"
        out.append({
            "slo": spec, "metric": name, "field": field,
            "value": value, "bound": bound, "op": op, "verdict": verdict,
        })
    return out


# ---------------------------------------------------------------------------
# cross-rank trace merge
# ---------------------------------------------------------------------------


def merge_traces(paths: Sequence[str], out_path: str) -> dict:
    """Fuse per-rank chrome traces into one timeline.

    Ranks already occupy distinct pids (Profiler.set_process stamps
    pid=rank at export); colliding pids — two files exported without
    set_process — are remapped to keep one process row per input file.
    Cross-rank correlation: a trace_id riding the PBTX frame extension
    appears in the sender's ``transport:send`` instant and the receiver's
    ``transport:deliver`` instant; any trace_id seen under >=2 distinct
    pids is a confirmed cross-rank span pair.
    """
    events: List[dict] = []
    used_pids: set = set()
    ranks: List[dict] = []
    dropped_total = 0
    for path in paths:
        with open(path) as f:
            doc = json.load(f)
        evs = doc.get("traceEvents", [])
        other = doc.get("otherData", {})
        file_pids = sorted({e.get("pid", 0) for e in evs})
        remap: Dict[int, int] = {}
        for pid in file_pids:
            new = pid
            while new in used_pids:
                new += 1000  # keep rank digits readable after a remap
            remap[pid] = new
            used_pids.add(new)
        for e in evs:
            if remap.get(e.get("pid", 0), 0) != e.get("pid", 0):
                e = dict(e, pid=remap[e.get("pid", 0)])
            events.append(e)
        dropped_total += int(other.get("dropped_events", 0))
        ranks.append({
            "file": os.path.basename(path),
            "rank": other.get("rank"),
            "pids": sorted(remap.values()),
            "events": len(evs),
        })

    # cross-rank pairs: trace_id -> set of pids that logged it
    tid_pids: Dict[str, set] = {}
    for e in events:
        args = e.get("args") or {}
        tid = args.get("trace_id")
        if tid:
            tid_pids.setdefault(tid, set()).add(e.get("pid", 0))
    cross = sorted(t for t, pids in tid_pids.items() if len(pids) >= 2)

    events.sort(key=lambda e: (e.get("ts", 0), e.get("pid", 0)))
    merged = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged_from": [os.path.basename(p) for p in paths],
            "dropped_events": dropped_total,
            "cross_rank_trace_ids": len(cross),
        },
    }
    from paddlebox_tpu.utils.fs import atomic_write

    with atomic_write(out_path) as f:
        json.dump(merged, f)
    return {
        "out": out_path,
        "ranks": ranks,
        "process_rows": sorted(used_pids),
        "events": len(events),
        "trace_ids": len(tid_pids),
        "cross_rank_trace_ids": len(cross),
        "cross_rank_sample": cross[:5],
    }


# ---------------------------------------------------------------------------
# selfcheck: exercised by tools/verify_drive.py
# ---------------------------------------------------------------------------


def selfcheck() -> int:
    """End-to-end smoke of the whole obs plane in a temp dir: histogram
    quantiles, metric-series round trip, flight-recorder dump, profiler
    export, and a 2-rank trace merge with a shared trace_id."""
    from paddlebox_tpu.obs.flight_recorder import FlightRecorder
    from paddlebox_tpu.obs.histogram import Histogram
    from paddlebox_tpu.obs.metrics_writer import MetricsWriter, read_series
    from paddlebox_tpu.obs.trace_context import TraceContext
    from paddlebox_tpu.utils.monitor import STAT_ADD
    from paddlebox_tpu.utils.trace import Profiler

    with tempfile.TemporaryDirectory() as tmp:
        # histogram: exact extrema, ordered quantiles
        h = Histogram()
        h.observe_many(float(v) for v in range(1, 1001))
        p50, p99 = h.quantiles((0.5, 0.99))
        assert h.count == 1000 and h.min == 1.0 and h.max == 1000.0
        assert 1.0 <= p50 <= p99 <= 1000.0, (p50, p99)

        # metric series: snapshot -> rotate-safe read back
        w = MetricsWriter(tmp, rank=0, interval_s=0.0)
        STAT_ADD("obs.selfcheck_ticks")
        w.snapshot("pass:0", extra={"auc": 0.5})
        w.snapshot("pass:1")
        recs = list(read_series(tmp, rank=0))
        assert [r["label"] for r in recs] == ["pass:0", "pass:1"], recs
        assert recs[0]["extra"]["auc"] == 0.5

        # flight recorder: incident bundle lands atomically
        fr = FlightRecorder(capacity=8)
        fr.note_span("selfcheck", "obs", 0.0, 1.0, {})
        fr.note_incident("selfcheck_incident", {"detail": "smoke"})
        path = fr.dump("selfcheck", dir_path=os.path.join(tmp, "inc"))
        assert path is not None and os.path.exists(path), path
        with open(path) as f:
            bundle = json.load(f)
        assert bundle["incidents"] and bundle["spans"], bundle

        # two profilers sharing one trace context -> merged cross-rank pair
        ctx = TraceContext.new()
        trace_paths = []
        for rank in range(2):
            prof = Profiler(max_events=64)
            prof.enable()
            prof.set_process(rank)
            prof.instant(
                "transport:send" if rank == 0 else "transport:deliver",
                dict(ctx.as_args()), category="transport",
            )
            tp = os.path.join(tmp, f"trace-{rank}.json")
            prof.export_chrome_trace(tp)
            trace_paths.append(tp)
        rep = merge_traces(trace_paths, os.path.join(tmp, "merged.json"))
        assert len(rep["process_rows"]) == 2, rep
        assert rep["cross_rank_trace_ids"] >= 1, rep

    print("OBS SELFCHECK PASS")
    return 0


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("obs_dir", nargs="?", help="metrics dir (ckpt root/obs)")
    ap.add_argument("--rank", type=int, default=None,
                    help="restrict the table to one rank's series")
    ap.add_argument("--slo", action="append", default=[],
                    metavar="NAME:FIELD<=BOUND",
                    help="SLO over a final histogram summary, e.g. "
                         "serve.latency_ms:p99<=50 (repeatable)")
    ap.add_argument("--merge-traces", nargs="+", metavar="JSON",
                    help="OUT.json IN0.json IN1.json ... — fuse per-rank "
                         "chrome traces into one timeline")
    ap.add_argument("--selfcheck", action="store_true",
                    help="run the obs-plane smoke (verify drive gate)")
    ap.add_argument("--json", action="store_true", help="machine output")
    args = ap.parse_args(argv)

    if args.selfcheck:
        return selfcheck()

    if args.merge_traces:
        if len(args.merge_traces) < 2:
            ap.error("--merge-traces needs OUT.json plus >=1 input trace")
        rep = merge_traces(args.merge_traces[1:], args.merge_traces[0])
        print(json.dumps(rep, indent=None if args.json else 2))
        return 0

    if not args.obs_dir:
        ap.error("give an obs_dir, --merge-traces, or --selfcheck")
    records = load_series(args.obs_dir, rank=args.rank)
    if not records:
        print(f"no metric series under {args.obs_dir}", file=sys.stderr)
        return 1
    hists = summarize_histograms(records)
    verdicts = slo_verdicts(hists, args.slo)
    if args.json:
        print(json.dumps({
            "records": len(records),
            "passes": len(_pass_records(records)),
            "histograms": hists,
            "slo": verdicts,
        }))
    else:
        print(render_pass_table(records))
        if hists:
            print("\ndistributions (cumulative):")
            for name in sorted(hists):
                s = hists[name]
                print(f"  {name}: n={s.get('count')} p50={_fmt_num(s.get('p50'))} "
                      f"p90={_fmt_num(s.get('p90'))} "
                      f"p99={_fmt_num(s.get('p99'))} "
                      f"max={_fmt_num(s.get('max'))}")
        for v in verdicts:
            print(f"SLO {v['verdict']}: {v['slo']} (value={v['value']})")
    return 1 if any(v["verdict"] == "FAIL" for v in verdicts) else 0


if __name__ == "__main__":
    sys.exit(main())
