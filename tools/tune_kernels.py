"""Sweep artifact -> committed kernel plan (tools/kernel_plan.json).

The KernelPlan registry (ops/kernel_plan.py) routes each sparse pull/push
to "native" (XLA gather/scatter) or "pallas" (row-DMA kernels) per
(op, backend, shape bucket). This tool is the only writer of the committed
plan artifact, so every routing decision in the file carries provenance:
either a measured op_probe sweep (``--artifact``, produced by
``python tools/op_probe.py --scatter-sweep --sweep-artifact=...`` on a
healthy chip) or the hand-seeded defaults from the v5p measurements in the
pallas_kernels docstring (``--default``).

Usage:
  python tools/tune_kernels.py --default [--out tools/kernel_plan.json]
  python tools/tune_kernels.py --artifact tools/op_sweep.json \
      [--min-speedup 1.1] [--out tools/kernel_plan.json]

``--min-speedup`` is the hysteresis: pallas must beat native by at least
this factor to win a bucket, so noise near the crossover can't flap the
committed plan between regenerations.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlebox_tpu.ops.kernel_plan import (  # noqa: E402
    PALLAS_LANE,
    KernelPlan,
    PlanEntry,
    log2_bucket,
)

DEFAULT_OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "kernel_plan.json")

# v5p single chip, R=1M x W=128, U=160k (ops/pallas_kernels.py docstring):
# XLA take 2.8 ms vs pallas gather 9.2 ms; XLA scatter-set 7.4 ms. Native
# wins both ops at the only lane-aligned shape measured so far, so the
# seeded plan pins native at W=128 and leaves everything else to the
# builtin fallback.
V5P_MEASURED = {
    "pull": ("native", "v5p R=1M W=128 U=160k: XLA take 2.8ms vs pallas 9.2ms"),
    "push": ("native", "v5p R=1M W=128 U=160k: scatter-set 7.4ms vs pallas 9.2ms"),
}


def default_entries() -> list:
    return [
        PlanEntry(op=op, backend="tpu", impl=impl, width=PALLAS_LANE, why=why)
        for op, (impl, why) in V5P_MEASURED.items()
    ]


def entries_from_artifact(art: dict, min_speedup: float) -> list:
    """Measured sweep points -> plan entries (only comparisons that exist).

    The scatter sweep measures the push side at W=128: "w128" is the
    native scatter-add and "pallas" the row-DMA writeback at the same
    (rows, U) shape. A pull comparison needs a gather sweep point that
    does not exist yet, so artifact-driven tuning emits push entries only
    — pulls keep the defaults until the sweep grows a pallas-gather point.
    """
    if art.get("backend") != "tpu":
        print(
            f"artifact backend {art.get('backend')!r} is not tpu: no pallas "
            "crossover can be concluded; emitting no measured entries",
            file=sys.stderr,
        )
        return []
    points = art.get("points", {})
    native = points.get(f"w{PALLAS_LANE}", {}).get("ms")
    pallas = points.get("pallas", {}).get("ms")
    if native is None or pallas is None:
        missing = [
            n for n, v in ((f"w{PALLAS_LANE}", native), ("pallas", pallas))
            if v is None
        ]
        print(
            f"artifact lacks measured point(s) {missing}: nothing to compare",
            file=sys.stderr,
        )
        return []
    impl = "pallas" if pallas * min_speedup <= native else "native"
    shape = art.get("shape", {})
    why = (
        f"measured {art['backend']} rows={shape.get('rows')} "
        f"u={shape.get('u')} W={PALLAS_LANE}: native {native}ms vs "
        f"pallas {pallas}ms (min_speedup {min_speedup})"
    )
    exact = PlanEntry(
        op="push",
        backend="tpu",
        impl=impl,
        width=PALLAS_LANE,
        rows_log2=log2_bucket(int(shape.get("rows", 1))),
        uniq_log2=log2_bucket(int(shape.get("u", 1))),
        why=why,
    )
    # width-only generalization: the measured bucket's winner covers other
    # (rows, U) bands at this width until they are measured themselves
    general = PlanEntry(
        op="push", backend="tpu", impl=impl, width=PALLAS_LANE,
        why=why + " [generalized across row/uniq buckets]",
    )
    return [exact, general]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--artifact", help="op_probe --sweep-artifact JSON to tune from")
    ap.add_argument("--default", action="store_true",
                    help="emit the hand-seeded v5p-measurement plan")
    ap.add_argument("--out", default=DEFAULT_OUT,
                    help=f"plan path to (over)write (default {DEFAULT_OUT})")
    ap.add_argument("--min-speedup", type=float, default=1.1,
                    help="pallas must beat native by this factor to win")
    args = ap.parse_args()
    if bool(args.artifact) == bool(args.default):
        ap.error("exactly one of --artifact or --default is required")

    if args.default:
        entries = default_entries()
        source = "tune_kernels --default (v5p measurements, pallas_kernels.py)"
    else:
        with open(args.artifact) as f:
            art = json.load(f)
        entries = entries_from_artifact(art, args.min_speedup)
        if not entries:
            return 1
        source = f"tune_kernels --artifact {os.path.basename(args.artifact)}"

    plan = KernelPlan(entries=entries, fallback="native", source=source)
    plan.save(args.out)
    print(f"wrote {args.out}: {len(entries)} entries, fallback=native")
    for e in entries:
        print(f"  {e.op}@{e.backend} w={e.width} r={e.rows_log2} "
              f"u={e.uniq_log2} -> {e.impl}  ({e.why})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
