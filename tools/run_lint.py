#!/usr/bin/env python
"""pbox-lint CLI — run the project linter without importing the package.

``paddlebox_tpu/__init__`` pulls in jax; the analysis subpackage is
stdlib-only by design, so this driver loads it by path with importlib and
never pays that import (works on boxes with no jax at all).

Exit codes:
  0  no new errors (warnings and baseline-grandfathered errors are OK)
  1  new errors found (or syntax errors in scanned files)
  2  usage / internal error

Typical invocations:
  python tools/run_lint.py paddlebox_tpu/
  python tools/run_lint.py paddlebox_tpu/ --format=json
  python tools/run_lint.py paddlebox_tpu/ --update-baseline
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_DEFAULT_BASELINE = os.path.join(_REPO, "tools", "lint_baseline.json")


def _load_analysis():
    """Import paddlebox_tpu.analysis by path, skipping the package root."""
    pkg_dir = os.path.join(_REPO, "paddlebox_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "pbox_analysis",
        os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir],
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["pbox_analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="pbox-lint", description=__doc__)
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: paddlebox_tpu/)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=_DEFAULT_BASELINE,
                    help="baseline file (default: tools/lint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: every error gates")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current errors and exit 0")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress warnings and grandfathered findings")
    args = ap.parse_args(argv)

    paths = args.paths or [os.path.join(_REPO, "paddlebox_tpu")]
    for p in paths:
        if not os.path.exists(p):
            print(f"pbox-lint: no such path: {p}", file=sys.stderr)
            return 2

    try:
        analysis = _load_analysis()
    except Exception as e:  # loading the linter itself failed
        print(f"pbox-lint: failed to load analysis package: {e}",
              file=sys.stderr)
        return 2

    result = analysis.lint_paths(paths, analysis.default_rules(), root=_REPO)

    if args.update_baseline:
        analysis.save_baseline(args.baseline, result.findings)
        n = sum(1 for f in result.findings if f.severity == analysis.ERROR)
        print(f"pbox-lint: baseline rewritten with {n} error(s) -> "
              f"{os.path.relpath(args.baseline, _REPO)}")
        return 0

    baseline = {} if args.no_baseline else analysis.load_baseline(args.baseline)
    new, grandfathered, stale = analysis.apply_baseline(
        result.findings, baseline
    )
    new_errors = [f for f in new if f.severity == analysis.ERROR]
    new_warnings = [f for f in new if f.severity == analysis.WARNING]

    if args.format == "json":
        print(json.dumps({
            "new_errors": [f.as_dict() for f in new_errors],
            "warnings": [f.as_dict() for f in new_warnings],
            "grandfathered": [f.as_dict() for f in grandfathered],
            "stale_baseline": [
                {"rule": r, "path": p, "message": m} for r, p, m in stale
            ],
            "parse_errors": [f.as_dict() for f in result.parse_errors],
            "ok": not new_errors and not result.parse_errors,
        }, indent=2))
    else:
        for f in result.parse_errors:
            print(f.render())
        for f in new_errors:
            print(f.render())
        if not args.quiet:
            for f in new_warnings:
                print(f.render())
            for f in grandfathered:
                print(f"{f.render()}  (baseline)")
            for r, p, m in stale:
                print(f"stale baseline entry (no longer fires — run "
                      f"--update-baseline to drop): {r} {p} {m}")
        print(
            f"pbox-lint: {len(new_errors)} new error(s), "
            f"{len(new_warnings)} warning(s), "
            f"{len(grandfathered)} baselined, {len(stale)} stale"
        )

    if result.parse_errors or new_errors:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
