#!/usr/bin/env python
"""pbox-lint CLI — run the project linter without importing the package.

``paddlebox_tpu/__init__`` pulls in jax; the analysis subpackage is
stdlib-only by design, so this driver loads it by path with importlib and
never pays that import (works on boxes with no jax at all).

Exit codes:
  0  no new errors (warnings and baseline-grandfathered errors are OK)
  1  new errors found (or syntax errors in scanned files)
  2  usage / internal error

Typical invocations:
  python tools/run_lint.py                             # full default scan
  python tools/run_lint.py paddlebox_tpu/ tools/ tests/
  python tools/run_lint.py --changed                   # files vs HEAD only
  python tools/run_lint.py --changed=main --format=json
  python tools/run_lint.py --update-baseline

The default scan set is paddlebox_tpu/ + tools/ + tests/ with per-root
rule profiles (analysis.DEFAULT_PROFILES): flow rules that would drown in
test-harness noise (JIT001, THR006) are off under tests/, everything else
is on everywhere.  ``--changed[=REF]`` lints only files that differ from
a git ref (default HEAD) for sub-second pre-commit runs; whole-program
rules still load the FULL default set for resolution (call graph,
registries, fault-site coverage) but only report on the changed files.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_DEFAULT_BASELINE = os.path.join(_REPO, "tools", "lint_baseline.json")
_DEFAULT_ROOTS = ("paddlebox_tpu", "tools", "tests")


def _load_analysis():
    """Import paddlebox_tpu.analysis by path, skipping the package root."""
    pkg_dir = os.path.join(_REPO, "paddlebox_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "pbox_analysis",
        os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir],
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["pbox_analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def _changed_files(ref: str) -> list:
    """Tracked .py files differing from ``ref`` plus untracked .py files,
    repo-relative."""
    out = set()
    diff = subprocess.run(
        ["git", "diff", "--name-only", "--diff-filter=d", ref, "--", "*.py"],
        cwd=_REPO, capture_output=True, text=True, timeout=30,
    )
    if diff.returncode != 0:
        raise RuntimeError(
            f"git diff {ref} failed: {diff.stderr.strip() or diff.stdout.strip()}"
        )
    out.update(l for l in diff.stdout.splitlines() if l.strip())
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard", "--", "*.py"],
        cwd=_REPO, capture_output=True, text=True, timeout=30,
    )
    if untracked.returncode == 0:
        out.update(l for l in untracked.stdout.splitlines() if l.strip())
    return sorted(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="pbox-lint", description=__doc__)
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint "
                         "(default: paddlebox_tpu/ tools/ tests/)")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="lint only files differing from a git ref (default "
                         "HEAD); whole-program rules still resolve over the "
                         "full default scan set")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=_DEFAULT_BASELINE,
                    help="baseline file (default: tools/lint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: every error gates")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current errors and exit 0")
    ap.add_argument("--no-profiles", action="store_true",
                    help="disable the per-root rule profiles (every rule "
                         "applies everywhere)")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress warnings and grandfathered findings")
    args = ap.parse_args(argv)

    try:
        analysis = _load_analysis()
    except Exception as e:  # loading the linter itself failed
        print(f"pbox-lint: failed to load analysis package: {e}",
              file=sys.stderr)
        return 2

    default_roots = [
        os.path.join(_REPO, r) for r in _DEFAULT_ROOTS
        if os.path.isdir(os.path.join(_REPO, r))
    ]
    context_paths: list = []
    if args.changed is not None:
        if args.paths:
            print("pbox-lint: --changed and explicit paths are exclusive",
                  file=sys.stderr)
            return 2
        try:
            changed = _changed_files(args.changed)
        except Exception as e:
            print(f"pbox-lint: {e}", file=sys.stderr)
            return 2
        roots = tuple(r + os.sep for r in _DEFAULT_ROOTS)
        paths = [
            os.path.join(_REPO, f) for f in changed
            if f.startswith(roots) and os.path.exists(os.path.join(_REPO, f))
        ]
        if not paths:
            print(f"pbox-lint: no changed .py files vs {args.changed} "
                  "under the scan roots")
            return 0
        context_paths = default_roots
    else:
        paths = args.paths or default_roots
        for p in paths:
            if not os.path.exists(p):
                print(f"pbox-lint: no such path: {p}", file=sys.stderr)
                return 2
        # explicit single-file/dir runs still get whole-program resolution
        # against the default roots (cheap, and THR006/FLT008 need it)
        if args.paths:
            context_paths = default_roots

    profiles = None if args.no_profiles else analysis.DEFAULT_PROFILES
    result = analysis.lint_paths(
        paths, analysis.default_rules(), root=_REPO,
        context_paths=context_paths, profiles=profiles,
    )

    if args.update_baseline:
        analysis.save_baseline(args.baseline, result.findings)
        n = sum(1 for f in result.findings if f.severity == analysis.ERROR)
        print(f"pbox-lint: baseline rewritten with {n} error(s) -> "
              f"{os.path.relpath(args.baseline, _REPO)}")
        return 0

    baseline = {} if args.no_baseline else analysis.load_baseline(args.baseline)
    new, grandfathered, stale = analysis.apply_baseline(
        result.findings, baseline
    )
    new_errors = [f for f in new if f.severity == analysis.ERROR]
    new_warnings = [f for f in new if f.severity == analysis.WARNING]

    if args.format == "json":
        print(json.dumps({
            "new_errors": [f.as_dict() for f in new_errors],
            "warnings": [f.as_dict() for f in new_warnings],
            "grandfathered": [f.as_dict() for f in grandfathered],
            "stale_baseline": [
                {"rule": r, "path": p, "message": m} for r, p, m in stale
            ],
            "parse_errors": [f.as_dict() for f in result.parse_errors],
            "ok": not new_errors and not result.parse_errors,
        }, indent=2))
    else:
        for f in result.parse_errors:
            print(f.render())
        for f in new_errors:
            print(f.render())
        if not args.quiet:
            for f in new_warnings:
                print(f.render())
            for f in grandfathered:
                print(f"{f.render()}  (baseline)")
            for r, p, m in stale:
                print(f"stale baseline entry (no longer fires — run "
                      f"--update-baseline to drop): {r} {p} {m}")
        print(
            f"pbox-lint: {len(new_errors)} new error(s), "
            f"{len(new_warnings)} warning(s), "
            f"{len(grandfathered)} baselined, {len(stale)} stale"
        )

    if result.parse_errors or new_errors:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
