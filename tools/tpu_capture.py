#!/usr/bin/env python
"""Full TPU capture: everything the perf mandate needs from ONE healthy
chip window, self-contained and artifact-producing.

Runs, in order of value-per-minute (so even a short healthy window yields
a usable artifact — the file is (re)written after every stage):

  1. headline   bench.py at the default knobs (resident + carrier + bf16)
  2. scatter    tools/op_probe.py --scatter-sweep (the SCATTER_NOTES
                decision input: push floor vs padded-width candidates —
                round 5's window closed before this stage, so it now runs
                SECOND: it is the only item never measured on hardware)
  3. ablations  wire=fp32, wire=int8, carried=off, pv join phase
  4. sweep      bench.py across (resident_scan_batches x max_inflight)

Writes tools/last_good_tpu_capture.json after each stage and appends a
compact line to tools/tpu_capture_history.jsonl at the end. bench.py
embeds the capture file as "tpu_capture" in any later CPU-fallback JSON,
so a wedged driver run still carries the measured TPU numbers.

Invoked automatically by tools/tpu_probe_loop.py on the first healthy
probe; can also be run by hand:

  python tools/tpu_capture.py [--quick]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import CAPTURE_LOCK_PATH, CAPTURE_PATH, bench_config_id  # noqa: E402
from paddlebox_tpu.utils.fs import atomic_write  # noqa: E402

HISTORY_PATH = os.path.join(REPO, "tools", "tpu_capture_history.jsonl")
# a wedged-backend capture attempt records its evidence HERE — never over
# CAPTURE_PATH, which only ever holds measurements from a healthy window
WEDGED_PATH = os.path.join(REPO, "tools", "tpu_capture_wedged.json")
# children share one persistent compile cache so every stage after the
# first warm-starts its XLA compiles — more measurements per window
CHILD_COMPILE_CACHE = os.path.join(REPO, "tools", "compile_cache")
# resumable scatter-sweep artifact (op_probe --sweep-artifact): measured
# points survive a wedge and are skipped on the next capture attempt
SWEEP_ARTIFACT = os.path.join(REPO, "tools", "op_sweep.json")


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def run_bench(env_extra: dict, timeout: float = 480):
    """One bench.py subprocess; returns its JSON line or an error dict."""
    env = dict(os.environ)
    env.update({k: str(v) for k, v in env_extra.items()})
    # the chip was probed healthy moments ago: one init probe is enough,
    # and a wedge mid-capture should fail fast, not burn the window
    env.setdefault("PBOX_BENCH_INIT_RETRIES", "1")
    env.setdefault("PBOX_BENCH_INIT_TIMEOUT", "150")
    # our own bench children must not wait on our own capture lock
    env["PBOX_BENCH_NO_LOCK_WAIT"] = "1"
    # persistent compile cache shared across every child of this capture
    # (and across captures): only the first stage pays full XLA compile
    env.setdefault("PBOX_COMPILE_CACHE_DIR", CHILD_COMPILE_CACHE)
    try:
        p = subprocess.run(
            [sys.executable, "bench.py"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"bench timed out after {timeout:.0f}s"}
    for line in reversed(p.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    tail = (p.stderr or "").strip().splitlines()[-3:]
    return {"error": f"no JSON from bench rc={p.returncode}: " + " | ".join(tail)}


def _on_tpu(out) -> bool:
    return isinstance(out, dict) and out.get("platform") == "tpu"


def _save(cap: dict) -> None:
    cap["updated_at"] = _now()
    with atomic_write(CAPTURE_PATH) as f:
        json.dump(cap, f, indent=1)


def main() -> int:
    quick = "--quick" in sys.argv
    # advertise the in-flight capture so a concurrently-launched bench.py
    # (e.g. the driver's round-end run) waits instead of sharing the chip
    # and the host core with us — racing degrades BOTH measurements.
    # tmp + atomic rename inside the try: a half-written (empty) lock must
    # never persist, and a failed write must still unlink
    try:
        tmp = f"{CAPTURE_LOCK_PATH}.{os.getpid()}.tmp"
        # lock-acquisition protocol: pid tmp + replace, unlinked in finally
        # pbox-lint: disable=IO004
        with open(tmp, "w") as f:
            f.write(str(os.getpid()))
        os.replace(tmp, CAPTURE_LOCK_PATH)
        return _main_locked(quick)
    finally:
        for p in (tmp, CAPTURE_LOCK_PATH):
            try:
                os.unlink(p)
            # lock/tmp cleanup: absence is exactly the goal state
            # pbox-lint: disable=EXC007
            except OSError:
                pass


def _main_locked(quick: bool) -> int:
    cap = {
        "started_at": _now(),
        "bench_config": bench_config_id(),
        "quick": quick,
        "compile_cache_dir": CHILD_COMPILE_CACHE,
    }

    # -- 0. backend watchdog: is the chip actually alive RIGHT NOW? The
    # probe loop saw it healthy, but wedges happen between probe and
    # capture — a wedged verdict writes its evidence to WEDGED_PATH and
    # bails before any stage can waste the driver's budget. ensure_backend
    # itself never writes artifacts, so last_good_tpu_capture.json is
    # structurally safe from this path.
    from paddlebox_tpu.utils.backendguard import ensure_backend

    verdict = ensure_backend(
        timeout_s=float(os.environ.get("PBOX_BENCH_INIT_TIMEOUT", "150")),
        retries=int(os.environ.get("PBOX_BENCH_INIT_RETRIES", "1")),
    )
    cap["backend_init"] = verdict.as_dict()
    if verdict.wedged:
        wedged = {
            "backend_init": "wedged",
            "verdict": verdict.as_dict(),
            "bench_config": bench_config_id(),
            "ts": _now(),
        }
        with atomic_write(WEDGED_PATH) as f:
            json.dump(wedged, f, indent=1)
        print(f"[capture] backend wedged; evidence -> {WEDGED_PATH}",
              file=sys.stderr, flush=True)
        return 1

    # -- 1. headline at default knobs ------------------------------------
    print("[capture] headline bench...", file=sys.stderr, flush=True)
    headline = run_bench({})
    cap["headline"] = headline
    if not _on_tpu(headline):
        # chip regressed between the probe and the run: bail WITHOUT
        # saving — a CPU-fallback stub must never overwrite a previous
        # healthy window's full TPU artifact
        print(f"[capture] headline not on tpu: {headline}", file=sys.stderr)
        return 1
    _save(cap)

    # -- 2. scatter decision sweep (SCATTER_NOTES adopt/reject input): the
    # only item with ZERO hardware measurements across five rounds runs
    # right after the headline ------------------------------------------
    print("[capture] scatter sweep...", file=sys.stderr, flush=True)
    # One subprocess + timeout PER probe point, artifact saved after each:
    # the r05 all-or-nothing 900s sweep lost every measurement when a
    # single point wedged the chip — now a wedge costs its own slice and
    # the completed points survive in the artifact.
    point_timeout = float(os.environ.get("PBOX_CAPTURE_POINT_TIMEOUT", "180"))
    points = []
    try:
        p = subprocess.run(
            [sys.executable, "tools/op_probe.py", "--list-sweep-points"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        if p.returncode == 0:
            points = [ln.strip() for ln in p.stdout.splitlines() if ln.strip()]
    except subprocess.TimeoutExpired:
        pass
    if not points:  # listing wedged/failed: fall back to the known set
        points = ["w8", "w16", "w21", "w24", "w32", "w64", "w128",
                  "hints", "gather_set", "bf16", "pallas"]
    sweep_points = {}
    cap["scatter_sweep"] = {
        "point_timeout_s": point_timeout, "points": sweep_points,
        "artifact_path": SWEEP_ARTIFACT,
    }
    for pt in points:
        # --sweep-artifact makes each point RESUMABLE: a point already
        # measured (this capture or a previous partial one) is skipped by
        # op_probe itself, so retried captures only pay for the remainder
        try:
            p = subprocess.run(
                [sys.executable, "tools/op_probe.py",
                 f"--scatter-sweep={pt}",
                 f"--sweep-artifact={SWEEP_ARTIFACT}"],
                cwd=REPO, capture_output=True, text=True,
                timeout=point_timeout,
            )
            sweep_points[pt] = {
                "rc": p.returncode,
                "stdout": p.stdout[-2000:].strip(),
                "stderr": p.stderr[-800:].strip(),
            }
        except subprocess.TimeoutExpired:
            sweep_points[pt] = {
                "error": f"timed out after {point_timeout:.0f}s"
            }
        try:  # structured per-point ms, written atomically by op_probe
            with open(SWEEP_ARTIFACT) as f:
                cap["scatter_sweep"]["artifact"] = json.load(f)
        # optional artifact: absent/torn simply means not embedded
        # pbox-lint: disable=EXC007
        except (OSError, ValueError):
            pass
        _save(cap)  # partial sweep survives a later wedge
        print(f"[capture]   point {pt}: "
              f"{sweep_points[pt].get('error', 'ok')}",
              file=sys.stderr, flush=True)

    # -- 3. ablations at default knobs (the VERDICT-required sub-fields:
    # carrier / wire / pv — each one bench run) --------------------------
    ablations = {}
    for name, env_extra in [
        ("carried_off", {"PBOX_ENABLE_CARRIED_TABLE": 0}),
        ("wire_fp32", {"PBOX_WIRE_DTYPE": "fp32"}),
        ("wire_int8", {"PBOX_WIRE_DTYPE": "int8"}),
        ("pv_join", {"PBOX_BENCH_PV": 1}),
    ]:
        print(f"[capture] ablation {name}...", file=sys.stderr, flush=True)
        # NO_CACHE: non-default-knob runs must not clobber the last-good
        # headline cache (bench_config_id doesn't encode knobs)
        ablations[name] = run_bench(
            {**env_extra, "PBOX_BENCH_NO_CACHE": 1}, timeout=600
        )
        cap["ablations"] = ablations
        _save(cap)

    # -- 4. knob sweep ----------------------------------------------------
    combos = [(8, 2), (16, 2)] if quick else [(4, 2), (8, 1), (8, 2), (8, 4), (16, 2), (32, 2)]
    sweep = []
    for scan_k, inflight in combos:
        out = run_bench({
            "PBOX_RESIDENT_SCAN_BATCHES": scan_k,
            "PBOX_MAX_INFLIGHT_STEPS": inflight,
            "PBOX_BENCH_NO_CACHE": 1,
        })
        row = {"scan": scan_k, "inflight": inflight, "out": out}
        sweep.append(row)
        cap["sweep"] = sweep
        _save(cap)
        v = out.get("value") if _on_tpu(out) else out.get("error", "not-tpu")
        print(f"[capture] sweep scan={scan_k} inflight={inflight}: {v}",
              file=sys.stderr, flush=True)
    good = [r for r in sweep if _on_tpu(r["out"])]
    good.append({"scan": None, "inflight": None, "out": headline})
    best = max(good, key=lambda r: r["out"]["value"])
    cap["best"] = {"scan": best["scan"], "inflight": best["inflight"],
                   "value": best["out"]["value"],
                   "vs_baseline": best["out"]["vs_baseline"]}
    cap["finished_at"] = _now()
    _save(cap)

    # append-only history journal; atomic_write cannot append
    # pbox-lint: disable=IO004
    with open(HISTORY_PATH, "a") as f:
        f.write(json.dumps({
            "ts": cap["finished_at"],
            "headline": headline.get("value"),
            "vs_baseline": headline.get("vs_baseline"),
            "best": cap.get("best"),
            "quick": quick,
        }) + "\n")
    print(f"[capture] done: headline {headline.get('value')} "
          f"({headline.get('vs_baseline')}x)", file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
