#!/usr/bin/env python3
"""proto-check: explicit-state model checker for the elastic membership protocol.

A compact Python model of the control plane that membership.py and
supervisor.py implement over the wire, exhaustively explored by BFS over
every interleaving of votes, frame deliveries, decisions, deaths and
joins within small bounds (<= 3 ranks, bounded epochs, injectable
failures at every step).  The model is deliberately tiny — its value is
that the enumeration is *exhaustive* within the bounds, so an invariant
that holds here holds for every schedule the bounds can express,
including the adversarial ones a soak run hits once a week.

Correspondence to the real protocol (tags pinned against the
analysis/protocol.py extraction by tests/test_proto_check.py):

- ``begin``      ~ agree_membership + sync_map   (ctl:member:*, ctl:mapsync:*)
- ``vote/deliver/decide`` ~ exchange_verdict     (ctl:verdict:*@e*)
- ``announce_join`` ~ the join handshake         (ctl:join:announce, ctl:join:offer:*)
- a commit's map install ~ the range handoff     (migrate:*)

State: per-rank installed map (epoch + an ownership carve of NSHARDS
shard ranges) or None, the set of live processes, at most one active
round (migrate / shrink / join) with per-rank votes, per-rank *delivered*
vote snapshots (delivery is per-recipient — the whole point), and
per-rank decisions.  A death clears the dead rank's installed map (the
process state dies with it) and may strand its vote undelivered to some
recipients but not others — exactly the TCP-teardown race PR 16 is
about.

Invariants, checked on every reachable state / round completion:

- **I1 epoch-monotonic**: a rank never installs a lower epoch than it has.
- **I2 ownership-partition**: every installed map's ranges partition
  [0, NSHARDS) — single owner per shard range, no gaps.
- **I3 epoch-content**: two live ranks holding the same epoch hold the
  identical map (same-epoch different-fingerprint = split-brain).
- **I4 verdict-agreement**: no round ends with one rank committing and
  another recording a *vote*-abort (death-aborts and wedges are
  distinct outcomes and legal alongside a commit).
- **I5 join-abort-rollback**: a join round with zero commits leaves every
  surviving old member at the base epoch and the joiner uninstalled.

``--broken NAME`` swaps in one deliberately wrong protocol variant
(see BROKEN); each variant violates exactly one invariant, which is how
the checker itself is tested.  Exit codes: 0 clean fixpoint, 1 any
violation, 2 state budget exhausted before the fixpoint.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import deque, namedtuple
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

NSHARDS = 6

# Model-transition -> wire-tag vocabulary the transition abstracts.
# tests/test_proto_check.py pins every value as covered by the
# analysis/protocol.py extraction, so the model cannot silently drift
# from the code it claims to check.
MODEL_TAGS = {
    "member": "ctl:member:",
    "mapsync": "ctl:mapsync:",
    "verdict": "ctl:verdict:",
    "join_announce": "ctl:join:announce",
    "join_offer": "ctl:join:offer:",
    "migrate": "migrate:",
    # streaming micro-pass boundary (train/stream.py): the cut and confirm
    # rounds are verdict-family exchanges (epoch-fenced allgathers), so
    # the vote/deliver/decide transitions of this model cover them — the
    # single-rank durability half (two-phase stream cursor) is pinned by
    # the FLT008 crash-window tests in tests/test_stream.py instead.
    "stream_cut": "ctl:verdict:stream-cut:",
    "stream_confirm": "ctl:verdict:stream-confirm:",
}

MapT = namedtuple("MapT", "epoch ranges")  # ranges: ((owner, lo, hi), ...)
Round = namedtuple(
    "Round", "kind base_epoch new_map parts joiner votes seen decided"
)
State = namedtuple(
    "State", "alive installed rnd deaths_left joins_left nos_left joiner"
)

YES, NO = "y", "n"
COMMIT, ABORT, ABORT_DEATH, WEDGED = "commit", "abort", "abort_death", "wedged"

# name -> (invariant it violates, what the bug is, bounds that reach it)
BROKEN: Dict[str, Tuple[str, str, Dict[str, int]]] = {
    "stale_adopt": (
        "I1",
        "sync_map adopts the minimum-epoch map among the living instead "
        "of the maximum, downgrading fresher ranks",
        {"ranks": 3, "deaths": 1, "joins": 0, "nos": 0, "max_epochs": 2},
    ),
    "skip_mapsync": (
        "I3",
        "a round's base is the proposer's own installed map, not the max "
        "among the living — a rank that wedged through the previous "
        "commit re-mints an epoch number under different contents",
        {"ranks": 3, "deaths": 1, "joins": 0, "nos": 0, "max_epochs": 2},
    ),
    "nonatomic_commit": (
        "I4",
        "a peer death mid-round is recorded as a plain vote-abort, so a "
        "rank that already saw every vote commits while its survivor "
        "neighbour aborts the same round",
        {"ranks": 3, "deaths": 1, "joins": 0, "nos": 0, "max_epochs": 2},
    ),
    "join_abort_keeps_epoch": (
        "I5",
        "an aborted join leaves the proposed map installed on the "
        "joiner instead of rolling back to 'never a member'",
        {"ranks": 3, "deaths": 0, "joins": 1, "nos": 1, "max_epochs": 2},
    ),
    "double_owner": (
        "I2",
        "the shard carve lets the first range bleed one shard into the "
        "second — two owners for the same range",
        {"ranks": 3, "deaths": 0, "joins": 0, "nos": 0, "max_epochs": 1},
    ),
}

INVARIANTS = {
    "I1": "epoch-monotonic",
    "I2": "ownership-partition",
    "I3": "epoch-content",
    "I4": "verdict-agreement",
    "I5": "join-abort-rollback",
}


def carve(order, nshards=NSHARDS, overlap=False):
    """Contiguous shard carve over ``order`` (an owner sequence)."""
    n = len(order)
    per, extra = divmod(nshards, n)
    ranges = []
    lo = 0
    for i, r in enumerate(order):
        hi = lo + per + (1 if i < extra else 0)
        ranges.append((r, lo, hi))
        lo = hi
    if overlap and len(ranges) >= 2:
        o, l, h = ranges[0]
        ranges[0] = (o, l, min(h + 1, nshards))
    return tuple(ranges)


def map_members(m: MapT) -> frozenset:
    return frozenset(r for r, _, _ in m.ranges)


@dataclass
class CheckResult:
    states: int
    transitions: int
    violations: List[Dict[str, str]]
    complete: bool
    bounds: Dict[str, int] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.violations

    def as_dict(self) -> Dict:
        return {
            "states": self.states,
            "transitions": self.transitions,
            "violations": self.violations,
            "complete": self.complete,
            "bounds": self.bounds,
        }


class Checker:
    def __init__(
        self,
        ranks: int = 3,
        deaths: int = 1,
        joins: int = 1,
        nos: int = 1,
        max_epochs: int = 3,
        nshards: int = NSHARDS,
        broken: Optional[str] = None,
        max_states: int = 400_000,
        max_violations: int = 5,
    ):
        if broken is not None and broken not in BROKEN:
            raise ValueError(f"unknown broken variant: {broken!r}")
        if ranks < 2:
            raise ValueError("need at least 2 ranks")
        self.ranks = ranks
        self.deaths = deaths
        self.joins = joins
        self.nos = nos
        self.max_epochs = max_epochs
        self.nshards = nshards
        self.b = broken
        self.max_states = max_states
        self.max_violations = max_violations
        self.violations: List[Dict[str, str]] = []

    # -- invariant plumbing --------------------------------------------------

    def _violate(self, inv: str, detail: str) -> None:
        v = {"invariant": inv, "detail": detail}
        if v not in self.violations:
            self.violations.append(v)

    def _install(self, installed, r, m):
        """Install map ``m`` on rank ``r``; None when it would violate I1."""
        old = installed[r]
        if old is not None and m.epoch < old.epoch:
            self._violate(
                "I1",
                f"rank {r} installed epoch {old.epoch} would be replaced "
                f"by epoch {m.epoch}",
            )
            return None
        return tuple(m if i == r else x for i, x in enumerate(installed))

    def _check_state(self, s: State) -> bool:
        """I2/I3 over the installed maps of live ranks."""
        ok = True
        by_epoch: Dict[int, Tuple[int, tuple]] = {}
        for r in sorted(s.alive):
            m = s.installed[r]
            if m is None:
                continue
            rs = sorted(m.ranges, key=lambda t: t[1])
            lo, good = 0, True
            for _, l, h in rs:
                if l != lo or h <= l:
                    good = False
                    break
                lo = h
            if not (good and lo == self.nshards):
                self._violate(
                    "I2",
                    f"rank {r} map e{m.epoch} ranges {m.ranges} do not "
                    f"partition [0,{self.nshards})",
                )
                ok = False
            prev = by_epoch.get(m.epoch)
            if prev is not None and prev[1] != m.ranges:
                self._violate(
                    "I3",
                    f"epoch {m.epoch} installed with two contents: rank "
                    f"{prev[0]} {prev[1]} vs rank {r} {m.ranges}",
                )
                ok = False
            else:
                by_epoch.setdefault(m.epoch, (r, m.ranges))
        return ok

    # -- state space ---------------------------------------------------------

    def initial(self) -> State:
        # with a join budget the last rank starts as a live standby
        # (announced processes exist before they are members)
        n_members = self.ranks - (1 if self.joins > 0 else 0)
        m0 = MapT(0, carve(tuple(range(n_members)), self.nshards))
        installed = tuple(
            m0 if r < n_members else None for r in range(self.ranks)
        )
        return State(
            alive=frozenset(range(self.ranks)),
            installed=installed,
            rnd=None,
            deaths_left=self.deaths,
            joins_left=self.joins,
            nos_left=self.nos,
            joiner=None,
        )

    def _begin_kind(self, s: State, base: MapT, kind: str):
        mem = map_members(base)
        live_mem = tuple(r for r in sorted(mem) if r in s.alive)
        if not live_mem:
            return None
        new_epoch = base.epoch + 1
        if new_epoch > self.max_epochs:
            return None
        overlap = self.b == "double_owner"
        joiner = None
        if kind == "migrate":
            # rebalance: membership intact, ownership order rotated
            if len(live_mem) < 2 or len(live_mem) != len(mem):
                return None
            order = [r for r, _, _ in base.ranges]
            order = order[1:] + order[:1]
            parts = live_mem
            new_map = MapT(new_epoch, carve(order, self.nshards, overlap))
        elif kind == "shrink":
            if len(live_mem) == len(mem):
                return None  # nobody to shrink out
            parts = live_mem
            new_map = MapT(new_epoch, carve(live_mem, self.nshards, overlap))
        else:  # join
            if s.joiner is None or s.joiner not in s.alive:
                return None
            joiner = s.joiner
            order = tuple(sorted(set(live_mem) | {joiner}))
            parts = tuple(sorted(set(live_mem) | {joiner}))
            new_map = MapT(new_epoch, carve(order, self.nshards, overlap))
        # mapsync: lagging participants adopt the base before voting
        inst = s.installed
        if self.b == "skip_mapsync":
            pass  # the bug: nobody syncs, everyone votes from its own map
        else:
            for p in live_mem:
                cur = inst[p]
                adopt = cur is None or cur.epoch < base.epoch
                if self.b == "stale_adopt":
                    adopt = cur is None or cur.epoch != base.epoch
                if adopt:
                    nxt = self._install(inst, p, base)
                    if nxt is None:
                        return None  # I1 recorded; drop the branch
                    inst = nxt
        n = len(parts)
        rnd = Round(
            kind=kind,
            base_epoch=base.epoch,
            new_map=new_map,
            parts=parts,
            joiner=joiner,
            votes=(None,) * n,
            seen=(frozenset(),) * n,
            decided=(None,) * n,
        )
        return s._replace(
            installed=inst,
            rnd=rnd,
            joiner=None if kind == "join" else s.joiner,
        )

    def _begins(self, s: State) -> List[State]:
        holders = [r for r in sorted(s.alive) if s.installed[r] is not None]
        if not holders:
            return []
        maps = sorted(
            {s.installed[r] for r in holders},
            key=lambda m: (m.epoch, m.ranges),
        )
        if self.b == "skip_mapsync":
            bases = maps  # any holder may propose from its own map
        elif self.b == "stale_adopt":
            bases = [maps[0]]
        else:
            bases = [maps[-1]]
        out = []
        for base in bases:
            for kind in ("migrate", "shrink", "join"):
                ns = self._begin_kind(s, base, kind)
                if ns is not None:
                    out.append(ns)
        return out

    def _end_round(self, s: State) -> Optional[State]:
        rnd = s.rnd
        idx = {p: i for i, p in enumerate(rnd.parts)}
        decided = [rnd.decided[idx[p]] for p in rnd.parts]
        commits = decided.count(COMMIT)
        if commits and ABORT in decided:
            self._violate(
                "I4",
                f"{rnd.kind} round @e{rnd.new_map.epoch}: "
                f"commit and vote-abort in the same round ({decided})",
            )
            return None
        if rnd.kind == "join" and commits == 0:
            j = rnd.joiner
            if j in s.alive and s.installed[j] is not None:
                self._violate(
                    "I5",
                    f"aborted join @e{rnd.new_map.epoch}: joiner {j} still "
                    f"has a map installed",
                )
                return None
            for p in rnd.parts:
                if p == j or p not in s.alive:
                    continue
                m = s.installed[p]
                if m is not None and m.epoch != rnd.base_epoch:
                    self._violate(
                        "I5",
                        f"aborted join @e{rnd.new_map.epoch}: rank {p} at "
                        f"epoch {m.epoch}, expected base {rnd.base_epoch}",
                    )
                    return None
        return s._replace(rnd=None)

    def successors(self, s: State) -> List[State]:
        out: List[State] = []
        # -- die: any live process, as long as one map holder survives
        if s.deaths_left > 0:
            for r in sorted(s.alive):
                holders = [
                    x for x in s.alive
                    if x != r and s.installed[x] is not None
                ]
                if not holders:
                    continue
                inst = tuple(
                    None if i == r else m for i, m in enumerate(s.installed)
                )
                out.append(
                    s._replace(
                        alive=s.alive - {r},
                        installed=inst,
                        deaths_left=s.deaths_left - 1,
                        joiner=None if s.joiner == r else s.joiner,
                    )
                )
        # -- announce_join: a live standby (no map) asks in
        if s.joins_left > 0 and s.joiner is None and s.rnd is None:
            for r in sorted(s.alive):
                if s.installed[r] is None:
                    out.append(
                        s._replace(joins_left=s.joins_left - 1, joiner=r)
                    )
        rnd = s.rnd
        if rnd is None:
            out.extend(self._begins(s))
            return out
        idx = {p: i for i, p in enumerate(rnd.parts)}
        # -- vote
        for p in rnd.parts:
            i = idx[p]
            if p not in s.alive or rnd.votes[i] is not None:
                continue
            v = tuple(
                YES if j == i else x for j, x in enumerate(rnd.votes)
            )
            out.append(s._replace(rnd=rnd._replace(votes=v)))
            if s.nos_left > 0:
                v2 = tuple(
                    NO if j == i else x for j, x in enumerate(rnd.votes)
                )
                out.append(
                    s._replace(
                        rnd=rnd._replace(votes=v2),
                        nos_left=s.nos_left - 1,
                    )
                )
        # -- deliver: a recipient's allgather snapshot catches up to the
        # votes cast so far (frames from the already-dead included: a
        # final frame may or may not survive the sender's teardown)
        voted = frozenset(
            p for p in rnd.parts if rnd.votes[idx[p]] is not None
        )
        for p in rnd.parts:
            i = idx[p]
            if (
                p in s.alive
                and rnd.decided[i] is None
                and not voted <= rnd.seen[i]
            ):
                seen = tuple(
                    voted | x if j == i else x
                    for j, x in enumerate(rnd.seen)
                )
                out.append(s._replace(rnd=rnd._replace(seen=seen)))
        # -- decide
        for p in rnd.parts:
            i = idx[p]
            if p not in s.alive or rnd.decided[i] is not None:
                continue
            seen = rnd.seen[i]
            delivered_no = any(rnd.votes[idx[q]] == NO for q in seen)
            dead_missing = [
                q for q in rnd.parts if q not in s.alive and q not in seen
            ]
            inst = s.installed
            if delivered_no:
                verdict = ABORT
                if (
                    self.b == "join_abort_keeps_epoch"
                    and rnd.kind == "join"
                    and p == rnd.joiner
                ):
                    nxt = self._install(inst, p, rnd.new_map)
                    if nxt is None:
                        continue
                    inst = nxt
            elif seen >= set(rnd.parts):
                verdict = COMMIT
                nxt = self._install(inst, p, rnd.new_map)
                if nxt is None:
                    continue
                inst = nxt
            elif dead_missing:
                # someone's vote can never arrive: PeerDeadError
                if rnd.kind == "join" and set(dead_missing) <= {rnd.joiner}:
                    verdict = ABORT_DEATH
                else:
                    verdict = WEDGED
                if self.b == "nonatomic_commit":
                    verdict = ABORT
            else:
                continue  # still waiting on live voters
            d = tuple(
                verdict if j == i else x
                for j, x in enumerate(rnd.decided)
            )
            out.append(
                s._replace(installed=inst, rnd=rnd._replace(decided=d))
            )
        # -- end_round: every live participant has decided
        if all(
            p not in s.alive or rnd.decided[idx[p]] is not None
            for p in rnd.parts
        ):
            ns = self._end_round(s)
            if ns is not None:
                out.append(ns)
        return out

    # -- driver --------------------------------------------------------------

    def run(self) -> CheckResult:
        self.violations = []
        init = self.initial()
        self._check_state(init)
        visited = {init}
        q = deque([init])
        transitions = 0
        complete = True
        while q:
            if len(self.violations) >= self.max_violations:
                complete = False
                break
            s = q.popleft()
            for ns in self.successors(s):
                transitions += 1
                if ns in visited:
                    continue
                if len(visited) >= self.max_states:
                    complete = False
                    q.clear()
                    break
                visited.add(ns)
                if not self._check_state(ns):
                    continue  # recorded; do not expand a broken state
                q.append(ns)
        return CheckResult(
            states=len(visited),
            transitions=transitions,
            violations=list(self.violations),
            complete=complete,
            bounds={
                "ranks": self.ranks,
                "deaths": self.deaths,
                "joins": self.joins,
                "nos": self.nos,
                "max_epochs": self.max_epochs,
                "broken": self.b or "",
            },
        )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="proto_check",
        description="model-check the elastic membership protocol",
    )
    ap.add_argument("--ranks", type=int, default=None)
    ap.add_argument("--deaths", type=int, default=None)
    ap.add_argument("--joins", type=int, default=None)
    ap.add_argument("--nos", type=int, default=None,
                    help="budget of no-votes (resource refusals)")
    ap.add_argument("--max-epochs", type=int, default=None)
    ap.add_argument("--max-states", type=int, default=400_000)
    ap.add_argument("--broken", default=None, choices=sorted(BROKEN))
    ap.add_argument("--list-broken", action="store_true")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    if args.list_broken:
        for name in sorted(BROKEN):
            inv, desc, _ = BROKEN[name]
            print(f"{name:24s} {inv} ({INVARIANTS[inv]}): {desc}")
        return 0

    defaults = {"ranks": 3, "deaths": 1, "joins": 1, "nos": 1,
                "max_epochs": 3}
    if args.broken:
        defaults.update(BROKEN[args.broken][2])
    bounds = {
        k: getattr(args, k) if getattr(args, k) is not None else v
        for k, v in defaults.items()
    }

    chk = Checker(broken=args.broken, max_states=args.max_states, **bounds)
    res = chk.run()

    if args.json:
        print(json.dumps(res.as_dict(), indent=2))
    else:
        tag = args.broken or "-"
        print(
            f"proto-check: ranks={bounds['ranks']} deaths={bounds['deaths']} "
            f"joins={bounds['joins']} nos={bounds['nos']} "
            f"max_epochs={bounds['max_epochs']} broken={tag}"
        )
        fix = "fixpoint" if res.complete else "budget exhausted"
        print(f"explored {res.states} states / {res.transitions} "
              f"transitions ({fix})")
        inv_line = ", ".join(f"{k} {v}" for k, v in INVARIANTS.items())
        print(f"invariants: {inv_line}")
        if res.ok:
            print("OK: no violations")
        else:
            for v in res.violations:
                print(f"VIOLATION {v['invariant']}: {v['detail']}")
    if not res.ok:
        return 1
    if not res.complete:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
