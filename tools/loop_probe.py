"""Simulate the train_pass loop at bench shapes with different feed
strategies (4-array dict vs one fused buffer; same-thread vs prefetch
threads) and dispatch windows, to pick the fastest transport discipline.
"""

from __future__ import annotations

import os
import sys
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.table import SparseOptimizerConfig, ValueLayout
from paddlebox_tpu.train import TrainStepConfig
from paddlebox_tpu.train.train_step import (
    init_train_state,
    jit_train_step,
    make_train_step,
)

NUM_SLOTS = 39
EMBEDX_DIM = 16
BATCH = 4096
HIDDEN = (512, 256, 128)
ROWS = 2_514_944
L = NUM_SLOTS * BATCH
U = 131_072
N_BATCHES = 48


def make_host_batches(rng, n):
    out = []
    for _ in range(n):
        out.append(
            {
                "uniq_rows": rng.integers(0, ROWS, U).astype(np.int32),
                "inverse": rng.integers(0, U, L).astype(np.int32),
                "segments": (np.arange(L) % (NUM_SLOTS * BATCH)).astype(np.int32),
                "labels": (rng.random(BATCH) < 0.2).astype(np.float32),
            }
        )
    return out


def main():
    layout = ValueLayout(embedx_dim=EMBEDX_DIM)
    opt_cfg = SparseOptimizerConfig(embedx_threshold=0.0)
    rng = np.random.default_rng(0)
    host_table = rng.standard_normal((ROWS, layout.width)).astype(np.float32) * 0.01
    model = DeepFM(
        num_slots=NUM_SLOTS, feat_width=layout.pull_width,
        embedx_dim=EMBEDX_DIM, hidden=HIDDEN,
    )
    params = model.init(jax.random.PRNGKey(0))
    cfg = TrainStepConfig(
        num_slots=NUM_SLOTS, batch_size=BATCH, layout=layout,
        sparse_opt=opt_cfg, auc_buckets=100_000,
    )
    step = jit_train_step(make_train_step(model.apply, optax.adam(1e-3), cfg))
    host_batches = make_host_batches(rng, N_BATCHES)

    # fused variant: one int32 buffer; unpack inside jit
    def fuse(hb):
        return np.concatenate(
            [
                hb["uniq_rows"],
                hb["inverse"],
                hb["segments"],
                hb["labels"].view(np.int32),
            ]
        )

    fused_batches = [fuse(hb) for hb in host_batches]
    raw_step = make_train_step(model.apply, optax.adam(1e-3), cfg)

    def step_fused_fn(state, buf):
        o = 0
        uniq_rows = jax.lax.dynamic_slice_in_dim(buf, o, U); o += U
        inverse = jax.lax.dynamic_slice_in_dim(buf, o, L); o += L
        segments = jax.lax.dynamic_slice_in_dim(buf, o, L); o += L
        labels = jax.lax.bitcast_convert_type(
            jax.lax.dynamic_slice_in_dim(buf, o, BATCH), jnp.float32
        )
        return raw_step(
            state,
            {
                "uniq_rows": uniq_rows,
                "inverse": inverse,
                "segments": segments,
                "labels": labels,
            },
        )

    step_fused = jax.jit(step_fused_fn, donate_argnums=(0,))

    def run(name, mode, inflight_cap, workers=3, depth=6):
        table = jax.device_put(host_table)
        jax.block_until_ready(table)
        # fresh params per run: the step donates state, so a prior run's
        # params buffers are dead
        state = init_train_state(
            table, model.init(jax.random.PRNGKey(0)), optax.adam(1e-3), 100_000
        )
        ex = ThreadPoolExecutor(workers)

        if mode == "dict":
            put = lambda i: {
                k: jax.device_put(v) for k, v in host_batches[i % len(host_batches)].items()
            }
            stepf = step
        else:
            put = lambda i: jax.device_put(fused_batches[i % len(fused_batches)])
            stepf = step_fused

        # warmup/compile
        st, m = stepf(state, put(0))
        jax.block_until_ready(m["loss"])
        state = st

        futs: deque = deque()
        for i in range(min(depth, N_BATCHES)):
            futs.append(ex.submit(put, i))
        inflight: deque = deque()
        t0 = time.perf_counter()
        for i in range(N_BATCHES):
            feed = futs.popleft().result()
            nxt = i + depth
            if nxt < N_BATCHES:
                futs.append(ex.submit(put, nxt))
            state, m = stepf(state, feed)
            inflight.append(m["loss"])
            if len(inflight) > inflight_cap:
                jax.block_until_ready(inflight.popleft())
        final_loss = float(m["loss"])  # forces the full chain
        jax.block_until_ready(state.table)
        dt = time.perf_counter() - t0
        sps = N_BATCHES * BATCH / dt
        print(f"{name:34s} {dt/N_BATCHES*1e3:8.2f} ms/batch  {sps:10.0f} sps  loss={final_loss:.4f}")
        ex.shutdown(wait=False)

    def run_steps_only(name, inflight_cap):
        """Preload every feed to the device first: pure step throughput."""
        table = jax.device_put(host_table)
        jax.block_until_ready(table)
        state = init_train_state(
            table, model.init(jax.random.PRNGKey(0)), optax.adam(1e-3), 100_000
        )
        feeds = [jax.device_put(fb) for fb in fused_batches]
        jax.block_until_ready(feeds)
        st, m = step_fused(state, feeds[0])
        jax.block_until_ready(m["loss"])
        state = st
        inflight: deque = deque()
        t0 = time.perf_counter()
        for i in range(1, N_BATCHES):
            state, m = step_fused(state, feeds[i])
            inflight.append(m["loss"])
            if len(inflight) > inflight_cap:
                jax.block_until_ready(inflight.popleft())
        final_loss = float(m["loss"])
        jax.block_until_ready(state.table)
        dt = time.perf_counter() - t0
        print(
            f"{name:34s} {dt/(N_BATCHES-1)*1e3:8.2f} ms/batch  "
            f"{(N_BATCHES-1)*BATCH/dt:10.0f} sps  loss={final_loss:.4f}"
        )

    def run_transfers_only(name, workers=3, depth=6):
        """No compute: just stream every fused buffer to the device."""
        ex = ThreadPoolExecutor(workers)
        t0 = time.perf_counter()
        futs = [ex.submit(jax.device_put, fb) for fb in fused_batches]
        outs = [f.result() for f in futs]
        jax.block_until_ready(outs)
        dt = time.perf_counter() - t0
        mb = sum(fb.nbytes for fb in fused_batches) / 1e6
        print(
            f"{name:34s} {dt/N_BATCHES*1e3:8.2f} ms/batch  "
            f"({mb/dt:8.1f} MB/s)"
        )
        ex.shutdown(wait=False)

    for trial in range(2):
        run_steps_only("steps only (preloaded feeds)", 4)
        run_transfers_only("transfers only")
        run("fused feed, inflight=4", "fused", 4)
        run("dict feed, inflight=4", "dict", 4)


if __name__ == "__main__":
    main()
