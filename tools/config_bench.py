"""All five BASELINE.json configs, one command: per-config end-to-end
training throughput + AUC on synthetic data at each config's shape.

bench.py is the headline artifact (config 3, DeepFM, full shape);
this harness proves the other configurations RUN end to end on the same
machinery and tracks their relative throughput:

  1. LR on Criteo-shaped slots (single-device, plain logistic regression)
  2. Wide&Deep (wide linear arm + deep tower)
  3. DeepFM (reduced shape here; bench.py measures the full one)
  4. DNN+DCN multi-slot (108 sparse slots, cross network)
  5. MMoE multi-task bottom (shared experts, CTR head)

Prints one JSON line per config. Usage:
  python tools/config_bench.py [--rows N] [--batches N]
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from bench import apply_legacy_init_env  # noqa: E402
from paddlebox_tpu.utils.backendguard import (  # noqa: E402
    probe_backend_with_retries,
)


def write_files(tmpdir, rng, n_rows, n_slots, key_space):
    path = os.path.join(tmpdir, "part-000.txt")
    hot = rng.integers(1, 1 << 10, (n_rows, n_slots))
    cold = rng.integers(1, key_space, (n_rows, n_slots))
    keys = np.where(rng.random((n_rows, n_slots)) < 0.3, hot, cold)
    labels = (rng.random(n_rows) < 0.2).astype(np.int32)
    with open(path, "w") as f:
        for i in range(n_rows):
            f.write(
                f"1 {labels[i]}.0 "
                + " ".join(f"1 {k}" for k in keys[i])
                + "\n"
            )
    return [path]


def convert_data_dir(data_dir: str, workdir: str):
    """Real-format (Kaggle Criteo) dir -> converted slot-format files.

    Every *.txt in the dir converts line-by-line via convert_criteo_line;
    malformed/truncated lines take the reject path. Returns (files,
    accepted, rejected)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from criteo_convergence import convert_criteo_line

    out_files, n_ok, n_rej = [], 0, 0
    for fn in sorted(os.listdir(data_dir)):
        if not fn.endswith(".txt"):
            continue
        op = os.path.join(workdir, "conv-" + fn)
        # scratch conversion, consumed by this same bench run
        # pbox-lint: disable=IO004
        with open(os.path.join(data_dir, fn)) as fi, open(op, "w") as fo:
            for line in fi:
                s = line.rstrip("\n")
                out = convert_criteo_line(s) if s else None
                if out is None:
                    n_rej += 1
                    continue
                fo.write(out + "\n")
                n_ok += 1
        out_files.append(op)
    if not out_files or n_ok == 0:
        raise ValueError(f"no usable *.txt lines under {data_dir}")
    return out_files, n_ok, n_rej


def run_config(name, model_fn, n_slots, batch, embedx, rows, batches,
               key_space, data_files=None):
    import jax
    import optax

    from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
    from paddlebox_tpu.table import (
        HostSparseTable,
        SparseOptimizerConfig,
        ValueLayout,
    )
    from paddlebox_tpu.train import CTRTrainer, TrainStepConfig

    rng = np.random.default_rng(0)
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(n_slots)],
        label_slot="label",
    )
    layout = ValueLayout(embedx_dim=embedx)
    opt_cfg = SparseOptimizerConfig(embedx_threshold=0.0)
    table = HostSparseTable(layout, opt_cfg, n_shards=8, seed=0)
    with tempfile.TemporaryDirectory() as tmpdir:
        files = (
            data_files
            if data_files is not None
            else write_files(tmpdir, rng, rows, n_slots, key_space)
        )
        ds = BoxPSDataset(schema, table, batch_size=batch, shuffle_mode="local", seed=0)
        ds.set_filelist(files)
        ds.load_into_memory()
        ds.begin_pass(round_to=256)
        model = model_fn(layout)
        cfg = TrainStepConfig(
            num_slots=n_slots, batch_size=batch, layout=layout,
            sparse_opt=opt_cfg, auc_buckets=10_000,
        )
        tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-3))
        tr.init_params(jax.random.PRNGKey(0))
        tr.prepare_pass(ds, n_batches=batches)
        tr.train_pass(ds, n_batches=min(8, batches))  # warm
        t0 = time.perf_counter()
        out = tr.train_pass(ds, n_batches=batches)
        dt = time.perf_counter() - t0
        ds.end_pass(tr.trained_table_device())
        table.drain_pending()
    return {
        "config": name,
        "slots": n_slots,
        "batch": batch,
        "samples_per_sec": round(batches * batch / dt, 1),
        "auc": round(out["auc_cumulative"], 4),
        "loss": round(out["loss"], 4),
    }


def main():
    rows = 65_536
    batches = 24
    data_dir = None
    for i, a in enumerate(sys.argv):
        if a == "--rows":
            rows = int(sys.argv[i + 1])
        if a == "--batches":
            batches = int(sys.argv[i + 1])
        if a == "--data-dir":
            data_dir = sys.argv[i + 1]
    apply_legacy_init_env()
    info, _ = probe_backend_with_retries()
    import jax

    if info is None:
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform

    from paddlebox_tpu.models import (
        DCN,
        DeepFM,
        LogisticRegression,
        MMoE,
        WideDeep,
        task_head,
    )

    configs = [
        (
            "1-lr-criteo",
            lambda lay: LogisticRegression(39, lay.pull_width),
            39, 1024, 8,
        ),
        (
            "2-widedeep",
            lambda lay: WideDeep(39, lay.pull_width, hidden=(64, 32)),
            39, 1024, 8,
        ),
        (
            "3-deepfm-small",
            lambda lay: DeepFM(
                num_slots=39, feat_width=lay.pull_width, embedx_dim=8,
                hidden=(64, 32),
            ),
            39, 1024, 8,
        ),
        (
            "4-dcn-multislot",
            lambda lay: DCN(108, lay.pull_width, n_cross=3, hidden=(64, 32)),
            108, 512, 8,
        ),
        (
            "5-mmoe",
            lambda lay: task_head(
                MMoE(39, lay.pull_width, n_experts=4, expert_hidden=(32,)),
                task=0,
            ),
            39, 1024, 8,
        ),
    ]
    data_ctx = tempfile.TemporaryDirectory() if data_dir else None
    data_files = None
    n_ok = n_rej = 0
    if data_dir:
        # real-format mode: every config runs the converted 39-slot Criteo
        # stream (the day real data appears, point --data-dir at it);
        # malformed lines take the reject path and are counted
        data_files, n_ok, n_rej = convert_data_dir(data_dir, data_ctx.name)
        print(
            json.dumps({
                "data_dir": data_dir, "accepted": n_ok, "rejected": n_rej,
            }),
            flush=True,
        )
    try:
        for name, fn, n_slots, batch, embedx in configs:
            n_batches = batches
            if data_dir:
                n_slots = 39  # the converted stream's slot count
                if name.startswith("4-dcn"):
                    from paddlebox_tpu.models import DCN as _DCN

                    fn = lambda lay: _DCN(  # noqa: E731
                        39, lay.pull_width, n_cross=3, hidden=(64, 32)
                    )
                # size this config to the real corpus (wraparound keeps
                # shapes); per-config locals so one config's clamp can't
                # leak into the next
                batch = min(batch, max(64, n_ok // 4))
                n_batches = min(batches, max(2, n_ok // batch))
            try:
                r = run_config(
                    name, fn, n_slots, batch, embedx, rows, n_batches,
                    key_space=1 << 20, data_files=data_files,
                )
                r["platform"] = platform
                if data_dir:
                    r["real_format"] = True
                    r["rejected_lines"] = n_rej
                print(json.dumps(r), flush=True)
            except Exception as e:  # one config failing must not hide the rest
                print(json.dumps({"config": name, "error": repr(e)[:300]}), flush=True)
    finally:
        if data_ctx is not None:
            data_ctx.cleanup()


if __name__ == "__main__":
    main()
