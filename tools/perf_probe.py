"""Micro-attribution of the bench.py device step on the live backend.

Times each stage of the jitted train step in isolation at bench shapes so a
slow headline number can be blamed on a specific op (pull gather, fwd/bwd,
push scatter, AUC, H2D feed). Not part of the test suite — a tuning tool.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.ops.pull_push import pull_sparse_rows, push_sparse_rows
from paddlebox_tpu.ops.seqpool_cvm import fused_seqpool_cvm
from paddlebox_tpu.table import SparseOptimizerConfig, ValueLayout
from paddlebox_tpu.train import TrainStepConfig
from paddlebox_tpu.train.train_step import (
    init_train_state,
    jit_train_step,
    make_train_step,
)

NUM_SLOTS = 39
EMBEDX_DIM = 16
BATCH = 4096
HIDDEN = (512, 256, 128)
ROWS = 2_514_944  # ~bench pass working set, rounded
L = NUM_SLOTS * BATCH  # flat keys (1 key/slot like bench data)
U = 131_072  # deduped uniq rows per batch, bucket-padded


def timeit(name, fn, *args, n=20):
    fn(*args)  # compile
    jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n * 1e3
    print(f"{name:28s} {dt:9.3f} ms")
    return dt


def main():
    print("platform:", jax.devices()[0].platform)
    layout = ValueLayout(embedx_dim=EMBEDX_DIM)
    opt_cfg = SparseOptimizerConfig(embedx_threshold=0.0)
    rng = np.random.default_rng(0)
    W = layout.width
    table = jnp.asarray(rng.standard_normal((ROWS, W)).astype(np.float32) * 0.01)
    uniq_rows = jnp.asarray(
        rng.integers(0, ROWS, U).astype(np.int32)
    )
    inverse = jnp.asarray(rng.integers(0, U, L).astype(np.int32))
    segments = jnp.asarray(np.arange(L, dtype=np.int32) % (NUM_SLOTS * BATCH))
    labels = jnp.asarray((rng.random(BATCH) < 0.2).astype(np.float32))

    model = DeepFM(
        num_slots=NUM_SLOTS, feat_width=layout.pull_width,
        embedx_dim=EMBEDX_DIM, hidden=HIDDEN,
    )
    params = model.init(jax.random.PRNGKey(0))
    cfg = TrainStepConfig(
        num_slots=NUM_SLOTS, batch_size=BATCH, layout=layout,
        sparse_opt=opt_cfg, auc_buckets=100_000,
    )

    # --- stage 1: pull gather + inverse take
    @jax.jit
    def stage_pull(table, uniq_rows, inverse):
        pulled = pull_sparse_rows(table, uniq_rows, layout, 0.0, 1.0)
        return jnp.take(pulled, inverse, axis=0)

    timeit("pull gather+take", stage_pull, table, uniq_rows, inverse)

    # --- stage 2: seqpool + model fwd/bwd (dense math only)
    flat = stage_pull(table, uniq_rows, inverse)

    @jax.jit
    def stage_fwdbwd(params, flat):
        def loss_fn(p, fr):
            feats = fused_seqpool_cvm(
                fr, segments, num_slots=NUM_SLOTS, batch_size=BATCH
            )
            logits = model.apply(p, feats, None)
            return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, labels))

        return jax.value_and_grad(loss_fn, argnums=(0, 1))(params, flat)

    timeit("seqpool+fwd/bwd", stage_fwdbwd, params, flat)

    # --- stage 3: grad merge (segment_sum at L->U)
    gflat = stage_fwdbwd(params, flat)[1][1]

    @jax.jit
    def stage_merge(gflat):
        merged = jax.ops.segment_sum(gflat, inverse, num_segments=U)
        show = jax.ops.segment_sum(
            jnp.ones((L,), jnp.float32), inverse, num_segments=U
        )
        return merged, show

    timeit("grad segment_sum", stage_merge, gflat)

    # --- stage 4: push scatter (adagrad + at[].add)
    merged, show = stage_merge(gflat)

    @jax.jit
    def stage_push(table, uniq_rows, merged, show):
        return push_sparse_rows(
            table, uniq_rows, merged, show, show * 0.2, layout, opt_cfg
        )

    timeit("push update+scatter", stage_push, table, uniq_rows, merged, show)

    # --- stage 5: AUC bucket update
    from paddlebox_tpu.metrics.auc import auc_init, auc_update

    auc = auc_init(100_000)
    preds = jax.nn.sigmoid(jnp.asarray(rng.standard_normal(BATCH), jnp.float32))

    @jax.jit
    def stage_auc(auc, preds, labels):
        return auc_update(auc, preds, labels)

    timeit("auc bucket update", stage_auc, auc, preds, labels)

    # --- full fused step (donated), on-device feed
    step = jit_train_step(make_train_step(model.apply, optax.adam(1e-3), cfg))
    state = init_train_state(table, params, optax.adam(1e-3), 100_000)
    batch = {
        "uniq_rows": uniq_rows,
        "inverse": inverse,
        "segments": segments,
        "labels": labels,
    }

    state, m = step(state, batch)  # compile
    jax.block_until_ready(state.table)
    n = 20
    t0 = time.perf_counter()
    for _ in range(n):
        state, m = step(state, batch)
    jax.block_until_ready(state.table)
    print(f"{'FULL step (device feed)':28s} {(time.perf_counter()-t0)/n*1e3:9.3f} ms")

    # --- H2D feed transfer alone
    host_batch = {k: np.asarray(v) for k, v in batch.items()}

    def h2d(hb):
        return {k: jax.device_put(v) for k, v in hb.items()}

    out = h2d(host_batch)
    jax.block_until_ready(list(out.values()))
    t0 = time.perf_counter()
    for _ in range(n):
        out = h2d(host_batch)
        jax.block_until_ready(list(out.values()))
    print(f"{'H2D feed transfer':28s} {(time.perf_counter()-t0)/n*1e3:9.3f} ms")
    nbytes = sum(v.nbytes for v in host_batch.values())
    print(f"feed bytes/batch: {nbytes/1e6:.2f} MB")


if __name__ == "__main__":
    main()
