"""serve_soak: a supervised training day publishing deltas while a follower serves.

One process, three concurrent roles over a shared checkpoint root:

- **producer** (main thread): trains one pass per publish (save_base for
  pass 0, save_delta after), and captures reference predictions for a
  fixed probe set against the LIVE trainer table immediately after each
  save — the trainer-direct side of the bitwise-parity gate.
- **follower** (poller thread): ``Follower.run`` tails latest.json and
  applies the chain as it grows.
- **load generator** (client threads): fires batched score requests at a
  target QPS through the :class:`ScoreServer` front-end while versions
  swap underneath it.

After the day, every version the follower served is re-scored offline and
compared bitwise against the producer's capture at the same delta index.
The report carries p50/p99 score latency, achieved QPS, per-version
train-to-serve staleness, and the parity verdict — the acceptance gate of
the serving tentpole (docs/SERVING.md).

``--fleet N`` runs the networked variant instead: N followers behind PBTX
framing share one staged download (FleetStage), a FleetClient load-balances
with retries + hedging, and the day includes follower kill, drain/admit,
and rejoin while publishes keep landing — the fault-tolerant-serving
acceptance gate (zero client-visible failures, bitwise parity live and
offline, single disk fetch per publish independent of N).

``--device-tier`` runs the mesh-sharded-scoring A/B instead: the SAME day
twice — host-only (``device_scoring_tier=off``) then device-tier on — with
bitwise parity required inside each leg AND between the legs (the off
ablation must be bitwise-identical), followed by a lookup-throughput
microbench (large synthetic version, hot-key query mix at hit rate >= 0.9)
comparing ``TableVersion.lookup_rows`` host-only against the tiered path.
The committed report is SOAK_SERVESHARD.json; the platform is stamped
because on a CPU mesh the numbers are a proxy for the TPU target.

Run:  python tools/serve_soak.py --passes 6 --qps 40 [--fleet 3 | --device-tier] [--json report.json]
Exit: 0 on full parity + no request errors, 1 otherwise.
"""
import argparse
import hashlib
import json
import os
import socket
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax

jax.config.update("jax_platforms", "cpu")
import optax

from paddlebox_tpu import config
from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
from paddlebox_tpu.utils.fs import atomic_write
from paddlebox_tpu.data.parser import parse_line
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel.transport import TcpTransport
from paddlebox_tpu.serve import (
    Follower,
    FleetClient,
    FleetFollower,
    FleetStage,
    ScoreServer,
    Scorer,
    ServeRequestError,
    table_source,
    version_source,
)
from paddlebox_tpu.table import HostSparseTable, SparseOptimizerConfig, ValueLayout
from paddlebox_tpu.train import CheckpointManager, CTRTrainer, TrainStepConfig
from paddlebox_tpu.utils.monitor import STAT_GET

S, B = 4, 16
DATE = "20260807"
LAYOUT = ValueLayout(embedx_dim=4)
OPT = SparseOptimizerConfig(
    embedx_threshold=0.0, show_clk_decay=0.97, shrink_threshold=0.0
)
SCHEMA = SlotSchema(
    [SlotInfo("label", type="float", dense=True, dim=1)]
    + [SlotInfo(f"s{i}") for i in range(S)],
    label_slot="label",
)


def make_stack(root):
    """Producer trainer + checkpoint manager over ``root``."""
    table = HostSparseTable(LAYOUT, OPT, n_shards=4, seed=0)
    ds = BoxPSDataset(SCHEMA, table, batch_size=B, shuffle_mode="none")
    cfg = TrainStepConfig(
        num_slots=S, batch_size=B, layout=LAYOUT, sparse_opt=OPT, auc_buckets=500
    )
    model = DeepFM(S, LAYOUT.pull_width, LAYOUT.embedx_dim, hidden=(8,))
    trainer = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    trainer.init_params(jax.random.PRNGKey(0))
    return table, ds, cfg, trainer, CheckpointManager(root)


def make_follower(root, cfg):
    model = DeepFM(S, LAYOUT.pull_width, LAYOUT.embedx_dim, hidden=(8,))
    tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
    return Follower(root, LAYOUT, OPT, n_host_shards=4, trainer=tr), Scorer(model, cfg)


def write_pass_file(rng, path, rows, lo):
    lines = []
    for _ in range(rows):
        keys = rng.integers(lo, lo + 200, S)
        lines.append(f"1 {float(keys[0] % 2)} " + " ".join(f"1 {k}" for k in keys))
    # fixture writer: path is this run's scratch space
    # pbox-lint: disable=IO004
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return lines


def run_soak(workdir, passes=6, rows=400, qps=40.0, probe_n=32):
    """The full concurrent day; returns the report dict (see module doc)."""
    root = os.path.join(workdir, "ckpt")
    rng = np.random.default_rng(0)
    # counters are process-global and the A/B driver runs two days in one
    # process, so the report carries deltas over this day only
    tier_stats0 = {
        n: STAT_GET(n)
        for n in (
            "serve.device_tier_hits",
            "serve.device_tier_misses",
            "serve.device_tier_builds",
        )
    }
    table, ds, cfg, trainer, mgr = make_stack(root)
    fol, scorer = make_follower(root, cfg)

    # the probe rides inside pass 0's training data: parity probes must use
    # keys the published model has trained (an unseen key would be CREATED
    # in the trainer table by the reference pull, skewing the comparison)
    pass0_path = os.path.join(workdir, "pass-0.txt")
    pass0_lines = write_pass_file(rng, pass0_path, rows, 1)
    probe = [parse_line(ln, SCHEMA) for ln in pass0_lines[:probe_n]]

    def run_pass(lo, path=None):
        if path is None:
            path = os.path.join(workdir, f"pass-{lo}.txt")
            write_pass_file(rng, path, rows, lo)
        ds.set_filelist([path])
        ds.load_into_memory()
        ds.begin_pass(round_to=8)
        trainer.train_pass(ds)
        ds.end_pass(trainer.trained_table_device())
        table.drain_pending()

    # reference preds per delta idx, captured trainer-direct right after
    # each save (the producer's truth the follower must match bitwise)
    reference = {}

    def capture_reference(idx):
        reference[idx] = scorer.score_records(
            probe, SCHEMA, table_source(LAYOUT, table), trainer.params, trainer.opt_state
        )

    # capture every version the follower commits: versions are immutable
    # and carry their own (sparse, dense) pair, so they can be re-scored
    # offline after the day for the per-delta bitwise parity sweep
    captured = {}
    orig_commit = fol.scoring.commit

    def commit_and_capture(*a, **k):
        v = orig_commit(*a, **k)
        captured[v.delta_idx] = v
        return v

    fol.scoring.commit = commit_and_capture

    # ---- follower + server up before anything is published: the soak
    # exercises the cold-start path (empty version, no params) too
    stop = threading.Event()
    poller = threading.Thread(
        target=fol.run, args=(stop,), kwargs={"poll_interval_s": 0.02}, daemon=True
    )
    poller.start()
    srv = ScoreServer(fol, scorer, SCHEMA)
    srv.start()

    client_errors = []
    requests_sent = [0]
    t_gen = [0.0]

    def load_gen():
        # own rng: the shared one feeds write_pass_file from the main
        # thread, and concurrent draws here would make the training day
        # nondeterministic (the --device-tier A/B compares two days bitwise)
        lg_rng = np.random.default_rng(1234)
        period = 1.0 / qps
        while not stop.is_set():
            t0 = time.perf_counter()
            if fol.version().params is not None:  # serving is warm
                k = int(lg_rng.integers(0, probe_n - 8))
                try:
                    srv.score(probe[k : k + 8], timeout=30)
                    requests_sent[0] += 1
                    if t_gen[0] == 0.0:
                        t_gen[0] = time.perf_counter()
                except Exception as e:  # noqa: BLE001 — soak must report, not die
                    client_errors.append(repr(e))
            left = period - (time.perf_counter() - t0)
            if left > 0:
                time.sleep(left)

    clients = [threading.Thread(target=load_gen, daemon=True) for _ in range(2)]
    t_start = time.perf_counter()
    for c in clients:
        c.start()

    # ---- the training day: publish while the fleet above keeps serving
    for p in range(passes):
        lo = 1 + p * 120
        run_pass(lo, path=pass0_path if p == 0 else None)
        if p == 0:
            mgr.save_base(DATE, table, trainer)
        else:
            mgr.save_delta(DATE, table, trainer)
        capture_reference(p)

    # let the follower drain the tail of the chain
    deadline = time.time() + 30
    while fol.version().delta_idx < passes - 1 and time.time() < deadline:
        time.sleep(0.05)
    time.sleep(0.2)  # a few more serves against the final version
    stop.set()
    for c in clients:
        c.join(timeout=10)
    srv.stop()
    poller.join(timeout=10)
    elapsed = time.perf_counter() - t_start

    # ---- offline parity sweep: every version the follower committed must
    # score the probe bitwise-equal to the producer's capture at that pass
    head = fol.version()
    parity = {"checked": 0, "missing": [], "mismatched": []}
    for idx in sorted(reference):
        v = captured.get(idx)
        if v is None:
            # the follower never committed this index — a skipped link is a
            # parity failure too (ok requires checked == passes)
            parity["missing"].append(idx)
            continue
        got = scorer.score_records(
            probe, SCHEMA, version_source(LAYOUT, v), v.params, v.opt_state
        )
        parity["checked"] += 1
        if not np.array_equal(got, reference[idx]):
            parity["mismatched"].append(idx)

    lat = srv.latency_percentiles()
    achieved_qps = requests_sent[0] / elapsed if elapsed > 0 else 0.0
    head_tier = head.device_tier
    report = {
        "passes": passes,
        "rows_per_pass": rows,
        "elapsed_s": round(elapsed, 3),
        "requests": requests_sent[0],
        "achieved_qps": round(achieved_qps, 2),
        "latency": lat,
        # the producer-truth fingerprint per pass: two runs of the same day
        # (off vs on) must agree on every one of these for the ablation to
        # count as bitwise-identical
        "reference_sha": {
            str(i): hashlib.sha256(reference[i].tobytes()).hexdigest()
            for i in sorted(reference)
        },
        "device_tier": {
            "head_rows": 0 if head_tier is None else head_tier.n_rows,
            "builds": STAT_GET("serve.device_tier_builds")
            - tier_stats0["serve.device_tier_builds"],
            "hits": STAT_GET("serve.device_tier_hits")
            - tier_stats0["serve.device_tier_hits"],
            "misses": STAT_GET("serve.device_tier_misses")
            - tier_stats0["serve.device_tier_misses"],
        },
        "staleness_s": [
            {"delta_idx": i, "lag_s": round(lag, 4)} for i, lag in srv.staleness
        ],
        "served_head_delta_idx": head.delta_idx,
        "follower_applies": STAT_GET("serve.applies"),
        "apply_failures": STAT_GET("serve.apply_failures"),
        "request_errors": client_errors[:5],
        "parity": parity,
        "ok": (
            not parity["mismatched"]
            and not parity["missing"]
            and parity["checked"] == passes
            and head.delta_idx == passes - 1
            and not client_errors
            and requests_sent[0] > 0
        ),
    }
    return report


_TIER_FLAGS = ("device_scoring_tier", "device_tier_hot_show", "device_tier_capacity")


def _bench_tier_lookup(n_rows, n_hot, width, batch, iters, hot_frac=0.95):
    """Lookup-throughput microbench: one large committed version, a hot
    query mix, host ``lookup_rows`` vs tiered ``lookup_rows_tiered``.

    The tier holds ``n_hot`` of ``n_rows`` published rows; queries draw
    ``hot_frac`` of each batch from the hot set (tier hit rate ~= hot_frac,
    the >= 0.9 regime the headline claims). Every timed path is also
    checked bitwise against the host answer.
    """
    from paddlebox_tpu.serve.scoring_table import ScoringTable

    rng = np.random.default_rng(3)
    keys = np.unique(rng.integers(1, 2**62, int(n_rows * 1.2), dtype=np.uint64))[
        :n_rows
    ]
    rows = rng.standard_normal((len(keys), width)).astype(np.float32)
    hot_idx = np.sort(rng.choice(len(keys), n_hot, replace=False))
    hotness = np.zeros(len(keys), dtype=np.float32)
    hotness[hot_idx] = 2.0

    kw = dict(date=DATE, delta_idx=0, decay_epoch=0)
    v_host = ScoringTable(width).commit(keys, rows, **kw)  # hotness=None
    config.set_flag("device_tier_capacity", n_hot)
    config.set_flag("device_tier_hot_show", 1.0)
    v_tier = ScoringTable(width).commit(keys, rows, hotness=hotness, **kw)
    tier = v_tier.device_tier
    if tier is None:
        return {"mesh": "unavailable", "throughput_ok": False}

    hot_keys = keys[hot_idx]
    cold_keys = np.delete(keys, hot_idx)
    n_hot_q = int(batch * hot_frac)
    batches = [
        np.concatenate(
            [
                rng.choice(hot_keys, n_hot_q),
                rng.choice(cold_keys, batch - n_hot_q),
            ]
        )
        for _ in range(iters)
    ]

    # warmup compiles the bucketed collective and touches both row arrays
    for q in batches[:2]:
        v_host.lookup_rows(q)
        v_tier.lookup_rows_tiered(q)
    ref, _ = v_host.lookup_rows(batches[0])
    got, _, _ = v_tier.lookup_rows_tiered(batches[0])
    bitwise = bool(np.array_equal(ref, got))

    t0 = time.perf_counter()
    for q in batches:
        v_host.lookup_rows(q)
    host_s = time.perf_counter() - t0

    hits0, miss0 = tier.hits, tier.misses
    t0 = time.perf_counter()
    for q in batches:
        v_tier.lookup_rows_tiered(q)
    tier_s = time.perf_counter() - t0
    d_hits = tier.hits - hits0
    d_miss = tier.misses - miss0
    hit_rate = d_hits / max(1, d_hits + d_miss)

    n_keys = batch * iters
    host_kps = n_keys / host_s if host_s > 0 else 0.0
    tier_kps = n_keys / tier_s if tier_s > 0 else 0.0
    return {
        "rows": int(len(keys)),
        "hot_rows": tier.n_rows,
        "width": width,
        "batch": batch,
        "iters": iters,
        "hit_rate": round(hit_rate, 4),
        "host_keys_per_s": round(host_kps),
        "tier_keys_per_s": round(tier_kps),
        "speedup": round(tier_kps / host_kps, 3) if host_kps else None,
        "bitwise_equal": bitwise,
        "throughput_ok": bool(bitwise and hit_rate >= 0.9 and tier_kps >= host_kps),
    }


def run_device_tier_ab(
    workdir,
    passes=6,
    rows=400,
    qps=40.0,
    probe_n=32,
    bench_rows=500_000,
    bench_hot=65_536,
    bench_batch=8192,
    bench_iters=30,
):
    """The mesh-sharded-scoring headline: same day host-only then
    device-tier, bitwise inside and ACROSS the legs, plus the lookup
    microbench. Returns the SOAK_SERVESHARD report dict."""
    prev = {n: config.get_flag(n) for n in _TIER_FLAGS}
    try:
        config.set_flag("device_scoring_tier", "off")
        host_leg = run_soak(
            os.path.join(workdir, "host"), passes=passes, rows=rows, qps=qps,
            probe_n=probe_n,
        )
        config.set_flag("device_scoring_tier", "on")
        # every trained key qualifies: the probe set must ride the tier
        config.set_flag("device_tier_hot_show", 0.0)
        tier_leg = run_soak(
            os.path.join(workdir, "tier"), passes=passes, rows=rows, qps=qps,
            probe_n=probe_n,
        )
        bench = _bench_tier_lookup(
            bench_rows, bench_hot, LAYOUT.pull_width, bench_batch, bench_iters
        )
    finally:
        for n, v in prev.items():
            config.set_flag(n, v)

    ablation_bitwise = host_leg["reference_sha"] == tier_leg["reference_sha"]
    tier_used = (
        tier_leg["device_tier"]["builds"] == passes
        and tier_leg["device_tier"]["head_rows"] > 0
        and tier_leg["device_tier"]["hits"] > 0
    )
    report = {
        "mode": "device_tier_ab",
        "platform": jax.default_backend(),
        "mesh_devices": jax.device_count(),
        "passes": passes,
        "host_leg": host_leg,
        "tier_leg": tier_leg,
        "ablation_bitwise_identical": ablation_bitwise,
        "tier_used": tier_used,
        "lookup_bench": bench,
        "ok": (
            host_leg["ok"]
            and tier_leg["ok"]
            and host_leg["device_tier"]["builds"] == 0
            and ablation_bitwise
            and tier_used
            and bench.get("throughput_ok", False)
        ),
    }
    return report


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


_FLEET_FLAGS = {
    # soak-speed gossip/transport so churn converges inside seconds
    "transport_heartbeat_s": 0.05,
    "transport_backoff_s": 0.01,
    "serve_health_beat_s": 0.05,
    "serve_health_dead_s": 0.5,
    "serve_hedge_ms": 150.0,
    "serve_client_retries": 4,
    "serve_client_backoff_s": 0.02,
    "serve_request_timeout_ms": 10000.0,
}


def run_fleet_soak(workdir, n_followers=3, passes=6, rows=400, qps=30.0, probe_n=32):
    """The networked day with churn: kill follower N after pass 2, drain
    follower 2 after pass 3 (admit after pass 4), rejoin N as a new
    incarnation after pass 4 — all while publishes land and the client
    keeps scoring. Returns the report dict (``ok`` is the gate)."""
    root = os.path.join(workdir, "ckpt")
    stage_dir = os.path.join(workdir, "stage")
    rng = np.random.default_rng(0)
    prev_flags = {n: config.get_flag(n) for n in _FLEET_FLAGS}
    for n, v in _FLEET_FLAGS.items():
        config.set_flag(n, v)
    try:
        return _run_fleet_soak(
            workdir, root, stage_dir, rng, n_followers, passes, rows, qps, probe_n
        )
    finally:
        for n, v in prev_flags.items():
            config.set_flag(n, v)


def _run_fleet_soak(workdir, root, stage_dir, rng, n_followers, passes, rows, qps, probe_n):
    table, ds, cfg, trainer, mgr = make_stack(root)
    model = DeepFM(S, LAYOUT.pull_width, LAYOUT.embedx_dim, hidden=(8,))
    scorer = Scorer(model, cfg)  # ONE compiled program serves the whole fleet

    pass0_path = os.path.join(workdir, "pass-0.txt")
    pass0_lines = write_pass_file(rng, pass0_path, rows, 1)
    probe_lines = pass0_lines[:probe_n]
    probe = [parse_line(ln, SCHEMA) for ln in probe_lines]

    def run_pass(lo, path=None):
        if path is None:
            path = os.path.join(workdir, f"pass-{lo}.txt")
            write_pass_file(rng, path, rows, lo)
        ds.set_filelist([path])
        ds.load_into_memory()
        ds.begin_pass(round_to=8)
        trainer.train_pass(ds)
        ds.end_pass(trainer.trained_table_device())
        table.drain_pending()

    reference = {}

    def capture_reference(idx):
        reference[idx] = scorer.score_records(
            probe, SCHEMA, table_source(LAYOUT, table), trainer.params, trainer.opt_state
        )

    # ---- transports: rank 0 = client, 1..N = followers -------------------
    eps = [f"127.0.0.1:{p}" for p in _free_ports(n_followers + 1)]
    client_tp = TcpTransport(0, eps, timeout=30.0)
    follower_ranks = list(range(1, n_followers + 1))

    # one stager mirrors origin -> stage for the WHOLE host
    stage = FleetStage(root, stage_dir)
    stage_stop = threading.Event()
    stage_thread = threading.Thread(
        target=stage.run, args=(stage_stop, 0.02), daemon=True
    )
    stage_thread.start()

    # per-(incarnation) committed-version capture for the offline parity sweep
    captured = []  # (name, follower, {delta_idx: version})

    def make_fleet_follower(rank, name):
        tp = TcpTransport(rank, eps, timeout=30.0)
        tr = CTRTrainer(DeepFM(S, LAYOUT.pull_width, LAYOUT.embedx_dim, hidden=(8,)),
                        cfg, dense_opt=optax.adam(1e-2))
        fol = Follower(stage_dir, LAYOUT, OPT, n_host_shards=4, trainer=tr)
        caps = {}
        orig_commit = fol.scoring.commit

        def commit_and_capture(*a, **k):
            v = orig_commit(*a, **k)
            caps[v.delta_idx] = v
            return v

        fol.scoring.commit = commit_and_capture
        captured.append((name, fol, caps))
        ff = FleetFollower(tp, 0, fol, scorer, SCHEMA, poll_interval_s=0.02)
        ff.start()
        return tp, ff

    fleet = {}  # rank -> (tp, ff); current incarnation only
    for r in follower_ranks:
        fleet[r] = make_fleet_follower(r, f"rank{r}")

    client = FleetClient(client_tp, follower_ranks, SCHEMA)
    client.start()

    # ---- load generator --------------------------------------------------
    stop_load = threading.Event()
    client_errors = []
    live_results = []  # (t_sent, src, delta_idx, k, preds)
    requests_sent = [0]

    def load_gen():
        # own rng, same reason as run_soak: keep the training day
        # deterministic by never touching the shared rng off-thread
        lg_rng = np.random.default_rng(1234)
        period = 2.0 / qps  # two generator threads share the target rate
        while not stop_load.is_set():
            t0 = time.perf_counter()
            if client.view.queryable():
                k = int(lg_rng.integers(0, probe_n - 8))
                t_sent = time.monotonic()
                try:
                    preds, meta = client.score_lines(probe_lines[k : k + 8], timeout=10)
                    requests_sent[0] += 1
                    live_results.append(
                        (t_sent, meta["src"], meta["delta_idx"], k, preds)
                    )
                except ServeRequestError as e:
                    client_errors.append(repr(e))
                except Exception as e:  # noqa: BLE001 — soak must report, not die
                    client_errors.append(repr(e))
            left = period - (time.perf_counter() - t0)
            if left > 0:
                time.sleep(left)

    clients = [threading.Thread(target=load_gen, daemon=True) for _ in range(2)]
    t_start = time.perf_counter()
    for c in clients:
        c.start()

    # ---- the training day with churn ------------------------------------
    kill_rank = follower_ranks[-1]
    drain_rank = follower_ranks[1] if n_followers > 1 else follower_ranks[0]
    timeline = []
    drain_window = [None, None]  # (confirmed_at, admit_sent_at) monotonic
    for p in range(passes):
        lo = 1 + p * 120
        run_pass(lo, path=pass0_path if p == 0 else None)
        if p == 0:
            mgr.save_base(DATE, table, trainer)
        else:
            mgr.save_delta(DATE, table, trainer)
        capture_reference(p)
        time.sleep(0.3)  # let the stage + fleet chase the watermark
        if p == 2:
            tp, ff = fleet.pop(kill_rank)
            tp.close()  # abrupt: in-flight requests to it are lost
            ff.stop()
            timeline.append({"pass": p, "event": f"killed rank {kill_rank}"})
        elif p == 3:
            ok = client.drain(drain_rank, wait_s=10.0)
            drain_window[0] = time.monotonic()
            timeline.append(
                {"pass": p, "event": f"drained rank {drain_rank}", "confirmed": ok}
            )
        elif p == 4:
            drain_window[1] = time.monotonic()
            ok = client.admit(drain_rank, wait_s=10.0)
            timeline.append(
                {"pass": p, "event": f"admitted rank {drain_rank}", "confirmed": ok}
            )
            fleet[kill_rank] = make_fleet_follower(kill_rank, f"rank{kill_rank}b")
            timeline.append({"pass": p, "event": f"rejoined rank {kill_rank}"})

    # ---- convergence: every live follower reaches the head ---------------
    deadline = time.time() + 30
    while time.time() < deadline:
        if all(
            ff.follower.version().delta_idx == passes - 1
            for _, ff in fleet.values()
        ):
            break
        time.sleep(0.05)
    time.sleep(0.5)  # a few more serves against the final fleet
    stop_load.set()
    for c in clients:
        c.join(timeout=10)
    elapsed = time.perf_counter() - t_start
    fleet_view = client.view.snapshot()
    staleness_log = {r: list(v) for r, v in client.view.staleness_log.items()}
    client.stop()
    for tp, ff in fleet.values():
        ff.stop()
        tp.close()
    client_tp.close()
    stage_stop.set()
    stage_thread.join(timeout=10)

    # ---- live parity: every answered request must match the reference ----
    live_parity = {"checked": 0, "mismatched": 0, "unknown_version": 0}
    for _t, _src, idx, k, preds in live_results:
        ref = reference.get(idx)
        if ref is None:
            live_parity["unknown_version"] += 1
            continue
        live_parity["checked"] += 1
        if not np.array_equal(preds, ref[k : k + 8]):
            live_parity["mismatched"] += 1

    # ---- offline parity: every version any incarnation committed ---------
    offline = {"checked": 0, "mismatched": [], "heads": {}, "cold_commits": 0}
    for name, _fol, caps in captured:
        offline["heads"][name] = max(caps) if caps else None
        for idx, v in sorted(caps.items()):
            if v.params is None:
                # a mid-catch-up commit on a fresh joiner: dense pairs with
                # the chain head, so these are cold (never queryable) and
                # carry no dense to score with
                offline["cold_commits"] += 1
                continue
            got = scorer.score_records(
                probe, SCHEMA, version_source(LAYOUT, v), v.params, v.opt_state
            )
            offline["checked"] += 1
            if not np.array_equal(got, reference[idx]):
                offline["mismatched"].append((name, idx))

    # ---- drain honored: nothing SENT inside the window served by drain_rank
    drained_served = 0
    if drain_window[0] is not None and drain_window[1] is not None:
        # +0.1s grace: finish-in-flight means a request dispatched just
        # before confirmation may legitimately still answer from the rank
        drained_served = sum(
            1 for t, src, *_ in live_results
            if src == drain_rank and drain_window[0] + 0.1 < t < drain_window[1]
        )

    lat = client.latency_percentiles()
    achieved_qps = requests_sent[0] / elapsed if elapsed > 0 else 0.0
    rejoined_head = max(
        (max(caps) for name, _f, caps in captured if name.endswith("b") and caps),
        default=None,
    )
    report = {
        "fleet": n_followers,
        "passes": passes,
        "elapsed_s": round(elapsed, 3),
        "requests": requests_sent[0],
        "achieved_qps": round(achieved_qps, 2),
        "latency": lat,
        "client_errors": client_errors[:5],
        "retries": STAT_GET("serve.client_retries"),
        "hedges": STAT_GET("serve.hedges"),
        "hedge_wasted": STAT_GET("serve.hedge_wasted"),
        "shed": STAT_GET("serve.shed_requests"),
        "late_responses": STAT_GET("serve.late_responses"),
        "request_recv_faults": STAT_GET("serve.request_recv_errors"),
        "drains": STAT_GET("serve.drains"),
        "stage_fetches": STAT_GET("serve.fleet_stage_fetches"),
        "timeline": timeline,
        "fleet_view_at_end": {str(r): s for r, s in fleet_view.items()},
        "staleness_log": {
            str(r): [
                {"epoch": e, "delta_idx": d, "staleness_s": round(s, 4)}
                for e, d, s in log
            ]
            for r, log in staleness_log.items()
        },
        "live_parity": live_parity,
        "offline_parity": {
            "checked": offline["checked"],
            "mismatched": offline["mismatched"],
            "heads": offline["heads"],
        },
        "drained_rank_served_during_window": drained_served,
        "ok": (
            not client_errors
            and requests_sent[0] > 0
            and live_parity["checked"] > 0
            and live_parity["mismatched"] == 0
            and live_parity["unknown_version"] == 0
            and not offline["mismatched"]
            and offline["heads"].get("rank1") == passes - 1
            and rejoined_head == passes - 1
            and drained_served == 0
            # single disk fetch per publish, independent of fleet size:
            # at most one snapshot + one dense file per pass
            and STAT_GET("serve.fleet_stage_fetches") <= 2 * passes
            and all(s == "ready" for s in fleet_view.values())
        ),
    }
    return report


def run_stream_soak(
    workdir, cuts=8, rows=120, compact_every=4, qps=30.0, probe_n=16
):
    """Streaming freshness soak (the PR 20 acceptance gate): two legs over
    the same appended record stream.

    - **reference leg**: an uninterrupted StreamSupervisor consumes the
      stream (one cut per appended chunk) — its final table digest is the
      exactly-once oracle.
    - **chaos leg**: the same stream with a follower serving score traffic
      concurrently (freshness sampled at every chain-head commit) while
      the streaming supervisor is KILLED twice mid-soak — once in each
      ``stream.cut_publish`` crash window — and restarted from durable
      state only (checkpoint resume + stream cursor recovery). Zero
      records may be lost or duplicated: the digest must match the
      reference bitwise. Compaction runs every ``compact_every`` cuts;
      after the day a FRESH follower must catch up through the fold in
      O(post-fold tail) applies, not O(chain).

    Report: digests + bitwise verdict, recovery counters (one replay, one
    replay-skip), ``serve.freshness_s`` p50/p99, catch-up bound, and the
    checkpoint root (``obs/`` under it carries the metric series the
    ``obs_report --slo`` gate reads).
    """
    from paddlebox_tpu.serve.follower import apply_published_chain
    from paddlebox_tpu.train.stream import StreamSupervisor
    from paddlebox_tpu.train.supervisor import HealthGates, PassSupervisor
    from paddlebox_tpu.utils.faultinject import InjectedFault, fail_nth, inject
    from paddlebox_tpu.utils.monitor import STAT_HIST

    def digest(table):
        k = np.sort(table.keys())
        v = table.pull_or_create(k)
        h = hashlib.sha256()
        h.update(np.ascontiguousarray(k).tobytes())
        h.update(np.ascontiguousarray(v).tobytes())
        return h.hexdigest()

    def chunk_lines(rng, lo):
        lines = []
        for _ in range(rows):
            keys = rng.integers(lo, lo + 200, S)
            lines.append(
                f"1 {float(keys[0] % 2)} " + " ".join(f"1 {k}" for k in keys)
            )
        return lines

    def append(stream_dir, lines):
        # the upstream log appender the tailer follows
        # pbox-lint: disable=IO004
        with open(os.path.join(stream_dir, "events.txt"), "a") as f:
            f.write("\n".join(lines) + "\n")
            f.flush()

    def stream_stack(root, stream_dir, resume=False):
        table, ds, cfg, trainer, mgr = make_stack(root)
        sup = PassSupervisor(
            ds, trainer, checkpoint=mgr,
            gates=HealthGates(auc_min_history=99),  # micro-passes are tiny
        )
        if resume:
            # restart path: the table/dense state must be restored BEFORE
            # the stream supervisor runs cursor recovery (a spool replay
            # trains on top of the resumed chain head)
            mgr.resume(table, trainer)
        st = StreamSupervisor(
            sup, stream_dir, DATE, pattern="*.txt",
            compact_every=compact_every,
        )
        return table, cfg, trainer, mgr, sup, st

    # ---- reference leg: uninterrupted
    ref_root = os.path.join(workdir, "ref-ckpt")
    ref_stream = os.path.join(workdir, "ref-stream")
    os.makedirs(ref_stream)
    rng = np.random.default_rng(0)
    ref_table, _, _, _, _, ref_st = stream_stack(ref_root, ref_stream)
    for c in range(cuts):
        append(ref_stream, chunk_lines(rng, 1 + c * 120))
        ref_st.step()
    ref_digest = digest(ref_table)

    # ---- chaos leg: concurrent serve + two kill/restart cycles
    root = os.path.join(workdir, "ckpt")
    stream_dir = os.path.join(workdir, "stream")
    os.makedirs(stream_dir)
    rng = np.random.default_rng(0)  # same records as the reference leg
    table, cfg, trainer, mgr, sup, st = stream_stack(root, stream_dir)
    fol, scorer = make_follower(root, cfg)

    stop = threading.Event()
    poller = threading.Thread(
        target=fol.run, args=(stop,), kwargs={"poll_interval_s": 0.02},
        daemon=True,
    )
    poller.start()
    srv = ScoreServer(fol, scorer, SCHEMA)
    srv.start()
    client_errors = []
    requests_sent = [0]
    # probe keys ride chunk 0 (same seed, same first draw): present in
    # every published version, so a scored miss is a real serving fault
    probe_lines = chunk_lines(np.random.default_rng(0), 1)[:probe_n]
    probe = [parse_line(ln, SCHEMA) for ln in probe_lines]

    def load_gen():
        lg_rng = np.random.default_rng(1234)
        period = 1.0 / qps
        while not stop.is_set():
            t0 = time.perf_counter()
            if fol.version().params is not None:
                k = int(lg_rng.integers(0, probe_n - 8))
                try:
                    srv.score(probe[k : k + 8], timeout=30)
                    requests_sent[0] += 1
                except Exception as e:  # noqa: BLE001 — soak reports, not dies
                    client_errors.append(repr(e))
            left = period - (time.perf_counter() - t0)
            if left > 0:
                time.sleep(left)

    clients = [threading.Thread(target=load_gen, daemon=True) for _ in range(2)]
    for c in clients:
        c.start()

    # kill once in each cut crash window: cut 3 dies with the intent
    # durable but untrained (recovery must REPLAY the spool — zero loss),
    # cut 6 dies with the delta published but the stream cursor stale
    # (recovery must SKIP the retrain — zero duplicates)
    kills = {2: 1, 5: 2}  # chunk index -> cut_publish window (fault hit)
    replays0 = STAT_GET("stream.replays")
    skips0 = STAT_GET("stream.replays_skipped")
    killed = []
    for c in range(cuts):
        append(stream_dir, chunk_lines(rng, 1 + c * 120))
        window = kills.get(c)
        if window is None:
            st.step()
            continue
        try:
            with inject(fail_nth("stream.cut_publish", window)):
                st.step()
            raise RuntimeError("injected kill did not fire")
        except InjectedFault:
            killed.append({"cut": c + 1, "window": window})
        # restart: rebuild the entire producer stack from durable state
        table, cfg, trainer, mgr, sup, st = stream_stack(
            root, stream_dir, resume=True
        )

    # drain: the follower must reach the published chain head
    head = mgr.cursor()
    deadline = time.time() + 30
    while fol.version().delta_idx < head["delta_idx"] and time.time() < deadline:
        time.sleep(0.05)
    time.sleep(0.2)
    stop.set()
    for c in clients:
        c.join(timeout=10)
    srv.stop()
    poller.join(timeout=10)

    chaos_digest = digest(table)
    offline = HostSparseTable(LAYOUT, OPT, n_shards=4, seed=0)
    pos = apply_published_chain(root, offline)
    offline_digest = digest(offline)

    # fresh-follower catch-up bound: the compact fold caps the applies at
    # 1 (the fold) + the post-fold tail, independent of cuts-since-base
    covers = int(head.get("compact") or 0)
    ff0 = STAT_GET("serve.compact_fastforwards")
    applies0 = STAT_GET("serve.applies")
    late_fol, _ = make_follower(root, cfg)
    late_fol.poll_once()
    catchup_applies = STAT_GET("serve.applies") - applies0
    fastforwarded = STAT_GET("serve.compact_fastforwards") - ff0

    fresh = STAT_HIST("serve.freshness_s")
    fresh_summary = (
        fresh.summary((0.5, 0.99)) if fresh is not None else {"count": 0}
    )
    # capture the day's final counters + histograms (serve.freshness_s
    # included) into the metric series obs_report's --slo gate reads
    sup.metrics.snapshot("stream:final")

    report = {
        "mode": "stream",
        "platform": jax.devices()[0].platform,
        "cuts": cuts,
        "rows_per_cut": rows,
        "records_total": cuts * rows,
        "compact_every": compact_every,
        "kills": killed,
        "recovery": {
            "replays": STAT_GET("stream.replays") - replays0,
            "replays_skipped": STAT_GET("stream.replays_skipped") - skips0,
        },
        "digest_reference": ref_digest,
        "digest_chaos": chaos_digest,
        "digest_offline_chain": offline_digest,
        "bitwise": chaos_digest == ref_digest == offline_digest,
        "chain": {
            "delta_idx": int(head["delta_idx"]),
            "compact_covers": covers,
            "chain_len": int(head["delta_idx"]) + 1,
        },
        "catchup": {
            "fresh_follower_applies": int(catchup_applies),
            "compact_fastforwards": int(fastforwarded),
            "bound": int(head["delta_idx"]) - covers + 1,
        },
        "freshness_s": fresh_summary,
        "serving": {
            "requests": requests_sent[0],
            "client_errors": client_errors[:5],
            "served_head": int(fol.version().delta_idx),
        },
        "backlog_stretches": STAT_GET("stream.backlog_stretches"),
        "ckpt_root": root,
        "ok": (
            chaos_digest == ref_digest == offline_digest
            and len(killed) == 2
            and STAT_GET("stream.replays") - replays0 == 1
            and STAT_GET("stream.replays_skipped") - skips0 == 1
            and covers >= compact_every
            and catchup_applies == int(head["delta_idx"]) - covers + 1
            and fastforwarded == 1
            and fresh_summary.get("count", 0) > 0
            and not client_errors
            and pos["delta_idx"] == int(head["delta_idx"])
        ),
    }
    return report


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--passes", type=int, default=6, help="publishes in the day (1 base + N-1 deltas)")
    ap.add_argument("--rows", type=int, default=400, help="training rows per pass")
    ap.add_argument("--qps", type=float, default=40.0, help="target score QPS per client thread")
    ap.add_argument("--probe", type=int, default=32, help="probe records for the parity gate")
    ap.add_argument("--fleet", type=int, default=0, help="networked fleet size (0 = in-process single-follower soak)")
    ap.add_argument("--device-tier", action="store_true", help="mesh-sharded scoring A/B: host-only vs device-tier day + lookup microbench")
    ap.add_argument("--stream", action="store_true", help="streaming micro-pass freshness soak: tail-follow day with two mid-soak kill/restart cycles + concurrent serve")
    ap.add_argument("--cuts", type=int, default=8, help="micro-pass cuts in the streaming day (--stream)")
    ap.add_argument("--compact-every", type=int, default=4, help="fold the delta chain every N cuts (--stream)")
    ap.add_argument("--bench-rows", type=int, default=500_000, help="synthetic version size for the lookup microbench")
    ap.add_argument("--bench-hot", type=int, default=65_536, help="hot rows held by the tier in the microbench")
    ap.add_argument("--bench-batch", type=int, default=8192, help="keys per lookup batch in the microbench")
    ap.add_argument("--bench-iters", type=int, default=30, help="timed batches per leg in the microbench")
    ap.add_argument("--json", help="write the report to this path")
    args = ap.parse_args()

    with tempfile.TemporaryDirectory() as workdir:
        if args.stream:
            report = run_stream_soak(
                workdir, cuts=args.cuts, rows=args.rows,
                compact_every=args.compact_every, qps=args.qps,
                probe_n=args.probe,
            )
        elif args.device_tier:
            report = run_device_tier_ab(
                workdir, passes=args.passes, rows=args.rows, qps=args.qps,
                probe_n=args.probe, bench_rows=args.bench_rows,
                bench_hot=args.bench_hot, bench_batch=args.bench_batch,
                bench_iters=args.bench_iters,
            )
        elif args.fleet > 0:
            report = run_fleet_soak(
                workdir, n_followers=args.fleet, passes=args.passes,
                rows=args.rows, qps=args.qps, probe_n=args.probe,
            )
        else:
            report = run_soak(
                workdir, passes=args.passes, rows=args.rows, qps=args.qps, probe_n=args.probe
            )
    print(json.dumps(report, indent=2))
    if args.json:
        with atomic_write(args.json) as f:
            json.dump(report, f, indent=2)
    print("SERVE SOAK", "PASS" if report["ok"] else "FAIL")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
