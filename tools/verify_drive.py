"""Round-6 verify drive: full user flow through public imports on CPU.

1. slot-format file -> parse -> working set -> finalize -> train loop
   (AUC must rise, loss must fall) -> writeback -> save/reload equality
2. carried boundary with eager flush + INJECTED flush failure: the error
   must surface at the next pass boundary, the carrier must stay owed,
   and a retried drain must land the carried values in the checkpoint
3. error probes: zero-count slot line, unknown ws key
4. round-6 triad: committed kernel plan routes pull/push (native on CPU),
   persistent compile cache reports misses cold and hits warm in one
   process, and a wedged backend init falls back to CPU within deadline
5. static gates: the full three-root pbox-lint scan must exit 0 with the
   empty baseline, and the native tier must replay clean under ASan+UBSan
   (quick set; skips green on images without g++)
"""
import os, sys, tempfile
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import optax

from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
from paddlebox_tpu.data.parser import parse_line
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.table import HostSparseTable, SparseOptimizerConfig, ValueLayout
from paddlebox_tpu.train import CTRTrainer, TrainStepConfig
from paddlebox_tpu import config

S = 4
rng = np.random.default_rng(7)

def write_file(path, n=2000):
    # fixture writer: path is this run's scratch space
    # pbox-lint: disable=IO004
    with open(path, "w") as f:
        for _ in range(n):
            keys = rng.integers(1, 500, S)
            label = 1.0 if (keys % 7 == 0).any() else 0.0  # learnable
            f.write(f"1 {label} " + " ".join(f"1 {k}" for k in keys) + "\n")

schema = SlotSchema(
    [SlotInfo("label", type="float", dense=True, dim=1)]
    + [SlotInfo(f"s{i}") for i in range(S)],
    label_slot="label",
)
layout = ValueLayout(embedx_dim=8)
opt_cfg = SparseOptimizerConfig(embedx_threshold=0.0)

# --- 1. full flow -------------------------------------------------------
tmp = tempfile.mkdtemp()
f1 = os.path.join(tmp, "p1.txt"); write_file(f1)
table = HostSparseTable(layout, opt_cfg, n_shards=4, seed=0)
ds = BoxPSDataset(schema, table, batch_size=256, shuffle_mode="none")
ds.set_filelist([f1]); ds.load_into_memory(); ds.begin_pass(round_to=64)
model = DeepFM(S, layout.pull_width, layout.embedx_dim, hidden=(32,))
cfg = TrainStepConfig(num_slots=S, batch_size=256, layout=layout,
                      sparse_opt=opt_cfg, auc_buckets=1000)
tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-2))
tr.init_params(jax.random.PRNGKey(0))
out1 = tr.train_pass(ds)
tr.train_pass(ds)
out2 = tr.train_pass(ds)  # three passes: ~0.52 -> 0.71 -> 0.89 on this seed
assert out2["auc"] > 0.75, f"AUC did not rise: {out2}"
assert out2["loss"] < out1["loss"], (out1["loss"], out2["loss"])
print(f"[1] train ok: auc {out1['auc']:.3f} -> {out2['auc']:.3f}, "
      f"loss {out1['loss']:.4f} -> {out2['loss']:.4f}")

# --- 2. carried boundary + injected flush failure ----------------------
config.set_flag("enable_carried_table", 1)
config.set_flag("carried_eager_flush", 0)  # drain manually for injection
ds.end_pass(tr.trained_table_device())  # builds a carrier (no transfer)
assert table._pending_carriers, "carrier not registered"

# inject: make the NEXT drain fail once
orig_push = table.push
calls = {"n": 0}
def bad_push(keys, vals):
    calls["n"] += 1
    raise OSError("injected push IO error")
table.push = bad_push
try:
    table.drain_pending()
    raised = False
# the except IS the assertion: the injected error must surface here
# pbox-lint: disable=EXC007
except OSError:
    raised = True
table.push = orig_push
assert raised and calls["n"] == 1, "injected failure did not surface"
assert table._pending_carriers, "FAILED drain dropped the carrier (ADVICE bug)"
n = table.drain_pending()
assert n > 0, "retry drain flushed nothing"
print(f"[2] drain durability ok: carrier survived failed flush, retry wrote {n} keys")

# eager-flush thread error surfacing: store an error as the thread would
f2 = os.path.join(tmp, "p2.txt"); write_file(f2)
ds.set_filelist([f2]); ds.load_into_memory()
ds._eager_flush_error = RuntimeError("boom")
try:
    ds.begin_pass(round_to=64)
    print("[2b] FAIL: pending flush error not raised"); sys.exit(1)
except RuntimeError as e:
    assert "carrier flush failed" in str(e), e
print("[2b] eager-flush error surfaces at pass boundary")
# error consumed on raise; the real pass proceeds and closes out clean
ds.begin_pass(round_to=64)
tr.train_pass(ds)
probe_keys = ds.ws.sorted_keys[:50].copy()
ws_ref = ds.ws
ds.end_pass(tr.trained_table_device())
table.drain_pending()

# save/reload equality
sd = os.path.join(tmp, "base")
table.save_base(sd)
t2 = HostSparseTable(layout, opt_cfg, n_shards=4, seed=0)
t2.load(sd)
np.testing.assert_allclose(
    table.pull_or_create(probe_keys), t2.pull_or_create(probe_keys), rtol=1e-6
)
print("[3] save/reload row equality ok")

# --- error probes -------------------------------------------------------
try:
    parse_line("0 1.0 1 5", schema); print("FAIL zero-count"); sys.exit(1)
except ValueError:
    pass
try:
    ws_ref.lookup(np.array([999999999], dtype=np.uint64)); print("FAIL lookup"); sys.exit(1)
except KeyError as e:
    assert "999999999" in str(e)
print("[4] error probes ok")

# --- 5. kernel-plan routed dispatch ------------------------------------
# (the train passes above already went through _impl_for for every
# pull/push; here we pin down WHICH plan routed them and that the CPU
# eligibility clamp holds even for a pallas-shaped table)
from paddlebox_tpu.ops import kernel_plan
from paddlebox_tpu.ops.pull_push import _impl_for

plan = kernel_plan.get_plan()
assert plan.source.endswith(os.path.join("tools", "kernel_plan.json")), plan.source
aligned = jnp.zeros((1024, 128), jnp.float32)  # lane-aligned, DMA-able shape
assert _impl_for("pull", aligned, 64) == "native"
assert _impl_for("push", aligned, 64, unique_rows=True) == "native"
print(f"[5] kernel plan ok: source={plan.source}, CPU clamps to native")

# --- 6. persistent compile cache: cold miss -> warm hit ----------------
from paddlebox_tpu.utils import compilecache

cc_dir = compilecache.enable(os.path.join(tmp, "compile_cache"))
h0, m0 = compilecache.stats()["hits"], compilecache.stats()["misses"]
x = jnp.arange(512.0)
float(jax.jit(lambda v: (v * 3.0 + 1.0).sum())(x))  # cold: compiles, populates
s_cold = compilecache.stats()
assert s_cold["misses"] > m0, s_cold
float(jax.jit(lambda v: (v * 3.0 + 1.0).sum())(x))  # same HLO, new fn: disk hit
s_warm = compilecache.stats()
assert s_warm["hits"] > h0, s_warm
assert s_warm["entries"] > 0, s_warm
compilecache.disable()
print(f"[6] compile cache ok: {s_cold['misses'] - m0} cold miss(es) -> "
      f"{s_warm['hits'] - h0} warm hit(s), {s_warm['entries']} entr(ies) in {cc_dir}")

# --- 7. backend-init watchdog: wedge falls back to CPU -----------------
import time as _time
from paddlebox_tpu.utils import backendguard
from paddlebox_tpu.utils.faultinject import fail_always, inject

with inject(fail_always("backend.init")) as fplan:
    t0 = _time.monotonic()
    v = backendguard.ensure_backend(
        timeout_s=2.0, retries=2, backoff_s=0.0, probe="always", sleep=lambda s: None
    )
    took = _time.monotonic() - t0
assert v.verdict == "fallback_cpu" and v.wedged and v.platform == "cpu", v.as_dict()
assert fplan.failures("backend.init") == 2, fplan.failures("backend.init")
assert took <= 2.0 * 2 + 2.0, f"fallback blew the deadline: {took:.1f}s"
float(jnp.arange(8.0).sum())  # backend still usable after the verdict
print(f"[7] backend watchdog ok: wedged init -> {v.verdict} in {took:.2f}s")

# --- 8. publish-while-serve soak (the serving tentpole, short) ----------
# Trains a 3-pass day publishing base+deltas while a follower tails and
# serves; the gate is bitwise parity between follower scores and
# trainer-direct scores at every applied delta (docs/SERVING.md).
import serve_soak

with tempfile.TemporaryDirectory() as soak_dir:
    report = serve_soak.run_soak(soak_dir, passes=3, rows=200, qps=25.0, probe_n=16)
assert report["ok"], report
assert report["parity"]["checked"] == 3 and not report["parity"]["mismatched"]
print(f"[8] serve soak ok: {report['requests']} req @ {report['achieved_qps']} qps, "
      f"p50={report['latency']['p50_ms']:.1f}ms p99={report['latency']['p99_ms']:.1f}ms, "
      f"parity bitwise at {report['parity']['checked']} deltas")

# --- 8b. obs plane: selfcheck + flight-recorder smoke -------------------
# obs_report --selfcheck smokes the whole telemetry read/write path
# (histogram quantiles, metric-series round trip, incident bundle,
# 2-rank trace merge with a shared trace_id) in a subprocess; then an
# in-process flight-recorder dump proves THIS process's ring has the
# spans the sections above recorded.
import subprocess

_here = os.path.dirname(os.path.abspath(__file__))
r = subprocess.run(
    [sys.executable, os.path.join(_here, "obs_report.py"), "--selfcheck"],
    capture_output=True, text=True, timeout=300)
assert r.returncode == 0, f"obs selfcheck red:\n{r.stdout}{r.stderr}"
from paddlebox_tpu.obs.flight_recorder import FLIGHT_RECORDER
import json as _json

_inc_dir = os.path.join(tmp, "incidents")
FLIGHT_RECORDER.note_incident("verify_drive_smoke", {"section": "8b"})
_bundle_path = FLIGHT_RECORDER.dump("verify_drive_smoke", dir_path=_inc_dir)
assert _bundle_path is not None and os.path.exists(_bundle_path)
with open(_bundle_path) as _f:
    _bundle = _json.load(_f)
assert any(i["kind"] == "verify_drive_smoke" for i in _bundle["incidents"])
assert _bundle["spans"], "flight recorder saw no spans from the run above"
print(f"[8b] obs plane ok: selfcheck green, incident bundle has "
      f"{len(_bundle['spans'])} span(s) + {len(_bundle['incidents'])} incident(s)")

# --- 9. static gates: lint + native sanitize ----------------------------
# the same commands CI runs, end to end: whole-repo lint (default roots,
# empty baseline) and the ASan+UBSan quick replay of the native tier
r = subprocess.run([sys.executable, os.path.join(_here, "run_lint.py")],
                   capture_output=True, text=True, timeout=600)
assert r.returncode == 0, f"lint gate red:\n{r.stdout}{r.stderr}"
san = subprocess.run(
    [sys.executable, os.path.join(_here, "native_sanitize.py"), "--quick"],
    capture_output=True, text=True, timeout=900)
assert san.returncode == 0, f"sanitize replay red:\n{san.stdout}{san.stderr}"
san_line = san.stdout.strip().splitlines()[-1] if san.stdout.strip() else ""
print(f"[9] static gates ok: lint clean (empty baseline); {san_line}")

# --- 10. elastic membership: kill-rank soak + committed artifact --------
# The --kill-rank soak runs a 4-rank supervised day, kills rank 1 mid-
# pass, and requires the survivors' final digest + per-pass AUC to be
# bitwise-equal to a fresh 3-rank run; SOAK_ELASTIC.json is the committed
# record of that gate and must agree with a live re-run.
_soak_path = os.path.join(os.path.dirname(_here), "SOAK_ELASTIC.json")
assert os.path.exists(_soak_path), "SOAK_ELASTIC.json missing from the repo"
with open(_soak_path) as _f:
    _soak = _json.load(_f)
assert _soak["ok"] and _soak["bitwise_equal_to_fresh_shrunk_run"], _soak
assert _soak["auc_equal_per_pass"] and _soak["ownership_epoch_after"] == 1, _soak
r = subprocess.run(
    [sys.executable, os.path.join(_here, "chaos_probe.py"),
     "--kill-rank", "1", "--json"],
    capture_output=True, text=True, timeout=600)
assert r.returncode == 0, f"kill-rank soak red:\n{r.stdout}{r.stderr}"
_live = _json.loads(r.stdout.strip().splitlines()[-1])
assert _live["ok"] and _live["bitwise_equal_to_fresh_shrunk_run"], _live
print(f"[10] elastic membership ok: rank {_live['killed_rank']} killed "
      f"mid-pass, {len(_live['survivors'])} survivors adopted "
      f"{_live['membership_adopts']} range(s), epoch -> "
      f"{_live['ownership_epoch_after']}, digest+AUC bitwise vs fresh run")

# --- 11. frequency-adaptive ICI wire: A/B soak + committed artifact -----
# The --ici-wire leg trains the SAME zipf day under fp32 / bf16 /
# adaptive / ablation-off and gates the >=2x compiled-payload cut vs
# fp32, adaptive below uniform bf16, AUC neutrality, and the off-
# ablation bitwise match; SOAK_ICIWIRE.json is the committed record of
# that gate and must agree with a live re-run.
_iwsoak_path = os.path.join(os.path.dirname(_here), "SOAK_ICIWIRE.json")
assert os.path.exists(_iwsoak_path), "SOAK_ICIWIRE.json missing from the repo"
with open(_iwsoak_path) as _f:
    _iw = _json.load(_f)
assert _iw["ok"] and _iw["ablation_bitwise_fp32"], _iw
assert _iw["payload_ratio_fp32_over_adaptive"] >= 2.0, _iw
assert _iw["adaptive_below_bf16"] and _iw["auc_delta_adaptive_vs_fp32"] <= 0.02, _iw
r = subprocess.run(
    [sys.executable, os.path.join(_here, "chaos_probe.py"),
     "--ici-wire", "--json"],
    capture_output=True, text=True, timeout=600)
assert r.returncode == 0, f"ici-wire soak red:\n{r.stdout}{r.stderr}"
_iwl = _json.loads(r.stdout.strip().splitlines()[-1])
assert _iwl["ok"] and _iwl["ablation_bitwise_fp32"], _iwl
assert _iwl["payload_ratio_fp32_over_adaptive"] >= 2.0, _iwl
print(f"[11] adaptive ICI wire ok: payload cut "
      f"{_iwl['payload_ratio_fp32_over_adaptive']}x vs fp32, below bf16, "
      f"AUC delta {_iwl['auc_delta_adaptive_vs_fp32']}, "
      f"{_iwl['legs']['adaptive']['hot_keys']} hot key(s), ablation bitwise")
# --- 12. elastic grow: join-rank soak + committed artifact --------------
# The --join-rank soak kills rank 1 at pass 1 (shrink, epoch 1), rejoins
# a successor incarnation once the survivors installed the shrink (grow,
# epoch 2), and requires the final 4-rank digest + per-pass AUC to be
# bitwise-equal to a fresh fixed-size 4-rank run; the "join" block of
# SOAK_ELASTIC.json v2 is the committed record of that gate and must
# agree with a live re-run.
assert _soak.get("version", 1) >= 2 and "join" in _soak, \
    "SOAK_ELASTIC.json must be v2 with a join block"
_join = _soak["join"]
assert _join["ok"] and _join["bitwise_equal_to_fresh_grown_run"], _join
assert _join["auc_equal_per_pass"] and _join["ownership_epoch_after"] == 2, _join
assert _join["rejoined_trained_passes"] >= 1, _join
r = subprocess.run(
    [sys.executable, os.path.join(_here, "chaos_probe.py"),
     "--join-rank", "1", "--passes", "5", "--json"],
    capture_output=True, text=True, timeout=600)
assert r.returncode == 0, f"join-rank soak red:\n{r.stdout}{r.stderr}"
_jl = _json.loads(r.stdout.strip().splitlines()[-1])
assert _jl["ok"] and _jl["bitwise_equal_to_fresh_grown_run"], _jl
assert _jl["auc_equal_per_pass"] and _jl["ownership_epoch_after"] == 2, _jl
print(f"[12] elastic grow ok: rank {_jl['join_rank']} killed at pass "
      f"{_jl['kill_at_pass']}, rejoined and trained "
      f"{_jl['rejoined_trained_passes']} pass(es), epoch -> "
      f"{_jl['ownership_epoch_after']}, {_jl['membership_joins']} join "
      f"commit(s), digest+AUC bitwise vs fresh fixed-size run")
# --- 13. protocol verification: incremental lint + model check ----------
# The incremental lint path (--changed resolves context modules whole-
# program but reports only on the diff) must stay exit-0, and the
# bounded membership-protocol model must explore its state space to a
# fixpoint with zero invariant violations while a deliberately broken
# variant is caught on its invariant — the checker proves itself able
# to fail before its clean pass counts for anything.
r = subprocess.run(
    [sys.executable, os.path.join(_here, "run_lint.py"), "--changed"],
    capture_output=True, text=True, timeout=300)
assert r.returncode == 0, f"incremental lint red:\n{r.stdout}{r.stderr}"
r = subprocess.run(
    [sys.executable, os.path.join(_here, "proto_check.py"),
     "--ranks", "3", "--deaths", "1", "--joins", "1", "--nos", "1",
     "--max-epochs", "2", "--json"],
    capture_output=True, text=True, timeout=300)
assert r.returncode == 0, f"proto-check red:\n{r.stdout}{r.stderr}"
_pcl = _json.loads(r.stdout)
assert _pcl["complete"] and not _pcl["violations"] and _pcl["states"] > 0, _pcl
r = subprocess.run(
    [sys.executable, os.path.join(_here, "proto_check.py"),
     "--broken", "nonatomic_commit"],
    capture_output=True, text=True, timeout=300)
assert r.returncode == 1 and "VIOLATION I4" in r.stdout, \
    f"broken protocol variant not caught:\n{r.stdout}{r.stderr}"
print(f"[13] protocol verification ok: incremental lint clean, model "
      f"fixpoint {_pcl['states']} states / {_pcl['transitions']} "
      f"transitions with zero violations, broken variant caught on I4")
# --- 14. serving fleet under churn + injected faults --------------------
# The networked serving day: N followers over one shared stage, a
# follower killed, another drained and readmitted, the killed rank
# rejoining as a new incarnation — all during concurrent publishes and
# with faults injected at the three serve sites (lost request, torn
# stage fetch, dropped drain command). The gate mirrors the committed
# SOAK_SERVEFLEET.json headline: zero client-visible failures, bitwise
# parity on every served version, drain honored, and a single disk
# fetch per publish independent of fleet size.
r = subprocess.run(
    [sys.executable, os.path.join(_here, "chaos_probe.py"),
     "--serve-fleet", "--json"],
    capture_output=True, text=True, timeout=600)
assert r.returncode == 0, f"serve-fleet soak red:\n{r.stdout}{r.stderr}"
_sf = _json.loads(r.stdout.strip().splitlines()[-1])
assert _sf["ok"] and _sf["soak"]["ok"], _sf
assert all(n > 0 for n in _sf["faults_fired"].values()), _sf
_sk = _sf["soak"]
assert not _sk["client_errors"] and _sk["live_parity"]["mismatched"] == 0, _sk
assert _sk["drained_rank_served_during_window"] == 0, _sk
_committed = os.path.join(_here, os.pardir, "SOAK_SERVEFLEET.json")
if os.path.exists(_committed):
    with open(_committed) as _f:  # pbox-lint: disable=IO004
        _ref = _json.load(_f)
    assert _ref["ok"] and not _ref["client_errors"], \
        "committed SOAK_SERVEFLEET.json records a red run"
print(f"[14] serve fleet ok: {_sk['fleet']} followers, "
      f"{_sk['requests']} requests / 0 failures under kill+drain+rejoin, "
      f"{_sk['hedges']} hedge(s), faults fired {_sf['faults_fired']}, "
      f"live parity {_sk['live_parity']['checked']}/0 mismatched, "
      f"{_sk['stage_fetches']} stage fetches for {_sk['passes']} passes")
# --- 15. mesh-sharded scoring: device-tier A/B + crash probe ------------
# The --device-tier A/B runs the SAME serving day host-only and with the
# device-resident hot tier on, requiring bitwise parity inside each leg
# AND between them (the off ablation is bitwise-identical), plus the
# lookup microbench at hit rate >= 0.9; SOAK_SERVESHARD.json is the
# committed record of the full-size gate and must itself be green. The
# --serve-shard probe then crashes a follower mid-tier-build
# (serve.tier_build) and requires the old version to keep serving
# bitwise with no partial tier, the healed retry landing bitwise.
_ss_path = os.path.join(os.path.dirname(_here), "SOAK_SERVESHARD.json")
assert os.path.exists(_ss_path), "SOAK_SERVESHARD.json missing from the repo"
with open(_ss_path) as _f:
    _ss = _json.load(_f)
assert _ss["ok"] and _ss["ablation_bitwise_identical"] and _ss["tier_used"], _ss
assert _ss["lookup_bench"]["bitwise_equal"], _ss["lookup_bench"]
assert _ss["lookup_bench"]["hit_rate"] >= 0.9, _ss["lookup_bench"]
assert (
    _ss["lookup_bench"]["tier_keys_per_s"] >= _ss["lookup_bench"]["host_keys_per_s"]
), _ss["lookup_bench"]
with tempfile.TemporaryDirectory() as ab_dir:
    _ab = serve_soak.run_device_tier_ab(
        ab_dir, passes=3, rows=200, qps=25.0, probe_n=16,
        bench_rows=120_000, bench_hot=16_384, bench_batch=4096, bench_iters=8,
    )
assert _ab["host_leg"]["ok"] and _ab["tier_leg"]["ok"], _ab
assert _ab["ablation_bitwise_identical"] and _ab["tier_used"], _ab
assert _ab["lookup_bench"]["bitwise_equal"], _ab["lookup_bench"]
# the short-form bench is too small to re-gate throughput; the committed
# full-size artifact above carries that claim
r = subprocess.run(
    [sys.executable, os.path.join(_here, "chaos_probe.py"),
     "--serve-shard", "--json"],
    capture_output=True, text=True, timeout=600)
assert r.returncode == 0, f"serve-shard probe red:\n{r.stdout}{r.stderr}"
_sp = _json.loads(r.stdout.strip().splitlines()[-1])
assert _sp["ok"] and _sp["old_version_held_bitwise"], _sp
assert _sp["tier_build_faults_fired"] == 1 and _sp["parity_after_heal_bitwise"], _sp
print(f"[15] mesh-sharded scoring ok: A/B ablation bitwise over "
      f"{_ab['passes']} passes (tier {_ab['tier_leg']['device_tier']['hits']} "
      f"hit(s)), committed bench {_ss['lookup_bench']['speedup']}x at hit rate "
      f"{_ss['lookup_bench']['hit_rate']} on {_ss['platform']}, crash probe "
      f"held old version bitwise and healed to tier of "
      f"{_sp['final_tier_rows']} row(s)")
# --- 16. streaming micro-passes: freshness SLO + crash sweep ------------
# The streaming day: a tail-following supervisor cuts micro-passes on a
# time budget, publishes minute-level deltas through the watermark, and
# folds the chain hourly so follower catch-up stays O(tail). The gate
# mirrors the committed SOAK_STREAM.json headline — the supervisor is
# KILLED in both cut_publish crash windows mid-soak and the restart
# recovers exactly-once (one spool replay, one retrain skip, digest
# bitwise vs an uninterrupted twin) while a follower serves concurrently.
# The freshness SLO is then gated through obs_report over the run's own
# metric series (the --json verdicts are asserted PASS explicitly:
# NODATA must not slip through the exit code), and the --stream probe
# must fire ALL THREE streaming fault sites.
_st_path = os.path.join(os.path.dirname(_here), "SOAK_STREAM.json")
assert os.path.exists(_st_path), "SOAK_STREAM.json missing from the repo"
with open(_st_path) as _f:
    _sm = _json.load(_f)
assert _sm["ok"] and _sm["bitwise"] and len(_sm["kills"]) == 2, _sm
assert _sm["recovery"] == {"replays": 1, "replays_skipped": 1}, _sm
assert _sm["freshness_s"]["count"] > 0, _sm
assert _sm["catchup"]["fresh_follower_applies"] == _sm["catchup"]["bound"], _sm
with tempfile.TemporaryDirectory() as st_dir:
    _stk = serve_soak.run_stream_soak(
        st_dir, cuts=6, rows=100, compact_every=3, qps=20.0, probe_n=16)
    assert _stk["ok"] and _stk["bitwise"], _stk
    r = subprocess.run(
        [sys.executable, os.path.join(_here, "obs_report.py"),
         os.path.join(_stk["ckpt_root"], "obs"),
         "--slo", "serve.freshness_s:p99<=60", "--json"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, f"freshness SLO gate red:\n{r.stdout}{r.stderr}"
    _slo = _json.loads(r.stdout.strip().splitlines()[-1])["slo"]
    assert _slo and all(v["verdict"] == "PASS" for v in _slo), _slo
r = subprocess.run(
    [sys.executable, os.path.join(_here, "chaos_probe.py"),
     "--stream", "--json"],
    capture_output=True, text=True, timeout=600)
assert r.returncode == 0, f"stream probe red:\n{r.stdout}{r.stderr}"
_stp = _json.loads(r.stdout.strip().splitlines()[-1])
assert _stp["ok"], _stp
assert set(_stp["sites_fired"]) == {
    "stream.tail_read", "stream.cut_publish", "ckpt.compact"}, _stp
assert all(n >= 1 for n in _stp["sites_fired"].values()), _stp
print(f"[16] streaming plane ok: {_stk['cuts']} cuts with 2 kills "
      f"recovered exactly-once (bitwise), compact covers "
      f"{_stk['chain']['compact_covers']} of {_stk['chain']['chain_len']} "
      f"links, catch-up {_stk['catchup']['fresh_follower_applies']} "
      f"applies (bound {_stk['catchup']['bound']}), freshness p99 "
      f"{_slo[0]['value']:.2f}s <= 60s over {_stk['freshness_s']['count']} "
      f"commits, probe fired {_stp['sites_fired']}")
print("VERIFY DRIVE PASS")
