"""Criteo-Kaggle convergence artifact (BASELINE.md configs 1-2).

Trains DeepFM (or LR) through the FULL framework path — slot files ->
BoxPSDataset passes -> native pack -> jitted train step -> AUC registry —
on Criteo display-advertising data and records the final AUC, producing
``CONVERGENCE.json`` next to this script.

Two data modes:

- ``--data-dir DIR`` — REAL Criteo-Kaggle ``train.txt`` (tab-separated:
  label, 13 integer features, 26 categorical hex features). Lines are
  converted to the slot format the reference's data generators emit
  (criteo readers in the PaddleBox ecosystem do the same mapping):
  integer feature i -> slot i key ``(i << 40) | ceil(log2(v+1))``
  (the standard Criteo log2 bucketization), categorical j -> slot 13+j
  key ``(j+13) << 40 | int(hex, 16) & MASK``. Expected AUC after one
  epoch: ~0.77-0.79 (public DeepFM numbers on Criteo-Kaggle).

- ``--synthetic`` — this environment has no network egress and no local
  copy of Criteo, so quality parity is demonstrated on a Criteo-SHAPED
  synthetic: 39 slots, power-law key frequencies (hot head like Criteo's
  categorical skew), ~25% positive rate, and a planted logistic ground
  truth over per-key latent weights so the task has a known learnable
  structure (Bayes AUC ~0.86 at the default noise). The artifact records
  which mode produced it; the real-data number slots in by re-running
  with --data-dir once the dataset is available.

Usage:
  python tools/criteo_convergence.py --synthetic [--rows 400000]
  python tools/criteo_convergence.py --data-dir /path/to/criteo [--rows N]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N_INT, N_CAT = 13, 26
N_SLOTS = N_INT + N_CAT
CAT_MASK = (1 << 40) - 1


def convert_criteo_line(line: str) -> str | None:
    """One Kaggle train.txt line -> slot-format line (label + 39 slots).

    Returns None for malformed input — wrong column count, non-integer
    label/int feature, non-hex categorical — the reject path a real crawl
    needs (the reference's BufferedLineFileReader drops bad lines the same
    way, data_feed.cc line-parse error branches)."""
    parts = line.rstrip("\n").split("\t")
    if len(parts) != 1 + N_INT + N_CAT:
        return None
    label = parts[0]
    if label not in ("0", "1"):
        return None
    out = [f"1 {label}.0"]
    try:
        for i in range(N_INT):
            v = parts[1 + i]
            if v == "":
                bucket = 0
            else:
                iv = int(v)
                bucket = int(math.log2(iv + 1)) + 1 if iv >= 0 else 0
            out.append(
                f"1 {(np.uint64(i) << np.uint64(40)) | np.uint64(bucket + 1)}"
            )
        for j in range(N_CAT):
            v = parts[1 + N_INT + j]
            key = int(v, 16) & CAT_MASK if v else 0
            out.append(
                f"1 {(np.uint64(N_INT + j) << np.uint64(40)) | np.uint64(key + 1)}"
            )
    except ValueError:
        return None
    return " ".join(out)


def write_real_files(data_dir: str, workdir: str, rows: int, n_files: int = 8):
    src = os.path.join(data_dir, "train.txt")
    files = [
        # fixture writer: workdir is this run's scratch space
        # pbox-lint: disable=IO004
        open(os.path.join(workdir, f"part-{i:03d}.txt"), "w")
        for i in range(n_files)
    ]
    n = 0
    with open(src) as f:
        for line in f:
            s = convert_criteo_line(line)
            if s is None:
                continue
            files[n % n_files].write(s + "\n")
            n += 1
            if rows and n >= rows:
                break
    for fh in files:
        fh.close()
    return [fh.name for fh in files], n


def write_synthetic_files(
    workdir: str,
    rows: int,
    n_files: int = 8,
    seed: int = 0,
    world_seed: int = 0,
    vocab_rows: int | None = None,
):
    """Criteo-shaped synthetic with planted logistic structure.

    ``world_seed`` fixes the ground truth (vocab weights); ``seed`` only
    drives row sampling — a held-out eval set shares the world and differs
    in rows, exactly like a real train/test split."""
    world = np.random.default_rng(world_seed)
    rng = np.random.default_rng(seed)
    # per-slot vocabulary with power-law frequencies (categorical skew);
    # categorical vocab scales with the dataset so keys repeat enough for
    # their embeddings to learn (Criteo's own hot head dominates likewise)
    # vocab_rows pins the key space/world: an eval split must pass the
    # TRAIN row count here or it lives in a different world
    vr = vocab_rows if vocab_rows is not None else rows
    vocab = [
        64 if i < N_INT else max(1000, min(20_000, vr // 12))
        for i in range(N_SLOTS)
    ]
    # planted per-key latent weight; informative slots get higher variance
    slot_strength = world.uniform(0.2, 1.0, N_SLOTS)
    key_w = [
        world.normal(0.0, slot_strength[s], vocab[s]) for s in range(N_SLOTS)
    ]
    bias = -1.1  # ~25% positive rate like Criteo
    files = []
    per = rows // n_files
    for fi in range(n_files):
        path = os.path.join(workdir, f"part-{fi:03d}.txt")
        # zipf-ish draw: mix hot head and uniform tail (~70% of traffic on
        # ~2% of keys, the categorical skew that makes CTR tables work)
        keys = np.empty((per, N_SLOTS), np.int64)
        for s in range(N_SLOTS):
            hot = rng.integers(0, max(vocab[s] // 50, 2), per)
            cold = rng.integers(0, vocab[s], per)
            keys[:, s] = np.where(rng.random(per) < 0.7, hot, cold)
        # logit std ~2: Bayes AUC ~0.9, so a trained model has real signal
        # to recover and the held-out number is meaningful
        logit = bias + sum(
            key_w[s][keys[:, s]] for s in range(N_SLOTS)
        ) / 2.0
        labels = (rng.random(per) < 1.0 / (1.0 + np.exp(-logit))).astype(np.int32)
        # fixture writer: workdir is this run's scratch space
        # pbox-lint: disable=IO004
        with open(path, "w") as f:
            for i in range(per):
                f.write(
                    f"1 {labels[i]}.0 "
                    + " ".join(
                        f"1 {(s << 40) | (int(keys[i, s]) + 1)}"
                        for s in range(N_SLOTS)
                    )
                    + "\n"
                )
        files.append(path)
    return files, per * n_files


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", help="dir containing Criteo-Kaggle train.txt")
    ap.add_argument("--synthetic", action="store_true")
    ap.add_argument("--rows", type=int, default=400_000)
    ap.add_argument("--passes", type=int, default=3)
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--embedx", type=int, default=8)
    ap.add_argument("--model", choices=["deepfm", "lr"], default="deepfm")
    ap.add_argument(
        "--cpu", action="store_true",
        help="force the CPU backend (the env's sitecustomize pins "
        "JAX_PLATFORMS before argv is seen, so an env var cannot)",
    )
    ap.add_argument("--out", default=os.path.join(os.path.dirname(__file__), "..", "CONVERGENCE.json"))
    args = ap.parse_args()
    if not args.synthetic and not args.data_dir:
        ap.error("pick --synthetic or --data-dir")

    import jax

    if not args.cpu:
        try:
            backend = jax.default_backend()
        except RuntimeError:
            # wedged accelerator init (the axon tunnel's failure mode):
            # fall back instead of dying before the first row
            backend = "cpu"
        args.cpu = backend not in ("tpu",)
    if args.cpu:
        try:
            jax.config.update("jax_platforms", "cpu")
        # best-effort pin: the backend probe above already chose the path
        # pbox-lint: disable=EXC007
        except Exception:
            pass
    import optax

    from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
    from paddlebox_tpu.models import DeepFM, LogisticRegression
    from paddlebox_tpu.table import (
        HostSparseTable,
        SparseOptimizerConfig,
        ValueLayout,
    )
    from paddlebox_tpu.train import CTRTrainer, TrainStepConfig

    t0 = time.time()
    with tempfile.TemporaryDirectory() as workdir:
        if args.synthetic:
            files, n_rows = write_synthetic_files(workdir, args.rows)
            mode = "synthetic-criteo-shaped"
        else:
            files, n_rows = write_real_files(args.data_dir, workdir, args.rows)
            mode = "criteo-kaggle"
        schema = SlotSchema(
            [SlotInfo("label", type="float", dense=True, dim=1)]
            + [SlotInfo(f"s{i}") for i in range(N_SLOTS)],
            label_slot="label",
        )
        layout = ValueLayout(embedx_dim=args.embedx)
        opt_cfg = SparseOptimizerConfig(
            embed_lr=0.1, embedx_lr=0.1, embedx_threshold=0.0, initial_range=0.01
        )
        table = HostSparseTable(layout, opt_cfg, n_shards=64, seed=0)
        ds = BoxPSDataset(schema, table, batch_size=args.batch, seed=0,
                          shuffle_mode="local")
        ds.set_filelist(files)
        if args.model == "deepfm":
            model = DeepFM(num_slots=N_SLOTS, feat_width=layout.pull_width,
                           embedx_dim=args.embedx, hidden=(256, 128))
        else:
            model = LogisticRegression(num_slots=N_SLOTS, feat_width=layout.pull_width)
        cfg = TrainStepConfig(
            num_slots=N_SLOTS, batch_size=args.batch, layout=layout,
            sparse_opt=opt_cfg, auc_buckets=100_000, check_nan=True,
        )
        tr = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-3))
        tr.init_params(jax.random.PRNGKey(0))
        per_pass = []
        for p in range(args.passes):
            ds.set_date(f"pass{p}")
            ds.load_into_memory()
            ds.begin_pass(round_to=512)
            out = tr.train_pass(ds)
            ds.end_pass(tr.trained_table(), shrink=False)
            per_pass.append(round(out["auc"], 4))
            print(f"pass {p}: auc={out['auc']:.4f} loss={out['loss']:.4f}",
                  file=sys.stderr)
        # held-out eval: FRESH rows from the same distribution through the
        # metrics-only eval step (SetTestMode) — generalization, not
        # memorization, is what quality parity means
        eval_auc = None
        if args.synthetic:
            eval_dir = os.path.join(workdir, "eval")
            os.makedirs(eval_dir)
            eval_files, _ = write_synthetic_files(
                eval_dir, max(args.rows // 4, 20_000), seed=1234,
                vocab_rows=args.rows,
            )
            ds.set_date("eval")
            ds.set_filelist(eval_files)
            ds.load_into_memory()
            ds.begin_pass(round_to=512)
            tr.set_test_mode(True)
            ev = tr.train_pass(ds)
            tr.set_test_mode(False)
            ds.end_pass(tr.trained_table(), shrink=False)
            eval_auc = round(ev["auc"], 4)
            print(f"held-out eval: auc={eval_auc:.4f}", file=sys.stderr)
        artifact = {
            "metric": "ctr_convergence_auc",
            "mode": mode,
            "model": args.model,
            "rows": n_rows,
            "passes": args.passes,
            "batch": args.batch,
            "embedx_dim": args.embedx,
            "auc_per_pass": per_pass,
            "final_auc": per_pass[-1],
            "holdout_eval_auc": eval_auc,
            "platform": jax.devices()[0].platform,
            "wall_s": round(time.time() - t0, 1),
            "table_keys": len(table),
        }
    out_path = os.path.abspath(args.out)
    from paddlebox_tpu.utils.fs import atomic_write

    with atomic_write(out_path) as f:
        json.dump(artifact, f, indent=1)
    print(json.dumps(artifact))


if __name__ == "__main__":
    main()
