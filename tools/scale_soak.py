#!/usr/bin/env python
"""Scale soak: >=1e8 keys through the full multi-host pass path.

SURVEY §7 hard-part-1 is the reference's 1e11-key tiered store contract
(the closed lib's remit, cmake/external/box_ps.cmake:20-29); this harness
measures how far THIS machine's open implementation actually scales and
records the ceiling: a 2-process cluster (TcpTransport, real sockets)
pushes a synthetic pass of --keys total referenced keys through

  DistributedWorkingSet.finalize   (two-round key exchange + local build)
  pbx_block_stats                  (the pass-prepare pad sweep at scale)
  writeback + decay_and_shrink     (host-table publish at scale)
  maybe_spill + compaction         (mem_cap_rows forces the disk tier)

and dumps per-stage wall times, peak RSS, and spill/compaction counters to
SOAK_r05.json. Pass sizing: each rank references keys/2 uint64 keys with
~25% cross-rank overlap (the CTR recurrence shape), so the exchange routes
a realistic mix of owned and remote keys.

  python tools/scale_soak.py [--keys 1e8] [--out SOAK_r05.json]

--zipf switches to the tiered-store A/B soak (ROADMAP item 3): a seeded
zipf-skewed CTR key stream over a --keys key space is driven through
multi-pass pull/push/decay/spill cycles TWICE — once per spill policy
(freq, fifo) — at the same mem_cap_rows, recording per-pass wall times
(the degradation curve), promote counts, spill hit-rates, and per-shard
occupancy from table.tier_stats(), plus a full-table sha256 digest that
must be bitwise-identical across policies (catch-up decay is exact).

  python tools/scale_soak.py --zipf --keys 1e9 [--passes 8] [--draws 4e6]
      [--mem-cap ROWS] [--zipf-a 1.2] [--pin-show X] [--admit-rate R]
      [--no-digest] [--out SOAK_TIER.json]

--writeback switches to the parallel-writeback A/B soak (PR 13): the same
seeded multi-pass working-set schedule runs TWICE over fresh spill-enabled
tables — once with the legacy serial writeback (--writeback-threads 1
ablation path) and once through the chunked writer-pool pipeline with the
boundary-overlap kick — recording per-pass BLOCKED writeback seconds (the
handoff stall the tentpole kills), the seconds the overlap window hid, the
per-chunk queue-wait distribution, the spill stage writers' gather/fwrite
split from the native io counters, and a full-table sha256 digest that
must be bitwise-identical across arms.

  python tools/scale_soak.py --writeback [--keys 2e7] [--draws 2e6]
      [--passes 4] [--writeback-threads 4] [--chunk-keys 2e5]
      [--mem-cap ROWS] [--out SOAK_WRITEBACK.json]
"""

from __future__ import annotations

import json
import os
import resource
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def worker(rank: int, conf: dict) -> None:
    import numpy as np

    from paddlebox_tpu.parallel.transport import TcpTransport
    from paddlebox_tpu.table import (
        HostSparseTable,
        SparseOptimizerConfig,
        ValueLayout,
    )
    from paddlebox_tpu.table.dist_ws import DistributedWorkingSet
    from paddlebox_tpu.utils import native

    n_keys_local = conf["keys"] // 2
    layout = ValueLayout(embedx_dim=conf["embedx_dim"])
    opt = SparseOptimizerConfig(
        embedx_threshold=0.0, show_clk_decay=0.98, shrink_threshold=0.0
    )
    spill_dir = os.path.join(conf["workdir"], f"spill-{rank}")
    os.makedirs(spill_dir, exist_ok=True)
    table = HostSparseTable(
        layout, opt, n_shards=64, seed=0,
        mem_cap_rows=conf["mem_cap_rows"], spill_dir=spill_dir,
    )
    eps = [f"127.0.0.1:{p}" for p in conf["tp_ports"]]
    tp = TcpTransport(rank, eps, timeout=600.0)
    out = {"rank": rank, "keys_local": n_keys_local}

    rng = np.random.default_rng(rank)
    # ~25% of keys drawn from a shared pool (cross-rank overlap), the rest
    # rank-disjoint — the exchange routes a realistic owned/remote mix
    shared = rng.integers(1, conf["keys"] // 4, n_keys_local // 4).astype(
        np.uint64
    )
    own_lo = 1 << 40
    own = (
        rng.integers(0, 1 << 39, n_keys_local - len(shared)).astype(np.uint64)
        + np.uint64(own_lo + (rank << 39))
    )
    keys = np.concatenate([shared, own])

    ws = DistributedWorkingSet(tp, n_mesh_shards=conf["n_shards_mesh"])
    t0 = time.perf_counter()
    ws.add_keys(keys)
    out["add_keys_s"] = round(time.perf_counter() - t0, 3)
    del keys, shared, own

    t0 = time.perf_counter()
    dev = ws.finalize(table, round_to=4096)
    out["finalize_s"] = round(time.perf_counter() - t0, 3)
    out["referenced"] = int(ws.n_keys)
    out["capacity"] = int(ws.capacity)
    owned = sum(len(k) for k in ws.owned_shard_keys)
    out["owned"] = int(owned)

    # pad sweep at scale: synthetic records over the referenced keys (20
    # keys/record), swept by the native pbx_block_stats batch matrix
    if native.available():
        kpr = 20
        n_rec = ws.n_keys // kpr
        rows_all = ws.row_of_sorted.astype(np.int32)
        rec_rows = rows_all[: n_rec * kpr]
        base = (np.arange(n_rec, dtype=np.int64)) * kpr
        counts = np.full(n_rec, kpr, dtype=np.int64)
        bs = 2048
        n_blocks = min(512, n_rec // bs)
        blocks = (
            np.random.default_rng(1)
            .integers(0, n_rec, (n_blocks, bs))
            .astype(np.int64)
        )
        t0 = time.perf_counter()
        L, bm = native.block_stats(
            rec_rows, base, counts, blocks,
            ws.capacity, conf["n_shards_mesh"],
        )
        out["sweep_s"] = round(time.perf_counter() - t0, 3)
        out["sweep_blocks"] = int(n_blocks)
        out["sweep_records"] = int(n_blocks * bs)
        out["sweep_max_bucket"] = int(bm.max())
        del rec_rows, base, counts, blocks

    # publish: perturb the local slice and write it back (EndPass shape)
    t0 = time.perf_counter()
    dev[:, :, layout.SHOW] += 1.0
    ws.writeback(dev)
    out["writeback_s"] = round(time.perf_counter() - t0, 3)
    del dev

    t0 = time.perf_counter()
    table.decay_and_shrink()
    out["decay_s"] = round(time.perf_counter() - t0, 3)

    t0 = time.perf_counter()
    if table.mem_cap_rows is not None:
        table.maybe_spill()
    out["spill_s"] = round(time.perf_counter() - t0, 3)
    stats = getattr(table, "spill_stats", None)
    if callable(stats):
        out["spill_stats"] = stats()

    out["peak_rss_gb"] = round(
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / (1 << 20), 2
    )
    # registry distributions accumulated during the soak (push shard
    # times, wire frame sizes, ...) via the shared histogram API
    from paddlebox_tpu.utils.monitor import all_histograms

    out["distributions"] = {
        name: h.summary((0.5, 0.99))
        for name, h in sorted(all_histograms().items())
    }
    tp.barrier("soak-done")
    tp.close()
    from paddlebox_tpu.utils.fs import atomic_write

    # cross-process publish: the parent polls for this file
    with atomic_write(os.path.join(conf["workdir"], f"soak-{rank}.json")) as f:
        json.dump(out, f)
    print(f"rank {rank}: {json.dumps(out)}", flush=True)


# ---------------------------------------------------------------------------
# --zipf: tiered-store A/B soak (freq vs fifo at equal mem_cap_rows)
# ---------------------------------------------------------------------------


def _zipf_pass_keys(rng, key_space: int, draws: int, a: float):
    """One pass of a seeded zipf-skewed CTR stream: (unique keys, counts).

    The raw zipf ranks are folded into [0, key_space) and then mixed by an
    odd-constant uint64 multiply so hot keys land on uncorrelated shards
    (rank 1 would otherwise always hash identically across runs of any
    key_space).
    """
    import numpy as np

    raw = rng.zipf(a, draws)
    folded = ((raw - 1) % key_space).astype(np.uint64)
    with np.errstate(over="ignore"):
        keys = folded * np.uint64(0x9E3779B97F4A7C15) + np.uint64(1)
    return np.unique(keys, return_counts=True)


def _table_digest(table) -> str:
    """sha256 over the key-sorted full snapshot of every shard — bitwise
    table identity (the cap-never-hit / cross-policy equivalence oracle)."""
    import hashlib

    import numpy as np

    h = hashlib.sha256()
    for s in range(table.n_shards):
        keys, vals = table._snapshot_shard(
            s, only_touched=False, clear_touched=False
        )
        order = np.argsort(keys, kind="stable")
        h.update(keys[order].tobytes())
        h.update(np.ascontiguousarray(vals[order]).tobytes())
    return h.hexdigest()


def run_zipf_policy(policy: str, conf: dict) -> dict:
    """Drive one spill policy through the full multi-pass tier cycle.

    Fresh table + spill dir per policy; the key stream is re-derived from
    the same seed so both policies see the identical pass sequence.
    """
    import numpy as np

    from paddlebox_tpu import config
    from paddlebox_tpu.obs.histogram import Histogram
    from paddlebox_tpu.table import (
        HostSparseTable,
        SparseOptimizerConfig,
        ValueLayout,
    )
    from paddlebox_tpu.utils.monitor import STAT_OBSERVE

    layout = ValueLayout(embedx_dim=conf["embedx_dim"])
    opt = SparseOptimizerConfig(
        embedx_threshold=0.0,
        show_clk_decay=conf["decay"],
        shrink_threshold=0.0,
    )
    spill_dir = os.path.join(conf["workdir"], f"spill-{policy}")
    os.makedirs(spill_dir, exist_ok=True)
    saved = {
        n: config.get_flag(n)
        for n in ("spill_policy", "spill_pin_show", "spill_admit_show")
    }
    out = {"policy": policy, "passes": []}
    pass_hist = Histogram()  # per-pass wall-time distribution (shared API)
    try:
        config.set_flag("spill_policy", policy)
        config.set_flag("spill_pin_show", conf["pin_show"])
        config.set_flag("spill_admit_show", conf["admit_show"])
        table = HostSparseTable(
            layout, opt, n_shards=conf["n_shards"], seed=0,
            mem_cap_rows=conf["mem_cap_rows"], spill_dir=spill_dir,
        )
        prev = table.tier_stats()
        t_all = time.perf_counter()
        for p in range(conf["passes"]):
            rng = np.random.default_rng((conf["seed"], p))
            uniq, counts = _zipf_pass_keys(
                rng, conf["keys"], conf["draws"], conf["zipf_a"]
            )
            t0 = time.perf_counter()
            rows = table.pull_or_create(uniq)
            rows[:, layout.SHOW] += counts.astype(np.float32)
            table.push(uniq, rows)
            table.decay_and_shrink()
            if conf["admit_rate"] > 0.0:
                # re-derive the admission threshold from the live show
                # distribution: coldest ~admit_rate of keys go disk-first
                config.set_flag(
                    "spill_admit_show",
                    float(table.cache_threshold(conf["admit_rate"])),
                )
            table.maybe_spill()
            pass_s = time.perf_counter() - t0
            pass_hist.observe(pass_s)
            STAT_OBSERVE("soak.pass_s", pass_s)
            st = table.tier_stats()
            promotes = st["promoted_total"] - prev["promoted_total"]
            spilled = st["spilled_total"] - prev["spilled_total"]
            admitted = (
                st["admitted_disk_first"] - prev["admitted_disk_first"]
            )
            prev = st
            out["passes"].append({
                "pass": p,
                "pass_s": round(pass_s, 4),
                "uniq_keys": int(len(uniq)),
                "promotes": int(promotes),
                "spilled": int(spilled),
                "admitted_disk_first": int(admitted),
                # pulls served without a disk promote, over unique pulls
                "spill_hit_rate": round(1.0 - promotes / len(uniq), 6),
                "mem_rows": int(st["mem_rows"]),
                "disk_rows": int(st["disk_rows"]),
            })
        out["wall_s"] = round(time.perf_counter() - t_all, 3)
        # p50/p99 of the degradation curve via the shared histogram (the
        # hand-rolled percentile math this tool used to grow lives in
        # obs/histogram.py now); per-pass exact values stay in "passes"
        out["pass_s_dist"] = pass_hist.summary((0.5, 0.99))
        st = table.tier_stats()
        per_shard = st.pop("per_shard")
        out["tier_stats"] = {k: int(v) for k, v in st.items()}
        out["per_shard_mem_rows"] = [int(v) for v in per_shard["mem_rows"]]
        out["per_shard_disk_rows"] = [
            int(v) for v in per_shard["disk_rows"]
        ]
        if conf["digest"]:
            t0 = time.perf_counter()
            out["digest"] = _table_digest(table)
            out["digest_s"] = round(time.perf_counter() - t0, 3)
        del table
    finally:
        for n, v in saved.items():
            config.set_flag(n, v)
    return out


def zipf_main(argv) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="scale_soak.py --zipf")
    ap.add_argument("--zipf", action="store_true")
    ap.add_argument("--keys", default="1e9", help="key SPACE of the stream")
    ap.add_argument("--passes", type=int, default=8)
    ap.add_argument("--draws", default=None,
                    help="stream draws per pass (default min(4e6, keys))")
    ap.add_argument("--mem-cap", default=None,
                    help="mem_cap_rows (default draws//2: cap always hit)")
    ap.add_argument("--zipf-a", type=float, default=1.2)
    ap.add_argument("--decay", type=float, default=0.98)
    ap.add_argument("--pin-show", type=float, default=0.0)
    ap.add_argument("--admit-show", type=float, default=0.0)
    ap.add_argument("--admit-rate", type=float, default=0.0,
                    help="re-derive spill_admit_show from cache_threshold "
                         "each pass (freq policy)")
    ap.add_argument("--n-shards", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--no-digest", action="store_true")
    ap.add_argument("--out", default=os.path.join(REPO, "SOAK_TIER.json"))
    args = ap.parse_args(argv)

    from paddlebox_tpu.utils import native

    if not native.available():
        print("zipf soak needs the native table", file=sys.stderr)
        return 1
    keys = int(float(args.keys))
    draws = (
        int(float(args.draws)) if args.draws is not None
        else min(4_000_000, max(1000, keys))
    )
    with tempfile.TemporaryDirectory() as workdir:
        conf = {
            "keys": keys,
            "draws": draws,
            "passes": args.passes,
            "mem_cap_rows": (
                int(float(args.mem_cap)) if args.mem_cap is not None
                else max(1, draws // 2)
            ),
            "zipf_a": args.zipf_a,
            "decay": args.decay,
            "pin_show": args.pin_show,
            "admit_show": args.admit_show,
            "admit_rate": args.admit_rate,
            "n_shards": args.n_shards,
            "seed": args.seed,
            "embedx_dim": 8,
            "digest": not args.no_digest,
            "workdir": workdir,
        }
        policies = {}
        for policy in ("freq", "fifo"):
            policies[policy] = run_zipf_policy(policy, conf)
            print(
                f"{policy}: wall={policies[policy]['wall_s']}s "
                f"promotes={policies[policy]['tier_stats']['promoted_total']} "
                f"spilled={policies[policy]['tier_stats']['spilled_total']}",
                flush=True,
            )
    pf = policies["freq"]["tier_stats"]
    pq = policies["fifo"]["tier_stats"]
    hr = {
        k: round(
            sum(p["spill_hit_rate"] * p["uniq_keys"] for p in v["passes"])
            / max(1, sum(p["uniq_keys"] for p in v["passes"])),
            6,
        )
        for k, v in policies.items()
    }
    ab = {
        "mem_cap_rows": conf["mem_cap_rows"],
        "promotes_freq": pf["promoted_total"],
        "promotes_fifo": pq["promoted_total"],
        # fraction of fifo's disk promotes the freq ranking avoided
        "promote_improvement": round(
            1.0 - pf["promoted_total"] / max(1, pq["promoted_total"]), 6
        ),
        "spill_hit_rate_freq": hr["freq"],
        "spill_hit_rate_fifo": hr["fifo"],
        "wall_s_freq": policies["freq"]["wall_s"],
        "wall_s_fifo": policies["fifo"]["wall_s"],
    }
    if conf["digest"]:
        ab["bitwise_equal"] = (
            policies["freq"]["digest"] == policies["fifo"]["digest"]
        )
    conf.pop("workdir")
    result = {
        "metric": "tiered_store_zipf_soak",
        "conf": conf,
        "policies": policies,
        "ab": ab,
        "machine": {"cpus": os.cpu_count()},
    }
    from paddlebox_tpu.utils.fs import atomic_write

    with atomic_write(args.out) as f:
        json.dump(result, f, indent=1)
    print(json.dumps({"ab": ab}))
    return 0


# ---------------------------------------------------------------------------
# --writeback: parallel-writeback A/B soak (serial ablation vs writer pool)
# ---------------------------------------------------------------------------


def _wb_pass_keys(seed: int, p: int, key_space: int, draws: int):
    """Pass p's referenced keys: seeded uniform draws over the key space,
    mixed by an odd-constant multiply so the stream shards uniformly."""
    import numpy as np

    rng = np.random.default_rng((seed, p))
    raw = rng.integers(1, key_space, draws).astype(np.uint64)
    with np.errstate(over="ignore"):
        keys = raw * np.uint64(0x9E3779B97F4A7C15) + np.uint64(1)
    return np.unique(keys)


def _wb_stage_next(conf: dict, p: int):
    """The boundary-overlap window's work: derive the NEXT pass's key
    stream and premerge it into a fresh working set — exactly the staging
    the pipelined boundary overlaps with the writeback. Touches no table
    state, so running it beside the in-flight writeback cannot perturb
    the bitwise A/B."""
    from paddlebox_tpu.table.sparse_table import PassWorkingSet

    keys = _wb_pass_keys(conf["seed"], p + 1, conf["keys"], conf["draws"])
    ws = PassWorkingSet(n_mesh_shards=1)
    ws.add_keys(keys)
    return ws


def run_writeback_arm(threads: int, conf: dict) -> dict:
    """One A/B arm: the full multi-pass finalize/perturb/writeback/spill
    cycle over a fresh table, with ``threads`` selecting the serial
    ablation (<=1) or the chunked writer-pool pipeline. In the pool arm
    the writeback is kicked on a thread and the staging window runs
    beside it (the PR 4 boundary shape); ``blocked_s`` is what the
    handoff actually waited at the join."""
    import threading as _threading

    import numpy as np

    from paddlebox_tpu import config
    from paddlebox_tpu.table import (
        HostSparseTable,
        SparseOptimizerConfig,
        ValueLayout,
    )
    from paddlebox_tpu.utils.monitor import STAT_GET, all_histograms

    layout = ValueLayout(embedx_dim=conf["embedx_dim"])
    opt = SparseOptimizerConfig(
        embedx_threshold=0.0, show_clk_decay=0.98, shrink_threshold=0.0
    )
    spill_dir = os.path.join(conf["workdir"], f"spill-wb-{threads}")
    os.makedirs(spill_dir, exist_ok=True)
    saved = {
        n: config.get_flag(n)
        for n in ("writeback_threads", "writeback_chunk_keys")
    }
    out = {"threads": threads, "passes": []}
    try:
        config.set_flag("writeback_threads", threads)
        config.set_flag("writeback_chunk_keys", conf["chunk_keys"])
        table = HostSparseTable(
            layout, opt, n_shards=conf["n_shards"], seed=0,
            mem_cap_rows=conf["mem_cap_rows"], spill_dir=spill_dir,
        )
        io_prev = table._native.io_stats() if table.native else None
        from paddlebox_tpu.table.sparse_table import PassWorkingSet

        ws = PassWorkingSet(n_mesh_shards=1)
        ws.add_keys(_wb_pass_keys(conf["seed"], 0, conf["keys"],
                                  conf["draws"]))
        t_all = time.perf_counter()
        for p in range(conf["passes"]):
            dev = ws.finalize(table, round_to=4096)
            dev[:, :, layout.SHOW] += 1.0
            rec = {"pass": p, "uniq_keys": int(ws.n_keys)}
            if threads <= 1:
                # serial ablation: the handoff stalls for the whole push,
                # THEN the staging window runs (same total work)
                t0 = time.perf_counter()
                ws.writeback(dev)
                rec["blocked_s"] = time.perf_counter() - t0
                rec["push_s"] = rec["blocked_s"]
                t0 = time.perf_counter()
                ws_next = _wb_stage_next(conf, p)
                rec["window_s"] = time.perf_counter() - t0
            else:
                # boundary-overlap shape: kick the writeback, stage the
                # next pass beside it, measure what the join still waits
                err = []

                def _run(ws=ws, dev=dev):
                    try:
                        ws.writeback(dev)
                    except BaseException as e:  # propagated after join
                        err.append(e)

                th = _threading.Thread(target=_run)
                t_kick = time.perf_counter()
                th.start()
                ws_next = _wb_stage_next(conf, p)
                rec["window_s"] = time.perf_counter() - t_kick
                t0 = time.perf_counter()
                th.join()
                rec["blocked_s"] = time.perf_counter() - t0
                if err:
                    raise err[0]
                rec["push_s"] = float(STAT_GET("table.writeback.push_s"))
                rec["chunks"] = int(STAT_GET("table.writeback.chunks"))
                rec["pipeline_hidden_s"] = float(
                    STAT_GET("table.writeback.hidden_s")
                )
            rec["overlap_hidden_s"] = max(
                0.0, rec["push_s"] - rec["blocked_s"]
            )
            t0 = time.perf_counter()
            table.decay_and_shrink()
            table.maybe_spill()
            rec["boundary_rest_s"] = time.perf_counter() - t0
            ws = ws_next
            out["passes"].append({
                k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in rec.items()
            })
        out["wall_s"] = round(time.perf_counter() - t_all, 3)
        for field in ("blocked_s", "push_s", "overlap_hidden_s",
                      "window_s"):
            out[field + "_total"] = round(
                sum(r[field] for r in out["passes"]), 4
            )
        if io_prev is not None:
            io = table._native.io_stats()
            out["io"] = {
                "spill_gather_s": round(
                    (io["spill_gather_ns"] - io_prev["spill_gather_ns"])
                    / 1e9, 4),
                "spill_fwrite_s": round(
                    (io["spill_fwrite_ns"] - io_prev["spill_fwrite_ns"])
                    / 1e9, 4),
                "prepass_read_s": round(
                    (io["prepass_read_ns"] - io_prev["prepass_read_ns"])
                    / 1e9, 4),
                "stage_flushes": int(io["stage_flushes"]),
                "stage_bytes": int(io["stage_bytes"]),
            }
        if threads > 1:
            # per-chunk queue wait + per-shard push walls (pool arm only:
            # the serial path bypasses both histograms by design)
            out["distributions"] = {
                name: h.summary((0.5, 0.99))
                for name, h in sorted(all_histograms().items())
                if name.startswith("table.writeback.")
            }
        st = table.tier_stats()
        st.pop("per_shard")
        out["tier_stats"] = {k: int(v) for k, v in st.items()}
        t0 = time.perf_counter()
        out["digest"] = _table_digest(table)
        out["digest_s"] = round(time.perf_counter() - t0, 3)
        del table
    finally:
        for n, v in saved.items():
            config.set_flag(n, v)
    return out


def wb_main(argv) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="scale_soak.py --writeback")
    ap.add_argument("--writeback", action="store_true")
    ap.add_argument("--keys", default="2e7", help="key SPACE of the stream")
    ap.add_argument("--draws", default="2e6", help="stream draws per pass")
    ap.add_argument("--passes", type=int, default=4)
    ap.add_argument("--writeback-threads", type=int, default=4,
                    help="writer-pool size of the parallel arm (1 turns "
                         "the A/B into serial-vs-serial — the ablation "
                         "sanity run)")
    ap.add_argument("--chunk-keys", default="2e5",
                    help="writeback_chunk_keys for the pool arm")
    ap.add_argument("--mem-cap", default=None,
                    help="mem_cap_rows (default draws//2: cap always hit, "
                         "spill stage writers + push pre-pass engaged)")
    ap.add_argument("--n-shards", type=int, default=32)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=os.path.join(REPO, "SOAK_WRITEBACK.json"))
    args = ap.parse_args(argv)

    from paddlebox_tpu.utils import native

    if not native.available():
        print("writeback soak needs the native table", file=sys.stderr)
        return 1
    draws = int(float(args.draws))
    with tempfile.TemporaryDirectory() as workdir:
        conf = {
            "keys": int(float(args.keys)),
            "draws": draws,
            "passes": args.passes,
            "chunk_keys": int(float(args.chunk_keys)),
            "mem_cap_rows": (
                int(float(args.mem_cap)) if args.mem_cap is not None
                else max(1, draws // 2)
            ),
            "n_shards": args.n_shards,
            "seed": args.seed,
            "embedx_dim": 8,
            "workdir": workdir,
        }
        arms = {}
        for name, th in (("serial", 1), ("parallel", args.writeback_threads)):
            arms[name] = run_writeback_arm(th, conf)
            print(
                f"{name}(threads={th}): "
                f"blocked={arms[name]['blocked_s_total']}s "
                f"push={arms[name]['push_s_total']}s "
                f"hidden={arms[name]['overlap_hidden_s_total']}s "
                f"wall={arms[name]['wall_s']}s",
                flush=True,
            )
    sa, pa = arms["serial"], arms["parallel"]
    ab = {
        "writer_pool": args.writeback_threads,
        "chunk_keys": conf["chunk_keys"],
        # the headline: seconds the pass handoff STALLS on writeback —
        # the serial arm stalls for the whole push, the pool arm only
        # for what the overlap window didn't absorb
        "blocked_writeback_s_serial": sa["blocked_s_total"],
        "blocked_writeback_s_parallel": pa["blocked_s_total"],
        "blocked_cut_x": round(
            sa["blocked_s_total"] / max(1e-9, pa["blocked_s_total"]), 2
        ),
        "overlap_hidden_s": pa["overlap_hidden_s_total"],
        # total wall stays honest: on few-core hosts the overlap moves
        # the push INTO the window rather than shrinking the sum
        "wall_s_serial": sa["wall_s"],
        "wall_s_parallel": pa["wall_s"],
        "bitwise_equal": sa["digest"] == pa["digest"],
    }
    conf.pop("workdir")
    result = {
        "metric": "parallel_writeback_ab_soak",
        "conf": conf,
        "arms": arms,
        "ab": ab,
        "machine": {"cpus": os.cpu_count()},
    }
    from paddlebox_tpu.utils.fs import atomic_write

    with atomic_write(args.out) as f:
        json.dump(result, f, indent=1)
    print(json.dumps({"ab": ab}))
    return 0


def main() -> int:
    if "--zipf" in sys.argv:
        return zipf_main(sys.argv[1:])
    if "--writeback" in sys.argv:
        return wb_main(sys.argv[1:])
    keys = int(float(next(
        (sys.argv[i + 1] for i, a in enumerate(sys.argv) if a == "--keys"),
        "1e8",
    )))
    out_path = next(
        (sys.argv[i + 1] for i, a in enumerate(sys.argv) if a == "--out"),
        os.path.join(REPO, "SOAK_r05.json"),
    )
    if "--worker" in sys.argv:
        rank = int(sys.argv[sys.argv.index("--worker") + 1])
        with open(sys.argv[sys.argv.index("--conf") + 1]) as f:
            worker(rank, json.load(f))
        return 0

    with tempfile.TemporaryDirectory() as workdir:
        conf = {
            "keys": keys,
            "embedx_dim": 8,
            "n_shards_mesh": 8,
            # cap at ~60% of expected owned rows: forces the spill tier
            "mem_cap_rows": int(keys / 2 * 0.6),
            "tp_ports": _free_ports(2),
            "workdir": workdir,
        }
        conf_path = os.path.join(workdir, "conf.json")
        from paddlebox_tpu.utils.fs import atomic_write

        # cross-process publish: every spawned rank reads this
        with atomic_write(conf_path) as f:
            json.dump(conf, f)
        t0 = time.perf_counter()
        procs = [
            subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--worker",
                 str(r), "--conf", conf_path],
                cwd=REPO,
            )
            for r in range(2)
        ]
        rc = [p.wait() for p in procs]
        wall = time.perf_counter() - t0
        if any(rc):
            print(f"soak failed: rc={rc}", file=sys.stderr)
            return 1
        ranks = []
        for r in range(2):
            with open(os.path.join(workdir, f"soak-{r}.json")) as f:
                ranks.append(json.load(f))
    result = {
        "metric": "multihost_pass_scale_soak",
        "keys_total": keys,
        "wall_s": round(wall, 1),
        "ranks": ranks,
        "machine": {"cpus": os.cpu_count()},
    }
    from paddlebox_tpu.utils.fs import atomic_write

    with atomic_write(out_path) as f:
        json.dump(result, f, indent=1)
    print(json.dumps({
        "keys": keys, "wall_s": round(wall, 1),
        "finalize_s": [r["finalize_s"] for r in ranks],
        "peak_rss_gb": [r["peak_rss_gb"] for r in ranks],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
