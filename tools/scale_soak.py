#!/usr/bin/env python
"""Scale soak: >=1e8 keys through the full multi-host pass path.

SURVEY §7 hard-part-1 is the reference's 1e11-key tiered store contract
(the closed lib's remit, cmake/external/box_ps.cmake:20-29); this harness
measures how far THIS machine's open implementation actually scales and
records the ceiling: a 2-process cluster (TcpTransport, real sockets)
pushes a synthetic pass of --keys total referenced keys through

  DistributedWorkingSet.finalize   (two-round key exchange + local build)
  pbx_block_stats                  (the pass-prepare pad sweep at scale)
  writeback + decay_and_shrink     (host-table publish at scale)
  maybe_spill + compaction         (mem_cap_rows forces the disk tier)

and dumps per-stage wall times, peak RSS, and spill/compaction counters to
SOAK_r05.json. Pass sizing: each rank references keys/2 uint64 keys with
~25% cross-rank overlap (the CTR recurrence shape), so the exchange routes
a realistic mix of owned and remote keys.

  python tools/scale_soak.py [--keys 1e8] [--out SOAK_r05.json]
"""

from __future__ import annotations

import json
import os
import resource
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def worker(rank: int, conf: dict) -> None:
    import numpy as np

    from paddlebox_tpu.parallel.transport import TcpTransport
    from paddlebox_tpu.table import (
        HostSparseTable,
        SparseOptimizerConfig,
        ValueLayout,
    )
    from paddlebox_tpu.table.dist_ws import DistributedWorkingSet
    from paddlebox_tpu.utils import native

    n_keys_local = conf["keys"] // 2
    layout = ValueLayout(embedx_dim=conf["embedx_dim"])
    opt = SparseOptimizerConfig(
        embedx_threshold=0.0, show_clk_decay=0.98, shrink_threshold=0.0
    )
    spill_dir = os.path.join(conf["workdir"], f"spill-{rank}")
    os.makedirs(spill_dir, exist_ok=True)
    table = HostSparseTable(
        layout, opt, n_shards=64, seed=0,
        mem_cap_rows=conf["mem_cap_rows"], spill_dir=spill_dir,
    )
    eps = [f"127.0.0.1:{p}" for p in conf["tp_ports"]]
    tp = TcpTransport(rank, eps, timeout=600.0)
    out = {"rank": rank, "keys_local": n_keys_local}

    rng = np.random.default_rng(rank)
    # ~25% of keys drawn from a shared pool (cross-rank overlap), the rest
    # rank-disjoint — the exchange routes a realistic owned/remote mix
    shared = rng.integers(1, conf["keys"] // 4, n_keys_local // 4).astype(
        np.uint64
    )
    own_lo = 1 << 40
    own = (
        rng.integers(0, 1 << 39, n_keys_local - len(shared)).astype(np.uint64)
        + np.uint64(own_lo + (rank << 39))
    )
    keys = np.concatenate([shared, own])

    ws = DistributedWorkingSet(tp, n_mesh_shards=conf["n_shards_mesh"])
    t0 = time.perf_counter()
    ws.add_keys(keys)
    out["add_keys_s"] = round(time.perf_counter() - t0, 3)
    del keys, shared, own

    t0 = time.perf_counter()
    dev = ws.finalize(table, round_to=4096)
    out["finalize_s"] = round(time.perf_counter() - t0, 3)
    out["referenced"] = int(ws.n_keys)
    out["capacity"] = int(ws.capacity)
    owned = sum(len(k) for k in ws.owned_shard_keys)
    out["owned"] = int(owned)

    # pad sweep at scale: synthetic records over the referenced keys (20
    # keys/record), swept by the native pbx_block_stats batch matrix
    if native.available():
        kpr = 20
        n_rec = ws.n_keys // kpr
        rows_all = ws.row_of_sorted.astype(np.int32)
        rec_rows = rows_all[: n_rec * kpr]
        base = (np.arange(n_rec, dtype=np.int64)) * kpr
        counts = np.full(n_rec, kpr, dtype=np.int64)
        bs = 2048
        n_blocks = min(512, n_rec // bs)
        blocks = (
            np.random.default_rng(1)
            .integers(0, n_rec, (n_blocks, bs))
            .astype(np.int64)
        )
        t0 = time.perf_counter()
        L, bm = native.block_stats(
            rec_rows, base, counts, blocks,
            ws.capacity, conf["n_shards_mesh"],
        )
        out["sweep_s"] = round(time.perf_counter() - t0, 3)
        out["sweep_blocks"] = int(n_blocks)
        out["sweep_records"] = int(n_blocks * bs)
        out["sweep_max_bucket"] = int(bm.max())
        del rec_rows, base, counts, blocks

    # publish: perturb the local slice and write it back (EndPass shape)
    t0 = time.perf_counter()
    dev[:, :, layout.SHOW] += 1.0
    ws.writeback(dev)
    out["writeback_s"] = round(time.perf_counter() - t0, 3)
    del dev

    t0 = time.perf_counter()
    table.decay_and_shrink()
    out["decay_s"] = round(time.perf_counter() - t0, 3)

    t0 = time.perf_counter()
    if table.mem_cap_rows is not None:
        table.maybe_spill()
    out["spill_s"] = round(time.perf_counter() - t0, 3)
    stats = getattr(table, "spill_stats", None)
    if callable(stats):
        out["spill_stats"] = stats()

    out["peak_rss_gb"] = round(
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / (1 << 20), 2
    )
    tp.barrier("soak-done")
    tp.close()
    with open(os.path.join(conf["workdir"], f"soak-{rank}.json"), "w") as f:
        json.dump(out, f)
    print(f"rank {rank}: {json.dumps(out)}", flush=True)


def main() -> int:
    keys = int(float(next(
        (sys.argv[i + 1] for i, a in enumerate(sys.argv) if a == "--keys"),
        "1e8",
    )))
    out_path = next(
        (sys.argv[i + 1] for i, a in enumerate(sys.argv) if a == "--out"),
        os.path.join(REPO, "SOAK_r05.json"),
    )
    if "--worker" in sys.argv:
        rank = int(sys.argv[sys.argv.index("--worker") + 1])
        with open(sys.argv[sys.argv.index("--conf") + 1]) as f:
            worker(rank, json.load(f))
        return 0

    with tempfile.TemporaryDirectory() as workdir:
        conf = {
            "keys": keys,
            "embedx_dim": 8,
            "n_shards_mesh": 8,
            # cap at ~60% of expected owned rows: forces the spill tier
            "mem_cap_rows": int(keys / 2 * 0.6),
            "tp_ports": _free_ports(2),
            "workdir": workdir,
        }
        conf_path = os.path.join(workdir, "conf.json")
        with open(conf_path, "w") as f:
            json.dump(conf, f)
        t0 = time.perf_counter()
        procs = [
            subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--worker",
                 str(r), "--conf", conf_path],
                cwd=REPO,
            )
            for r in range(2)
        ]
        rc = [p.wait() for p in procs]
        wall = time.perf_counter() - t0
        if any(rc):
            print(f"soak failed: rc={rc}", file=sys.stderr)
            return 1
        ranks = []
        for r in range(2):
            with open(os.path.join(workdir, f"soak-{r}.json")) as f:
                ranks.append(json.load(f))
    result = {
        "metric": "multihost_pass_scale_soak",
        "keys_total": keys,
        "wall_s": round(wall, 1),
        "ranks": ranks,
        "machine": {"cpus": os.cpu_count()},
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({
        "keys": keys, "wall_s": round(wall, 1),
        "finalize_s": [r["finalize_s"] for r in ranks],
        "peak_rss_gb": [r["peak_rss_gb"] for r in ranks],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
