"""Sharded-table pull/push: fixed-shape all_to_all over the mesh axis.

TPU-native equivalent of the reference's multi-node sparse path — closed
`boxps::PullSparseGPU`/`PushSparseGPU` with inter-node key routing inside the
lib (box_wrapper_impl.h:122, :229) — re-expressed as XLA collectives:

pull (runs inside shard_map, per device):
  1. the host packer bucketed this device's unique rows by owning shard into
     ``req_ranks [n_shards, K]`` (rank-within-shard; pads -> padding row);
  2. ``all_to_all`` routes request buckets to owners over ICI;
  3. each owner gathers its local rows (one static-shape gather);
  4. ``all_to_all`` routes the value buckets back;
  -> pulled records laid out by bucket position, so the batch's flat
     ``inverse`` indices (host-computed) address them directly.

push reverses the route: per-bucket merged grads + show/clk counts travel to
the owner shard, which scatter-merges them per owned row and applies the
sparse optimizer exactly once per row (PushSparseGPU merge semantics) —
deterministic regardless of how many devices touched the row.

All shapes are static (K is the host-padded bucket size), so the collective
pattern compiles to fixed ICI traffic — no ragged RPC tier.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddlebox_tpu.ops.pull_push import (
    pull_sparse_rows,
    pull_sparse_rows_extended,
    sparse_update_rows,
)
from paddlebox_tpu.table.optimizers import SparseOptimizerConfig
from paddlebox_tpu.table.value_layout import ValueLayout


def _a2a(x, axis_name):
    return lax.all_to_all(x, axis_name, 0, 0, tiled=True)


def _bf16_vals_a2a(vals, axis_name):
    """Value columns over ICI at half width: bf16 on the wire, fp32 out."""
    return _a2a(vals.astype(jnp.bfloat16), axis_name).astype(jnp.float32)


def _int8_vals_a2a(recs, axis_name, sections):
    """Value sections of [n, k, W] records over ICI as per-record-scaled
    int8; returns the dequantized fp32 value columns [n, k, sum(widths)].

    Two collectives regardless of section count: one concatenated int8
    payload, one stacked scale matrix (same batching as the row wire's
    fetch_rows_start) — every extra all_to_all would add fixed launch/sync
    latency per batch."""
    qs, scales = [], []
    for a, b in sections:
        v = recs[:, :, a:b]
        s = jnp.maximum(jnp.abs(v).max(axis=2), 1e-12) / 127.0
        qs.append(
            jnp.clip(jnp.rint(v / s[..., None]), -127, 127).astype(jnp.int8)
        )
        scales.append(s)
    qr = _a2a(jnp.concatenate(qs, axis=2), axis_name)
    sr = _a2a(jnp.stack(scales, axis=2), axis_name)  # [n, k, n_sections]
    outs = []
    off = 0
    for si, (a, b) in enumerate(sections):
        wsec = b - a
        outs.append(
            qr[:, :, off : off + wsec].astype(jnp.float32)
            * sr[:, :, si : si + 1]
        )
        off += wsec
    return jnp.concatenate(outs, axis=2)


def _compressed_a2a(recs, axis_name, head: int, sections):
    """all_to_all [n, K, W] records under the ici_wire_dtype flag.

    ``head`` columns (counters/stats) always ride fp32; each ``(a, b)``
    span in ``sections`` is a separate VALUE FAMILY quantized with its own
    per-record max-abs scale under int8 — embedx and expand train on
    different gradients and can sit orders of magnitude apart, so one
    shared scale would quantize the smaller family to noise (the same
    per-block rule as the row wire, ops/wire_quant.py).

    ``adaptive`` splits each K-slot bucket at the static hot bound H =
    ici_hot_slots(K): the host packer ordered every bucket hot-first, so
    slots [0, H) carry the frequent keys and ride bf16 while slots [H, K)
    carry the cold tail and ride int8. Precision is decided purely by slot
    index — no per-row flag crosses the wire, the collective keeps one
    compiled shape per K, and hot keys past the bound simply ride the int8
    region (graceful, counted host-side under wire.ici_hot_overflow_keys).
    H=0 / H=K execute exactly the uniform int8 / bf16 paths, bitwise."""
    from paddlebox_tpu.ops import wire_quant as wq
    from paddlebox_tpu.utils.monitor import STAT_SET

    mode = wq.ici_effective_mode()
    # bytes-on-wire accounting for the compiled collective. Shapes are
    # static, so this is exact per-call payload — recorded at TRACE time
    # (STAT_SET, not ADD: a retrace must not double-count) alongside the
    # fp32 baseline it displaces, so bench/capture artifacts can report
    # the measured ICI compression ratio instead of asserting it.
    n, K, W = int(recs.shape[0]), int(recs.shape[1]), int(recs.shape[2])
    hot = wq.ici_hot_slots(K) if mode == "adaptive" else 0
    payload = wq.ici_wire_nbytes(n, K, W, head, len(sections), mode, hot)
    STAT_SET("wire.a2a_payload_bytes", payload)
    STAT_SET("wire.a2a_fp32_bytes", n * K * W * 4)
    STAT_SET("wire.a2a_hot_slots", hot)
    if mode == "adaptive":
        # blended effective bits across the mixed payload, so dashboards
        # reading one number still see where between 8 and 16 the wire sat
        bits = int(round(payload * 8 / (n * K * W)))
    else:
        bits = {"fp32": 32, "bf16": 16, "int8": 8}[mode]
    STAT_SET("wire.a2a_dtype_bits", bits)
    if mode == "adaptive":
        if hot <= 0:
            mode = "int8"  # whole bucket is tail: exactly the uniform wire
        elif hot >= K:
            mode = "bf16"  # whole bucket is hot: exactly the uniform wire
    if mode == "bf16":
        counts = _a2a(recs[:, :, :head], axis_name)
        vals = _bf16_vals_a2a(recs[:, :, head:], axis_name)
        return jnp.concatenate([counts, vals], axis=2)
    if mode == "int8":
        counts = _a2a(recs[:, :, :head], axis_name)
        vals = _int8_vals_a2a(recs, axis_name, sections)
        return jnp.concatenate([counts, vals], axis=2)
    if mode == "adaptive":
        # four collectives: fp32 head for all K slots, bf16 hot values,
        # int8 cold values + their scales. Hot and cold reassemble by
        # concatenation because slicing K (axis 1) commutes with the
        # all_to_all (which tiles axis 0): received bucket s's first H
        # slots are exactly sender s's first H slots.
        counts = _a2a(recs[:, :, :head], axis_name)
        hot_vals = _bf16_vals_a2a(recs[:, :hot, head:], axis_name)
        cold_vals = _int8_vals_a2a(recs[:, hot:, :], axis_name, sections)
        vals = jnp.concatenate([hot_vals, cold_vals], axis=1)
        return jnp.concatenate([counts, vals], axis=2)
    return _a2a(recs, axis_name)


def sharded_pull(
    table_local: jnp.ndarray,  # [cap, width] this shard's rows
    req_ranks: jnp.ndarray,  # int32 [n_shards, K] this device's requests
    layout: ValueLayout,
    embedx_threshold: float,
    scale: float = 1.0,
    axis_name: str = "dp",
    extended: bool = False,
) -> jnp.ndarray:
    """Pull records for this device's request buckets. [n_shards*K, pull_w].

    Output row s*K + j is the value for request slot j of shard s — exactly
    the bucket positions the host packer's ``inverse`` indices refer to.
    With ``extended`` each record carries the expand-embedding block as
    trailing columns (pull_box_extended_sparse parity over the mesh).
    """
    n, K = req_ranks.shape
    # route requests to owners: row d of the result = bucket from device d
    req_recv = lax.all_to_all(req_ranks, axis_name, 0, 0, tiled=True)  # [n, K]
    # owner-side gather (+ embedx gating/scaling, PullCopy parity)
    if extended:
        rec, exp = pull_sparse_rows_extended(
            table_local, req_recv.reshape(-1), layout, embedx_threshold, scale
        )
        resp = jnp.concatenate([rec, exp], axis=1).reshape(n, K, -1)
    else:
        resp = pull_sparse_rows(
            table_local, req_recv.reshape(-1), layout, embedx_threshold, scale
        ).reshape(n, K, -1)
    # route value buckets back: row s = bucket answered by shard s.
    # ici_wire_dtype=bf16 halves the ICI payload, int8 quarters it (the
    # quant pull-value family of box_wrapper.cc:419-437, applied to the
    # only wire this architecture still ships values over per batch); flag
    # read at trace time, so the cast compiles into the fixed collective.
    # The counter/stat head (everything before embed_w — show/clk plus
    # conv/pcoc extras) stays fp32; embedx and the extended pull's expand
    # block quantize as separate int8 sections.
    a = layout.embed_w_col  # first embedding-value column of the record
    W = resp.shape[2]
    pull_w = layout.pull_width
    sections = (
        [(a, pull_w), (pull_w, W)] if extended else [(a, W)]
    )
    resp_back = _compressed_a2a(resp, axis_name, a, sections)
    return resp_back.reshape(n * K, -1).astype(jnp.float32)


def sharded_serve_pull(
    table_local: jnp.ndarray,  # [cap, width] this shard's hot-tier rows
    req_ranks: jnp.ndarray,  # int32 [n_shards, K] this device's requests
    axis_name: str = "dp",
) -> jnp.ndarray:
    """Serve-side pull over the device scoring tier. [n_shards*K, width].

    Same request routing and bucket-position contract as :func:`sharded_pull`
    (output row s*K + j answers request slot j of shard s), but the rows
    return VERBATIM: no embedx gating, no CVM scaling, and fp32 on the wire
    regardless of ``ici_wire_dtype`` — the hot tier stores exact copies of
    the committed version's rows and the serving parity gate is bitwise, so
    the value path must be a pure routed gather.
    """
    n, K = req_ranks.shape
    req_recv = lax.all_to_all(req_ranks, axis_name, 0, 0, tiled=True)  # [n, K]
    resp = jnp.take(table_local, req_recv.reshape(-1), axis=0).reshape(n, K, -1)
    return _a2a(resp, axis_name).reshape(n * K, -1)


def sharded_push(
    table_local: jnp.ndarray,  # [cap, width]
    req_ranks: jnp.ndarray,  # int32 [n_shards, K]
    grads_bucket: jnp.ndarray,  # [n_shards*K, pull_w] merged grads per bucket pos
    show_bucket: jnp.ndarray,  # f32 [n_shards*K]
    clk_bucket: jnp.ndarray,  # f32 [n_shards*K]
    layout: ValueLayout,
    opt: SparseOptimizerConfig,
    axis_name: str = "dp",
) -> jnp.ndarray:
    """Route push records to owner shards, merge, apply optimizer once/row.

    Owner-side merge is a sort-based dedup over the n_shards*K received
    records (requests for the same row from different devices collapse into
    one merged record), so per-step work scales with the batch's request
    volume — never with the shard's capacity.
    """
    n, K = req_ranks.shape
    gw = grads_bucket.shape[1]  # pull_width, or pull_width+expand (extended)

    recs = jnp.concatenate(
        [show_bucket[:, None], clk_bucket[:, None], grads_bucket], axis=1
    ).reshape(n, K, gw + 2)
    # push grads in bf16 (half) or per-record-scaled int8 (quarter) over
    # ICI when flagged. The two show/clk count columns stay fp32: bf16 is
    # exact only to 256, and a hot key whose per-bucket count sums past
    # that would round — drifting everything show-gated downstream (embedx
    # unlock, shrink, cache thresholds). An extended push's expand grads
    # quantize as their own int8 section, like the pull side.
    pw2 = 2 + layout.push_width
    sections = (
        [(2, pw2), (pw2, gw + 2)] if gw > layout.push_width else [(2, gw + 2)]
    )
    recs_recv = _compressed_a2a(recs, axis_name, 2, sections)
    ranks_recv = lax.all_to_all(req_ranks, axis_name, 0, 0, tiled=True)  # [n, K]

    M = n * K
    return _owner_merge_push(
        table_local, ranks_recv.reshape(M), recs_recv.reshape(M, gw + 2),
        layout, opt,
    )


def _owner_merge_push(table_local, flat_ranks, flat_recs, layout, opt):
    """Owner-side merge+apply of M received push records [show, clk, grads].

    Factored out of :func:`sharded_push` so a single-device caller (tests)
    can run the exact merge the mesh owner runs, on the same flat record
    order the all_to_all delivers (device-major)."""
    M = flat_ranks.shape[0]
    # group duplicate ranks: sort, segment by run, merge records per run
    order = jnp.argsort(flat_ranks)
    sr = jnp.take(flat_ranks, order)
    srecs = jnp.take(flat_recs, order, axis=0)
    is_head = jnp.concatenate([jnp.ones((1,), bool), sr[1:] != sr[:-1]])
    seg = jnp.cumsum(is_head.astype(jnp.int32)) - 1  # [M] run id
    n_uniq = seg[-1] + 1
    merged = jax.ops.segment_sum(
        srecs, seg, num_segments=M, indices_are_sorted=True
    )  # rows >= n_uniq zero
    # one rank per run (duplicates in a run carry the same value; runs beyond
    # n_uniq stay 0, a safe in-bounds row)
    rep_rank = jnp.zeros((M,), sr.dtype).at[seg].set(sr)

    old = jnp.take(table_local, rep_rank, axis=0)
    new = sparse_update_rows(
        old, merged[:, 2:], merged[:, 0], merged[:, 1], layout, opt
    )
    # runs beyond n_uniq all alias rank 0 with zero records — mask them so
    # clipping side-effects can't scatter there repeatedly
    valid = (jnp.arange(M) < n_uniq)[:, None]
    return table_local.at[rep_rank].add((new - old) * valid)
