"""Device mesh plan for CTR training.

The reference's process/device topology — one BoxPSWorker per GPU
(boxps_trainer.cc:53-73), NCCL ring per node, closed `boxps::MPICluster`
across nodes (box_wrapper.h:531) — collapses on TPU into one
`jax.sharding.Mesh` with a single `dp` axis:

- the minibatch is data-parallel over `dp` (one worker per chip parity);
- the pass working-set table is *sharded* over the same axis (the model-
  parallel dimension of a CTR model is the embedding table, which dwarfs the
  dense net — so dp and "table mp" share one axis and pull/push ride ICI
  all_to_all);
- dense grads are psum'd over `dp` (the NCCL allreduce / SyncDense path).

TP/PP/SP over the dense net are deliberately absent, matching the reference
(SURVEY.md §2.3: tensor/sequence parallelism ❌ absent — CTR dense towers are
tiny). The mesh axis spans both ICI and DCN when multi-host; XLA places the
collectives.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def axis_size(axis_name: str) -> int:
    """``lax.axis_size`` across jax versions: 0.4.x has no lax.axis_size,
    but ``jax.core.axis_frame(name)`` returns the same static size inside
    a shard_map/pmap body."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    import jax.core

    return jax.core.axis_frame(axis_name)


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions.

    Newer jax exposes shard_map at top level with the replication check
    named ``check_vma``; 0.4.x only has jax.experimental.shard_map with the
    same knob named ``check_rep``. Every mesh-step maker routes through
    this wrapper so the supported jax range is decided in one place."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


@dataclass(frozen=True)
class MeshPlan:
    """A mesh + the named shardings the train step uses."""

    mesh: Mesh
    axis: str = "dp"

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    def sharded(self, *axes: Optional[str]) -> NamedSharding:
        """NamedSharding partitioning the given positional axes; e.g.
        ``plan.sharded(plan.axis)`` shards array axis 0 over dp."""
        return NamedSharding(self.mesh, P(*axes))

    @property
    def table_sharding(self) -> NamedSharding:
        """[n_shards, capacity, width] pass table: axis 0 over dp."""
        return self.sharded(self.axis)

    @property
    def batch_sharding(self) -> NamedSharding:
        """Per-device-leading batch arrays [n_dev, ...]: axis 0 over dp."""
        return self.sharded(self.axis)

    @property
    def replicated(self) -> NamedSharding:
        return self.sharded()


def make_mesh(
    n_devices: Optional[int] = None,
    axis: str = "dp",
    devices: Optional[Sequence[Any]] = None,
) -> MeshPlan:
    """Build the 1-D CTR mesh over the first ``n_devices`` devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if n_devices > len(devices):
        raise ValueError(f"asked for {n_devices} devices, have {len(devices)}")
    mesh = Mesh(np.asarray(devices[:n_devices]), (axis,))
    return MeshPlan(mesh=mesh, axis=axis)


def make_mesh_2d(
    n_pp: int,
    n_dp: int,
    axes: Sequence[str] = ("pp", "dp"),
    devices: Optional[Sequence[Any]] = None,
) -> MeshPlan:
    """A 2-D (pipeline x data) mesh: pipeline stages along ``axes[0]``,
    data-parallel replicas of each stage along ``axes[1]``.

    The returned plan's ``axis`` is the dp axis (batch/table machinery
    keys off it); the pipeline step takes the pp axis via its spec. The
    reference composes pipeline sections with data parallelism the same
    way (PipelineTrainer sections x fleet DP ranks)."""
    if n_pp < 1 or n_dp < 1:
        raise ValueError(f"mesh needs n_pp >= 1 and n_dp >= 1, got ({n_pp}, {n_dp})")
    explicit = devices is not None
    if devices is None:
        devices = jax.devices()
    need = n_pp * n_dp
    if need > len(devices):
        raise ValueError(f"asked for {need} devices, have {len(devices)}")
    grid = None
    if not explicit and need == len(devices):
        # ICI-aware layout: on real hardware the ppermute hops of the pp
        # axis should ride nearest-neighbor links, which a raw enumeration
        # reshape does not guarantee
        try:
            from jax.experimental import mesh_utils

            grid = mesh_utils.create_device_mesh((n_pp, n_dp), devices=devices)
        except Exception:
            # the raw-enumeration fallback below is correct but loses the
            # ICI-aware layout — count it so a fleet silently training on
            # suboptimal pp hops is visible in the stats
            from paddlebox_tpu.utils.monitor import STAT_ADD

            STAT_ADD("mesh.device_mesh_fallbacks")
            grid = None
    if grid is None:
        grid = np.asarray(devices[:need]).reshape(n_pp, n_dp)
    mesh = Mesh(grid, tuple(axes))
    return MeshPlan(mesh=mesh, axis=axes[1])


def put_sharded(plan: MeshPlan, x: Any) -> jax.Array:
    """Host array -> device array sharded on axis 0 over the mesh.

    Multi-host aware: when the mesh spans processes, ``x`` may be either
    the GLOBAL array (each process contributes its own row block, assuming
    the 1-D mesh orders devices by process — jax.devices() order) or just
    this process's LOCAL block ``[n_local_dev, ...]`` (the shape a
    DistributedWorkingSet finalize returns); both assemble into one global
    jax.Array without any cross-host transfer of remote rows.
    """
    sh = plan.batch_sharding
    if jax.process_count() == 1:
        return jax.device_put(x, sh)
    n = plan.n_devices
    per = n // jax.process_count()

    def place(leaf):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            # already a global array (e.g. opt state carried across passes):
            # re-placing to the same sharding is a no-op, and np.asarray
            # would crash on its non-addressable shards
            return jax.device_put(leaf, sh)
        leaf = np.asarray(leaf)
        if leaf.shape[0] == n:
            local = leaf[jax.process_index() * per : (jax.process_index() + 1) * per]
        elif leaf.shape[0] == per:
            local = leaf
        else:
            raise ValueError(
                f"put_sharded: leading dim {leaf.shape[0]} is neither the "
                f"global device count {n} nor this host's local count {per}"
            )
        return jax.make_array_from_process_local_data(
            sh, np.ascontiguousarray(local), (n,) + leaf.shape[1:]
        )

    return jax.tree.map(place, x)


def put_per_device_copies(plan: MeshPlan, arr: np.ndarray) -> jax.Array:
    """THIS process's host array, copied onto each of its local devices, as
    a global ``[n_devices, *arr.shape]`` array sharded on the device axis.

    The multi-host resident feed's placement: each host's pass arrays
    (row stream, counts, labels) differ, so they cannot be replicated —
    instead every device carries its own host's copy and shard_map hands
    each device a ``[1, ...]`` block. All processes must pass arrays of
    the SAME (padded/locksteped) shape."""
    arr = np.ascontiguousarray(arr)
    sh = NamedSharding(plan.mesh, P(plan.axis, *([None] * arr.ndim)))
    pid = jax.process_index()
    local = [d for d in plan.mesh.devices.flat if d.process_index == pid]
    shards = [jax.device_put(arr[None], d) for d in local]
    return jax.make_array_from_single_device_arrays(
        (plan.n_devices,) + arr.shape, sh, shards
    )


def put_axis1_blocks(plan: MeshPlan, local: np.ndarray) -> jax.Array:
    """Local ``[K, n_local_dev, ...]`` blocks -> global ``[K, n_dev, ...]``
    sharded on axis 1 (the resident feed's per-chunk index blocks: the
    scan axis stays whole, devices split)."""
    sh = NamedSharding(
        plan.mesh, P(None, plan.axis, *([None] * (local.ndim - 2)))
    )
    if jax.process_count() == 1:
        return jax.device_put(local, sh)
    n = plan.n_devices
    per = n // jax.process_count()
    if local.shape[1] != per:
        raise ValueError(
            f"put_axis1_blocks: axis-1 dim {local.shape[1]} != this host's "
            f"local device count {per}"
        )
    return jax.make_array_from_process_local_data(
        sh,
        np.ascontiguousarray(local),
        (local.shape[0], n) + local.shape[2:],
    )


def put_replicated(plan: MeshPlan, tree: Any) -> Any:
    """Replicate a pytree (dense params, opt state) on every device.

    Multi-host: every process must pass the same values (they are placed
    as fully-replicated global arrays)."""
    return jax.device_put(tree, plan.replicated)


def local_slice(plan: MeshPlan, x: jax.Array) -> np.ndarray:
    """This process's addressable row block of an axis-0-sharded array —
    the inverse of ``put_sharded``'s local form. Single-process: the whole
    array."""
    if jax.process_count() == 1:
        return np.asarray(x)
    shards = sorted(
        x.addressable_shards, key=lambda s: s.index[0].start or 0
    )
    return np.concatenate([np.asarray(s.data) for s in shards], axis=0)
