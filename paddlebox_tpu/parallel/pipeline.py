"""Pipeline parallelism: GPipe-style microbatch schedule over a 'pp' mesh axis.

TPU-native re-expression of the reference's pipeline stack — PipelineTrainer
+ SectionWorker (trainer.h:281-310, pipeline_trainer.cc:127) run each program
*section* on its own device, exchange activations with send_v2/recv_v2
(operators/collective/send_v2_op.cc), and schedule all microbatch forwards,
then all backwards, then one optimize pass (section_worker.cc:44-119).

Here the same structure compiles into ONE shard_map'd XLA program:

- each mesh position along ``axis_name`` holds ONE stage's params
  (stacked [n_stages, ...] pytree sharded on the pp axis);
- activations hop stages via ``lax.ppermute`` (the send_v2/recv_v2 analog,
  riding ICI) inside a ``lax.scan`` over n_micro + n_stages - 1 ticks —
  the classic fill/steady/drain rotation;
- the backward schedule needs no hand-writing: differentiating through the
  scan + ppermute replays the reverse permutes, which *is* the F-then-B
  microbatch schedule (with activation rematerialization per microbatch via
  jax.checkpoint on the stage, matching the reference's per-microbatch
  scopes rather than storing every stage activation);
- the optimize pass applies once per (global) batch on each stage's own
  params — grads never leave their stage, only activations move.

Stage contract: every stage maps [mb, H] -> [mb, H] at the ACTIVATION HOP
(static shapes keep the scan one XLA program), but stages need NOT be
uniform inside: ``hetero_mlp_stage_init`` pads arbitrary per-stage layer
counts and widths to [L, H, H] with exactness-preserving zero padding and
identity gates, matching the reference's arbitrary program cut points
(optimizer.py:5194) without giving up the single stacked-scan program.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from paddlebox_tpu.parallel.mesh import axis_size, MeshPlan, shard_map


@dataclass(frozen=True)
class PipelineSpec:
    n_micro: int  # microbatches per global batch (num_microbatches_ parity)
    axis_name: str = "pp"
    remat: bool = True  # re-run stage forward in backward (microbatch scopes)


def pipeline_forward(
    stage_apply: Callable,  # (stage_params, x[mb, H]) -> y[mb, H]
    spec: PipelineSpec,
    broadcast: bool = True,
) -> Callable:
    """Build ``fn(stage_params, x_micro) -> y_micro`` for use INSIDE a
    shard_map over the pp axis.

    ``x_micro`` [n_micro, mb, H] is consumed by stage 0. With ``broadcast``
    the returned ``y_micro`` [n_micro, mb, H] holds the last stage's outputs
    on EVERY device (masked psum) for uniform loss/metric reads — inference
    use. For TRAINING use ``broadcast=False`` (outputs stay zero off the
    last stage) and reduce the loss with a last-stage mask + scalar psum:
    broadcasting y first would route every stage's loss cotangent back
    through the psum and scale grads by n_stages.
    """
    apply = jax.checkpoint(stage_apply) if spec.remat else stage_apply

    def fn(stage_params: Any, x_micro: jnp.ndarray) -> jnp.ndarray:
        n = axis_size(spec.axis_name)
        idx = lax.axis_index(spec.axis_name)
        M = spec.n_micro
        T = M + n - 1
        perm = [(i, (i + 1) % n) for i in range(n)]
        zero = jnp.zeros_like(x_micro[0])

        def tick(buf, t):
            # stage 0 consumes microbatch t during the fill+steady window;
            # later stages consume the rotated buffer
            feed = lax.dynamic_index_in_dim(
                x_micro, jnp.minimum(t, M - 1), keepdims=False
            )
            x_in = jnp.where((idx == 0) & (t < M), feed, buf)
            y = apply(stage_params, x_in)
            # last stage emits microbatch t-(n-1) at tick t
            out = jnp.where((idx == n - 1) & (t >= n - 1), y, 0.0)
            return lax.ppermute(y, spec.axis_name, perm), out

        _, outs = lax.scan(tick, zero, jnp.arange(T))
        y_micro = outs[n - 1 :]  # [M, mb, H], nonzero only on last stage
        if not broadcast:
            return y_micro
        # broadcast last stage's outputs to every stage (masked psum): each
        # device contributed zeros except the last
        return lax.psum(y_micro, spec.axis_name)

    return fn


def make_pipeline_train_step(
    stage_apply: Callable,  # (stage_params, x[mb, H]) -> y[mb, H]
    loss_fn: Callable,  # (y[mb, H], target[mb, ...]) -> scalar mean loss
    dense_opt: optax.GradientTransformation,
    spec: PipelineSpec,
    plan: MeshPlan,
    dp_axis: Optional[str] = None,
) -> Callable:
    """Jitted ``step((params, opt_state), x_micro, targets) ->
    ((params, opt_state), loss)``.

    ``params``/``opt_state`` are stacked [n_stages, ...] pytrees sharded over
    the pp axis; ``x_micro`` [n_micro, mb, H] and ``targets`` [n_micro, mb, ...]
    are replicated (only stage 0 / the loss actually read them).

    ``dp_axis``: pipeline x data composition on a 2-D mesh
    (``make_mesh_2d``) — each pipeline replica trains its dp-shard of every
    microbatch (x_micro/targets split on the mb axis over dp), and stage
    grads pmean over dp before the local update, exactly the reference's
    PipelineTrainer-sections x fleet-DP-ranks layering.

    ``dense_opt`` may be a ``Zero1Optimizer`` over ``dp_axis``: each dp
    replica of a stage then holds 1/n_dp of that stage's optimizer moments
    and updates only its chunk (all_gather over dp rebuilds the full
    update) — pipeline x sharding, the fleet sharding meta-optimizer
    layered under PipelineTrainer sections. Bit-compatible with the plain
    inner optimizer for elementwise transforms.
    """
    from paddlebox_tpu.fleet.zero import Zero1Optimizer

    if spec.axis_name not in plan.mesh.axis_names:
        raise ValueError(
            f"PipelineSpec.axis_name {spec.axis_name!r} not a mesh axis "
            f"{plan.mesh.axis_names}; build the mesh with "
            f"make_mesh(n, axis={spec.axis_name!r})"
        )
    if dp_axis is not None and dp_axis not in plan.mesh.axis_names:
        raise ValueError(
            f"dp_axis {dp_axis!r} not a mesh axis {plan.mesh.axis_names}; "
            "build a 2-D mesh with make_mesh_2d(n_pp, n_dp)"
        )
    is_zero = isinstance(dense_opt, Zero1Optimizer)
    if is_zero:
        if dp_axis is None:
            raise ValueError(
                "pipeline ZeRO-1 shards optimizer state over the dp axis: "
                "pass dp_axis= on a pp x dp mesh"
            )
        dense_opt.check_axis(dp_axis, int(plan.mesh.shape[dp_axis]))
    fwd = pipeline_forward(stage_apply, spec, broadcast=False)
    ax = spec.axis_name

    def local_step(state, x_micro, targets):
        params, opt_state = state
        p_local = jax.tree.map(lambda x: x[0], params)
        # ZeRO-1 state carries a second (dp-sharded) leading axis
        o_local = jax.tree.map(
            (lambda x: x[0, 0]) if is_zero else (lambda x: x[0]), opt_state
        )

        def batch_loss(p):
            y = fwd(p, x_micro)  # [M, mb, H], zeros off the last stage
            per_mb = jax.vmap(loss_fn)(y, targets)  # [M]
            n = axis_size(ax)
            idx = lax.axis_index(ax)
            # LOCAL masked loss: only the last stage's output seeds a
            # cotangent; earlier stages still receive their grads through
            # the transposed ppermutes. Summing/psum-ing INSIDE the
            # differentiated function would seed every stage's copy and
            # scale grads by n_stages (psum's transpose is psum).
            return jnp.where(idx == n - 1, jnp.mean(per_mb), 0.0)

        loss_local, grads = jax.value_and_grad(batch_loss)(p_local)
        loss = lax.psum(loss_local, ax)  # reporting only, outside the grad
        if dp_axis is not None:
            # data-parallel replicas of this stage average their grads
            # (the NCCL allreduce between pipeline replicas); loss reports
            # the dp-mean too
            grads = jax.tree.map(lambda g: lax.pmean(g, dp_axis), grads)
            loss = lax.pmean(loss, dp_axis)
        # grads arrive on the stage that owns each parameter (autodiff of
        # ppermute routes them); the update pass is purely local —
        # SectionWorker's kOptimize-on-microbatch-0 parity. Under ZeRO-1
        # each dp replica updates only its chunk of this stage's params
        # (moments sharded 1/n_dp) and all_gathers the update over dp.
        if is_zero:
            updates, new_opt = dense_opt.update_local(grads, o_local, p_local)
        else:
            updates, new_opt = dense_opt.update(grads, o_local, p_local)
        new_p = optax.apply_updates(p_local, updates)
        new_state = (
            jax.tree.map(lambda x: x[None], new_p),
            jax.tree.map(
                (lambda x: x[None, None]) if is_zero else (lambda x: x[None]),
                new_opt,
            ),
        )
        return new_state, loss

    pp = P(ax)
    opt_spec = P(ax, dp_axis) if is_zero else pp
    rep = P()
    # microbatches split their mb axis over dp when composed
    data = rep if dp_axis is None else P(None, dp_axis)

    def step(state, x_micro, targets):
        params, opt_state = state
        specs_state = (
            jax.tree.map(lambda _: pp, params),
            jax.tree.map(lambda _: opt_spec, opt_state),
        )
        mapped = shard_map(
            local_step,
            mesh=plan.mesh,
            in_specs=(specs_state, data, data),
            out_specs=(specs_state, rep),
            check_vma=False,
        )
        return mapped(state, x_micro, targets)

    return jax.jit(step, donate_argnums=(0,))


def init_pipeline_state(
    plan: MeshPlan,
    stage_params: Sequence[Any],  # one pytree per stage, identical structure
    dense_opt: optax.GradientTransformation,
    axis: Optional[str] = None,
    dp_axis: Optional[str] = None,
) -> Tuple[Any, Any]:
    """Stack per-stage params along a leading pp-sharded axis + opt state.

    ``axis`` names the pipeline axis; defaults to the plan's axis (the 1-D
    pipeline mesh). On a 2-D pp x dp mesh pass the pp axis explicitly —
    stages shard over it and replicate over dp. With a ``Zero1Optimizer``
    (pass ``dp_axis`` too) the optimizer state gains a second leading axis
    [n_stages, n_dp, ...] sharded (pp, dp), so each dp replica physically
    holds 1/n_dp of its stage's moments."""
    from paddlebox_tpu.fleet.zero import Zero1Optimizer

    axis = axis or plan.axis
    n = int(plan.mesh.shape[axis])
    if len(stage_params) != n:
        raise ValueError(
            f"{len(stage_params)} stages for a {n}-stage {axis!r} axis"
        )
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params)
    sh = plan.sharded(axis)
    put = lambda t: jax.device_put(t, sh)
    if isinstance(dense_opt, Zero1Optimizer):
        if dp_axis is None:
            raise ValueError(
                "Zero1Optimizer pipeline state needs dp_axis= (pp x dp mesh)"
            )
        dense_opt.check_axis(dp_axis, int(plan.mesh.shape[dp_axis]))
        opt0 = jax.vmap(dense_opt.init_stacked)(stacked)  # [n_pp, n_dp, ...]
        sh_opt = plan.sharded(axis, dp_axis)
        return (
            jax.tree.map(put, stacked),
            jax.tree.map(lambda t: jax.device_put(t, sh_opt), opt0),
        )
    opt0 = jax.vmap(dense_opt.init)(stacked)
    return jax.tree.map(put, stacked), jax.tree.map(put, opt0)


# ---- heterogeneous stages via padded stacking ---------------------------
#
# The reference cuts ONE program at arbitrary points (optimizer.py:5194
# device_guard sections), so its stages have whatever shapes the cut
# produces. The stacked-scan design above wants one uniform [n_stages, ...]
# pytree — the TPU-native way to keep arbitrary cuts AND one XLA program is
# to pad every stage to the max layer count L and max width H:
#
#   * width padding is exact for matmul+bias+relu chains: padded weight
#     rows/cols and bias lanes are zero, so padded activation lanes stay
#     zero through the whole net and their cotangents die at the next
#     stage's zero weight rows — adam/sgd see zero grads and never move
#     the padding;
#   * layer-count padding uses a per-layer gate g in {0,1} (stop_gradient'd,
#     so it is carried in the params pytree but never trained):
#     w_eff = g*w + (1-g)*I and h' = g*relu(z) + (1-g)*z — a g=0 layer is
#     an exact identity with zero grads into its (w, b).
#
# Cost: the padded matmuls run at [H, H]; for MXU-tiled H (128/256) the
# padding rides lanes the systolic array would idle anyway.


def hetero_mlp_stage_init(
    rng, widths: Sequence[Sequence[int]]
) -> Tuple[List[Any], List[List[Tuple[np.ndarray, np.ndarray]]]]:
    """Per-stage params for a pipeline with DIFFERENT layer counts/widths.

    ``widths[s] = [d_0, d_1, ..., d_k]`` — stage s maps width d_0 to d_k
    through k relu layers. Consecutive stages must chain:
    ``widths[s][-1] == widths[s+1][0]``.

    Returns ``(stages, raw)``: ``stages`` are padded [L, H, H]/[L, H]/[L]
    pytrees (identical structure, ready for ``init_pipeline_state``), and
    ``raw`` holds the unpadded ``(w [d_in, d_out], b [d_out])`` numpy layers
    for building a sequential equality reference in tests.
    """
    for s in range(len(widths) - 1):
        if widths[s][-1] != widths[s + 1][0]:
            raise ValueError(
                f"stage {s} emits width {widths[s][-1]} but stage {s + 1} "
                f"consumes {widths[s + 1][0]}"
            )
    H = max(max(w) for w in widths)
    L = max(len(w) - 1 for w in widths)
    stages, raw = [], []
    for ws in widths:
        w_pad = np.zeros((L, H, H), np.float32)
        b_pad = np.zeros((L, H), np.float32)
        gate = np.zeros((L,), np.float32)
        layers = []
        for l in range(len(ws) - 1):
            d_in, d_out = ws[l], ws[l + 1]
            rng, k = jax.random.split(rng)
            w = np.asarray(
                jax.random.normal(k, (d_in, d_out)) / np.sqrt(d_in),
                np.float32,
            )
            b = np.zeros((d_out,), np.float32)
            w_pad[l, :d_in, :d_out] = w
            gate[l] = 1.0
            layers.append((w, b))
        stages.append({
            "w": jnp.asarray(w_pad),
            "b": jnp.asarray(b_pad),
            "g": jnp.asarray(gate),
        })
        raw.append(layers)
    return stages, raw


def hetero_mlp_stage_apply(stage_params, x):
    """[mb, H] -> [mb, H] over gated padded layers; exact identity where
    g=0, exact relu-MLP where g=1 (see the padding invariants above)."""
    eye = jnp.eye(x.shape[-1], dtype=x.dtype)

    def layer(h, wbg):
        w, b, g = wbg
        g = lax.stop_gradient(g)  # structural gate, never trained
        z = h @ (g * w + (1.0 - g) * eye) + g * b
        return g * jax.nn.relu(z) + (1.0 - g) * z, None

    h, _ = lax.scan(
        layer, x, (stage_params["w"], stage_params["b"], stage_params["g"])
    )
    return h


# ---- a simple homogeneous MLP stage for models/tests --------------------


def mlp_stage_init(rng, hidden: int, layers_per_stage: int, n_stages: int):
    """Per-stage params for a uniform [mb, H] -> [mb, H] relu MLP pipeline."""
    out = []
    for s in range(n_stages):
        ws, bs = [], []
        for l in range(layers_per_stage):
            rng, k = jax.random.split(rng)
            ws.append(jax.random.normal(k, (hidden, hidden)) * (1.0 / np.sqrt(hidden)))
            bs.append(jnp.zeros((hidden,)))
        out.append({"w": jnp.stack(ws), "b": jnp.stack(bs)})
    return out


def mlp_stage_apply(stage_params, x):
    def layer(h, wb):
        w, b = wb
        return jax.nn.relu(h @ w + b), None

    h, _ = lax.scan(layer, x, (stage_params["w"], stage_params["b"]))
    return h
