"""Distributed tier: device mesh, sharded sparse table pull/push, collectives.

TPU-native replacement for the reference's NCCL/MPI/boxps communication stack
(SURVEY.md §2.3): a `jax.sharding.Mesh` plus XLA collectives over ICI/DCN
stand in for NCCLCommContext + the closed `boxps::MPICluster`/`PaddleShuffler`;
the sparse table is itself device-sharded, replacing the RPC parameter-server
tier entirely.
"""

from paddlebox_tpu.parallel.mesh import (
    MeshPlan,
    make_mesh,
    put_replicated,
    put_sharded,
)
from paddlebox_tpu.parallel.sharded_pullpush import (
    sharded_pull,
    sharded_push,
)
from paddlebox_tpu.parallel.pipeline import (
    PipelineSpec,
    hetero_mlp_stage_apply,
    hetero_mlp_stage_init,
    init_pipeline_state,
    make_pipeline_train_step,
    pipeline_forward,
)
from paddlebox_tpu.parallel.ring_attention import (
    ring_attention,
    ulysses_attention,
)

__all__ = [
    "MeshPlan",
    "make_mesh",
    "put_replicated",
    "put_sharded",
    "sharded_pull",
    "sharded_push",
    "PipelineSpec",
    "hetero_mlp_stage_apply",
    "hetero_mlp_stage_init",
    "pipeline_forward",
    "make_pipeline_train_step",
    "init_pipeline_state",
    "ring_attention",
    "ulysses_attention",
]
