"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference (a 2020 CTR stack) has no attention-model sharding — SURVEY.md
§5 records the absence. This module is the framework's long-context tier,
new TPU-first scope: attention over sequences longer than one chip's HBM by
sharding the sequence axis across the mesh.

Two standard schemes (PAPERS.md: Ring Attention / blockwise parallel
transformers; DeepSpeed-Ulysses):

- ``ring_attention``: q stays put; (k, v) blocks rotate around the ring via
  ``lax.ppermute`` while a running flash-style log-sum-exp accumulator
  merges each block's contribution. Communication is neighbor-only (rides
  ICI), overlapping with the block matmuls; memory is O(S_local).

- ``ulysses_attention``: two ``all_to_all``s re-partition
  [seq-sharded, all heads] -> [full seq, head-sharded], run exact local
  attention per head, and swap back. Cheaper compute layout when
  n_heads >= n_devices; all-to-all traffic instead of neighbor traffic.

Both run INSIDE shard_map over the sequence-parallel axis and are exact
(not approximations) — verified against single-device full attention in
tests/test_ring_attention.py.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from paddlebox_tpu.parallel.mesh import axis_size

_NEG_INF = -1e30  # finite "-inf": keeps exp()=0 without NaN max/subtraction


def _block_scores(q, k, scale):
    # q [B, Sq, H, D], k [B, Sk, H, D] -> [B, H, Sq, Sk]; f32 accumulation
    # keeps the log-sum-exp exact for bf16 inputs (MXU-friendly: bf16 in,
    # f32 out is the native TPU matmul mode)
    return (
        jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
        * scale
    )


def _causal_mask(q_pos, k_pos):
    # [Sq, Sk] True where attention is allowed (k position <= q position)
    return q_pos[:, None] >= k_pos[None, :]


def ring_attention(
    q: jnp.ndarray,  # [B, S_local, H, D] this device's query block
    k: jnp.ndarray,  # [B, S_local, H, D]
    v: jnp.ndarray,  # [B, S_local, H, D]
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
    remat: bool = True,
) -> jnp.ndarray:
    """Exact attention over the full (sharded) sequence. [B, S_local, H, D].

    Sequence layout: device i holds global positions
    [i*S_local, (i+1)*S_local); with ``causal`` the mask applies to global
    positions, so fully-masked future blocks contribute exactly zero.

    ``remat`` checkpoints each ring step's body so the backward replays
    blocks instead of saving every step's [Sq, Sk] probability residual.
    The scan still saves each step's incoming (k, v) carry — residuals are
    O(S_global * D) per device with remat vs O(S_local * S_global +
    S_global * D) without; remat removes the quadratic term (the
    blockwise-parallel paper's recompute trade), not the kv carries.
    """
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, S, H, D = q.shape
    scale = scale if scale is not None else D ** -0.5
    perm = [(i, (i + 1) % n) for i in range(n)]

    q_pos = idx * S + jnp.arange(S)

    def body(carry, t):
        kv, o, m, l = carry  # kv=(k,v) currently held; o/m/l accumulators
        kt, vt = kv
        # the block arriving at step t originated on device (idx - t) mod n
        src = (idx - t) % n
        s = _block_scores(q, kt, scale)  # [B, H, Sq, Sk]
        if causal:
            k_pos = src * S + jnp.arange(S)
            allowed = _causal_mask(q_pos, k_pos)  # [Sq, Sk]
            s = jnp.where(allowed[None, None], s, _NEG_INF)
        m_blk = jnp.max(s, axis=-1)  # [B, H, Sq]
        m_new = jnp.maximum(m, m_blk)
        # renormalize previous accumulators to the new running max
        alpha = jnp.exp(m - m_new)  # [B, H, Sq]
        p = jnp.exp(s - m_new[..., None])  # [B, H, Sq, Sk]
        if causal:  # exp(NEG_INF - m) underflows to 0 already; keep exact
            p = jnp.where(allowed[None, None], p, 0.0)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vt, preferred_element_type=jnp.float32
        )
        kv_next = jax.tree.map(
            lambda x: lax.ppermute(x, axis_name, perm), (kt, vt)
        )
        return (kv_next, o_new, m_new, l_new), None

    o0 = jnp.zeros((B, H, S, D), jnp.float32)
    m0 = jnp.full((B, H, S), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, S), jnp.float32)
    if remat:
        # backward replays each ring step (block math AND its ppermute —
        # extra ICI traffic, the blockwise-parallel recompute trade) in
        # exchange for O(S_local) residual memory; all devices replay the
        # same schedule, so the re-run collectives stay matched
        body = jax.checkpoint(body)
    (_, o, m, l), _ = lax.scan(body, ((k, v), o0, m0, l0), jnp.arange(n))
    # l == 0 can only happen for rows with NO allowed keys; causal layouts
    # always allow self-attention, so guard only against degenerate inputs
    o = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.einsum("bhqd->bqhd", o).astype(q.dtype)


def ulysses_attention(
    q: jnp.ndarray,  # [B, S_local, H, D]
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
    remat: bool = True,
) -> jnp.ndarray:
    """DeepSpeed-Ulysses style: all_to_all to [full seq, H/n heads], exact
    attention, all_to_all back. Requires H % axis_size == 0."""
    n = axis_size(axis_name)
    B, S, H, D = q.shape
    if H % n != 0:
        raise ValueError(f"n_heads {H} not divisible by axis size {n}")
    scale = scale if scale is not None else D ** -0.5

    def seq_to_head(x):  # [B, S, H, D] -> [B, S*n, H/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def head_to_seq(x):  # [B, S*n, H/n, D] -> [B, S, H, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qf, kf, vf = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    # flash-style chunked local attention: the naive route materializes
    # [B, H/n, S*n, S*n] scores — O(S²) memory that defeats sequence
    # parallelism at exactly the lengths it exists for. Stream key chunks
    # through the same running log-sum-exp the ring body uses; memory is
    # O(S*n · chunk).
    of = _flash_local(qf, kf, vf, scale, causal, remat=remat)  # [B, S*n, H/n, D]
    return head_to_seq(of.astype(q.dtype))


def _flash_local(
    q: jnp.ndarray,  # [B, Sq, H, D]
    k: jnp.ndarray,  # [B, Sk, H, D]
    v: jnp.ndarray,
    scale: float,
    causal: bool,
    kv_chunk: int = 512,
    remat: bool = True,
) -> jnp.ndarray:
    """Exact single-device attention, keys streamed in chunks (flash-style
    online softmax). Returns [B, Sq, H, D] in f32 accumulation. Positions
    are global 0..S (q and k share the origin), so the causal mask matches
    the unchunked computation bit-for-bit in masking decisions.

    ``remat`` checkpoints each chunk's body: without it, autodiff of the
    scan saves every chunk's [B, H, Sq, chunk] probability block — O(S²)
    residual memory, the exact wall chunking exists to avoid. With it the
    backward replays each chunk (flash-attention's standard trade); the
    full k/v (O(S*D)) remain resident either way."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    # largest divisor of Sk that fits the target chunk (shapes are static
    # at trace time, so this is plain Python)
    chunk = min(kv_chunk, Sk)
    while Sk % chunk:
        chunk -= 1
    n_chunks = Sk // chunk
    q_pos = jnp.arange(Sq)

    def body(carry, t):
        o, m, l = carry
        kt = lax.dynamic_slice_in_dim(k, t * chunk, chunk, axis=1)
        vt = lax.dynamic_slice_in_dim(v, t * chunk, chunk, axis=1)
        s = _block_scores(q, kt, scale)  # [B, H, Sq, chunk]
        if causal:
            k_pos = t * chunk + jnp.arange(chunk)
            allowed = _causal_mask(q_pos, k_pos)
            s = jnp.where(allowed[None, None], s, _NEG_INF)
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        if causal:
            p = jnp.where(allowed[None, None], p, 0.0)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vt, preferred_element_type=jnp.float32
        )
        return (o_new, m_new, l_new), None

    if remat:
        body = jax.checkpoint(body)
    o0 = jnp.zeros((B, H, Sq, D), jnp.float32)
    m0 = jnp.full((B, H, Sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Sq), jnp.float32)
    (o, m, l), _ = lax.scan(body, (o0, m0, l0), jnp.arange(n_chunks))
    o = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.einsum("bhqd->bqhd", o)
