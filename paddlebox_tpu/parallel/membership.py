"""Key-ownership epochs: explicit, versioned shard-range -> rank maps.

The reference's closed ``boxps::MPICluster`` owns cluster membership: node
loss and key re-placement never surface in the open code. Our open rebuild
had membership frozen at construction — ownership was the *implicit*
arithmetic ``rank * shards_per_host`` in DistributedWorkingSet, carrier
splice pinning, trainer rank checks, and checkpoint shard naming — so a
dead peer killed the whole day. This module makes ownership an explicit,
versioned value:

- :class:`OwnershipMap` — contiguous shard ranges per live rank (largest-
  remainder apportionment, so ``n_mesh_shards % n_hosts`` need not be 0),
  stamped with an **ownership epoch** that bumps on every membership or
  placement change. Maps are value objects: ``shrink`` (drop dead ranks)
  and ``rebalance`` (same ranks, new boundaries) return new maps at
  epoch+1; every rank derives the identical successor map from the same
  inputs, so during steady state no map needs to ride the wire.
- :func:`agree_membership` — the survivor verdict round. The proposed dead
  set is encoded in the collective TAG itself: completing an allgather on
  ``ctl:member:<seq>:<dead>`` proves every live rank proposed exactly that
  set (ranks with divergent views fail into PeerDeadError, union the new
  evidence, and re-enter with the bigger set — convergence is bounded by
  the rank count).
- :func:`sync_map` — the map-base agreement that follows: survivors
  allgather their CURRENT map and every rank adopts the highest-epoch one.
  A rank whose membership round was interrupted mid-install (a second
  death) re-enters one map behind its peers; without this round each side
  would derive a successor from a different base — same epoch number,
  different boundaries — and the epoch checks could never tell. Two maps
  at the same epoch with different content are split-brain and raise.
- :func:`adopt_dead_shards` — a survivor pulls the shard ranges it gained
  from the dead rank's last manifest-verified checkpoint (the PR 1/PR 7
  CRC-verified resume path) into its own live table. Pure upsert: a retry
  after a mid-adopt crash lands bitwise-identical rows. When the dead
  chain's recorded ownership epoch predates the current map — the rank
  died before its post-flip re-anchor save landed — the ranges it gained
  in that flip are filled from the PREVIOUS owners' chains (``prev_map``):
  a flip is base-saved before any training resumes, so a stale chain
  means no pass confirmed since the flip and the previous owner's durable
  copy is bitwise the boundary state.
- :func:`plan_rebalance` / :func:`plan_moves` / shard-row wire codec — the
  planned-migration half: boundaries recut at cumulative-load quantiles,
  moving ranges streamed owner->owner over PBTX v3 (codec-framed, CRC'd,
  epoch-tagged so stale frames are unreceivable), both sides flipping to
  the new epoch atomically at a pass boundary.

Ownership filtering is the correctness backbone: keys are only ever READ
through the current map (exchange routing, writeback, digests, adoption),
so a stale copy left behind on a migration source or a dead rank's disk is
unreachable — no tombstones, no deletion protocol (see docs/ROBUSTNESS.md,
"Elastic membership & key migration").
"""

from __future__ import annotations

import json
import struct
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu.parallel.transport import PeerDeadError
from paddlebox_tpu.utils.faultinject import fire
from paddlebox_tpu.utils.monitor import STAT_ADD


def apportion(n_items: int, n_parts: int) -> List[int]:
    """Largest-remainder contiguous split: the first ``n_items % n_parts``
    parts get the ceiling, the rest the floor. Reproduces the old even
    split exactly when divisible."""
    if n_parts <= 0:
        raise ValueError(f"cannot apportion over {n_parts} parts")
    base, rem = divmod(int(n_items), int(n_parts))
    return [base + 1 if i < rem else base for i in range(n_parts)]


class OwnershipMap:
    """Versioned map: contiguous mesh-shard ranges -> live ranks.

    ``starts`` has ``len(live_ranks) + 1`` monotone boundaries with
    ``starts[0] == 0`` and ``starts[-1] == n_mesh_shards``; live rank
    ``live_ranks[i]`` owns shards ``[starts[i], starts[i+1])`` (possibly
    empty). Immutable by convention: membership/placement changes go
    through :meth:`shrink` / :meth:`rebalance`, which bump ``epoch``.
    """

    __slots__ = ("n_mesh_shards", "live_ranks", "starts", "epoch")

    def __init__(
        self,
        n_mesh_shards: int,
        live_ranks: Iterable[int],
        starts: Sequence[int],
        epoch: int = 0,
    ):
        live = tuple(sorted(int(r) for r in live_ranks))
        bounds = tuple(int(s) for s in starts)
        if not live:
            raise ValueError("ownership map needs at least one live rank")
        if len(set(live)) != len(live):
            raise ValueError(f"duplicate ranks in live set {live}")
        if len(bounds) != len(live) + 1:
            raise ValueError(
                f"{len(live)} live ranks need {len(live) + 1} boundaries, "
                f"got {len(bounds)}"
            )
        if bounds[0] != 0 or bounds[-1] != int(n_mesh_shards):
            raise ValueError(
                f"boundaries {bounds} must span [0, {n_mesh_shards}]"
            )
        if any(b > a for a, b in zip(bounds[1:], bounds)):
            raise ValueError(f"boundaries {bounds} must be non-decreasing")
        self.n_mesh_shards = int(n_mesh_shards)
        self.live_ranks = live
        self.starts = bounds
        self.epoch = int(epoch)

    # ---- construction ----------------------------------------------------

    @classmethod
    def even(cls, n_mesh_shards: int, n_ranks: int, epoch: int = 0) -> "OwnershipMap":
        """Canonical largest-remainder split over ranks 0..n_ranks-1."""
        return cls.even_over(n_mesh_shards, range(n_ranks), epoch)

    @classmethod
    def even_over(
        cls, n_mesh_shards: int, ranks: Iterable[int], epoch: int = 0
    ) -> "OwnershipMap":
        """Largest-remainder split over an arbitrary live set — the
        initial map of a fleet smaller than its endpoint list (slots
        reserved for future joiners)."""
        live = sorted(int(r) for r in ranks)
        counts = apportion(n_mesh_shards, len(live))
        starts = [0]
        for c in counts:
            starts.append(starts[-1] + c)
        return cls(n_mesh_shards, live, starts, epoch)

    def shrink(self, dead: Iterable[int]) -> "OwnershipMap":
        """Successor map without ``dead``, epoch bumped. Deterministic —
        every rank derives the same map from the same inputs.

        Minimal movement by design: every survivor KEEPS its exact range,
        and each dead gap is split at its midpoint between the flanking
        survivors (a leading gap goes wholly to the first survivor, a
        trailing gap to the last). So the only shard ranges that change
        owner came from dead ranks — the checkpoint-adoption path covers
        every move, and no live-to-live state transfer is ever needed
        during a death. Load skew a shrink introduces is the planned
        migration path's job to fix at a later pass boundary."""
        gone = set(int(d) for d in dead)
        survivors = [r for r in self.live_ranks if r not in gone]
        if not survivors:
            raise ValueError(f"shrinking {self.live_ranks} by {sorted(gone)} leaves no ranks")
        ranges = [self.range_of(r) for r in survivors]
        starts = [0]
        for (_, prev_hi), (nxt_lo, _) in zip(ranges, ranges[1:]):
            starts.append((prev_hi + nxt_lo) // 2)
        starts.append(self.n_mesh_shards)
        return OwnershipMap(self.n_mesh_shards, survivors, starts, self.epoch + 1)

    def rebalance(self, starts: Sequence[int]) -> "OwnershipMap":
        """Successor map with the same live set and new boundaries."""
        return OwnershipMap(self.n_mesh_shards, self.live_ranks, starts, self.epoch + 1)

    def grow(self, joiner: int, shard_loads=None) -> "OwnershipMap":
        """Successor map WITH ``joiner``, epoch bumped — the dual of
        :meth:`shrink`. Deterministic from (map, joiner, loads): every
        rank derives the identical successor, so only the decision to
        admit rides the wire, never the map itself.

        Minimal movement by design: only the joiner's flanking neighbors
        in rank order cede shards — every other survivor KEEPS its exact
        range, so the only live-to-live transfers a join ever needs are
        flank -> joiner, streamed through the existing stage-then-commit
        ``migrate_ranges`` path. The carve is hot-load-aware rather than
        key-count-aware: the combined flanking window is recut at
        cumulative-load quantiles (the :func:`plan_rebalance` sweep
        applied to the neighborhood), so the joiner takes the load-heavy
        middle of its neighborhood and the flanks keep balanced rims.
        ``shard_loads`` is a length-``n_mesh_shards`` hotness/occupancy
        vector (the supervisor feeds decayed show counts + tier
        occupancy); None or all-zero falls back to a uniform carve."""
        j = int(joiner)
        if j < 0:
            raise ValueError(f"joiner rank {j} must be >= 0")
        if j in self.live_ranks:
            raise ValueError(f"rank {j} is already live in {self!r}")
        if shard_loads is None:
            loads = np.ones(self.n_mesh_shards, dtype=np.float64)
        else:
            loads = np.asarray(shard_loads, dtype=np.float64)
            if len(loads) != self.n_mesh_shards:
                raise ValueError(
                    f"need {self.n_mesh_shards} shard loads, got {len(loads)}"
                )
        live = sorted(self.live_ranks + (j,))
        i = live.index(j)
        left = live[i - 1] if i > 0 else None
        right = live[i + 1] if i + 1 < len(live) else None
        # the carve window: the flanking survivors' combined contiguous
        # range (one flank when the joiner lands at either end)
        win_lo = self.range_of(left)[0] if left is not None else self.range_of(right)[0]
        win_hi = self.range_of(right)[1] if right is not None else self.range_of(left)[1]
        parts = [r for r in (left, j, right) if r is not None]
        cuts = [win_lo]
        if win_hi > win_lo:
            wloads = loads[win_lo:win_hi]
            if float(wloads.sum()) <= 0:
                wloads = np.ones(win_hi - win_lo, dtype=np.float64)
            wtotal = float(wloads.sum())
            cum = np.cumsum(wloads)
            for k in range(1, len(parts)):
                rel = int(
                    np.searchsorted(cum, wtotal * k / len(parts), side="left")
                ) + 1
                cut = win_lo + rel
                if win_hi - win_lo >= len(parts):
                    # load mass piled at either edge of the window must not
                    # starve a part into an empty range: when the window is
                    # wide enough, every part (joiner included) lands at
                    # least one shard
                    cut = min(max(cut, win_lo + k), win_hi - (len(parts) - k))
                cuts.append(min(max(cut, cuts[-1]), win_hi))
        else:
            # zero-width window (flanks own nothing): the joiner starts
            # empty and the planned-migration path fills it in later
            cuts.extend([win_lo] * (len(parts) - 1))
        cuts.append(win_hi)
        ranges = {
            r: self.range_of(r)
            for r in self.live_ranks
            if r != left and r != right
        }
        for part_rank, lo, hi in zip(parts, cuts, cuts[1:]):
            ranges[part_rank] = (lo, hi)
        starts = [ranges[r][0] for r in live]
        starts.append(self.n_mesh_shards)
        return OwnershipMap(self.n_mesh_shards, live, starts, self.epoch + 1)

    # ---- queries ---------------------------------------------------------

    def is_live(self, rank: int) -> bool:
        return int(rank) in self.live_ranks

    def range_of(self, rank: int) -> Tuple[int, int]:
        """[lo, hi) shard range this rank owns."""
        i = self.live_ranks.index(int(rank))
        return self.starts[i], self.starts[i + 1]

    def n_owned(self, rank: int) -> int:
        lo, hi = self.range_of(rank)
        return hi - lo

    def owner_of_shard(self, shards) -> np.ndarray:
        """Vectorized shard -> owning rank (int64 array)."""
        s = np.asarray(shards, dtype=np.int64)
        inner = np.asarray(self.starts[1:], dtype=np.int64)
        idx = np.searchsorted(inner, s, side="right")
        return np.asarray(self.live_ranks, dtype=np.int64)[idx]

    # ---- value semantics / wire form ------------------------------------

    def fingerprint(self) -> str:
        """Short content hash over boundaries + live set + epoch. Rides in
        verdict tags so two ranks holding divergent maps (same epoch,
        different boundaries) stall loudly instead of committing a
        split-brain flip."""
        import zlib as _zlib

        return f"{_zlib.crc32(self.to_json().encode()):08x}"

    def to_json(self) -> str:
        return json.dumps(
            {
                "n_mesh_shards": self.n_mesh_shards,
                "live_ranks": list(self.live_ranks),
                "starts": list(self.starts),
                "epoch": self.epoch,
            },
            sort_keys=True,
        )

    @classmethod
    def from_json(cls, blob: str) -> "OwnershipMap":
        d = json.loads(blob)
        return cls(d["n_mesh_shards"], d["live_ranks"], d["starts"], d["epoch"])

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, OwnershipMap)
            and self.n_mesh_shards == other.n_mesh_shards
            and self.live_ranks == other.live_ranks
            and self.starts == other.starts
            and self.epoch == other.epoch
        )

    def __hash__(self):
        return hash((self.n_mesh_shards, self.live_ranks, self.starts, self.epoch))

    def __repr__(self) -> str:
        return (
            f"OwnershipMap(epoch={self.epoch}, live={list(self.live_ranks)}, "
            f"starts={list(self.starts)})"
        )


# ---- membership verdict round -------------------------------------------


def agree_membership(
    transport, seq, timeout: Optional[float] = None
) -> List[int]:
    """Converge every survivor on one dead-rank set; returns it sorted.

    The proposal rides in the tag: an allgather on
    ``ctl:member:<seq>:<dead>`` completes only when every transport-live
    rank sent a frame under exactly that tag — i.e. proposed exactly that
    dead set. A survivor with extra evidence is, from this rank's view, a
    rank that died mid-round (its frame never arrives, the detector fires)
    — the PeerDeadError's ``dead`` list IS the missing evidence, so the
    proposal unions it and re-enters. Convergence is bounded by the rank
    count: each retry strictly grows the dead set.

    Tags carry no ``@e`` suffix on purpose: the pass-epoch discard floor
    advances during the death handling itself, and membership control
    frames must survive it.
    """
    for _ in range(transport.n_ranks + 1):
        dead = sorted(transport.dead_peers())
        name = ",".join(str(d) for d in dead) if dead else "-"
        try:
            transport.allgather(b"", f"ctl:member:{seq}:{name}", timeout=timeout)
            return dead
        except PeerDeadError as e:
            transport.mark_dead(e.dead)
    raise PeerDeadError(
        f"rank {transport.rank}: membership agreement for seq {seq!r} did "
        f"not converge within {transport.n_ranks + 1} rounds",
        sorted(transport.dead_peers()),
    )


def sync_map(
    transport,
    seq,
    dead: Sequence[int],
    my_map: OwnershipMap,
    timeout: Optional[float] = None,
) -> OwnershipMap:
    """Converge every survivor on one base map before deriving a successor.

    Survivors allgather their CURRENT map (the one wire-crossing a map
    ever does) and adopt the highest-epoch one: a rank whose previous
    membership round was cut short by a second death re-enters one map
    behind its peers, and shrinking divergent bases would yield maps with
    the SAME epoch but DIFFERENT boundaries — undetectable by the epoch
    checks. The tag embeds the agreed dead set, so this round only runs
    between ranks that already converged in :func:`agree_membership`.
    Raises on two same-epoch maps with different content (split-brain —
    the migrate commit verdict is built to make this impossible).
    """
    name = ",".join(str(d) for d in sorted(dead)) if dead else "-"
    views = transport.allgather(
        my_map.to_json().encode(), f"ctl:mapsync:{seq}:{name}", timeout=timeout
    )
    best = my_map
    for v in views:
        if not v:
            continue  # membership-dead slots contribute b"" placeholders
        m = OwnershipMap.from_json(v.decode())
        if m.epoch > best.epoch:
            best = m
        elif m.epoch == best.epoch and m != best:
            raise RuntimeError(
                f"rank {transport.rank}: ownership split-brain — two maps "
                f"at epoch {m.epoch} with different boundaries: {best!r} "
                f"vs {m!r}"
            )
    return best


# ---- adoption (failure path) --------------------------------------------


def adopt_dead_shards(
    table,
    shared_root: str,
    dead_rank: int,
    old_map: OwnershipMap,
    new_map: OwnershipMap,
    my_rank: int,
    prev_map: Optional[OwnershipMap] = None,
) -> int:
    """Pull the shard range this rank gained from ``dead_rank``'s last
    manifest-verified checkpoint into ``table``; returns keys adopted.

    The source is the dead rank's own per-rank checkpoint root
    (:func:`paddlebox_tpu.train.checkpoint.rank_root`), replayed through
    the CRC-verified resume path into a scratch table, then filtered to
    the shards that moved to this rank. ``table.push`` is an upsert, so a
    crash mid-adopt retried lands bitwise-identical (FLT008 contract —
    fault site ``membership.adopt_shard``). A dead rank that never
    checkpointed (death before the first base save) adopts zero keys: the
    retried pass recreates them from the seeded deterministic init, which
    is exactly what a fresh shrunk-membership run does.

    ``prev_map`` (the map the LAST flip replaced, recorded by the
    supervisor at install time) closes the residual durability window:
    when the dead chain's recorded ownership epoch predates ``old_map``'s
    — the rank died during its own post-flip re-anchor save — the ranges
    it gained in that flip are absent from (or stale leftovers in) its
    chain. Because every flip base-saves before training resumes, a stale
    chain implies no pass confirmed since the flip, so the PREVIOUS
    owners' durable chains hold the exact boundary state; those pieces
    are filled from them, overwriting any frozen leftover copies the dead
    chain contributed.
    """
    from paddlebox_tpu.table.sparse_table import HostSparseTable, key_to_shard
    from paddlebox_tpu.train.checkpoint import CheckpointManager, rank_root

    dead_lo, dead_hi = old_map.range_of(dead_rank)
    my_lo, my_hi = new_map.range_of(my_rank)
    lo, hi = max(dead_lo, my_lo), min(dead_hi, my_hi)
    if lo >= hi:
        return 0
    scratch = HostSparseTable(table.layout, table.opt, n_shards=table.n_shards, seed=0)
    ck = CheckpointManager(rank_root(shared_root, dead_rank))
    state = ck.resume(scratch)
    # -1 marks a cold chain: strictly older than any real epoch, so the
    # fallback below also covers a rank that died before its FIRST save
    # but after gaining ranges in a flip
    chain_epoch = -1 if state is None else int(state.get("ownership_epoch", 0))
    keys = np.zeros(0, dtype=np.uint64)
    if state is not None:
        keys = scratch.keys()
        shards = key_to_shard(keys, new_map.n_mesh_shards)
        keys = np.sort(keys[(shards >= lo) & (shards < hi)])
    fire("membership.adopt_shard")
    if len(keys):
        table.push(keys, scratch.pull_or_create(keys))
    n = int(len(keys))
    if prev_map is not None and chain_epoch < old_map.epoch:
        for prev_owner in prev_map.live_ranks:
            plo, phi = prev_map.range_of(prev_owner)
            plo, phi = max(plo, lo), min(phi, hi)
            if plo >= phi or int(prev_owner) == int(dead_rank):
                # the piece the dead rank ALREADY owned at its chain epoch
                # is authoritatively covered by its own chain above
                continue
            fb = HostSparseTable(
                table.layout, table.opt, n_shards=table.n_shards, seed=0
            )
            src = CheckpointManager(rank_root(shared_root, prev_owner))
            if src.resume(fb) is None:
                continue
            fkeys = fb.keys()
            fsh = key_to_shard(fkeys, new_map.n_mesh_shards)
            fkeys = np.sort(fkeys[(fsh >= plo) & (fsh < phi)])
            fire("membership.adopt_shard")
            if len(fkeys):
                # overwrite: within this piece the previous owner's chain
                # is fresher than anything the stale dead chain held
                table.push(fkeys, fb.pull_or_create(fkeys))
            n += int((~np.isin(fkeys, keys)).sum())
            STAT_ADD("membership.adopt_fallbacks")
    STAT_ADD("membership.adopts")
    STAT_ADD("membership.adopted_keys", n)
    return n


# ---- planned migration (boundary path) ----------------------------------

# shard-row transfer header: n_keys, row width (floats)
_XFER = struct.Struct("<QI")


def encode_shard_rows(keys: np.ndarray, rows: np.ndarray) -> bytes:
    """Wire form of a moving key range: header + sorted uint64 keys +
    float32 rows. Rides a PBTX v3 data frame, so codec framing, CRC32 and
    epoch tagging come from the transport."""
    keys = np.ascontiguousarray(keys, dtype=np.uint64)
    rows = np.ascontiguousarray(rows, dtype=np.float32)
    width = rows.shape[1] if rows.ndim == 2 else 0
    return _XFER.pack(len(keys), width) + keys.tobytes() + rows.tobytes()


def decode_shard_rows(payload: bytes) -> Tuple[np.ndarray, np.ndarray]:
    n, width = _XFER.unpack_from(payload)
    off = _XFER.size
    keys = np.frombuffer(payload, dtype=np.uint64, count=n, offset=off)
    rows = np.frombuffer(
        payload, dtype=np.float32, count=n * width, offset=off + n * 8
    ).reshape(n, width)
    return keys, rows


def plan_rebalance(
    omap: OwnershipMap, shard_loads: np.ndarray, skew_threshold: float
) -> Optional[OwnershipMap]:
    """Propose a successor map when per-rank load skew crosses the
    threshold; None when balanced enough or no load. Boundaries are recut
    at cumulative-load quantiles (contiguous weighted apportionment, the
    sweep-apportion idea applied to rows instead of shards). Deterministic
    from ``shard_loads`` — every rank holding the same global load vector
    derives the identical plan."""
    loads = np.asarray(shard_loads, dtype=np.float64)
    if len(loads) != omap.n_mesh_shards:
        raise ValueError(
            f"need {omap.n_mesh_shards} shard loads, got {len(loads)}"
        )
    total = float(loads.sum())
    n_live = len(omap.live_ranks)
    if total <= 0 or n_live < 2:
        return None
    per_rank = np.array(
        [float(loads[lo:hi].sum()) for lo, hi in
         (omap.range_of(r) for r in omap.live_ranks)]
    )
    mean = total / n_live
    if mean <= 0 or float(per_rank.max()) / mean < skew_threshold:
        return None
    cum = np.cumsum(loads)
    starts = [0]
    for i in range(1, n_live):
        cut = int(np.searchsorted(cum, total * i / n_live, side="left")) + 1
        cut = max(cut, starts[-1])
        cut = min(cut, omap.n_mesh_shards)
        starts.append(cut)
    starts.append(omap.n_mesh_shards)
    if tuple(starts) == omap.starts:
        return None
    return omap.rebalance(starts)


def plan_moves(
    old_map: OwnershipMap, new_map: OwnershipMap
) -> List[Tuple[int, int, int, int]]:
    """Shard ranges whose owner changes between two maps over the same
    shard space: ``(lo, hi, src_rank, dst_rank)`` per contiguous piece.
    Only live-in-both src ranks appear (a dead src is the adoption path,
    not a migration)."""
    if old_map.n_mesh_shards != new_map.n_mesh_shards:
        raise ValueError("maps cover different shard spaces")
    bounds = sorted(set(old_map.starts) | set(new_map.starts))
    moves = []
    for lo, hi in zip(bounds, bounds[1:]):
        if lo >= hi:
            continue
        src = int(old_map.owner_of_shard([lo])[0])
        dst = int(new_map.owner_of_shard([lo])[0])
        if src != dst and new_map.is_live(src):
            moves.append((lo, hi, src, dst))
    return moves


def migrate_ranges(
    transport,
    table,
    old_map: OwnershipMap,
    new_map: OwnershipMap,
    seq,
    epoch: int,
    timeout: Optional[float] = None,
) -> Dict[str, int]:
    """Stream every moving shard range owner -> owner; returns stats.

    Senders encode (keys, rows) for each outgoing piece and ship it on an
    epoch-tagged PBTX frame (``migrate:<seq>:<lo>-<hi>@e<epoch>``), firing
    fault site ``migrate.transfer`` per piece; receivers STAGE incoming
    pieces and only push them after the caller's commit verdict succeeds —
    the staged dict is returned inside ``stats["staged"]`` so the caller
    (the supervisor's boundary hook) controls the atomic flip. Until then
    the old epoch keeps serving; a failed plan is simply retried at the
    next boundary (FLT008 contract for ``migrate.transfer``).
    """
    from paddlebox_tpu.table.sparse_table import key_to_shard

    me = transport.rank
    moves = plan_moves(old_map, new_map)
    sent_bytes = 0
    sent_keys = 0
    for lo, hi, src, dst in moves:
        if src != me:
            continue
        keys = np.sort(table.keys())
        shards = key_to_shard(keys, old_map.n_mesh_shards)
        keys = keys[(shards >= lo) & (shards < hi)]
        rows = (
            table.pull_or_create(keys)
            if len(keys)
            else np.zeros((0, table.layout.width), np.float32)
        )
        fire("migrate.transfer")
        payload = encode_shard_rows(keys, rows)
        transport.send(dst, f"migrate:{seq}:{lo}-{hi}@e{epoch}", payload)
        sent_bytes += len(payload)
        sent_keys += len(keys)
    staged: List[Tuple[np.ndarray, np.ndarray]] = []
    recv_keys = 0
    for lo, hi, src, dst in moves:
        if dst != me:
            continue
        payload = transport.recv(
            f"migrate:{seq}:{lo}-{hi}@e{epoch}", src, timeout=timeout
        )
        keys, rows = decode_shard_rows(payload)
        staged.append((keys, rows))
        recv_keys += len(keys)
    return {
        "moves": len(moves),
        "sent_keys": sent_keys,
        "sent_bytes": sent_bytes,
        "recv_keys": recv_keys,
        "staged": staged,
    }


def commit_staged(table, staged) -> int:
    """Push staged migration pieces into the live table (upsert). Called
    only after the commit verdict — the atomic-flip half of migration."""
    n = 0
    for keys, rows in staged:
        if len(keys):
            table.push(keys, rows)
            n += len(keys)
    return n
