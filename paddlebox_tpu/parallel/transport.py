"""Cross-process host transport: the open PaddleShuffler/MPICluster tier.

The reference moves records between nodes through the closed
``boxps::PaddleShuffler`` (data_set.cc:1757-1926) and coordinates dense
sync/membership through the closed ``boxps::MPICluster`` (box_wrapper.h:
415-566). On TPU the *device* plane needs neither (XLA collectives over
ICI/DCN do dense sync); what remains is the *host* plane — record shuffle,
pass working-set key exchange, batch-count lockstep — which this module
provides over plain TCP:

- ``TcpTransport``: rank<->rank tagged message frames with persistent
  connections; primitives ``alltoall`` / ``allgather`` / ``allreduce_max``
  / ``barrier``. Peers are ``host:port`` strings, so the same code runs
  2 localhost subprocesses (the reference's own test pattern,
  test_dist_fleet_base.py:158-260) or N real hosts over DCN.
- ``TcpShuffleRouter``: the LocalShuffleRouter exchange/collect contract
  across processes, chunks = serialized ColumnarRecords.

Tags scope rounds (e.g. ``shuffle:3``): a fast rank's frames for round
N+1 queue in the inbox without corrupting a slow rank's round N collect.

Fault tolerance (the MPICluster resilience the reference delegates to the
closed boxps tier, rebuilt in the open — see docs/ROBUSTNESS.md,
"Distributed plane"):

- Every connection opens with a versioned HELLO handshake; the accepting
  side replies ``_HELLO_REPLY`` (magic, its protocol version, the count of
  data frames it has already delivered from that peer), so a reconnecting
  sender resumes exactly where the receiver left off. Version capability
  is negotiated here: a mismatched peer gets the reply (carrying the
  listener's version) and a closed connection, and the sender raises the
  typed :class:`VersionMismatchError` naming both versions — never a hang,
  never downstream CRC noise. A pre-v3 peer that closes without any reply
  surfaces the same typed error with ``peer_version=None``.
- Every frame carries a per-destination sequence number, a codec byte
  (PBTX v3: 0 = raw, 1 = chunked zlib via ``ops/host_codec.py``), and a
  CRC32 over tag + *encoded* payload — corruption is caught before any
  inflate runs. The receiver drops duplicates (``seq <= delivered``) and
  kills the connection on checksum or decode failure — the sender's
  resync replays the lost tail, so a frame is delivered exactly once or
  the send fails loudly.
- Compression happens on the sender's calling thread *before* taking the
  per-destination send lock, so one peer's codec work overlaps another
  peer's socket write; ``wire.host_bytes_*`` (actual frame bytes) vs
  ``wire.host_raw_bytes_*`` (what v2 would have shipped) at this choke
  point are the measurement the ROADMAP host-wire claim is graded
  against.
- The send path keeps un-acked frames in a per-destination resend buffer
  and heals dropped connections with bounded exponential backoff
  (``transport_send_retries`` x ``transport_backoff_s``).
- A heartbeat thread (``transport_heartbeat_s``) beats every peer; beats
  carry the delivered-count ack that prunes the peer's resend buffer, and
  received traffic feeds a per-peer failure detector (silent for
  ``transport_peer_dead_s``/2 -> suspect, for the full horizon -> dead).
- Collectives are deadline-aware: a timeout names exactly which ranks and
  tags are missing (straggler report), and a peer the detector declares
  dead fails the collective immediately instead of running out the clock.
- Tags may carry an epoch suffix ``@e<N>`` (the DistributedWorkingSet
  rounds do). ``discard_epochs_below`` raises a floor below which frames
  are dropped — in the inbox now, and on delivery for late arrivals — so
  a coordinated pass retry can never consume a stale attempt's frames.
"""

from __future__ import annotations

import re
import socket
import struct
import threading
import time
import zlib
from collections import deque
from typing import Dict, List, Optional, Tuple

from paddlebox_tpu import config
from paddlebox_tpu.obs.flight_recorder import FLIGHT_RECORDER
from paddlebox_tpu.obs.trace_context import EXT_LEN, current_trace, decode_ext
from paddlebox_tpu.ops import host_codec
from paddlebox_tpu.utils.faultinject import fire
from paddlebox_tpu.utils.monitor import STAT_ADD, STAT_OBSERVE
from paddlebox_tpu.utils.trace import PROFILER, Profiler

_MAGIC = b"PBTX"
_VERSION = 3
# connection handshake: magic, protocol version, sender rank
_HELLO = struct.Struct("<4sHH")
# v3 handshake reply: magic, listener's protocol version, delivered
# data-frame count (the resync point). On version mismatch the listener
# still sends this (delivered=0) before closing, so the peer can name the
# incompatible version instead of guessing from a dropped connection.
_HELLO_REPLY = struct.Struct("<4sHQ")
# heartbeat ack payload: delivered data-frame count
_ACK = struct.Struct("<Q")
# frame header: seq, kind, codec, tag_len, payload_len,
# crc32(tag + encoded payload) — the CRC covers the bytes as shipped, so
# corruption is caught before any inflate
_FRAME = struct.Struct("<QBBHII")

_KIND_DATA = 0
_KIND_HEARTBEAT = 1
# high bit of ``kind``: the body is prefixed with a 24-byte trace-context
# extension (obs/trace_context.py EXT_STRUCT) BEFORE the tag. Covered by
# the frame CRC. Only ever set when flag transport_trace_frames is on —
# a pre-extension v3 reader would mis-slice the body and CRC-fail, so the
# sender opts in per deployment rather than per handshake.
_KIND_FLAG_TRACE = 0x80
_KIND_MASK = 0x7F

# frame payload codecs (PBTX v3)
_CODEC_RAW = 0
_CODEC_ZLIB = 1

_EPOCH_RE = re.compile(r"@e(\d+)$")

config.define_flag(
    "shuffle_chunk_bytes",
    64 << 20,
    "max serialized bytes per shuffle sub-chunk: bounds the sender's "
    "serialization RAM and keeps frames flowing so the receive timeout "
    "paces per-chunk gaps, not whole-pass serialization",
)


config.define_flag(
    "transport_trace_frames", False,
    "stamp outgoing PBTX data frames with the sender's active "
    "trace-context (trace_id, span_id) as a header extension, so "
    "obs_report --merge-traces can correlate spans across ranks; leave "
    "off when any peer predates the extension",
)


def _tag_epoch(tag: str) -> Optional[int]:
    m = _EPOCH_RE.search(tag)
    return int(m.group(1)) if m else None


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


class TransportTimeout(TimeoutError):
    """A collective/recv deadline expired; ``missing`` names the
    still-absent (tag, src) pairs — the straggler report."""

    def __init__(self, msg: str, missing: List[Tuple[str, int]]):
        super().__init__(msg)
        self.missing = missing


class PeerDeadError(ConnectionError):
    """The failure detector declared a peer dead while a collective was
    waiting on it."""

    def __init__(self, msg: str, dead: List[int]):
        super().__init__(msg)
        self.dead = dead


class ProtocolError(ConnectionError):
    """Handshake magic/version mismatch — incompatible peer. Never
    retried: reconnecting cannot change the peer's protocol."""


class VersionMismatchError(ProtocolError):
    """HELLO version negotiation failed; names both protocol versions.

    ``peer_version`` is None when the peer closed without any version
    reply — the signature of a pre-v3 listener, which rejects unknown
    HELLO versions by silently dropping the connection."""

    def __init__(self, local: int, peer: Optional[int]):
        peer_s = (
            f"v{peer}"
            if peer is not None
            else "<= v2 (closed without a version reply)"
        )
        super().__init__(
            f"PBTX protocol version mismatch: local v{local}, peer {peer_s}"
        )
        self.local_version = local
        self.peer_version = peer


class _SendLink:
    """Sender-side state for one destination.

    Every field is guarded by the owning transport's per-destination send
    lock (``_send_locks[dst]``): ``sock`` (live connection or None),
    ``next_seq`` (last data seq assigned), ``acked`` (highest seq the peer
    confirmed via heartbeat ack or handshake), and ``retained`` — the
    in-order deque of (seq, frame_bytes) not yet acked, replayed after a
    reconnect so the receiver's stream resumes gaplessly."""

    __slots__ = ("sock", "next_seq", "acked", "retained", "was_connected")

    def __init__(self) -> None:
        self.sock: Optional[socket.socket] = None
        self.next_seq = 0
        self.acked = 0
        self.retained: deque = deque()
        self.was_connected = False


class TcpTransport:
    """Tagged rank-to-rank byte transport over TCP (fault-tolerant)."""

    def __init__(self, rank: int, endpoints: List[str], timeout: float = 120.0,
                 profiler: Optional[Profiler] = None):
        self.rank = rank
        self.n_ranks = len(endpoints)
        self.timeout = timeout
        # per-instance so an in-process multi-rank cluster (tests, chaos
        # soaks) can give each rank its own timeline; defaults to the
        # process-global profiler in real one-rank-per-process deployments
        self._profiler = profiler if profiler is not None else PROFILER
        self._endpoints = [self._parse(e) for e in endpoints]
        # (tag, src) -> FIFO of frames: a duplicate tag from one peer queues
        # behind the unconsumed first frame instead of overwriting it (a
        # dataset driven without set_date reuses pass-id-derived tags)
        self._cond = threading.Condition()
        self._inbox: Dict[Tuple[str, int], List[bytes]] = {}  # guarded-by: _cond
        self._delivered: Dict[int, int] = {}  # guarded-by: _cond
        self._last_seen: Dict[int, float] = {}  # guarded-by: _cond
        self._epoch_min = 0  # guarded-by: _cond
        # ranks the membership layer confirmed dead: collectives skip them
        # (send nothing, wait on nothing, b"" placeholder in results)
        self._dead: set = set()  # guarded-by: _cond
        self._send_locks: Dict[int, threading.Lock] = {
            r: threading.Lock() for r in range(self.n_ranks)
        }
        self._links: Dict[int, _SendLink] = {
            r: _SendLink() for r in range(self.n_ranks)
        }
        # accepted reader sockets: close() must tear these down too, or
        # their local port stays busy and a successor incarnation of this
        # rank cannot bind the same endpoint (elastic rejoin)
        self._conns: set = set()  # guarded-by: _cond
        self._closed = False
        # listener
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        host, port = self._endpoints[rank]
        self._server.bind((host, port))
        # rebind with the OS-assigned port if 0 was requested
        self._endpoints[rank] = self._server.getsockname()
        self._server.listen(self.n_ranks * 4)
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()
        # heartbeat: acks + failure detection; off when flag is 0 or the
        # "cluster" is a single rank
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        hb = float(config.get_flag("transport_heartbeat_s"))
        if hb > 0 and self.n_ranks > 1:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, args=(hb,), daemon=True
            )
            self._hb_thread.start()

    @staticmethod
    def _parse(ep: str) -> Tuple[str, int]:
        host, port = ep.rsplit(":", 1)
        return host, int(port)

    @property
    def port(self) -> int:
        return self._endpoints[self.rank][1]

    @staticmethod
    def _close_sock(sock: socket.socket) -> None:
        """Counted close — a failed close is rare but never silent."""
        try:
            sock.close()
        except OSError as e:
            STAT_ADD("transport.close_errors")
            PROFILER.instant("transport:close_error", {"error": repr(e)})

    # ---- receive side ----------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._server.accept()
            except OSError:
                if not self._closed:
                    # the listening socket died UNDER a live transport —
                    # peers will see connect timeouts; make the root cause
                    # visible on this side
                    STAT_ADD("transport.accept_errors")
                return
            if self._closed:
                # raced close(): a handshake here would impersonate a dead
                # incarnation and silently eat the peer's retained tail
                # best-effort courtesy shutdown; the close below is the
                # real teardown and counts its own errors
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                # pbox-lint: disable=EXC007
                except OSError:
                    pass
                self._close_sock(conn)
                return
            with self._cond:
                self._conns.add(conn)
            threading.Thread(
                target=self._reader, args=(conn,), daemon=True
            ).start()

    def _reader(self, conn: socket.socket) -> None:
        src = -1
        try:
            # handshake under the transport timeout so a wedged peer can't
            # pin this reader forever; the frame loop then blocks freely
            conn.settimeout(self.timeout)
            magic, version, src = _HELLO.unpack(_recv_exact(conn, _HELLO.size))
            if magic != _MAGIC or version != _VERSION:
                STAT_ADD("transport.protocol_errors")
                self._profiler.instant(
                    "transport:protocol_error",
                    {"magic": repr(magic), "version": version,
                     "local_version": _VERSION},
                )
                if magic == _MAGIC:
                    # named rejection: the peer's connect parses our
                    # version out of the reply and raises the typed
                    # VersionMismatchError instead of diagnosing a hangup
                    try:
                        conn.sendall(_HELLO_REPLY.pack(_MAGIC, _VERSION, 0))
                    # best-effort courtesy reply; the mismatch itself was
                    # counted above as transport.protocol_errors
                    # pbox-lint: disable=EXC007
                    except (ConnectionError, OSError):
                        pass
                return
            incarnation_reset = False
            with self._cond:
                if src in self._dead and self._delivered.get(src, 0) > 0:
                    # a HELLO from a membership-dead rank is a NEW
                    # incarnation dialing in (elastic rejoin): its stream
                    # restarts at seq 1, so the old incarnation's delivered
                    # count must not eat the fresh frames as duplicates.
                    # Reset BEFORE the reply so the very first frame (the
                    # join announce) is deliverable even while the rank is
                    # still membership-dead.
                    self._delivered[src] = 0
                    incarnation_reset = True
                delivered = self._delivered.get(src, 0)
                self._last_seen[src] = time.monotonic()
            if incarnation_reset:
                STAT_ADD("transport.incarnation_resets")
            # resync point: the peer replays every frame after this count
            conn.sendall(_HELLO_REPLY.pack(_MAGIC, _VERSION, delivered))
            conn.settimeout(None)
            while True:
                fire("transport.recv_frame")
                seq, kind, codec, tag_len, n, crc = _FRAME.unpack(
                    _recv_exact(conn, _FRAME.size)
                )
                ext_len = EXT_LEN if kind & _KIND_FLAG_TRACE else 0
                kind &= _KIND_MASK
                body = _recv_exact(conn, ext_len + tag_len + n)
                with self._cond:
                    self._last_seen[src] = time.monotonic()
                if zlib.crc32(body) != crc:
                    # corrupt frame: drop the connection BEFORE any
                    # inflate; the sender's resync replays everything
                    # un-delivered
                    STAT_ADD("transport.crc_errors")
                    self._profiler.instant(
                        "transport:crc_error", {"src": src, "seq": seq}
                    )
                    return
                tctx = decode_ext(body[:ext_len]) if ext_len else None
                tag = body[ext_len:ext_len + tag_len].decode()
                payload = body[ext_len + tag_len:]
                if kind == _KIND_DATA:
                    STAT_ADD(
                        "wire.host_bytes_recv",
                        _FRAME.size + ext_len + tag_len + n,
                    )
                if codec != _CODEC_RAW:
                    try:
                        fire("wire.host_decode")
                        if codec != _CODEC_ZLIB:
                            raise host_codec.HostCodecError(
                                f"unknown frame codec {codec}"
                            )
                        payload = host_codec.decompress_chunked(payload)
                    except (host_codec.HostCodecError, OSError) as e:
                        # decode failure (or injected wire.host_decode
                        # fault): kill the connection pre-delivery; the
                        # frame was never counted delivered, so the
                        # sender's resync replays it exactly once
                        STAT_ADD("transport.decode_errors")
                        self._profiler.instant(
                            "transport:decode_error",
                            {"src": src, "seq": seq, "error": repr(e)},
                        )
                        return
                if kind == _KIND_DATA:
                    STAT_ADD(
                        "wire.host_raw_bytes_recv",
                        _FRAME.size + tag_len + len(payload),
                    )
                if kind == _KIND_HEARTBEAT:
                    if len(payload) == _ACK.size:
                        self._prune_retained(src, _ACK.unpack(payload)[0])
                    continue
                dup = stale = False
                with self._cond:
                    if seq <= self._delivered.get(src, 0):
                        dup = True
                    else:
                        self._delivered[src] = seq
                        ep = _tag_epoch(tag)
                        if ep is not None and ep < self._epoch_min:
                            stale = True
                        else:
                            self._inbox.setdefault((tag, src), []).append(payload)
                            self._cond.notify_all()
                if dup:
                    STAT_ADD("transport.dup_frames_dropped")
                if stale:
                    STAT_ADD("transport.stale_frames_dropped")
                if tctx is not None and not dup and not stale:
                    # the cross-rank correlation point: this instant and
                    # the sender's transport:send share one trace_id
                    STAT_ADD("transport.trace_frames_recv")
                    args = tctx.as_args()
                    args.update({"src": src, "tag": tag, "seq": seq})
                    self._profiler.instant(
                        "transport:deliver", args, category="transport"
                    )
        except (ConnectionError, OSError):
            # a reader dying is how peer death first shows up on this
            # side; the heartbeat plane diagnoses it seconds later — count
            # the disconnect now so the two signals can be correlated
            STAT_ADD("transport.reader_disconnects")
            return
        finally:
            self._close_sock(conn)
            with self._cond:
                self._conns.discard(conn)

    def _pop_locked(self, tag: str, src: int) -> bytes:
        with self._cond:  # re-entrant: callers already hold it
            q = self._inbox[(tag, src)]
            payload = q.pop(0)
            if not q:
                del self._inbox[(tag, src)]
            return payload

    def _take_all(
        self, pairs: List[Tuple[str, int]], op: str, timeout: Optional[float]
    ) -> List[bytes]:
        """Wait for one frame per (tag, src); deadline-aware with a
        straggler report, and fail-fast on detector-dead peers. A dead
        peer also snapshots the flight recorder: the incident bundle
        (when flag obs_incident_dir is set) carries the last spans and
        stats leading up to the death."""
        try:
            return self._take_all_inner(pairs, op, timeout)
        except PeerDeadError as e:
            self._profiler.instant(
                "transport:peer_dead",
                {"op": op, "dead": list(e.dead), "rank": self.rank},
            )
            FLIGHT_RECORDER.dump("peer_dead", detail=str(e))
            raise

    def _take_all_inner(
        self, pairs: List[Tuple[str, int]], op: str, timeout: Optional[float]
    ) -> List[bytes]:
        budget = self.timeout if timeout is None else timeout
        deadline = time.monotonic() + budget
        dead_s = float(config.get_flag("transport_peer_dead_s"))
        with self._cond:
            while True:
                missing = [p for p in pairs if p not in self._inbox]
                if not missing:
                    return [self._pop_locked(tag, src) for tag, src in pairs]
                now = time.monotonic()
                dead = sorted(
                    {
                        src
                        for _tag, src in missing
                        if src != self.rank
                        and (
                            src in self._dead  # membership-confirmed
                            or (
                                src in self._last_seen
                                and now - self._last_seen[src] >= dead_s
                            )
                        )
                    }
                )
                if dead:
                    raise PeerDeadError(
                        f"rank {self.rank}: {op} failed — "
                        f"rank(s) {dead} considered dead (no traffic for "
                        f">= {dead_s:.1f}s)",
                        dead,
                    )
                if now >= deadline:
                    report = ", ".join(
                        f"rank {src} ({self._peer_status_locked(src, now)}, "
                        f"tag {tag!r})"
                        for tag, src in sorted(missing, key=lambda p: p[1])
                    )
                    raise TransportTimeout(
                        f"rank {self.rank}: {op} timed out after "
                        f"{budget:.1f}s still waiting on: {report}",
                        missing,
                    )
                # short slices so dead-peer detection runs while waiting
                self._cond.wait(min(0.25, deadline - now))

    def recv(self, tag: str, src: int, timeout: Optional[float] = None) -> bytes:
        """Blocking receive of one frame (tag, src) — the public primitive
        streamed protocols (TcpShuffleRouter) build on."""
        return self._take_all([(tag, src)], f"recv(tag={tag!r})", timeout)[0]

    def recv_first(
        self, tag: str, srcs: List[int], timeout: Optional[float] = None
    ) -> Tuple[int, bytes]:
        """Client-mode receive: block until ANY of ``srcs`` has a queued
        frame under ``tag``; pop and return ``(src, payload)``.

        The serve front-end's primitive: a fleet client listening to N
        followers takes whichever response/health beat lands first (which
        is what makes hedged dispatch a pure race, no cancellation
        protocol). Unlike :meth:`_take_all`, ONE dead source is normal
        here — the call only fails fast with :class:`PeerDeadError` when
        EVERY source is membership- or detector-dead, because a fleet
        with any live follower must keep consuming from it."""
        srcs = [int(s) for s in srcs]
        if not srcs:
            raise ValueError("recv_first needs at least one source rank")
        budget = self.timeout if timeout is None else timeout
        deadline = time.monotonic() + budget
        dead_s = float(config.get_flag("transport_peer_dead_s"))
        with self._cond:
            while True:
                for src in srcs:
                    if (tag, src) in self._inbox:
                        return src, self._pop_locked(tag, src)
                now = time.monotonic()
                dead = sorted(
                    src for src in set(srcs)
                    if src != self.rank
                    and (
                        src in self._dead
                        or (
                            src in self._last_seen
                            and now - self._last_seen[src] >= dead_s
                        )
                    )
                )
                if len(dead) == len(set(srcs)):
                    raise PeerDeadError(
                        f"rank {self.rank}: recv_first(tag={tag!r}) failed "
                        f"— every source rank {dead} considered dead",
                        dead,
                    )
                if now >= deadline:
                    raise TransportTimeout(
                        f"rank {self.rank}: recv_first(tag={tag!r}) timed "
                        f"out after {budget:.1f}s with no frame from any "
                        f"of ranks {sorted(set(srcs))}",
                        [(tag, s) for s in srcs],
                    )
                self._cond.wait(min(0.25, deadline - now))

    # ---- failure detector ------------------------------------------------

    def _peer_status_locked(self, src: int, now: float) -> str:
        if src == self.rank:
            return "alive"
        with self._cond:  # re-entrant: callers already hold it
            seen = self._last_seen.get(src)
        if seen is None:
            return "never seen"
        age = now - seen
        dead_s = float(config.get_flag("transport_peer_dead_s"))
        if age >= dead_s:
            return "dead"
        if age >= dead_s / 2:
            return "suspect"
        return "alive"

    def peer_status(self, src: int) -> str:
        """'alive' | 'suspect' | 'dead' | 'never seen' from received
        traffic (frames and heartbeats both count)."""
        with self._cond:
            return self._peer_status_locked(src, time.monotonic())

    def dead_peers(self) -> List[int]:
        with self._cond:
            now = time.monotonic()
            return [
                r
                for r in range(self.n_ranks)
                if r != self.rank
                and (
                    r in self._dead
                    or self._peer_status_locked(r, now) == "dead"
                )
            ]

    # ---- membership ------------------------------------------------------

    def mark_dead(self, ranks) -> None:
        """Confirm ranks dead at the membership layer: collectives stop
        sending to / waiting on them (their result slots become b""),
        direct sends fail fast, heartbeats stop. Reversed only by an
        explicit :meth:`mark_alive` when the membership layer admits a NEW
        incarnation at that slot (elastic join) — a recovered host rejoins
        with a fresh transport, not a resurrection of the old stream."""
        with self._cond:
            for r in ranks:
                r = int(r)
                if r != self.rank:
                    self._dead.add(r)
            # wake collectives blocked on a now-dead rank immediately
            self._cond.notify_all()

    def mark_alive(self, rank: int) -> None:
        """Readmit a previously mark_dead rank: the membership layer
        admitted a joiner at that slot (elastic grow).

        Deliberately touches ONLY membership + detector state. The
        outbound link keeps its seq space: a re-admitted peer that never
        actually died (an aborted join attempt, retried) still holds our
        delivered count, so resetting seqs would make every fresh frame
        look like a duplicate to it. A genuinely NEW incarnation (killed
        host rejoining with a fresh transport) is handled on the inbound
        side instead — its HELLO resets the delivered counter (see
        :meth:`_reader`), and its HELLO_REPLY resyncs our link the usual
        way. The detector gets a fresh grace window so the readmitted
        peer is not instantly re-declared dead by its old silence."""
        r = int(rank)
        if r == self.rank:
            return
        with self._cond:
            self._dead.discard(r)
            self._last_seen[r] = time.monotonic()
            self._cond.notify_all()

    def live_ranks(self) -> List[int]:
        """Ranks not membership-confirmed dead (always includes self).
        Detector state (suspect/dead by silence) does NOT remove a rank
        here — only an explicit mark_dead does, so collectives keep their
        fail-loudly semantics until membership actually changes."""
        with self._cond:
            return [r for r in range(self.n_ranks) if r not in self._dead]

    def is_marked_dead(self, rank: int) -> bool:
        with self._cond:
            return int(rank) in self._dead

    def pending_sources(self, tag: str) -> List[int]:
        """Non-consuming peek: source ranks with at least one queued frame
        under ``tag``. The elastic boundary scan uses this to notice
        waiting joiners without disturbing the inbox."""
        with self._cond:
            return sorted(
                {src for (t, src), q in self._inbox.items() if t == tag and q}
            )

    # ---- epoch discard ---------------------------------------------------

    def discard_epochs_below(self, epoch: int) -> int:
        """Raise the stale-epoch floor: queued frames whose tag ends with
        ``@e<k>``, k < epoch, are dropped now; late arrivals are dropped at
        delivery. Returns the number of frames purged from the inbox."""
        dropped = 0
        with self._cond:
            if epoch > self._epoch_min:
                self._epoch_min = epoch
            for key in list(self._inbox):
                ep = _tag_epoch(key[0])
                if ep is not None and ep < self._epoch_min:
                    dropped += len(self._inbox.pop(key))
        if dropped:
            STAT_ADD("transport.stale_frames_dropped", dropped)
        return dropped

    # ---- send side -------------------------------------------------------

    def _connect(self, dst: int) -> Tuple[socket.socket, int]:
        """Open + handshake one connection; returns (socket, acked_count)."""
        fire("transport.connect")
        s = socket.create_connection(self._endpoints[dst], timeout=self.timeout)
        try:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.sendall(_HELLO.pack(_MAGIC, _VERSION, self.rank))
            acked = self._read_hello_reply(s)
        except (ConnectionError, OSError):
            self._close_sock(s)
            raise
        return s, acked

    def _read_hello_reply(self, s: socket.socket) -> int:
        """Parse the listener's _HELLO_REPLY; typed failure on mismatch."""
        buf = bytearray()
        while len(buf) < _HELLO_REPLY.size:
            chunk = s.recv(_HELLO_REPLY.size - len(buf))
            if not chunk:
                if not buf:
                    # a pre-v3 listener rejects an unknown HELLO version
                    # by closing without any reply bytes
                    raise VersionMismatchError(_VERSION, None)
                raise ConnectionError("peer closed mid-handshake reply")
            buf.extend(chunk)
        magic, version, acked = _HELLO_REPLY.unpack(bytes(buf))
        if magic != _MAGIC:
            raise ProtocolError(
                f"handshake reply magic {magic!r} is not {_MAGIC!r} — "
                "peer is not a PBTX listener"
            )
        if version != _VERSION:
            raise VersionMismatchError(_VERSION, version)
        return acked

    def _reopen(self, dst: int, link: _SendLink) -> None:
        """(Re)connect and replay the un-acked tail. Caller holds the dst
        send lock."""
        sock, acked = self._connect(dst)
        if acked > link.acked:
            link.acked = acked
            while link.retained and link.retained[0][0] <= acked:
                link.retained.popleft()
        if link.was_connected:
            STAT_ADD("transport.reconnects")
        link.was_connected = True
        link.sock = sock
        for _seq, frame in link.retained:
            sock.sendall(frame)
            STAT_ADD("transport.frames_resent")

    def _prune_retained(self, dst: int, acked: int) -> None:
        with self._send_locks[dst]:
            link = self._links[dst]
            if acked > link.acked:
                link.acked = acked
                while link.retained and link.retained[0][0] <= acked:
                    link.retained.popleft()

    def _flush(self, dst: int, link: _SendLink, frame: Optional[bytes],
               tag: str, retries: Optional[int]) -> None:
        """Put ``frame`` (already retained) on the wire, reconnecting with
        bounded exponential backoff. Caller holds the dst send lock."""
        attempts = (
            int(config.get_flag("transport_send_retries"))
            if retries is None
            else retries
        )
        backoff = float(config.get_flag("transport_backoff_s"))
        for attempt in range(attempts + 1):
            try:
                fire("transport.send")
                if link.sock is None:
                    # the reopen replays the retained tail, frame included
                    self._reopen(dst, link)
                elif frame is not None:
                    link.sock.sendall(frame)
                return
            except ProtocolError:
                # incompatible peer: reconnecting cannot change its
                # protocol version, so fail loudly instead of burning the
                # retry budget (the typed error names both versions)
                STAT_ADD("transport.protocol_errors")
                raise
            except (ConnectionError, OSError) as e:
                if link.sock is not None:
                    self._close_sock(link.sock)
                    link.sock = None
                if attempt >= attempts:
                    if retries is None:
                        # data-path exhaustion; heartbeat callers count
                        # their own transport.heartbeat_errors instead
                        STAT_ADD("transport.send_errors")
                    self._profiler.instant(
                        "transport:send_error",
                        {
                            "dst": dst,
                            "tag": tag,
                            "attempts": attempt + 1,
                            "error": repr(e),
                        },
                    )
                    raise ConnectionError(
                        f"rank {self.rank}: send to rank {dst} "
                        f"(tag={tag!r}) failed after {attempt + 1} "
                        f"attempt(s): {e}"
                    ) from e
                STAT_ADD("transport.send_retries")
                time.sleep(min(backoff * (2 ** attempt), 5.0))

    def _encode_payload(self, payload: bytes) -> Tuple[int, bytes]:
        """Pick the wire codec for one data payload. Small payloads and
        payloads the codec fails to shrink ship raw — the codec byte makes
        every frame self-describing, so mixed traffic is fine."""
        if (
            len(payload) >= int(config.get_flag("host_compress_min_bytes"))
            and config.get_flag("host_wire_codec")
        ):
            comp = host_codec.compress_chunked(
                payload, int(config.get_flag("host_compress_level"))
            )
            if len(comp) < len(payload):
                return _CODEC_ZLIB, comp
        return _CODEC_RAW, payload

    def send(self, dst: int, tag: str, payload: bytes) -> None:
        tb = tag.encode()
        with self._cond:
            dst_dead = dst in self._dead
        if dst_dead:
            # fail fast instead of burning the retry budget against a rank
            # membership already buried
            raise PeerDeadError(
                f"rank {self.rank}: send to rank {dst} (tag={tag!r}) "
                "refused — rank is membership-confirmed dead",
                [dst],
            )
        if dst == self.rank:
            stale = False
            with self._cond:
                ep = _tag_epoch(tag)
                if ep is not None and ep < self._epoch_min:
                    stale = True
                else:
                    self._inbox.setdefault((tag, self.rank), []).append(payload)
                    self._cond.notify_all()
            if stale:
                STAT_ADD("transport.stale_frames_dropped")
            return
        # encode OUTSIDE the per-destination send lock, on the caller's
        # worker thread: one peer's compression overlaps another peer's
        # socket write instead of serializing behind it
        codec, wire_payload = self._encode_payload(payload)
        kind = _KIND_DATA
        ext = b""
        if config.get_flag("transport_trace_frames"):
            ctx = current_trace()
            if ctx is not None:
                # fresh span id per frame, same trace id: the receiver's
                # transport:deliver correlates back to this send
                wire_ctx = ctx.child()
                ext = wire_ctx.encode_ext()
                kind |= _KIND_FLAG_TRACE
                STAT_ADD("transport.trace_frames_sent")
                args = wire_ctx.as_args()
                args.update({"dst": dst, "tag": tag})
                self._profiler.instant(
                    "transport:send", args, category="transport"
                )
        body = ext + tb + wire_payload
        crc = zlib.crc32(body)
        with self._send_locks[dst]:
            link = self._links[dst]
            link.next_seq += 1
            frame = (
                _FRAME.pack(
                    link.next_seq, kind, codec, len(tb),
                    len(wire_payload), crc,
                )
                + body
            )
            link.retained.append((link.next_seq, frame))
            # counted per logical send (replays are not re-counted):
            # actual frame bytes vs what an uncompressed v2 frame of the
            # same header size would have shipped
            STAT_ADD("wire.host_bytes_sent", len(frame))
            STAT_ADD(
                "wire.host_raw_bytes_sent",
                _FRAME.size + len(tb) + len(payload),
            )
            STAT_OBSERVE("wire.frame_bytes", len(frame))
            # the frame is retained BEFORE the first wire attempt, so every
            # failure path (including a fault injected on the very first
            # send) replays it through the reconnect resync
            self._flush(dst, link, frame, tag, None)

    # ---- heartbeat -------------------------------------------------------

    def _heartbeat_loop(self, interval: float) -> None:
        while not self._hb_stop.wait(interval):
            if self._closed:
                return
            with self._cond:
                dead = set(self._dead)
            for dst in range(self.n_ranks):
                if dst == self.rank or dst in dead:
                    continue
                try:
                    fire("transport.heartbeat")
                    self._send_heartbeat(dst)
                except (ConnectionError, OSError):
                    # a down peer makes beats fail by design; the detector
                    # (driven by RECEIVED traffic) is what marks it dead
                    STAT_ADD("transport.heartbeat_errors")

    def _send_heartbeat(self, dst: int) -> None:
        with self._cond:
            delivered = self._delivered.get(dst, 0)
        payload = _ACK.pack(delivered)
        frame = (
            _FRAME.pack(
                0, _KIND_HEARTBEAT, _CODEC_RAW, 0, len(payload),
                zlib.crc32(payload),
            )
            + payload
        )
        with self._send_locks[dst]:
            link = self._links[dst]
            # single attempt, not retained: beats are periodic and
            # idempotent — but a beat that REOPENS a dropped connection
            # replays the retained data tail, which is exactly how a
            # receiver-side drop heals without waiting for the next send
            self._flush(dst, link, frame, "heartbeat", 0)

    # ---- collectives -----------------------------------------------------

    def alltoall(
        self, payloads: List[bytes], tag: str, timeout: Optional[float] = None
    ) -> List[bytes]:
        """payloads[d] goes to rank d; returns what every rank sent here.

        Membership-aware: ranks marked dead (``mark_dead``) are skipped on
        both sides — nothing is sent to them, nothing awaited from them,
        and their result slot is ``b""``. Callers that unpack typed
        payloads must skip non-live slots (see ``allreduce_max``)."""
        if len(payloads) != self.n_ranks:
            raise ValueError(f"need {self.n_ranks} payloads, got {len(payloads)}")
        live = self.live_ranks()
        for dst in live:
            try:
                self.send(dst, tag, payloads[dst])
            except PeerDeadError:
                raise
            except (ConnectionError, OSError):
                # the frame was retained before the first wire attempt, so
                # a transient drop heals via the heartbeat reconnect resync;
                # a real death fails the wait below with the detector's
                # typed PeerDeadError naming the rank — strictly more
                # information than a raw ConnectionError here
                STAT_ADD("transport.collective_send_deferred")
        got = self._take_all(
            [(tag, src) for src in live],
            f"alltoall(tag={tag!r})",
            timeout,
        )
        if len(live) == self.n_ranks:
            return got
        by_src = dict(zip(live, got))
        return [by_src.get(src, b"") for src in range(self.n_ranks)]

    def allgather(
        self, payload: bytes, tag: str, timeout: Optional[float] = None
    ) -> List[bytes]:
        return self.alltoall([payload] * self.n_ranks, tag, timeout=timeout)

    def allreduce_max(
        self, value: int, tag: str, timeout: Optional[float] = None
    ) -> int:
        vals = self.allgather(struct.pack("<q", int(value)), tag, timeout=timeout)
        # dead ranks contribute b"" placeholder slots, not votes
        return max(struct.unpack("<q", v)[0] for v in vals if len(v) == 8)

    def barrier(self, tag: str, timeout: Optional[float] = None) -> None:
        self.allgather(b"", "barrier:" + tag, timeout=timeout)

    def close(self) -> None:
        self._closed = True
        self._hb_stop.set()
        try:
            # shutdown BEFORE close: the accept thread blocked in accept()
            # holds the listening socket open past a bare close(), so the
            # dead incarnation would keep completing handshakes and eat
            # frames meant for its successor (elastic rejoin)
            self._server.shutdown(socket.SHUT_RDWR)
        # an already-dead listener (ENOTCONN and kin) is exactly the
        # state shutdown is driving toward; close() below counts errors
        # pbox-lint: disable=EXC007
        except OSError:
            pass
        try:
            self._server.close()
        except OSError as e:
            STAT_ADD("transport.close_errors")
            PROFILER.instant("transport:close_error", {"error": repr(e)})
        with self._cond:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            # shutdown BEFORE close: a reader blocked in recv() holds the
            # kernel socket open, so a bare close() would neither send FIN
            # to the peer nor wake the reader — the peer's link then looks
            # healthy forever and its frames vanish into this dead
            # incarnation instead of erroring over to the successor
            try:
                c.shutdown(socket.SHUT_RDWR)
            # a peer-reset conn is already down — the state shutdown is
            # driving toward; _close_sock counts real close errors
            # pbox-lint: disable=EXC007
            except OSError:
                pass
            self._close_sock(c)
        for r in range(self.n_ranks):
            with self._send_locks[r]:
                link = self._links[r]
                if link.sock is not None:
                    self._close_sock(link.sock)
                    link.sock = None
                link.retained.clear()


class TcpShuffleRouter:
    """LocalShuffleRouter's exchange/collect contract across processes.

    One router per (transport, dataset); ``exchange`` serializes each
    destination's ColumnarRecords chunk and all-to-alls them; ``collect``
    deserializes what arrived. The zero-length completion message of the
    reference's protocol (data_set.cc:1835-1866) is implicit: the chunk
    count header always arrives, even when zero chunks follow.

    Large passes stream in bounded sub-chunks (``shuffle_chunk_bytes``):
    the sender serializes at most one sub-chunk per destination at a time
    (peak extra RAM is the chunk size, not the whole part) and frames start
    arriving as soon as the first sub-chunk is cut, so the receive timeout
    paces per-chunk gaps instead of whole-pass serialization. The
    receiver's inbox is intentionally UNBOUNDED — it holds at most the
    in-flight pass, exactly like the reference's shuffle_channel_
    (data_set.cc:1870-1926); chunking bounds the sender side only.

    Round isolation under faults: the transport's per-destination frame
    sequencing means a round replayed by a reconnecting sender can never
    double-deliver a sub-chunk — duplicates are dropped by seq before the
    inbox, so ``collect`` sees each sub-chunk exactly once
    (tests/test_multihost.py::test_shuffle_round_no_double_delivery).
    """

    def __init__(self, transport: TcpTransport):
        self.transport = transport
        self.n_nodes = transport.n_ranks
        self._round = 0

    @staticmethod
    def _sub_ranges(chunk, chunk_bytes: int):
        """Split a ColumnarRecords part into ~<=chunk_bytes record ranges.

        Sized from EVERY serialized component (values, offsets, bases,
        search/cmatch/rank metadata, ins_id chars) — undercounting would
        let metadata-heavy stores blow past the sender-RAM bound."""
        import numpy as np

        n = len(chunk)
        total = (
            chunk.u64_values.nbytes
            + chunk.f_values.nbytes
            + chunk.u64_offsets.nbytes
            + chunk.f_offsets.nbytes
            + chunk.u64_base.nbytes
            + chunk.f_base.nbytes
            + chunk.search_ids.nbytes
            + chunk.cmatch.nbytes
            + chunk.rank.nbytes
            + (len(chunk.ins_id_chars) if chunk.ins_id_chars else 0)
            + (chunk.ins_id_off.nbytes if chunk.ins_id_off is not None else 0)
        )
        per = max(1, int(n * chunk_bytes / max(total, 1)))
        return [np.arange(i, min(i + per, n)) for i in range(0, n, per)]

    def exchange(self, from_node: int, parts: list) -> None:
        from paddlebox_tpu.data.record_store import ColumnarRecords

        if from_node != self.transport.rank:
            raise ValueError("exchange must be called by the owning rank")
        chunk_bytes = int(config.get_flag("shuffle_chunk_bytes"))
        tag = f"shuffle:{self._round}"
        tp = self.transport
        # header first (sub-chunk count), then the streamed sub-chunks;
        # destinations interleave so no single slow peer starves the rest
        ranges = []
        for dst, chunk in enumerate(parts):
            if isinstance(chunk, ColumnarRecords):
                ranges.append(self._sub_ranges(chunk, chunk_bytes) if len(chunk) else [])
            elif len(chunk) == 0:
                ranges.append([])
            else:
                raise TypeError(
                    "TcpShuffleRouter moves ColumnarRecords chunks; got "
                    f"{type(chunk).__name__} (enable the native parser or "
                    "convert with ColumnarRecords.from_records)"
                )
        for dst, rs in enumerate(ranges):
            tp.send(dst, tag + "/n", struct.pack("<I", len(rs)))
        max_chunks = max((len(rs) for rs in ranges), default=0)
        for i in range(max_chunks):
            for dst, rs in enumerate(ranges):
                if i < len(rs):
                    tp.send(dst, f"{tag}/{i}", parts[dst].select(rs[i]).to_bytes())

    def collect(self, node: int) -> list:
        from paddlebox_tpu.data.record_store import ColumnarRecords

        if node != self.transport.rank:
            raise ValueError("collect must be called by the owning rank")
        tag = f"shuffle:{self._round}"
        tp = self.transport
        out = []
        counts = [
            struct.unpack("<I", tp.recv(tag + "/n", src))[0]
            for src in range(self.n_nodes)
        ]
        for src, n in enumerate(counts):
            for i in range(n):
                out.append(ColumnarRecords.from_bytes(tp.recv(f"{tag}/{i}", src)))
        self._round += 1
        return out
