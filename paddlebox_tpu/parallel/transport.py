"""Cross-process host transport: the open PaddleShuffler/MPICluster tier.

The reference moves records between nodes through the closed
``boxps::PaddleShuffler`` (data_set.cc:1757-1926) and coordinates dense
sync/membership through the closed ``boxps::MPICluster`` (box_wrapper.h:
415-566). On TPU the *device* plane needs neither (XLA collectives over
ICI/DCN do dense sync); what remains is the *host* plane — record shuffle,
pass working-set key exchange, batch-count lockstep — which this module
provides over plain TCP:

- ``TcpTransport``: rank<->rank tagged message frames with persistent
  connections; primitives ``alltoall`` / ``allgather`` / ``allreduce_max``
  / ``barrier``. Peers are ``host:port`` strings, so the same code runs
  2 localhost subprocesses (the reference's own test pattern,
  test_dist_fleet_base.py:158-260) or N real hosts over DCN.
- ``TcpShuffleRouter``: the LocalShuffleRouter exchange/collect contract
  across processes, chunks = serialized ColumnarRecords.

Tags scope rounds (e.g. ``shuffle:3``): a fast rank's frames for round
N+1 queue in the inbox without corrupting a slow rank's round N collect.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Dict, List, Optional, Tuple

_HDR = struct.Struct("<III")  # src_rank, tag_len, payload_len

from paddlebox_tpu import config

config.define_flag(
    "shuffle_chunk_bytes",
    64 << 20,
    "max serialized bytes per shuffle sub-chunk: bounds the sender's "
    "serialization RAM and keeps frames flowing so the receive timeout "
    "paces per-chunk gaps, not whole-pass serialization",
)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


class TcpTransport:
    """Tagged rank-to-rank byte transport over TCP."""

    def __init__(self, rank: int, endpoints: List[str], timeout: float = 120.0):
        self.rank = rank
        self.n_ranks = len(endpoints)
        self.timeout = timeout
        self._endpoints = [self._parse(e) for e in endpoints]
        # (tag, src) -> FIFO of frames: a duplicate tag from one peer queues
        # behind the unconsumed first frame instead of overwriting it (a
        # dataset driven without set_date reuses pass-id-derived tags)
        self._inbox: Dict[Tuple[str, int], List[bytes]] = {}
        self._cond = threading.Condition()
        self._send_socks: Dict[int, socket.socket] = {}
        self._send_locks: Dict[int, threading.Lock] = {
            r: threading.Lock() for r in range(self.n_ranks)
        }
        self._closed = False
        # listener
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        host, port = self._endpoints[rank]
        self._server.bind((host, port))
        # rebind with the OS-assigned port if 0 was requested
        self._endpoints[rank] = self._server.getsockname()
        self._server.listen(self.n_ranks * 4)
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()

    @staticmethod
    def _parse(ep: str) -> Tuple[str, int]:
        host, port = ep.rsplit(":", 1)
        return host, int(port)

    @property
    def port(self) -> int:
        return self._endpoints[self.rank][1]

    # ---- receive side ----------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(
                target=self._reader, args=(conn,), daemon=True
            ).start()

    def _reader(self, conn: socket.socket) -> None:
        try:
            while True:
                hdr = _recv_exact(conn, _HDR.size)
                src, tag_len, n = _HDR.unpack(hdr)
                tag = _recv_exact(conn, tag_len).decode()
                payload = _recv_exact(conn, n)
                with self._cond:
                    self._inbox.setdefault((tag, src), []).append(payload)
                    self._cond.notify_all()
        except (ConnectionError, OSError):
            return

    def _take(self, tag: str, src: int) -> bytes:
        with self._cond:
            ok = self._cond.wait_for(
                lambda: (tag, src) in self._inbox, timeout=self.timeout
            )
            if not ok:
                raise TimeoutError(
                    f"rank {self.rank}: no frame tag={tag!r} from rank {src} "
                    f"within {self.timeout}s"
                )
            q = self._inbox[(tag, src)]
            payload = q.pop(0)
            if not q:
                del self._inbox[(tag, src)]
            return payload

    def recv(self, tag: str, src: int) -> bytes:
        """Blocking receive of one frame (tag, src) — the public primitive
        streamed protocols (TcpShuffleRouter) build on."""
        return self._take(tag, src)

    # ---- send side -------------------------------------------------------

    def _sock_to(self, dst: int) -> socket.socket:
        s = self._send_socks.get(dst)
        if s is None:
            s = socket.create_connection(self._endpoints[dst], timeout=self.timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._send_socks[dst] = s
        return s

    def send(self, dst: int, tag: str, payload: bytes) -> None:
        tb = tag.encode()
        if dst == self.rank:
            with self._cond:
                self._inbox.setdefault((tag, self.rank), []).append(payload)
                self._cond.notify_all()
            return
        with self._send_locks[dst]:
            s = self._sock_to(dst)
            s.sendall(_HDR.pack(self.rank, len(tb), len(payload)) + tb + payload)

    # ---- collectives -----------------------------------------------------

    def alltoall(self, payloads: List[bytes], tag: str) -> List[bytes]:
        """payloads[d] goes to rank d; returns what every rank sent here."""
        if len(payloads) != self.n_ranks:
            raise ValueError(f"need {self.n_ranks} payloads, got {len(payloads)}")
        for dst in range(self.n_ranks):
            self.send(dst, tag, payloads[dst])
        return [self._take(tag, src) for src in range(self.n_ranks)]

    def allgather(self, payload: bytes, tag: str) -> List[bytes]:
        return self.alltoall([payload] * self.n_ranks, tag)

    def allreduce_max(self, value: int, tag: str) -> int:
        vals = self.allgather(struct.pack("<q", int(value)), tag)
        return max(struct.unpack("<q", v)[0] for v in vals)

    def barrier(self, tag: str) -> None:
        self.allgather(b"", "barrier:" + tag)

    def close(self) -> None:
        self._closed = True
        try:
            self._server.close()
        except OSError:
            pass
        for s in self._send_socks.values():
            try:
                s.close()
            except OSError:
                pass


class TcpShuffleRouter:
    """LocalShuffleRouter's exchange/collect contract across processes.

    One router per (transport, dataset); ``exchange`` serializes each
    destination's ColumnarRecords chunk and all-to-alls them; ``collect``
    deserializes what arrived. The zero-length completion message of the
    reference's protocol (data_set.cc:1835-1866) is implicit: the chunk
    count header always arrives, even when zero chunks follow.

    Large passes stream in bounded sub-chunks (``shuffle_chunk_bytes``):
    the sender serializes at most one sub-chunk per destination at a time
    (peak extra RAM is the chunk size, not the whole part) and frames start
    arriving as soon as the first sub-chunk is cut, so the receive timeout
    paces per-chunk gaps instead of whole-pass serialization. The
    receiver's inbox is intentionally UNBOUNDED — it holds at most the
    in-flight pass, exactly like the reference's shuffle_channel_
    (data_set.cc:1870-1926); chunking bounds the sender side only.
    """

    def __init__(self, transport: TcpTransport):
        self.transport = transport
        self.n_nodes = transport.n_ranks
        self._round = 0

    @staticmethod
    def _sub_ranges(chunk, chunk_bytes: int):
        """Split a ColumnarRecords part into ~<=chunk_bytes record ranges.

        Sized from EVERY serialized component (values, offsets, bases,
        search/cmatch/rank metadata, ins_id chars) — undercounting would
        let metadata-heavy stores blow past the sender-RAM bound."""
        import numpy as np

        n = len(chunk)
        total = (
            chunk.u64_values.nbytes
            + chunk.f_values.nbytes
            + chunk.u64_offsets.nbytes
            + chunk.f_offsets.nbytes
            + chunk.u64_base.nbytes
            + chunk.f_base.nbytes
            + chunk.search_ids.nbytes
            + chunk.cmatch.nbytes
            + chunk.rank.nbytes
            + (len(chunk.ins_id_chars) if chunk.ins_id_chars else 0)
            + (chunk.ins_id_off.nbytes if chunk.ins_id_off is not None else 0)
        )
        per = max(1, int(n * chunk_bytes / max(total, 1)))
        return [np.arange(i, min(i + per, n)) for i in range(0, n, per)]

    def exchange(self, from_node: int, parts: list) -> None:
        from paddlebox_tpu.data.record_store import ColumnarRecords

        if from_node != self.transport.rank:
            raise ValueError("exchange must be called by the owning rank")
        chunk_bytes = int(config.get_flag("shuffle_chunk_bytes"))
        tag = f"shuffle:{self._round}"
        tp = self.transport
        # header first (sub-chunk count), then the streamed sub-chunks;
        # destinations interleave so no single slow peer starves the rest
        ranges = []
        for dst, chunk in enumerate(parts):
            if isinstance(chunk, ColumnarRecords):
                ranges.append(self._sub_ranges(chunk, chunk_bytes) if len(chunk) else [])
            elif len(chunk) == 0:
                ranges.append([])
            else:
                raise TypeError(
                    "TcpShuffleRouter moves ColumnarRecords chunks; got "
                    f"{type(chunk).__name__} (enable the native parser or "
                    "convert with ColumnarRecords.from_records)"
                )
        for dst, rs in enumerate(ranges):
            tp.send(dst, tag + "/n", struct.pack("<I", len(rs)))
        max_chunks = max((len(rs) for rs in ranges), default=0)
        for i in range(max_chunks):
            for dst, rs in enumerate(ranges):
                if i < len(rs):
                    tp.send(dst, f"{tag}/{i}", parts[dst].select(rs[i]).to_bytes())

    def collect(self, node: int) -> list:
        from paddlebox_tpu.data.record_store import ColumnarRecords

        if node != self.transport.rank:
            raise ValueError("collect must be called by the owning rank")
        tag = f"shuffle:{self._round}"
        tp = self.transport
        out = []
        counts = [
            struct.unpack("<I", tp.recv(tag + "/n", src))[0]
            for src in range(self.n_nodes)
        ]
        for src, n in enumerate(counts):
            for i in range(n):
                out.append(ColumnarRecords.from_bytes(tp.recv(f"{tag}/{i}", src)))
        self._round += 1
        return out
