"""AucRunner: slot-shuffle feature-importance evaluation.

Re-expresses the reference's AucRunner mode (BoxWrapper::InitializeAucRunner
box_wrapper.h:680-712, GetRandomReplace / RecordReplace / RecordReplaceBack
box_wrapper.cc:652-790, FeasignValuesCandidateList / FeasignValuesReplacer
data_feed.h:1075-1244, BoxHelper::SlotsShuffle box_wrapper.h:961-985):

To score how much a slot (feature) contributes, the trained model is
evaluated on the pass data with that slot's feasigns *replaced* by feasigns
drawn from other random records ("slot shuffle") — the AUC drop vs. the
unshuffled eval is the slot's importance.

Mechanics mirrored from the reference:

- **Candidate pools** (``CandidatePool``): reservoir samples of per-slot
  feasign lists collected from the pass's own records. ``pool_num`` pools
  divide the data (records are assigned round-robin like the reference's
  ``j % auc_runner_pool_div``) so candidates come from a bounded window.
- **Per-record assignment**: every record gets (pool_id, replaced_id) once
  per pass (``observe``), so each eval phase replaces a record's chosen
  slots with the *same* candidate — deterministic across slot groups, which
  keeps phase-to-phase AUC diffs attributable to the slots, not the draw.
- **replace / replace_back** (``slots_shuffle``): swapping slot s's keys in
  a record changes its length, so the flat (values, offsets) arrays are
  rebuilt per record; originals are stashed for exact restoration, matching
  FeasignValuesReplacer::replace/replace_back semantics.
- **Phase flip**: each ``slots_shuffle`` call flips the runner phase
  (BoxWrapper::FlipPhase parity, box_wrapper.h:620-622) so phase-filtered
  metrics (metrics/registry.py) separate shuffled-eval AUC from train AUC.

The per-record Python loop is the C++ thread-pool loop's analog; records are
host objects and this runs between device passes, off the jit path.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from paddlebox_tpu.data.slot_record import SlotRecord
from paddlebox_tpu.data.slot_schema import SlotSchema


class CandidatePool:
    """Reservoir of per-slot feasign lists (FeasignValuesCandidateList parity).

    Each candidate is ``{slot_idx: uint64 values}`` captured from one record
    for the replaced slots. ``add_and_get`` both reservoir-inserts the
    record's own values and returns the id of the candidate the record will
    use when shuffled (AddAndGet, data_feed.h:1099-1123 — sans the
    multi-pass new/cache queues, which exist only to bound C++ reallocation).
    """

    def __init__(self, capacity: int, rng: np.random.Generator):
        self.capacity = capacity
        self._rng = rng
        self._seen = 0
        self.candidates: List[Dict[int, np.ndarray]] = []

    def __len__(self) -> int:
        return len(self.candidates)

    @property
    def full(self) -> bool:
        return len(self.candidates) == self.capacity

    def add_and_get(self, values: Dict[int, np.ndarray]) -> int:
        self._seen += 1
        if not self.full:
            self.candidates.append(values)
        else:
            # reservoir: replace a random existing candidate with prob cap/seen
            j = int(self._rng.integers(0, self._seen))
            if j < self.capacity:
                self.candidates[j] = values
        return int(self._rng.integers(0, len(self.candidates)))

    def get(self, replaced_id: int) -> Dict[int, np.ndarray]:
        return self.candidates[replaced_id]


class AucRunner:
    """Slot-shuffle eval driver over a pass's in-memory records.

    Usage (mirrors test sequence around BoxHelper::SlotsShuffle):

        runner = AucRunner(schema, replaced_slots=["s3", "s7"], capacity=1000)
        runner.observe(dataset.records)            # build pools + assignment
        runner.slots_shuffle(dataset.records, {"s3"})   # eval phase: s3 shuffled
        ... evaluate, read AUC ...
        runner.slots_shuffle(dataset.records, set())    # restore all
    """

    def __init__(
        self,
        schema: SlotSchema,
        replaced_slots: Sequence[str],
        capacity: int = 10000,
        pool_num: int = 1,
        seed: int = 0,
    ):
        self.schema = schema
        self.replaced_slot_idx: Set[int] = {
            schema.sparse_slot_index(s) for s in replaced_slots
        }
        self.pool_num = pool_num
        self._rng = np.random.default_rng(seed)
        self.pools = [CandidatePool(capacity, self._rng) for _ in range(pool_num)]
        # per-record assignment, parallel to the observed record list
        self._pool_id: Optional[np.ndarray] = None
        self._replaced_id: Optional[np.ndarray] = None
        # record_id -> {slot_idx: original values} while shuffled
        self._saved: List[Optional[Dict[int, np.ndarray]]] = []
        self.last_slots: Set[int] = set()
        self.phase = 1
        self._lock = threading.Lock()

    # ---- pass setup ------------------------------------------------------

    def observe(self, records: Sequence[SlotRecord]) -> None:
        """Build candidate pools from the pass records and fix each record's
        (pool_id, replaced_id) draw (GetRandomReplace parity,
        box_wrapper.cc:736-760)."""
        with self._lock:
            n = len(records)
            self._pool_id = np.arange(n, dtype=np.int64) % self.pool_num
            self._replaced_id = np.zeros(n, dtype=np.int64)
            self._saved = [None] * n
            self.last_slots = set()
            for i, rec in enumerate(records):
                vals = {
                    s: rec.slot_keys(s).copy() for s in self.replaced_slot_idx
                }
                self._replaced_id[i] = self.pools[self._pool_id[i]].add_and_get(vals)

    # ---- shuffle / restore ----------------------------------------------

    def _rebuild(self, rec: SlotRecord, new_vals: Dict[int, np.ndarray]) -> None:
        """Rewrite rec's flat u64 arrays with ``new_vals`` for chosen slots
        (FeasignValuesReplacer offset-fixup parity, vectorized)."""
        n_slots = len(rec.u64_offsets) - 1
        parts = []
        lens = np.empty(n_slots, dtype=np.int64)
        for s in range(n_slots):
            v = new_vals.get(s)
            if v is None:
                v = rec.slot_keys(s)
            parts.append(v)
            lens[s] = len(v)
        rec.u64_values = (
            np.concatenate(parts).astype(np.uint64, copy=False)
            if parts
            else np.zeros(0, np.uint64)
        )
        off = np.zeros(n_slots + 1, dtype=np.uint32)
        np.cumsum(lens, out=off[1:])
        rec.u64_offsets = off

    def slots_shuffle(
        self, records: Sequence[SlotRecord], slots: Set[str]
    ) -> Dict[str, int]:
        """Replace ``slots``' feasigns with pooled candidates; restores the
        previously shuffled slots first (SlotsShuffle driver parity,
        box_wrapper.h:961-985). Empty ``slots`` = restore only. Flips phase.

        Returns {"deleted": n, "added": n} feasign counts like the VLOGs.
        """
        if self._pool_id is None:
            raise RuntimeError("observe(records) must run before slots_shuffle")
        if len(records) != len(self._pool_id):
            raise ValueError("record list changed since observe()")
        slot_idx = {self.schema.sparse_slot_index(s) for s in slots}
        bad = slot_idx - self.replaced_slot_idx
        if bad:
            raise ValueError(
                f"slots {bad} were not declared in replaced_slots at init"
            )
        deleted = added = 0
        with self._lock:
            self.phase ^= 1  # FlipPhase
            for i, rec in enumerate(records):
                new_vals: Dict[int, np.ndarray] = {}
                saved = self._saved[i]
                if saved is not None:  # restore last round's slots
                    for s, orig in saved.items():
                        new_vals[s] = orig
                        deleted += int(rec.u64_offsets[s + 1] - rec.u64_offsets[s])
                        if s not in slot_idx:  # else it never materializes
                            added += len(orig)
                if slot_idx:
                    cand = self.pools[self._pool_id[i]].get(
                        int(self._replaced_id[i])
                    )
                    save: Dict[int, np.ndarray] = {}
                    for s in slot_idx:
                        cur = new_vals.get(s)
                        if cur is None:
                            save[s] = rec.slot_keys(s).copy()
                            deleted += len(save[s])
                        else:  # restored-and-reshuffled: deletion already counted
                            save[s] = cur
                        cv = cand[s]
                        new_vals[s] = cv
                        added += len(cv)
                    self._saved[i] = save
                else:
                    self._saved[i] = None
                if new_vals:
                    self._rebuild(rec, new_vals)
            self.last_slots = slot_idx
        return {"deleted": int(deleted), "added": int(added)}
