from paddlebox_tpu.metrics.auc import AucState, auc_init, auc_update, auc_compute

__all__ = ["AucState", "auc_init", "auc_update", "auc_compute"]
