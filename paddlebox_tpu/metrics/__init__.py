from paddlebox_tpu.metrics.auc import AucState, auc_init, auc_update, auc_compute
from paddlebox_tpu.metrics.auc_runner import AucRunner, CandidatePool
from paddlebox_tpu.metrics.registry import (
    CmatchRankMaskMetricMsg,
    CmatchRankMetricMsg,
    MaskMetricMsg,
    MetricMsg,
    MetricRegistry,
    MultiTaskMetricMsg,
)

__all__ = [
    "AucState",
    "auc_init",
    "auc_update",
    "auc_compute",
    "AucRunner",
    "CandidatePool",
    "MetricMsg",
    "MetricRegistry",
    "MaskMetricMsg",
    "MultiTaskMetricMsg",
    "CmatchRankMetricMsg",
    "CmatchRankMaskMetricMsg",
]
