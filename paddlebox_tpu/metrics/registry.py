"""Named metric registry with phase / cmatch / rank / mask filtering.

Parity with the reference's metric machinery (box_wrapper.h:281-361 MetricMsg
hierarchy, box_wrapper.cc:1111-1172 InitMetric/GetMetricMsg dispatch, pybind
box_helper_py.cc:87-97):

- ``MetricMsg``          — plain label/pred AUC metric with a phase filter
  (workers only feed metrics whose phase matches the current join/update
  phase, boxps_worker.cc:413)
- ``CmatchRankMetricMsg``— filters on (cmatch, rank) pairs; ``ignore_rank``
  degrades it to cmatch-only
- ``MultiTaskMetricMsg`` — cmatch-group filter (== CmatchRankMetricMsg with
  ignore_rank, kept as a named class for reference parity)
- ``MaskMetricMsg``      — counts samples where an output mask var != 0
- ``CmatchRankMaskMetricMsg`` — both filters

TPU-native shape: every metric owns a device-resident ``AucState`` (bucketed
pos/neg tables, metrics/auc.py); ``add_data`` builds the sample mask with
jnp ops and dispatches one fused masked bucket-scatter — async, no host sync
per batch. ``get_metric_msg`` is the only host sync (pass end), computing the
full stat block (auc/bucket_error/mae/rmse/ctr/copc) then resetting, exactly
like the reference's compute-and-reset contract.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from paddlebox_tpu import config
from paddlebox_tpu.metrics.auc import AucState, auc_compute, auc_init, auc_update


def parse_cmatch_rank_group(group: str) -> List[Tuple[int, int]]:
    """Parse "401:0,401:1" (or "401_0" / bare "401") into (cmatch, rank)
    pairs; bare cmatch entries get rank -1 = any."""
    pairs: List[Tuple[int, int]] = []
    for item in group.split(","):
        item = item.strip()
        if not item:
            continue
        for sep in (":", "_"):
            if sep in item:
                c, r = item.split(sep, 1)
                pairs.append((int(c), int(r)))
                break
        else:
            pairs.append((int(item), -1))
    return pairs


@jax.jit
def _masked_update(state: AucState, preds, labels, mask) -> AucState:
    return auc_update(state, preds, labels, mask)


def _var(outputs: Dict[str, jnp.ndarray], name: str, metric: str) -> jnp.ndarray:
    try:
        return jnp.asarray(outputs[name]).reshape(-1)
    except KeyError:
        raise KeyError(
            f"metric {metric!r} needs output var {name!r} but the batch does "
            "not carry it — cmatch/rank require logkey parsing on the schema "
            "(parse_logkey), mask vars must be returned by the step"
        ) from None


def _nonzero_mask(outputs, var: str, metric: str) -> jnp.ndarray:
    return (_var(outputs, var, metric) != 0).astype(jnp.int32)


class MetricMsg:
    """Base metric: label/pred AUC with phase filtering."""

    method = "auc"

    def __init__(
        self,
        name: str,
        label_var: str = "labels",
        pred_var: str = "preds",
        phase: int = -1,
        bucket_size: Optional[int] = None,
    ):
        self.name = name
        self.label_var = label_var
        self.pred_var = pred_var
        self.phase = phase  # -1 = every phase
        self.bucket_size = bucket_size or config.get_flag("auc_num_buckets")
        self.state: AucState = auc_init(self.bucket_size)
        # serializes the read-modify-write on state for concurrent feeders
        # (multiple worker threads feed one registry in the reference too)
        self._state_lock = threading.Lock()

    # -- filtering ---------------------------------------------------------

    def metric_phase(self) -> int:
        return self.phase

    def sample_mask(self, outputs: Dict[str, jnp.ndarray]) -> Optional[jnp.ndarray]:
        """None = count everything. Subclasses narrow it."""
        return None

    # -- accumulation ------------------------------------------------------

    def add_data(self, outputs: Dict[str, jnp.ndarray], phase: int = -1) -> bool:
        """Accumulate one batch if the phase matches; returns whether counted.

        ``outputs`` maps var names to device (or numpy) arrays; preds/labels
        flatten to [N] so sharded [n_dev, b] outputs feed directly.
        """
        if self.phase >= 0 and phase >= 0 and phase != self.phase:
            return False
        preds = _var(outputs, self.pred_var, self.name)
        labels = _var(outputs, self.label_var, self.name).astype(jnp.float32)
        mask = self.sample_mask(outputs)
        if mask is None:
            mask = jnp.ones(preds.shape, jnp.int32)
        if "ins_weight" in outputs:
            # ghost-padded instances (pv join batches) never count
            mask = mask * (_var(outputs, "ins_weight", self.name) > 0).astype(jnp.int32)
        with self._state_lock:
            self.state = _masked_update(self.state, preds, labels, mask)
        return True

    # -- readout -----------------------------------------------------------

    def get_metric(self) -> Dict[str, float]:
        """Compute the stat block and reset (GetMetricMsg contract)."""
        with self._state_lock:
            state, self.state = self.state, auc_init(self.bucket_size)
        return auc_compute(state)

    def get_metric_msg(self) -> str:
        """The reference's log line format (box_wrapper.cc:1141-1160)."""
        m = self.get_metric()
        return (
            f"{self.name}: AUC={m['auc']:.6f} BUCKET_ERROR={m['bucket_error']:.6f} "
            f"MAE={m['mae']:.6f} RMSE={m['rmse']:.6f} "
            f"Actual CTR={m['actual_ctr']:.6f} Predicted CTR={m['predicted_ctr']:.6f} "
            f"COPC={m['copc']:.6f} INS_NUM={m['ins_num']:.0f}"
        )

    def reset(self) -> None:
        with self._state_lock:
            self.state = auc_init(self.bucket_size)


class MaskMetricMsg(MetricMsg):
    """Counts samples where ``mask_var`` != 0 (box_wrapper.h mask variant)."""

    def __init__(self, name: str, mask_var: str, **kw):
        super().__init__(name, **kw)
        if not mask_var:
            raise ValueError(f"metric {name!r}: mask_auc needs a mask_var")
        self.mask_var = mask_var

    def sample_mask(self, outputs):
        return _nonzero_mask(outputs, self.mask_var, self.name)


class CmatchRankMetricMsg(MetricMsg):
    """Counts samples matching any (cmatch, rank) pair; ``ignore_rank``
    matches on cmatch alone (CmatchRankMetricMsg parity)."""

    def __init__(
        self,
        name: str,
        cmatch_rank_group: str,
        ignore_rank: bool = False,
        cmatch_var: str = "cmatch",
        rank_var: str = "rank",
        **kw,
    ):
        super().__init__(name, **kw)
        self.cmatch_var = cmatch_var
        self.rank_var = rank_var
        self.ignore_rank = ignore_rank
        self.pairs = parse_cmatch_rank_group(cmatch_rank_group)
        if not self.pairs:
            raise ValueError(f"empty cmatch_rank group for metric {name!r}")
        # constant lookup tables, built once (hot add_data path stays pure
        # device dispatch)
        self._cs = jnp.asarray([c for c, _ in self.pairs])
        self._rs = jnp.asarray([r for _, r in self.pairs])

    def sample_mask(self, outputs):
        cmatch = _var(outputs, self.cmatch_var, self.name)
        hit = cmatch[:, None] == self._cs[None, :]
        if not self.ignore_rank:
            rank = _var(outputs, self.rank_var, self.name)
            hit = hit & ((rank[:, None] == self._rs[None, :]) | (self._rs[None, :] < 0))
        return jnp.any(hit, axis=1).astype(jnp.int32)


class MultiTaskMetricMsg(CmatchRankMetricMsg):
    """cmatch-group filter: the reference's MultiTaskMetricMsg is exactly the
    rank-blind cmatch membership test."""

    def __init__(self, name: str, cmatch_group: str, cmatch_var: str = "cmatch", **kw):
        super().__init__(
            name, cmatch_group, ignore_rank=True, cmatch_var=cmatch_var, **kw
        )


class CmatchRankMaskMetricMsg(CmatchRankMetricMsg):
    """(cmatch, rank) filter AND an output mask var (reference's combined
    variant)."""

    def __init__(self, name: str, cmatch_rank_group: str, mask_var: str, **kw):
        super().__init__(name, cmatch_rank_group, **kw)
        if not mask_var:
            raise ValueError(f"metric {name!r}: combined variant needs a mask_var")
        self.mask_var = mask_var

    def sample_mask(self, outputs):
        return super().sample_mask(outputs) * _nonzero_mask(
            outputs, self.mask_var, self.name
        )


class MetricRegistry:
    """Name-keyed metric table (BoxWrapper metric_name_list_ parity).

    ``init_metric`` mirrors the pybind surface (box_helper_py.cc:87-97):
    method selects the variant, empty group/mask strings select the base.
    """

    def __init__(self):
        self._metrics: Dict[str, MetricMsg] = {}
        self._lock = threading.Lock()

    def init_metric(
        self,
        name: str,
        method: str = "auc",
        label_var: str = "labels",
        pred_var: str = "preds",
        cmatch_rank_var: str = "cmatch",
        mask_var: str = "",
        phase: int = -1,
        cmatch_rank_group: str = "",
        ignore_rank: bool = False,
        bucket_size: Optional[int] = None,
    ) -> MetricMsg:
        if method not in ("auc", "multi_task_auc", "cmatch_rank_auc", "mask_auc"):
            raise ValueError(f"unknown metric method {method!r}")
        kw = dict(
            label_var=label_var, pred_var=pred_var, phase=phase, bucket_size=bucket_size
        )
        m: MetricMsg
        if method == "multi_task_auc":
            m = MultiTaskMetricMsg(name, cmatch_rank_group, cmatch_var=cmatch_rank_var, **kw)
        elif cmatch_rank_group and mask_var:
            m = CmatchRankMaskMetricMsg(
                name,
                cmatch_rank_group,
                mask_var,
                ignore_rank=ignore_rank,
                cmatch_var=cmatch_rank_var,
                **kw,
            )
        elif method == "cmatch_rank_auc" or cmatch_rank_group:
            m = CmatchRankMetricMsg(
                name,
                cmatch_rank_group,
                ignore_rank=ignore_rank,
                cmatch_var=cmatch_rank_var,
                **kw,
            )
        elif method == "mask_auc" or mask_var:
            m = MaskMetricMsg(name, mask_var, **kw)
        else:
            m = MetricMsg(name, **kw)
        with self._lock:
            self._metrics[name] = m
        return m

    def names(self) -> List[str]:
        with self._lock:
            return list(self._metrics)

    def __getitem__(self, name: str) -> MetricMsg:
        with self._lock:
            return self._metrics[name]

    def add_all(self, outputs: Dict[str, jnp.ndarray], phase: int = -1) -> int:
        """Feed one batch's outputs to every phase-matching metric
        (AddAucMonitor parity, boxps_worker.cc:408-418). Returns how many
        metrics counted the batch."""
        with self._lock:
            metrics = list(self._metrics.values())
        return sum(1 for m in metrics if m.add_data(outputs, phase))

    def get_metric_msg(self, name: str) -> str:
        return self[name].get_metric_msg()

    def get_metric(self, name: str) -> Dict[str, float]:
        return self[name].get_metric()
