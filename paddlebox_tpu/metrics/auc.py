"""Online AUC — bucketed calculator, device-resident.

Parity with BasicAucCalculator (box_wrapper.h:61-138): predictions hash into
``n_buckets`` pos/neg count tables (reference uses 1e6 doubles, CPU or GPU
collection via cuda_add_data box_wrapper.cu:1581); AUC plus bucket_error,
MAE, RMSE, actual/predicted CTR derive from the tables.

TPU-native shape: the state is two int32 bucket tables updated by scatter-add
*inside* the jitted train step (no host sync per step, exact counts to 2^31
per bucket); multi-device reduction is one psum at read time
(collect_data_nccl parity, box_wrapper.h:129). Every derived statistic —
including MAE/RMSE/predicted CTR — integrates over the bucket tables in f64
on the host at pass end, so nothing accumulates in f32 (the reference keeps
doubles for the same reason; with 1e6 buckets the center-of-bucket
approximation error is <1e-6, far below metric noise).
"""

from __future__ import annotations

from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class AucState(NamedTuple):
    pos: jnp.ndarray  # int32 [n_buckets] click counts per prediction bucket
    neg: jnp.ndarray  # int32 [n_buckets] non-click counts


AUC_BUCKET_CAP = np.int32(1 << 30)  # saturation ceiling (overflow guard)


def auc_init(n_buckets: int = 1_000_000) -> AucState:
    return AucState(
        pos=jnp.zeros((n_buckets,), jnp.int32),
        neg=jnp.zeros((n_buckets,), jnp.int32),
    )


def auc_update(
    state: AucState,
    preds: jnp.ndarray,  # f32 [B] in [0, 1]
    labels: jnp.ndarray,  # f32 [B] 0/1
    mask: jnp.ndarray | None = None,  # [B] 1 = count this sample
) -> AucState:
    """Jit-safe accumulate (add_data/cuda_add_data parity)."""
    n_buckets = state.pos.shape[0]
    if mask is None:
        imask = jnp.ones(preds.shape, jnp.int32)
    else:
        imask = mask.astype(jnp.int32)
    bucket = jnp.clip((preds * n_buckets).astype(jnp.int32), 0, n_buckets - 1)
    ilab = (labels > 0.5).astype(jnp.int32)
    # ONE fused scatter over [pos ++ neg]: a click adds at bucket, a
    # non-click at n_buckets + bucket — halves the per-step scatter cost
    # vs two separate bucket-table updates (cuda_add_data also writes both
    # tables in one kernel, box_wrapper.cu:1581)
    tab = jnp.concatenate([state.pos, state.neg])
    tab = tab.at[bucket + (1 - ilab) * n_buckets].add(imask)
    # saturate at 2^30: a bucket that hot stops counting instead of
    # wrapping int32 and corrupting every derived metric; auc_compute
    # reports `saturated` so the condition is visible
    tab = jnp.minimum(tab, AUC_BUCKET_CAP)
    return AucState(pos=tab[:n_buckets], neg=tab[n_buckets:])


def auc_psum(state: AucState, axis_name: str) -> AucState:
    """Cross-device reduction (collect_data_nccl + MPI parity)."""
    return jax.tree.map(lambda x: jax.lax.psum(x, axis_name), state)


def auc_compute(state: AucState) -> Dict[str, float]:
    """Host-side f64 integration (BasicAucCalculator::compute parity)."""
    pos = np.asarray(state.pos, dtype=np.float64)
    neg = np.asarray(state.neg, dtype=np.float64)
    # saturation check runs BEFORE the device-axis sum: clipping happens
    # per device slice, so a sum of N healthy slices must not false-alarm
    saturated = float(
        np.any(pos >= float(AUC_BUCKET_CAP)) or np.any(neg >= float(AUC_BUCKET_CAP))
    )
    if pos.ndim > 1:  # device-sharded bucket tables [n_dev, buckets]
        pos = pos.reshape(-1, pos.shape[-1]).sum(axis=0)
        neg = neg.reshape(-1, neg.shape[-1]).sum(axis=0)
    n_buckets = len(pos)
    center = (np.arange(n_buckets, dtype=np.float64) + 0.5) / n_buckets

    # AUC = P(score_pos > score_neg): for each negative bucket, count
    # positives in strictly higher buckets + half of same-bucket ties
    tot_pos = np.cumsum(pos)
    p, n = tot_pos[-1], np.sum(neg)
    pos_above = p - tot_pos
    area = np.sum(neg * (pos_above + pos / 2.0))
    auc = float(area / (p * n)) if p > 0 and n > 0 else 0.5

    # bucket error: impression-weighted |predicted - actual| ctr over
    # buckets with enough traffic
    show = pos + neg
    keep = show > 8
    if keep.any():
        rel = np.abs(center[keep] - pos[keep] / show[keep])
        bucket_error = float(np.sum(rel * show[keep]) / np.sum(show[keep]))
    else:
        bucket_error = 0.0

    count = float(p + n)
    safe = max(count, 1.0)
    pred_sum = float(np.sum(center * show))
    # label 1 -> |pred-label| = 1-pred ; label 0 -> pred
    abserr = float(np.sum(pos * (1.0 - center) + neg * center))
    sqrerr = float(np.sum(pos * (1.0 - center) ** 2 + neg * center**2))
    return {
        "auc": auc,
        "bucket_error": bucket_error,
        "mae": abserr / safe,
        "rmse": float(np.sqrt(sqrerr / safe)),
        "actual_ctr": float(p) / safe,
        "predicted_ctr": pred_sum / safe,
        "copc": float(p) / max(pred_sum, 1e-12),
        "ins_num": count,
        # any bucket at the saturation cap under-counted: metrics are
        # approximate from here on (overflow guard, not silent wraparound)
        "saturated": saturated,
    }
