"""PV (page-view) instance merging and rank_offset construction.

Parity with the reference's join-phase machinery:
- ``PreprocessInstance`` sorts records by search_id and groups each query's
  ads into one ``SlotPvInstance`` (data_set.cc:1968-2009);
- ``PostprocessInstance`` restores the flat record list for the update phase;
- ``GetRankOffset`` builds the [ins, 2*max_rank+1] matrix rank_attention
  consumes (data_feed.cc:2531-2580): col 0 is the ad's own 1-based rank (-1
  if invalid), col 2m+1/2m+2 are the rank and batch row of the pv's ad with
  rank m+1. An ad is rank-valid iff its cmatch is in ``valid_cmatch`` and
  1 <= rank <= max_rank (the reference hard-codes cmatch 222/223).

TPU-shaped difference: the reference serves join batches of N whole pvs with
a data-dependent total ad count; XLA wants static shapes, so ``pack_pv_batches``
packs whole pvs into fixed-size instance batches and pads the tail with
weight-0 ghost copies of the last real ad — ghosts contribute nothing to the
loss, metrics, or per-key show/clk counts (ins_weight plumbs through the
train step).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, List, Sequence, Tuple

import numpy as np

from paddlebox_tpu.data.slot_record import SlotRecord

DEFAULT_VALID_CMATCH = (222, 223)


@dataclass
class PvInstance:
    """One page view: the ads served for one search_id (SlotPvInstance)."""

    search_id: int
    ads: List[SlotRecord] = field(default_factory=list)

    def merge_instance(self, rec: SlotRecord) -> None:
        self.ads.append(rec)


def merge_pv_instances(
    records: Sequence[SlotRecord], sort: bool = True
) -> List[PvInstance]:
    """Group records into pv instances by search_id (PreprocessInstance).

    ``sort=True`` mirrors the reference's stable sort by search_id so a
    query's ads land together even after a global shuffle.
    """
    if sort:
        records = sorted(records, key=lambda r: r.search_id)
    pvs: List[PvInstance] = []
    for rec in records:
        if pvs and pvs[-1].search_id == rec.search_id:
            pvs[-1].merge_instance(rec)
        else:
            pvs.append(PvInstance(search_id=rec.search_id, ads=[rec]))
    return pvs


def flatten_pv_instances(pvs: Sequence[PvInstance]) -> List[SlotRecord]:
    """Back to the flat record list (PostprocessInstance parity)."""
    out: List[SlotRecord] = []
    for pv in pvs:
        out.extend(pv.ads)
    return out


def _ad_rank(rec: SlotRecord, max_rank: int, valid_cmatch) -> int:
    if rec.cmatch in valid_cmatch and 1 <= rec.rank <= max_rank:
        return rec.rank
    return -1


def build_rank_offset(
    pvs: Sequence[PvInstance],
    ins_number: int,
    max_rank: int = 3,
    valid_cmatch: Sequence[int] = DEFAULT_VALID_CMATCH,
) -> np.ndarray:
    """[ins_number, 2*max_rank+1] int32 matrix (GetRankOffset parity).

    Ads are assumed laid out pv-contiguously in the batch, pvs in order;
    rows past the pvs' total ad count stay all -1 (ghost padding).
    """
    col = 2 * max_rank + 1
    mat = np.full((ins_number, col), -1, dtype=np.int32)
    index = 0
    for pv in pvs:
        start = index
        ranks = [_ad_rank(ad, max_rank, valid_cmatch) for ad in pv.ads]
        for j, rank in enumerate(ranks):
            mat[index, 0] = rank
            if rank > 0:
                for k, fast_rank in enumerate(ranks):
                    if fast_rank > 0:
                        m = fast_rank - 1
                        mat[index, 2 * m + 1] = fast_rank
                        mat[index, 2 * m + 2] = start + k
            index += 1
    return mat


def _iter_pv_blocks(
    pvs: Sequence[PvInstance],
    b: int,
    n_devices: int,
    drop_remainder: bool = False,
) -> Iterator[List[List[PvInstance]]]:
    """The greedy pv->block packing grid, shared by pack/count/stats so the
    three can never disagree about batch composition. Each yielded item is
    up to n_devices groups of whole pvs, each group <= b instances."""
    blocks: List[List[PvInstance]] = [[]]
    cur_ins = 0
    for pv in pvs:
        n = len(pv.ads)
        if n > b:
            raise ValueError(
                f"pv with {n} ads exceeds join block size {b} "
                f"({b * n_devices} instances / {n_devices} devices)"
            )
        if cur_ins + n > b:
            if len(blocks) == n_devices:
                yield blocks
                blocks = [[]]
            else:
                blocks.append([])
            cur_ins = 0
        blocks[-1].append(pv)
        cur_ins += n
    if any(g for g in blocks) and not drop_remainder:
        yield blocks


def first_pv_record(pvs: Sequence[PvInstance]):
    """First real ad, used as the weight-0 ghost for all-ghost batches."""
    for pv in pvs:
        if pv.ads:
            return pv.ads[0]
    return None


def pack_pv_batches(
    pvs: Sequence[PvInstance],
    batch_size: int,
    max_rank: int = 3,
    valid_cmatch: Sequence[int] = DEFAULT_VALID_CMATCH,
    drop_remainder: bool = False,
    n_devices: int = 1,
    min_batches: int = 0,
) -> Iterator[Tuple[List[SlotRecord], np.ndarray, np.ndarray]]:
    """Yield (records, rank_offset, ins_weight) join-phase batches.

    Whole pvs pack greedily into ``batch_size`` instance slots; the tail pads
    with weight-0 ghost copies of the last real ad so every batch has the
    same static shape. A pv with more ads than a block is rejected.

    With ``n_devices > 1`` the batch is packed as ``n_devices`` blocks of
    ``batch_size / n_devices`` slots, NO pv crossing a block boundary, and
    rank_offset peer rows are DEVICE-LOCAL (0..b-1 within each block) — the
    shape the mesh join step's per-device rank_attention gathers over. The
    records stream out device-major, matching the sharded packer's
    ins -> device mapping (ins // b).

    ``min_batches`` keeps multi-host meshes in lockstep (the pv analog of
    compute_thread_batch_nccl, data_set.cc:2069-2135): after the local pvs
    run out, all-ghost batches (weight 0 everywhere, rank_offset all -1)
    are emitted until ``min_batches`` have been yielded, so a host with
    fewer page views still executes every collective of the pass.
    """
    if batch_size % n_devices:
        raise ValueError(f"batch {batch_size} not divisible by {n_devices} devices")
    b = batch_size // n_devices

    def emit(blocks: List[List[PvInstance]]):
        while len(blocks) < n_devices:  # tail: some devices all-ghost
            blocks.append([])
        records: List[SlotRecord] = []
        weight = np.zeros(batch_size, dtype=np.float32)
        ros = []
        for d, group in enumerate(blocks):
            recs = flatten_pv_instances(group)
            n_real = len(recs)
            weight[d * b : d * b + n_real] = 1.0
            ghost = recs[-1] if recs else _GHOST_FALLBACK(blocks)
            while len(recs) < b:  # ghost-pad the block
                recs.append(ghost)
            records.extend(recs)
            ros.append(build_rank_offset(group, b, max_rank, valid_cmatch))
        return records, np.concatenate(ros, axis=0), weight

    def _GHOST_FALLBACK(blocks):
        for g in blocks:
            for pv in g:
                if pv.ads:
                    return pv.ads[0]
        raise ValueError("cannot ghost-pad an entirely empty pv batch")

    if min_batches and drop_remainder:
        raise ValueError("min_batches (lockstep) and drop_remainder conflict")
    emitted = 0
    for blocks in _iter_pv_blocks(pvs, b, n_devices, drop_remainder):
        yield emit(blocks)
        emitted += 1
    ghost = first_pv_record(pvs) if emitted < min_batches else None
    while emitted < min_batches:
        if ghost is None:
            raise ValueError(
                "lockstep needs at least one local record to ghost-pad "
                "with (this host holds zero page views)"
            )
        yield (
            [ghost] * batch_size,
            np.full((batch_size, 2 * max_rank + 1), -1, dtype=np.int32),
            np.zeros(batch_size, dtype=np.float32),
        )
        emitted += 1


@dataclass
class PvPlan:
    """Pass-deterministic join-phase feed plan, as arrays.

    ``pack_pv_batches``' record stream re-expressed at the index level: pv
    batch composition is fully determined once ``preprocess_instance`` has
    grouped the pass (the reference likewise fixes batch_offsets_ at
    PrepareTrain, data_set.cc:2155-2192), so the whole join phase can be
    materialized ONCE per pass as three stacked tensors and every later
    consumer — the native host packer, the device-resident feed, the
    multi-host pad lockstep — becomes vectorized array math instead of a
    per-record Python sweep.

    - ``idx`` [n_batches, B] int64: store record index per instance slot
      (ghost padding repeats a real record's index; ``ins_weight`` zeroes it)
    - ``rank_offset`` [n_batches, B, 2*max_rank+1] int32 (device-local peer
      rows when ``n_devices`` > 1, matching the mesh join step)
    - ``ins_weight`` [n_batches, B] float32 (0 on ghosts)
    """

    idx: np.ndarray
    rank_offset: np.ndarray
    ins_weight: np.ndarray
    n_devices: int

    @property
    def n_batches(self) -> int:
        return self.idx.shape[0]


def build_pv_plan(
    pvs: Sequence[PvInstance],
    batch_size: int,
    max_rank: int = 3,
    valid_cmatch: Sequence[int] = DEFAULT_VALID_CMATCH,
    n_devices: int = 1,
    min_batches: int = 0,
):
    """Materialize pack_pv_batches as a PvPlan (one pass over the pvs).

    Returns None when any record lacks a store index (``_store_idx`` is
    stamped when records materialize from a ColumnarRecords store) — such
    datasets keep the record-level pv path.
    """
    idxs, ros, wts = [], [], []
    for recs, ro, w in pack_pv_batches(
        pvs,
        batch_size,
        max_rank=max_rank,
        valid_cmatch=valid_cmatch,
        n_devices=n_devices,
        min_batches=min_batches,
    ):
        row = np.empty(len(recs), np.int64)
        for j, r in enumerate(recs):
            si = getattr(r, "_store_idx", None)
            if si is None:
                return None
            row[j] = si
        idxs.append(row)
        ros.append(ro)
        wts.append(w)
    col = 2 * max_rank + 1
    if not idxs:
        return PvPlan(
            np.zeros((0, batch_size), np.int64),
            np.zeros((0, batch_size, col), np.int32),
            np.zeros((0, batch_size), np.float32),
            n_devices,
        )
    return PvPlan(
        np.stack(idxs), np.stack(ros), np.stack(wts), n_devices
    )


def count_pv_batches(
    pvs: Sequence[PvInstance], batch_size: int, n_devices: int = 1
) -> int:
    """Number of batches pack_pv_batches will yield (no materialization).

    Multi-host join phases allreduce-max this count so every host runs the
    same number of mesh collectives (lockstep parity)."""
    if batch_size % n_devices:
        raise ValueError(f"batch {batch_size} not divisible by {n_devices} devices")
    b = batch_size // n_devices
    return sum(1 for _ in _iter_pv_blocks(pvs, b, n_devices))
