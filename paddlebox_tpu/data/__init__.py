from paddlebox_tpu.data.slot_schema import SlotSchema, SlotInfo
from paddlebox_tpu.data.slot_record import SlotRecord, SlotBatch, build_batch
from paddlebox_tpu.data.parser import parse_line, parse_logkey
from paddlebox_tpu.data.dataset import BoxPSDataset, LocalShuffleRouter

__all__ = [
    "SlotSchema",
    "SlotInfo",
    "SlotRecord",
    "SlotBatch",
    "build_batch",
    "parse_line",
    "parse_logkey",
    "BoxPSDataset",
    "LocalShuffleRouter",
]
