from paddlebox_tpu.data.slot_schema import SlotSchema, SlotInfo
from paddlebox_tpu.data.slot_record import SlotRecord, SlotBatch, build_batch
from paddlebox_tpu.data.parser import parse_line, parse_logkey
from paddlebox_tpu.data.dataset import BoxPSDataset, LocalShuffleRouter
from paddlebox_tpu.data.quarantine import (
    DataPoisonedError,
    QuarantineLog,
    read_dead_letter,
)
from paddlebox_tpu.data.data_generator import DataGenerator, MultiSlotDataGenerator
from paddlebox_tpu.data.pv_instance import (
    PvInstance,
    build_rank_offset,
    flatten_pv_instances,
    merge_pv_instances,
    pack_pv_batches,
)

__all__ = [
    "SlotSchema",
    "SlotInfo",
    "SlotRecord",
    "SlotBatch",
    "build_batch",
    "parse_line",
    "DataGenerator",
    "MultiSlotDataGenerator",
    "parse_logkey",
    "BoxPSDataset",
    "LocalShuffleRouter",
    "DataPoisonedError",
    "QuarantineLog",
    "read_dead_letter",
    "PvInstance",
    "build_rank_offset",
    "flatten_pv_instances",
    "merge_pv_instances",
    "pack_pv_batches",
]
