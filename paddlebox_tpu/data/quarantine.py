"""Data-plane fault domain: record quarantine + dead-letter files.

The reference feed tolerates dirty production logs — a malformed line is
counted and skipped, never fatal (SlotPaddleBoxDataFeed::ParseOneInstance
returns false and bumps an error counter; data_feed.cc keeps reading) —
because a bad upstream data drop is the single most common production
incident for a log-fed CTR system. Our parser tier is strict by design
(it is the semantics oracle the native tier is tested against), so the
tolerance lives one layer up, here:

- In ``data_quarantine`` mode (flag, default on) a per-line parse failure
  is CAPTURED, not raised: the original line, file, line number, and
  exception land in a :class:`QuarantineLog`, and the records around it
  keep loading. File-level failures (unreadable file, truncated gz, pipe
  converter death) quarantine the whole file the same way. A missing
  input (``FileNotFoundError``) is NOT quarantined — that is a transient
  fault (late upstream drop) owned by the fs/load retry tier; quarantine
  owns *corruption*, which no retry can heal.
- At the end of the load the log settles into ``PassStats``
  (``bad_lines`` / ``bad_files`` / per-file breakdown) and, when anything
  was quarantined, writes a **dead-letter file**: JSONL under the
  quarantine dir (checkpoint root by default — the supervisor wires
  ``<ckpt_root>/quarantine``), one summary line then one entry per
  quarantined line/file, so an operator can replay or triage the exact
  bytes that were dropped.
- ``begin_pass`` runs a **bounded-loss admission gate**: above
  ``max_bad_line_fraction`` / ``max_bad_file_fraction`` the pass is
  rejected with :class:`DataPoisonedError` — a *deterministic* fault the
  PassSupervisor routes around the transient retry loop (corruption
  replays identically on every retry; see train/supervisor.py
  ``on_poisoned_pass``).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Any, Dict, List, Optional

from paddlebox_tpu import config

config.define_flag(
    "data_quarantine",
    1,
    "capture per-line parse failures and unreadable part files into a "
    "per-pass dead-letter file instead of aborting the load; begin_pass "
    "then admission-gates the pass on the corrupt fraction. 0 = strict: "
    "the first bad line raises out of load_into_memory",
)
config.define_flag(
    "max_bad_line_fraction",
    0.01,
    "begin_pass admission gate: reject the pass (DataPoisonedError) when "
    "quarantined lines exceed this fraction of all lines read",
)
config.define_flag(
    "max_bad_file_fraction",
    0.2,
    "begin_pass admission gate: reject the pass (DataPoisonedError) when "
    "quarantined (skipped) part files exceed this fraction of the filelist",
)
config.define_flag(
    "data_quarantine_dir",
    "",
    "where dead-letter files land; empty = the dataset's quarantine_dir "
    "(the supervisor wires <checkpoint_root>/quarantine) or a "
    "pbox_quarantine dir under the system temp dir as last resort",
)


class DataPoisonedError(RuntimeError):
    """The pass's input data is corrupt beyond the admission thresholds.

    DETERMINISTIC, unlike the transient faults the retry machinery heals:
    replaying the same filelist hits the same corruption on every attempt,
    so the supervisor never burns its backoff/retry budget on it (see
    ``on_poisoned_pass``). Carries the admission report and the
    dead-letter path naming exactly what was dropped.
    """

    def __init__(
        self,
        detail: str,
        report: Optional[Dict[str, Any]] = None,
        dead_letter: Optional[str] = None,
    ):
        super().__init__(detail)
        self.detail = detail
        self.report = report or {}
        self.dead_letter = dead_letter


def resolve_quarantine_dir(explicit: Optional[str]) -> str:
    """Quarantine dir precedence: dataset arg > flag > tempdir fallback."""
    d = explicit or str(config.get_flag("data_quarantine_dir"))
    if not d:
        d = os.path.join(tempfile.gettempdir(), "pbox_quarantine")
    return d


class QuarantineLog:
    """Thread-safe collector for one load's quarantined lines and files.

    Readers quarantine from the dataset's thread pool, so all state is
    serialized on one lock. Entry storage is bounded (``MAX_KEPT``) so a
    fully corrupt multi-GB file cannot balloon host RAM — counts keep
    accumulating past the cap and the dead-letter summary records the
    truncation.
    """

    MAX_KEPT = 10_000
    MAX_LINE_CHARS = 4096

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: List[Dict[str, Any]] = []  # guarded-by: _lock
        self.bad_lines = 0  # guarded-by: _lock
        self.bad_files = 0  # guarded-by: _lock
        self.per_file: Dict[str, int] = {}  # guarded-by: _lock

    def quarantine_line(
        self, path: str, line_no: int, line: str, exc: BaseException
    ) -> None:
        with self._lock:
            self.bad_lines += 1
            self.per_file[path] = self.per_file.get(path, 0) + 1
            if len(self._entries) < self.MAX_KEPT:
                self._entries.append(
                    {
                        "kind": "line",
                        "file": path,
                        "line_no": int(line_no),
                        "line": line[: self.MAX_LINE_CHARS],
                        "error": repr(exc),
                    }
                )

    def quarantine_file(self, path: str, exc: BaseException) -> None:
        with self._lock:
            self.bad_files += 1
            self.per_file.setdefault(path, 0)
            if len(self._entries) < self.MAX_KEPT:
                self._entries.append(
                    {"kind": "file", "file": path, "error": repr(exc)}
                )

    @property
    def total(self) -> int:
        with self._lock:
            return self.bad_lines + self.bad_files

    def settle(self, stats) -> None:
        """Fold the counters into a PassStats (the one accounting path —
        both parser tiers and the file-level skips report through here)."""
        with self._lock:
            stats.bad_lines = self.bad_lines
            stats.bad_files = self.bad_files
            stats.bad_by_file = dict(self.per_file)

    def write(self, dirpath: str, name: str, meta: Dict[str, Any]) -> str:
        """Write the dead-letter file (JSONL: one summary line, then one
        entry per quarantined line/file) and return its path."""
        from paddlebox_tpu.utils.fs import atomic_write

        os.makedirs(dirpath, exist_ok=True)
        path = os.path.join(dirpath, f"{name}.deadletter.jsonl")
        with self._lock:
            summary = {
                "kind": "summary",
                "bad_lines": self.bad_lines,
                "bad_files": self.bad_files,
                "entries": len(self._entries),
                "truncated": self.bad_lines + self.bad_files
                > len(self._entries),
                **meta,
            }
            entries = list(self._entries)
        with atomic_write(path) as f:
            f.write(json.dumps(summary) + "\n")
            for e in entries:
                f.write(json.dumps(e) + "\n")
        return path


def read_dead_letter(path: str) -> Dict[str, Any]:
    """Parse a dead-letter file -> {"summary": dict, "entries": [dict]}.
    The triage/round-trip counterpart of :meth:`QuarantineLog.write`."""
    summary: Dict[str, Any] = {}
    entries: List[Dict[str, Any]] = []
    with open(path) as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            obj = json.loads(raw)
            if obj.get("kind") == "summary":
                summary = obj
            else:
                entries.append(obj)
    return {"summary": summary, "entries": entries}
