"""Overlapped feed pipeline: background pack + device upload.

The reference keeps GPUs fed by packing minibatches on pinned host buffers
in worker threads and issuing async H2D copies ahead of compute
(MiniBatchGpuPack + copy_host2device, data_feed.h:1418-1542, :1492-1504).
The TPU analog: a small thread pool runs pack (native C++, GIL-released)
and ``device_put`` (async under the hood — it returns before the transfer
completes) for batch N+1..N+depth while the device steps batch N. The
consumer sees feeds strictly in batch order; depth bounds host memory the
way the reference's reused pack buffers do.
"""

from __future__ import annotations

from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Iterator, TypeVar

T = TypeVar("T")
R = TypeVar("R")

from paddlebox_tpu import config

config.define_flag("feed_pipeline_workers", 3, "background packer thread count")
config.define_flag(
    "feed_pipeline_depth", 6, "max batches packed/uploaded ahead of compute"
)


def prefetch(
    jobs: Iterable[T],
    fn: Callable[[T], R],
    workers: int | None = None,
    depth: int | None = None,
) -> Iterator[R]:
    """Yield ``fn(job)`` in order, computing up to ``depth`` jobs ahead on
    ``workers`` threads. Exceptions surface at the failing job's position;
    the window keeps order deterministic (same batches, same sequence, with
    or without the pipeline)."""
    workers = workers or config.get_flag("feed_pipeline_workers")
    depth = depth or config.get_flag("feed_pipeline_depth")
    it = iter(jobs)
    ex = ThreadPoolExecutor(max_workers=workers)
    futs: deque = deque()
    try:
        for job in it:
            futs.append(ex.submit(fn, job))
            if len(futs) >= depth:
                break
        sentinel = object()
        while futs:
            f = futs.popleft()
            nxt = next(it, sentinel)
            if nxt is not sentinel:
                futs.append(ex.submit(fn, nxt))
            yield f.result()
    finally:
        for f in futs:
            f.cancel()
        ex.shutdown(wait=True, cancel_futures=True)
