"""Overlapped feed pipeline: background pack + device upload.

The reference keeps GPUs fed by packing minibatches on pinned host buffers
in worker threads and issuing async H2D copies ahead of compute
(MiniBatchGpuPack + copy_host2device, data_feed.h:1418-1542, :1492-1504).
The TPU analog: a small thread pool runs pack (native C++, GIL-released)
and ``device_put`` (async under the hood — it returns before the transfer
completes) for batch N+1..N+depth while the device steps batch N. The
consumer sees feeds strictly in batch order; depth bounds host memory the
way the reference's reused pack buffers do.
"""

from __future__ import annotations

import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Iterator, TypeVar

T = TypeVar("T")
R = TypeVar("R")

from paddlebox_tpu import config
from paddlebox_tpu.utils.faultinject import fire as _fault_fire

# occupancy gauge over every live prefetch window in the process: how many
# jobs are exercising the pool right now, and the deepest it ever got. The
# high-water mark is the tuning signal for feed_pipeline_workers/depth — a
# hwm pinned at workers*depth means the device is starved on pack/upload.
_gauge_lock = threading.Lock()
_inflight = 0  # guarded-by: _gauge_lock
_inflight_hwm = 0  # guarded-by: _gauge_lock


def prefetch_inflight() -> int:
    """Jobs currently executing across all prefetch windows."""
    with _gauge_lock:
        return _inflight


def prefetch_inflight_hwm(reset: bool = False) -> int:
    """Deepest concurrent-job count seen so far (optionally reset)."""
    global _inflight_hwm
    with _gauge_lock:
        hwm = _inflight_hwm
        if reset:
            _inflight_hwm = _inflight
        return hwm

config.define_flag("feed_pipeline_workers", 3, "background packer thread count")
config.define_flag(
    "feed_pipeline_depth", 6, "max batches packed/uploaded ahead of compute"
)
config.define_flag(
    "feed_pipeline_retries",
    1,
    "re-runs of a failed prefetch job before its exception surfaces (a "
    "transient packer/device_put hiccup should not kill the pass)",
)


def prefetch(
    jobs: Iterable[T],
    fn: Callable[[T], R],
    workers: int | None = None,
    depth: int | None = None,
    retries: int | None = None,
) -> Iterator[R]:
    """Yield ``fn(job)`` in order, computing up to ``depth`` jobs ahead on
    ``workers`` threads. A failed job is re-run up to ``retries`` times
    (transient packer/``device_put`` hiccups heal in place); a persistent
    exception surfaces at the failing job's position — the window keeps
    order deterministic (same batches, same sequence, with or without the
    pipeline)."""
    workers = workers or config.get_flag("feed_pipeline_workers")
    depth = depth or config.get_flag("feed_pipeline_depth")
    if retries is None:
        retries = config.get_flag("feed_pipeline_retries")

    def run(job: T) -> R:
        global _inflight, _inflight_hwm
        with _gauge_lock:
            _inflight += 1
            if _inflight > _inflight_hwm:
                _inflight_hwm = _inflight
        try:
            _fault_fire("pipeline.prefetch_job")
            return fn(job)
        finally:
            with _gauge_lock:
                _inflight -= 1

    it = iter(jobs)
    ex = ThreadPoolExecutor(max_workers=workers)
    futs: deque = deque()
    try:
        for job in it:
            futs.append((job, ex.submit(run, job)))
            if len(futs) >= depth:
                break
        sentinel = object()
        while futs:
            job, f = futs.popleft()
            nxt = next(it, sentinel)
            if nxt is not sentinel:
                futs.append((nxt, ex.submit(run, nxt)))
            try:
                yield f.result()
            except Exception:
                # retry in the consumer thread: delivery position (and thus
                # order) is preserved by construction, and the in-flight
                # window behind this job keeps working meanwhile
                from paddlebox_tpu.utils.monitor import STAT_ADD

                for attempt in range(max(0, retries)):
                    STAT_ADD("pipeline_prefetch_retries")
                    try:
                        yield run(job)
                        break
                    except Exception:
                        if attempt + 1 >= retries:
                            raise
                else:
                    raise
    finally:
        for _, f in futs:
            f.cancel()
        ex.shutdown(wait=True, cancel_futures=True)
