"""Pass-scoped in-memory dataset: the BoxPSDataset / PadBoxSlotDataset analog.

Reference surface being rebuilt (SURVEY.md B7/B17):
- python driver `BoxPSDataset` (python/paddle/fluid/dataset.py:1081-1221):
  set_date / load_into_memory / preload_into_memory / wait_preload_done /
  begin_pass / end_pass(need_save_delta) / slots_shuffle;
- C++ `PadBoxSlotDataset` (framework/data_set.cc:1515-2192): threaded file
  read into SlotRecords, feasign collection into the pass working set
  (PSAgent::AddKeys, data_set.cc:1647), node-striped file lists ("dualbox",
  data_set.cc:1452-1464), record shuffle before train (PrepareTrain,
  data_set.cc:2155-2192), equalized minibatch counts across devices
  (compute_thread_batch_nccl, data_set.cc:2069-2135).

TPU-shaped differences: the "device working set" is one dense jax array
sharded over the mesh (built by PassWorkingSet.finalize) instead of closed
HBM caches, and record routing across hosts is pluggable (``router``) with
hash semantics identical to the reference (search_id % n, XXH-style ins_id
hash, random).
"""

from __future__ import annotations

import itertools
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from paddlebox_tpu import config
from paddlebox_tpu.data.parser import parse_line
from paddlebox_tpu.data.quarantine import (
    DataPoisonedError,
    QuarantineLog,
    resolve_quarantine_dir,
)
from paddlebox_tpu.data.pv_instance import (
    PvInstance,
    flatten_pv_instances,
    merge_pv_instances,
    pack_pv_batches,
)
from paddlebox_tpu.data.record_store import ColumnarRecords
from paddlebox_tpu.data.slot_record import SlotBatch, SlotRecord, build_batch
from paddlebox_tpu.data.slot_schema import SlotSchema
from paddlebox_tpu.table.sparse_table import HostSparseTable, PassWorkingSet
from paddlebox_tpu.utils.faultinject import fire
from paddlebox_tpu.utils.fs import fs_glob
from paddlebox_tpu.utils.line_reader import BufferedLineFileReader
from paddlebox_tpu.utils.monitor import STAT_ADD, STAT_OBSERVE, STAT_SET
from paddlebox_tpu.utils.trace import record_event

config.define_flag(
    "padbox_dataset_shuffle_thread_num", 8, "default dataset reader thread count"
)
config.define_flag(
    "enable_carried_table",
    1,
    "keep the trained pass table in device HBM across the pass boundary "
    "and splice surviving rows into the next pass's table device-to-device "
    "(D2H only the departing keys, H2D only the new ones); 0 = classic "
    "full writeback + full re-upload",
)
config.define_flag(
    "carried_eager_flush",
    0,
    "after the carried-table splice, flush the carrier to the host store "
    "on a background thread (full-table D2H overlapping the next pass). "
    "Frees the extra HBM the lazy default pins for a whole pass — use "
    "when HBM, not transport bandwidth, is the constraint",
)
config.define_flag(
    "boundary_pipeline",
    1,
    "pipelined pass boundary: the load thread premerges the staged pass's "
    "key chunks (and, with boundary_prefetch_pull, prefetches host rows) "
    "while the current pass trains, so begin_pass finds the dedup/pull "
    "already done; 0 = classic serial boundary",
)
config.define_flag(
    "overlap_writeback",
    1,
    "kick the end-of-pass host writeback the moment the trained table "
    "lands (kick_writeback, called by the supervisor right after "
    "train_pass): the boundary worker joins the kick instead of writing "
    "back inline, so boundary.writeback_s records only the residual "
    "blocking tail and the hidden seconds flow into overlap_hidden_s. "
    "Safe under an armed guard (rollback covers partial writeback; "
    "revert_pass cancels the kick at a chunk boundary); 0 = classic "
    "writeback inside the boundary worker",
)
config.define_flag(
    "boundary_prefetch_pull",
    1,
    "with boundary_pipeline: the feed stage pull_or_creates host rows for "
    "staged keys NOT in the live pass (those rows cannot change before the "
    "boundary except by decay, which the consumer compensates bitwise). "
    "Auto-disabled when shrink_threshold != 0 or a mem_cap spill tier is "
    "active — either could invalidate prefetched rows",
)


def _ins_id_dest(ins_id: str, n_parts: int) -> int:
    # xxhash in the reference; any good string hash preserves semantics
    import hashlib

    return (
        int.from_bytes(hashlib.blake2b(ins_id.encode(), digest_size=8).digest(), "little")
        % n_parts
    )


def shuffle_route(records: Sequence[SlotRecord], n_parts: int, mode: str, seed: int) -> List[int]:
    """Destination part of each record (ShuffleData routing parity,
    data_set.cc:1772-1791): 'search_id' groups a query's ads on one node,
    'ins_id' spreads by instance hash, 'random' is uniform."""
    if mode == "search_id":
        return [r.search_id % n_parts for r in records]
    if mode == "ins_id":
        return [_ins_id_dest(r.ins_id, n_parts) for r in records]
    if mode == "random":
        rng = np.random.default_rng(seed)
        return list(rng.integers(0, n_parts, len(records)))
    raise ValueError(f"unknown shuffle mode {mode!r}")


def shuffle_route_store(
    store: ColumnarRecords, n_parts: int, mode: str, seed: int
) -> np.ndarray:
    """Vectorized shuffle_route over a columnar store -> int dest array."""
    n = len(store)
    if mode == "search_id":
        return (store.search_ids % np.uint64(n_parts)).astype(np.int64)
    if mode == "ins_id":
        return np.array(
            [_ins_id_dest(store.ins_id(i), n_parts) for i in range(n)], np.int64
        )
    if mode == "random":
        rng = np.random.default_rng(seed)
        return rng.integers(0, n_parts, n)
    raise ValueError(f"unknown shuffle mode {mode!r}")


class LocalShuffleRouter:
    """In-process stand-in for the closed ``boxps::PaddleShuffler`` RPC tier:
    exchanges record chunks between n logical nodes living in one process. A
    multi-host deployment plugs a host-RPC implementation with the same
    exchange()/collect() contract (parallel/transport.py TcpShuffleRouter,
    exercised by tests/test_multihost.py). A chunk is
    either a ``List[SlotRecord]`` or a ``ColumnarRecords``; the dataset
    normalizes on collect."""

    def __init__(self, n_nodes: int):
        self.n_nodes = n_nodes
        self._inboxes: List[list] = [[] for _ in range(n_nodes)]
        self._cond = threading.Condition()
        self._done = 0
        self._collected = 0

    def exchange(self, from_node: int, parts: list) -> None:
        """Deliver this node's outgoing chunks (one per destination); marks
        the node finished sending (the zero-length completion message of the
        reference's protocol, data_set.cc:1835-1866, collapses into this
        call). A node racing ahead into the next pass blocks here until
        every node collected the current one, so passes can never interleave
        in the inboxes."""
        with self._cond:
            self._cond.wait_for(lambda: self._done < self.n_nodes)
            for dst, chunk in enumerate(parts):
                if len(chunk):
                    self._inboxes[dst].append(chunk)
            self._done += 1
            self._cond.notify_all()

    def collect(self, node: int) -> list:
        """Blocks until every node has exchanged (ShuffleResultWaitGroup
        parity) so no late-arriving records are dropped. Returns the list
        of received chunks."""
        with self._cond:
            self._cond.wait_for(lambda: self._done >= self.n_nodes)
            out = self._inboxes[node]
            self._inboxes[node] = []
            self._collected += 1
            if self._collected >= self.n_nodes:  # re-arm for the next pass
                self._done = 0
                self._collected = 0
                self._cond.notify_all()  # wake exchangers blocked on the barrier
        return out


def _trained_to_host(arr, layout) -> np.ndarray:
    """Device trained table -> host ndarray, honoring the boundary wire
    format. Shared by the boundary worker's classic writeback and the
    overlapped kick_writeback thread, so both paths produce identical
    bytes."""
    if not isinstance(arr, np.ndarray) and not getattr(
        arr, "is_fully_addressable", True
    ):
        # multi-host global array: writeback wants this host's local
        # shard block only
        shards = sorted(
            arr.addressable_shards, key=lambda s: s.index[0].start or 0
        )
        arr = np.concatenate([np.asarray(s.data) for s in shards], axis=0)
    if not isinstance(arr, np.ndarray):
        from paddlebox_tpu.ops.wire_quant import fetch_rows

        shape = arr.shape
        arr = fetch_rows(
            arr.reshape(-1, shape[-1]), layout,
            str(config.get_flag("wire_dtype")),
        ).reshape(shape)
    return np.asarray(arr)


class _WritebackKick:
    """An in-flight overlapped writeback started by kick_writeback.

    The future resolves to the kick thread's total wall seconds (for the
    hidden-overlap accounting) or to the failure; ``cancel`` is checked by
    the chunked writeback at chunk boundaries (revert path)."""

    def __init__(self, ws):
        from concurrent.futures import Future

        self.ws = ws
        self.cancel = threading.Event()
        self.fut: "Future[float]" = Future()
        self.thread: Optional[threading.Thread] = None


@dataclass
class PassStats:
    """Per-load accounting, consistent across the native and Python tiers:

    ``lines``          every non-empty line seen (parsed + benign + bad)
    ``parsed``         lines that produced a record
    ``skipped_benign`` parser returned None legitimately (all-zero record,
                       '#' cache line) — the native tier's nstats["skipped"]
    ``bad_lines``      quarantined parse failures (0 unless data_quarantine)
    ``bad_files``      whole part files skipped (unreadable / truncated /
                       converter death)
    """

    files: int = 0
    lines: int = 0
    records: int = 0
    keys: int = 0
    parsed: int = 0
    skipped_benign: int = 0
    bad_lines: int = 0
    bad_files: int = 0
    bad_by_file: Dict[str, int] = field(default_factory=dict)
    dead_letter: Optional[str] = None


class BoxPSDataset:
    """One node's view of the pass data pipeline.

    Life cycle per pass (test_paddlebox_datafeed.py:103-119 sequence):
        set_date -> [pre]load_into_memory -> begin_pass
        -> batches()/train -> end_pass(need_save_delta)
    """

    def __init__(
        self,
        schema: SlotSchema,
        table: HostSparseTable,
        batch_size: int,
        n_mesh_shards: int = 1,
        read_threads: Optional[int] = None,
        rank: int = 0,
        nranks: int = 1,
        shuffle_mode: str = "none",  # none|local|search_id|ins_id|random
        router: Optional[LocalShuffleRouter] = None,
        transport=None,  # parallel.transport.TcpTransport for multi-host
        pipe_command: Optional[str] = None,
        line_parser: Optional[Callable[[str, SlotSchema], Optional[SlotRecord]]] = None,
        drop_remainder: bool = True,
        seed: int = 0,
        quarantine_dir: Optional[str] = None,
    ):
        self.schema = schema
        self.table = table
        self.batch_size = batch_size
        self.n_mesh_shards = n_mesh_shards
        self.read_threads = (
            read_threads
            if read_threads is not None
            else config.get_flag("padbox_dataset_shuffle_thread_num")
        )
        self.rank = rank
        self.nranks = nranks
        self.shuffle_mode = shuffle_mode
        self.router = router
        self.transport = transport
        self.pipe_command = pipe_command
        self.line_parser = line_parser or parse_line
        self.drop_remainder = drop_remainder
        self.seed = seed
        # where dead-letter files land (None -> data_quarantine_dir flag ->
        # tempdir fallback); the supervisor wires <checkpoint_root>/quarantine
        self.quarantine_dir = quarantine_dir
        self._dead_letter_seq = 0  # synchronized-by: load-thread exclusivity (one load/preload in flight)
        self._loading_qlog: Optional[QuarantineLog] = None  # synchronized-by: load-thread exclusivity

        self.date: Optional[str] = None
        self.pass_id = 0
        # bumped by every revert_pass: scopes the distributed working-set
        # exchange tags so a retried pass never consumes frames from the
        # aborted attempt (see TcpTransport.discard_epochs_below)
        self.pass_epoch = 0
        # explicit key-ownership map (parallel/membership.OwnershipMap),
        # installed/replaced by the elastic supervisor on membership or
        # placement changes; None = even split over all transport ranks
        self.ownership = None
        self.current_phase = 1  # 1 join, 0 update (data_set.h:291)
        self._filelist: List[str] = []
        # pass data lives EITHER columnar (store + shuffle order — the fast
        # path) or as a SlotRecord list (fallback parser / pv / eval paths);
        # the `records` property materializes a view list on demand.
        self.store: Optional[ColumnarRecords] = None
        self._order: Optional[np.ndarray] = None
        self._records: List[SlotRecord] = []
        self.ws: Optional[PassWorkingSet] = None
        self.device_table: Optional[np.ndarray] = None
        self.stats = PassStats()
        self._preload_thread: Optional[threading.Thread] = None
        self._preload_exc: Optional[BaseException] = None  # synchronized-by: preload join handoff (wait_preload_done)
        self._end_pass_fut = None  # pending end_pass_async worker
        self._in_pass = False
        # staged (store, order, records, ws, stats) loaded but not begun
        self._staged = None  # synchronized-by: preload join handoff (wait_preload_done)
        # staged boundary prefetch {src, keys, rows, epoch} built by the
        # feed stage alongside _staged; consumed (or dropped) by begin_pass.
        # Same synchronization discipline as _staged: written only by the
        # load path, read after wait_preload_done joins it.
        self._boundary_prefetch = None  # synchronized-by: preload join handoff (wait_preload_done)
        # stage time hidden behind training (reported via overlap_hidden_s);
        # accumulated on the load/preload thread, settled on the trainer
        # thread at wait_end_pass
        self._stage_lock = threading.Lock()
        self._stage_hidden_s = 0.0  # guarded-by: _stage_lock
        # serializes the live-pass slot swap (store/_order/_records/ws/
        # stats/_in_pass) between a finishing preload's publish and the
        # end_pass worker's failure re-open: main flips _in_pass False
        # BEFORE the worker runs, so without this lock a preload thread
        # that reads the flag can publish pass N+1 concurrently with a
        # failing worker restoring pass N — a torn mix of two passes.
        # RLock: the publish decision and _publish itself both take it.
        self._pass_lock = threading.RLock()
        self._loading_stats = self.stats  # synchronized-by: load-thread exclusivity (one load/preload in flight; wait_preload_done joins)

    # ---- record access ---------------------------------------------------

    @property
    def records(self) -> List[SlotRecord]:
        """Materialized SlotRecord view of the pass (compat paths: pv merge,
        AucRunner, direct inspection). Store-backed passes materialize
        lazily; the columnar fast path stays live."""
        if not self._records and self.store is not None and len(self.store):
            order = (
                self._order
                if self._order is not None
                else np.arange(len(self.store))
            )
            recs = []
            for i in order:
                r = self.store.record(int(i))
                # remember provenance so a reordering round-trip (pv merge ->
                # flatten) can stay columnar as a permutation of the store
                r._store_idx = int(i)
                recs.append(r)
            self._records = recs
        return self._records

    @records.setter
    def records(self, value) -> None:
        # assigning a list makes it the source of truth (pv flatten etc.);
        # the columnar store would be stale, so drop it
        self._records = list(value)
        self.store = None
        self._order = None

    # ---- pass config -----------------------------------------------------

    def set_date(self, date: str) -> None:
        """New day/pass id (BoxHelper::SetDate parity, box_wrapper.h:810)."""
        self.date = date
        self.pass_id += 1

    def set_filelist(self, files: Sequence[str]) -> None:
        """Full cluster file list; this node reads its rank-strided slice
        (dualbox striping, data_set.cc:1452-1464)."""
        expanded: List[str] = []
        for f in files:
            hits = fs_glob(f) if any(c in f for c in "*?[") else [f]
            expanded.extend(hits)
        self._filelist = expanded[self.rank :: self.nranks]

    def set_current_phase(self, phase: int) -> None:
        self.current_phase = phase

    # ---- pv merge (join phase) ------------------------------------------

    def preprocess_instance(
        self, max_rank: int = 3, valid_cmatch=(222, 223)
    ) -> int:
        """Group this pass's records into pv instances for join-phase
        training (PreprocessInstance parity, data_set.cc:1968-2009).
        Returns the pv count. Requires logkey parsing (search_id)."""
        if not self.schema.parse_logkey:
            raise RuntimeError(
                "preprocess_instance needs search_ids: build the SlotSchema "
                "with parse_logkey=True (else every record has search_id=0 "
                "and the whole pass merges into one pv)"
            )
        self.pvs: List[PvInstance] = merge_pv_instances(self.records)
        self._pv_max_rank = max_rank
        self._pv_valid_cmatch = tuple(valid_cmatch)
        self._pv_merged = True
        return len(self.pvs)

    @property
    def pv_merged(self) -> bool:
        """True between preprocess_instance and postprocess_instance."""
        return getattr(self, "_pv_merged", False)

    def postprocess_instance(self) -> None:
        """Restore the flat record view for the update phase
        (PostprocessInstance parity).

        When the pass is store-backed and every record still knows its
        store index, the pv-flattened order becomes a PERMUTATION of the
        columnar store — the update phase keeps the fast path (and, on a
        multi-host mesh, the transport-locksteped pads that require it)."""
        if not getattr(self, "_pv_merged", False):
            return
        flat = flatten_pv_instances(self.pvs)
        idx = [getattr(r, "_store_idx", None) for r in flat]
        if (
            self.store is not None
            and len(flat) == len(self.store)
            and all(i is not None for i in idx)
        ):
            self._records = flat
            self._order = np.asarray(idx, dtype=np.int64)
        else:
            self.records = flat  # setter: list becomes source of truth
        self.pvs = []
        self._pv_merged = False
        self._pv_plan_cache = None

    def pv_plan(self, n_devices: int = 1, min_batches: int = 0):
        """Cached index-level join-phase feed plan (see PvPlan).

        None when the pass isn't store-backed (records lack store indices);
        then consumers fall back to the record-level pv path. The cache is
        keyed by the pvs object identity plus the packing args — a repeat
        call over the same merged pass (warmup epoch, join eval, pad
        lockstep) costs nothing."""
        if not getattr(self, "_pv_merged", False):
            raise RuntimeError("preprocess_instance first")
        if self.store is None:
            return None
        key = (n_devices, min_batches)
        c = getattr(self, "_pv_plan_cache", None)
        if c is None or c[0] is not self.pvs:
            c = (self.pvs, {})
            self._pv_plan_cache = c
        if key not in c[1]:
            from paddlebox_tpu.data.pv_instance import build_pv_plan

            c[1][key] = build_pv_plan(
                self.pvs,
                self.batch_size,
                max_rank=self._pv_max_rank,
                valid_cmatch=self._pv_valid_cmatch,
                n_devices=n_devices,
                min_batches=min_batches,
            )
        return c[1][key]

    def num_pv_batches(self, n_devices: int = 1, global_count: bool = False) -> int:
        """Join-phase batch count; ``global_count`` allreduce-maxes it over
        the transport so every host runs the same number of mesh
        collectives (the pv analog of ``num_batches(global_count=True)``,
        compute_thread_batch_nccl parity data_set.cc:2069-2135)."""
        if not getattr(self, "_pv_merged", False):
            raise RuntimeError("preprocess_instance first")
        from paddlebox_tpu.data.pv_instance import count_pv_batches

        n = count_pv_batches(self.pvs, self.batch_size, n_devices=n_devices)
        if global_count and self.transport is not None and self.transport.n_ranks > 1:
            n = self.transport.allreduce_max(n, f"pv-count:{self.pass_id}")
        return n

    def pv_batches(
        self,
        n_batches: Optional[int] = None,
        n_devices: int = 1,
        min_batches: int = 0,
    ):
        """Join-phase batches: (SlotBatch with rank_offset, ins_weight).

        Whole pvs pack into ``batch_size`` instance slots, ghost-padded
        (see data/pv_instance.py). SlotBatch.rank_offset is set; ins_weight
        masks ghosts out of loss/metrics/show-clk. With ``n_devices > 1``
        the batch is device-blocked (no pv crosses a device, rank_offset
        rows device-local) for the mesh join step. ``min_batches`` appends
        all-ghost batches for multi-host lockstep (see pack_pv_batches).
        """
        if not getattr(self, "_pv_merged", False):
            raise RuntimeError("preprocess_instance first")
        packed = pack_pv_batches(
            self.pvs,
            self.batch_size,
            max_rank=self._pv_max_rank,
            valid_cmatch=self._pv_valid_cmatch,
            n_devices=n_devices,
            min_batches=min_batches,
        )
        if n_batches is not None:
            packed = itertools.islice(packed, n_batches)
        for records, rank_offset, weight in packed:
            sb = build_batch(records, self.schema)
            sb.rank_offset = rank_offset
            yield sb, weight

    # ---- load ------------------------------------------------------------

    def _native_eligible(self, path: str) -> bool:
        # native fast path applies when nothing needs the line-by-line
        # machinery (pipe converter, sampling, custom parser)
        return (
            self.pipe_command is None
            and self.line_parser is parse_line
            and config.get_flag("sample_rate") >= 1.0
            and config.get_flag("enable_native_parser")
            and not path.startswith(("hdfs:", "afs:"))  # fs dispatch tier
            and not path.endswith(".gz")
        )

    def _parse_lines(self, path: str, numbered_lines, qlog) -> list:
        """Parse (line_no, line) pairs with per-line quarantine; the one
        line-accounting path for the Python tier AND the native tier's
        corrupt-buffer fallback (so both report identically)."""
        out = []
        n_lines = n_parsed = n_benign = 0
        for line_no, line in numbered_lines:
            if not line:
                continue
            n_lines += 1
            try:
                rec = self.line_parser(line, self.schema)
            except Exception as e:  # noqa: BLE001 — quarantined + counted
                if qlog is None:  # strict mode: first bad line is fatal
                    raise
                qlog.quarantine_line(path, line_no, line, e)
                continue
            if rec is None:
                n_benign += 1
            else:
                n_parsed += 1
                out.append(rec)
        with self._stats_lock:
            st = self._loading_stats
            st.lines += n_lines
            st.parsed += n_parsed
            st.skipped_benign += n_benign
        return out

    def _read_one(self, path: str):
        """Read one part file -> ColumnarRecords chunk (native tier) or
        SlotRecord list (Python tier).

        File-level failures (unreadable, truncated gz, pipe-converter death,
        decode errors) quarantine the WHOLE file in data_quarantine mode —
        except FileNotFoundError: a missing input is a transient fault (late
        upstream drop) the fs/load-retry tier owns, and healing it by
        dropping the file would silently starve the pass."""
        qlog = self._loading_qlog
        try:
            fire("data.file_read")
            return self._read_one_inner(path, qlog)
        except FileNotFoundError:
            raise
        except Exception as e:  # noqa: BLE001 — quarantined + counted
            if qlog is None:
                raise
            qlog.quarantine_file(path, e)
            # empty columnar chunk when the pass could have gone columnar,
            # so one quarantined file never knocks the pass off the fast path
            if self._native_eligible(path):
                return ColumnarRecords.empty(
                    self.schema.num_sparse, self.schema.num_float
                )
            return []

    def _read_one_inner(self, path: str, qlog):
        if self._native_eligible(path):
            from paddlebox_tpu.utils import native

            if native.available():
                from paddlebox_tpu.utils.fs import fs_read_bytes_retry

                data = fs_read_bytes_retry(path)
                nstats: dict = {}
                try:
                    chunk = native.parse_buffer_columnar(
                        data, self.schema, nstats
                    )
                except ValueError:
                    if qlog is None:
                        raise
                    # the native parser rejects the whole buffer on its
                    # first corrupt line; re-parse per line so each bad
                    # line quarantines individually, and re-wrap columnar
                    # so the pass stays on the fast path
                    recs = self._parse_lines(
                        path,
                        enumerate(
                            data.decode("utf-8", errors="replace").splitlines(),
                            1,
                        ),
                        qlog,
                    )
                    return ColumnarRecords.from_records(recs, self.schema)
                with self._stats_lock:
                    st = self._loading_stats
                    skipped = nstats.get("skipped", 0)
                    st.lines += len(chunk) + skipped
                    st.parsed += len(chunk)
                    st.skipped_benign += skipped
                return chunk

        # per-file seed decorrelates sampling across part files (same-seeded
        # readers would keep/drop identical line indices)
        seed = hash((self.seed, self.pass_id, path)) & 0x7FFFFFFF
        begin_file = getattr(self.line_parser, "begin_file", None)
        if begin_file is not None:  # per-file parser state (e.g. cache lines)
            begin_file(path)
        reader = BufferedLineFileReader(path, converter=self.pipe_command, seed=seed)
        # lines_read is incremented before the reader yields, so it IS the
        # 1-based number of the line in flight
        return self._parse_lines(
            path, ((reader.lines_read, line) for line in reader), qlog
        )

    def load_into_memory(self) -> None:
        """Threaded read -> (optional shuffle) -> staged records + key set.

        Loads into a STAGING slot, not the live pass — so it can run while
        the previous pass is still training (double buffering; the reference
        survives two passes in RAM the same way, via the record object pool,
        data_feed.h:934). ``begin_pass`` consumes the staged data.
        """
        if self._staged is not None:
            raise RuntimeError("staged pass not yet consumed by begin_pass")
        if self._preload_thread is not None and threading.current_thread() is not self._preload_thread:
            raise RuntimeError("preload in flight; wait_preload_done first")
        self._stats_lock = threading.Lock()
        stats = PassStats(files=len(self._filelist))
        self._loading_stats = stats
        self._loading_qlog = (
            QuarantineLog() if config.get_flag("data_quarantine") else None
        )
        ws = self._new_working_set()
        parts: list = []
        try:
            if self._filelist:
                with ThreadPoolExecutor(max_workers=self.read_threads) as pool:
                    parts = list(pool.map(self._read_one, self._filelist))
            qlog, self._loading_qlog = self._loading_qlog, None
        except BaseException:
            self._loading_qlog = None
            raise
        if qlog is not None:
            self._settle_quarantine(stats, qlog)

        store, order, records = self._normalize_and_shuffle(parts)

        # MergeInsKeys parity (data_set.cc:1628-1683): every feasign of the
        # pass feeds the working set. Runs post-shuffle (ownership is final
        # only after routing).
        if store is not None:
            if len(store.u64_values):
                ws.add_keys(store.u64_values)
            stats.records = len(store)
        else:
            chunk = 4096
            for i in range(0, len(records), chunk):
                ws.add_keys(
                    np.concatenate([r.u64_values for r in records[i : i + chunk]])
                )
            stats.records = len(records)
        self._staged = (store, order, records, ws, stats)
        try:
            self._stage_boundary_prefetch(ws)
        except BaseException:
            # a failed feed stage must not wedge the retry loop: the next
            # load_into_memory would refuse over the leftover staged slot
            self.discard_staged()
            raise
        with self._pass_lock:
            # flag read and publish are one atomic step: an end_pass
            # worker's failure re-open must not interleave (it restores
            # pass N's slots and would tear a concurrent N+1 publish)
            if not self._in_pass:
                # no pass training right now: publish immediately so
                # memory_data_size()/stats match reference post-load
                # semantics (begin_pass still consumes the staged tuple)
                self._publish(self._staged)

    def _stage_boundary_prefetch(self, ws) -> None:
        """Stage 2 of the boundary feed pipeline: premerge the staged
        pass's key chunks and (gated) prefetch its host rows, all on the
        load/preload thread while the current pass trains.

        The premerge collapses ``ws._key_chunks`` so the later finalize
        re-merges a singleton list through merge_unique_keys' no-copy fast
        path; the prefetch pulls rows only for keys NOT in the live pass —
        the live pass's keys are the only host rows the boundary's
        writeback/splice can change, so everything prefetched stays valid
        modulo show/clk decay, which the consumer re-applies bitwise
        (:func:`sparse_table._rows_with_prefetch`)."""
        if not config.get_flag("boundary_pipeline"):
            return
        self._boundary_prefetch = None
        fire("boundary.premerge")
        t0 = time.perf_counter()
        with record_event("boundary.premerge", "boundary"):
            merged = ws.premerge(
                int(config.get_flag("boundary_merge_threads"))
            )
        premerge_s = time.perf_counter() - t0
        STAT_SET("boundary.premerge_s", premerge_s)
        STAT_OBSERVE("boundary.premerge_s", premerge_s)
        if self._in_pass:
            with self._stage_lock:
                self._stage_hidden_s += premerge_s

        live = self.ws
        table = self.table
        if (
            not config.get_flag("boundary_prefetch_pull")
            or not self._in_pass
            or not len(merged)
            or not isinstance(ws, PassWorkingSet)
            or not isinstance(live, PassWorkingSet)
            or not live._finalized
            or table.opt.shrink_threshold != 0
            or table.mem_cap_rows is not None
        ):
            return
        # exclude the live pass's keys: their host rows are not final
        # until its writeback/splice lands at the boundary
        exclude = live.sorted_keys
        if len(exclude):
            pos = np.minimum(
                np.searchsorted(exclude, merged), len(exclude) - 1
            )
            need = merged[exclude[pos] != merged]
        else:
            need = merged
        if not len(need):
            return
        # a departing-slice push from the PREVIOUS boundary may still be
        # in flight and can cover keys in `need` (departed two passes ago,
        # returning now): wait for it to land, WITHOUT consuming a failure
        # — that stays armed for the end_pass worker's join_push
        carrier = getattr(self, "_carrier", None)
        if carrier is not None and not carrier.flushed:
            carrier.wait_push()
        fire("boundary.stage_pull")
        t0 = time.perf_counter()
        with record_event("boundary.stage_pull", "boundary"):
            rows, epoch = table.prefetch_rows(need)
        pull_s = time.perf_counter() - t0
        STAT_SET("boundary.prefetch_pull_s", pull_s)
        STAT_OBSERVE("boundary.prefetch_pull_s", pull_s)
        with self._stage_lock:
            self._stage_hidden_s += pull_s
        self._boundary_prefetch = {
            "src": merged, "keys": need, "rows": rows, "epoch": epoch,
        }

    def discard_staged(self) -> None:
        """Drop a staged-but-unconsumed load and its boundary prefetch
        (supervisor cancel path: a staged pass N+1 must not survive a
        coordinated revert of pass N)."""
        self._staged = None
        self._boundary_prefetch = None

    # ---- quarantine / admission -----------------------------------------

    def _settle_quarantine(self, stats: PassStats, qlog: QuarantineLog) -> None:
        """Fold the load's quarantine log into its PassStats, write the
        dead-letter file when anything was quarantined, and publish the
        data.quarantine.* gauges."""
        qlog.settle(stats)
        if qlog.total:
            self._dead_letter_seq += 1
            name = (
                f"pass-{self.date or 'na'}-{self.pass_id:04d}"
                f"-r{self.rank}-{self._dead_letter_seq:03d}"
            )
            with record_event("data.quarantine.dead_letter", "data"):
                stats.dead_letter = qlog.write(
                    resolve_quarantine_dir(self.quarantine_dir),
                    name,
                    meta={
                        "date": self.date,
                        "pass_id": self.pass_id,
                        "rank": self.rank,
                        "files": stats.files,
                        "lines": stats.lines,
                    },
                )
            STAT_ADD("data.quarantine.dead_letter_files")
        STAT_SET("data.quarantine.bad_lines", stats.bad_lines)
        STAT_SET("data.quarantine.bad_files", stats.bad_files)
        if stats.bad_lines:
            STAT_ADD("data.quarantine.bad_lines_total", stats.bad_lines)
        if stats.bad_files:
            STAT_ADD("data.quarantine.bad_files_total", stats.bad_files)

    def admission_report(self) -> Dict:
        """Bounded-loss admission verdict for the pass about to begin.

        Computed over the STAGED load when one is pending (the pass
        ``begin_pass`` would consume), else the live stats. ``poisoned``
        is True when quarantine is on and either corrupt fraction exceeds
        its threshold — the caller (begin_pass, or the supervisor's
        poison-aware pre-check) decides fail/skip/degrade."""
        st = self._staged[4] if self._staged is not None else self.stats
        max_lf = float(config.get_flag("max_bad_line_fraction"))
        max_ff = float(config.get_flag("max_bad_file_fraction"))
        lf = st.bad_lines / max(1, st.lines)
        ff = st.bad_files / max(1, st.files)
        poisoned = bool(config.get_flag("data_quarantine")) and (
            lf > max_lf or ff > max_ff
        )
        parts = []
        if lf > max_lf:
            parts.append(
                f"{st.bad_lines}/{st.lines} lines quarantined "
                f"({lf:.5f} > max_bad_line_fraction {max_lf:.5f})"
            )
        if ff > max_ff:
            parts.append(
                f"{st.bad_files}/{st.files} part files quarantined "
                f"({ff:.5f} > max_bad_file_fraction {max_ff:.5f})"
            )
        detail = ""
        if poisoned:
            detail = "pass data poisoned: " + "; ".join(parts)
            if st.dead_letter:
                detail += f"; dead-letter: {st.dead_letter}"
        return {
            "poisoned": poisoned,
            "detail": detail,
            "line_fraction": lf,
            "file_fraction": ff,
            "bad_lines": st.bad_lines,
            "bad_files": st.bad_files,
            "lines": st.lines,
            "files": st.files,
            "dead_letter": st.dead_letter,
        }

    def check_admission(self) -> Dict:
        """Raise DataPoisonedError when the pending pass is over the
        bounded-loss thresholds; returns the report otherwise."""
        rep = self.admission_report()
        if rep["poisoned"]:
            raise DataPoisonedError(
                rep["detail"], report=rep, dead_letter=rep["dead_letter"]
            )
        return rep

    def drop_pass_data(self) -> None:
        """Abandon the loaded-but-unbegun pass data (supervisor
        on_poisoned_pass="skip_pass"): staged slot, published records, and
        the un-finalized working set all go; the table is untouched."""
        self.discard_staged()
        if not self._in_pass:
            self.store = None
            self._order = None
            self._records = []
            self.ws = None
            self.stats = PassStats()

    def _new_working_set(self):
        """Fresh (un-finalized) working set for this pass: multi-host
        key-exchange flavor when a transport spans ranks, else local.
        Shared by the load path and revert_pass so their retrains can never
        diverge."""
        if self.transport is not None and self.transport.n_ranks > 1:
            # multi-host: host-sharded table ownership + key exchange;
            # n_mesh_shards is the GLOBAL mesh shard count. ``ownership``
            # (an OwnershipMap, set by the elastic supervisor on membership
            # or placement changes) pins the key routing; None keeps the
            # default even split over all ranks.
            from paddlebox_tpu.table.dist_ws import DistributedWorkingSet

            return DistributedWorkingSet(
                self.transport,
                self.n_mesh_shards,
                pass_id=self.pass_id,
                epoch=self.pass_epoch,
                ownership=getattr(self, "ownership", None),
            )
        return PassWorkingSet(n_mesh_shards=self.n_mesh_shards)

    def _publish(self, staged) -> None:
        store, order, records, ws, stats = staged
        with self._pass_lock:
            self.store = store
            self._order = order
            self._records = records if records is not None else []
            self.ws = ws
            self.stats = stats
            # new data in memory: lockstep batch count must be renegotiated
            self._load_gen = getattr(self, "_load_gen", 0) + 1

    def _normalize_and_shuffle(self, parts: list):
        """File-part chunks -> (store, order, records): columnar when every
        part is columnar (native parse), SlotRecord list otherwise."""
        if parts and all(isinstance(p, ColumnarRecords) for p in parts):
            non_empty = [p for p in parts if len(p)]
            if non_empty:
                store = (
                    ColumnarRecords.concat(non_empty)
                    if len(non_empty) > 1
                    else non_empty[0]
                )
                return self._shuffle_store(store)
        records: List[SlotRecord] = []
        for p in parts:
            records.extend(p.records() if isinstance(p, ColumnarRecords) else p)
        return None, None, self._shuffle_records(records)

    def _shuffle_store(self, store: ColumnarRecords):
        """Columnar shuffle: routing moves arrays, local order is a
        permutation (no data movement at all)."""
        mode = self.shuffle_mode
        rng = np.random.default_rng(self.seed + self.pass_id)
        if mode == "none":
            return store, None, []
        if mode != "local" and self.router is not None:
            dests = shuffle_route_store(
                store, self.router.n_nodes, mode, self.seed + self.pass_id
            )
            parts = [
                store.select(np.nonzero(dests == d)[0])
                for d in range(self.router.n_nodes)
            ]
            self.router.exchange(self.rank, parts)
            chunks = self.router.collect(self.rank)
            cols = [c for c in chunks if isinstance(c, ColumnarRecords)]
            lists = [c for c in chunks if not isinstance(c, ColumnarRecords)]
            if lists:  # mixed transports: normalize to records
                records = [r for c in lists for r in c]
                for c in cols:
                    records.extend(c.records())
                order = rng.permutation(len(records))
                return None, None, [records[i] for i in order]
            store = (
                ColumnarRecords.concat(cols)
                if cols
                else ColumnarRecords.empty(store.n_sparse, store.n_float)
            )
        elif mode != "local" and self.nranks != 1:
            raise RuntimeError("global shuffle across ranks needs a router")
        return store, rng.permutation(len(store)), []


    def preload_into_memory(self) -> None:
        """Overlap next pass's IO with current training
        (PreLoadIntoMemory, data_set.cc:1576-1626)."""
        if self._preload_thread is not None:
            raise RuntimeError("preload already running")

        def run():
            try:
                self.load_into_memory()
            except BaseException as e:  # surfaced in wait_preload_done
                self._preload_exc = e

        self._preload_thread = threading.Thread(target=run, daemon=True)
        self._preload_thread.start()

    def wait_preload_done(self) -> None:
        if self._preload_thread is None:
            return
        self._preload_thread.join()
        self._preload_thread = None
        if self._preload_exc is not None:
            exc, self._preload_exc = self._preload_exc, None
            raise exc

    def _shuffle_records(self, records: List[SlotRecord]) -> List[SlotRecord]:
        mode = self.shuffle_mode
        if mode == "none":
            return records
        rng = np.random.default_rng(self.seed + self.pass_id)
        if mode == "local":
            order = rng.permutation(len(records))
            return [records[i] for i in order]
        # global modes route records between nodes, then local-shuffle
        if self.router is None:
            if self.nranks != 1:
                raise RuntimeError("global shuffle across ranks needs a router")
            order = rng.permutation(len(records))
            return [records[i] for i in order]
        dests = shuffle_route(records, self.router.n_nodes, mode, self.seed + self.pass_id)
        parts: List[List[SlotRecord]] = [[] for _ in range(self.router.n_nodes)]
        for r, d in zip(records, dests):
            parts[d].append(r)
        self.router.exchange(self.rank, parts)
        mine = [
            r
            for chunk in self.router.collect(self.rank)
            for r in (chunk.records() if isinstance(chunk, ColumnarRecords) else chunk)
        ]
        order = rng.permutation(len(mine))
        return [mine[i] for i in order]

    # ---- AucRunner slot-shuffle eval ------------------------------------

    def slots_shuffle(self, slots) -> dict:
        """Replace ``slots``' feasigns in the in-memory records with pooled
        candidates for feature-importance eval (BoxPSDataset.slots_shuffle
        parity, python dataset.py:1191-1210 -> BoxHelper::SlotsShuffle).

        The AucRunner is created lazily over all sparse slots on first use;
        pass ``slots=[]``/set() to restore the previous shuffle. Shuffled
        keys must still resolve in the pass working set — candidates come
        from this pass's own records, so they always do.
        """
        from paddlebox_tpu.metrics.auc_runner import AucRunner

        if not self.records:
            raise RuntimeError("slots_shuffle needs in-memory records")
        recs = self.records  # materializes the store view if needed
        runner = getattr(self, "_auc_runner", None)
        if runner is None or getattr(self, "_auc_runner_pass", None) != self.pass_id:
            cap = config.get_flag("auc_runner_pool_size")
            runner = AucRunner(
                self.schema,
                replaced_slots=[s.name for s in self.schema.used_sparse],
                capacity=cap,
                seed=self.seed + self.pass_id,
            )
            runner.observe(recs)
            self._auc_runner = runner
            self._auc_runner_pass = self.pass_id
        out = runner.slots_shuffle(recs, set(slots))
        if self.store is not None:
            # the runner rewrote record arrays; the columnar store is stale —
            # rebuild it (order baked in) so the fast path serves the
            # shuffled keys
            self.store = ColumnarRecords.from_records(recs, self.schema)
            self._order = None
            self.store.invalidate_rows()
        return out

    @property
    def auc_runner_phase(self) -> int:
        runner = getattr(self, "_auc_runner", None)
        return runner.phase if runner is not None else 1

    # ---- pass lifecycle --------------------------------------------------

    def _eager_drain(self) -> None:
        """Background carrier flush (carried_eager_flush). A failure here
        must be LOUD: drain_pending keeps the failed carrier registered so
        durability is preserved, and the exception is stored and re-raised
        at the next pass boundary instead of dying with the thread."""
        try:
            self.table.drain_pending()
        except Exception as e:  # noqa: BLE001 — surfaced at the boundary
            self._eager_flush_error = e

    def _raise_pending_flush_error(self) -> None:
        # join the in-flight drain first so the check is deterministic: an
        # unjoined thread could fail AFTER this boundary's check and the
        # error would surface a boundary late (or never, at process end)
        t = getattr(self, "_eager_thread", None)
        if t is not None and t.is_alive():
            t.join()
        self._eager_thread = None
        err = getattr(self, "_eager_flush_error", None)
        if err is not None:
            self._eager_flush_error = None
            raise RuntimeError(
                "background carrier flush failed — carried values remain "
                "owed and will be retried by the next drain_pending"
            ) from err

    def begin_pass(
        self,
        round_to: int = 512,
        enable_revert: bool = False,
        trainer=None,
        admit_poisoned: bool = False,
    ) -> np.ndarray:
        """Consume the staged load, finalize the working set, build the device
        table (BeginFeedPass+EndFeedPass+BeginPass collapse: on TPU the HBM
        staging IS the finalize, box_wrapper.cc:580-626).

        ``enable_revert=True`` arms a PassGuard (Confirm/Revert parity,
        fleet_wrapper.h:319-321): the pass keys' pre-train rows (and, with
        ``trainer``, the dense params/opt state) are snapshotted so
        ``revert_pass()`` can reject everything this pass publishes;
        ``end_pass`` confirms.

        Bounded-loss admission gate: a pass whose load quarantined more
        than ``max_bad_line_fraction`` / ``max_bad_file_fraction`` raises
        :class:`DataPoisonedError` BEFORE anything is finalized or armed —
        ``admit_poisoned=True`` overrides (the supervisor's
        ``on_poisoned_pass="degrade"`` path, which trains over the pass
        with the quarantined records dropped)."""
        # a pending async end_pass mutates the host table (writeback/decay/
        # spill); finalize must see its final state
        self.wait_end_pass()
        self._raise_pending_flush_error()
        if self._in_pass:
            # either end_pass was never called, or a FAILED end_pass
            # re-opened the pass; silently starting a new one would strand
            # its state (and discard any armed rollback snapshot)
            raise RuntimeError(
                "previous pass is still open — call end_pass (or, after a "
                "failed end_pass, retry it / revert_pass) before begin_pass"
            )
        if not admit_poisoned:
            # gate BEFORE consuming the staged slot: a rejected pass leaves
            # the staged data intact so the caller can still degrade
            # (begin_pass(admit_poisoned=True)) or drop_pass_data it
            self.check_admission()
        if self._staged is not None:
            self._publish(self._staged)
            self._staged = None
        prefetch, self._boundary_prefetch = self._boundary_prefetch, None
        if self.ws is None:
            raise RuntimeError("load_into_memory first")
        if enable_revert:
            # the rollback snapshot reads host rows — device-carried values
            # must land first or the snapshot (and a later revert) would
            # resurrect pre-carry state
            self.table.drain_pending()
        if not self.ws._finalized:
            carrier = getattr(self, "_carrier", None)
            if carrier is not None and carrier.flushed:
                carrier = None
            if carrier is not None:
                # PassWorkingSet takes a TableCarrier; the multi-host
                # DistributedWorkingSet takes a MultiHostCarrier (per-host
                # shard-block splice) — same kwarg, same delta boundary
                self.device_table = self.ws.finalize(
                    self.table, round_to=round_to, carrier=carrier,
                    prefetch=prefetch,
                )
                if config.get_flag("carried_eager_flush"):
                    self._eager_thread = threading.Thread(
                        target=self._eager_drain, daemon=False
                    )
                    self._eager_thread.start()
            else:
                self.device_table = self.ws.finalize(
                    self.table, round_to=round_to, prefetch=prefetch
                )
        self.stats.keys = self.ws.n_keys
        # monitor parity: the reference bumps STAT_total_feasign_num_in_mem
        # as passes stage into memory (box_wrapper.cc:1282)
        STAT_SET("total_feasign_num_in_mem", self.stats.keys)
        STAT_SET("total_records_in_mem", self.memory_data_size())
        self._in_pass = True
        self._guard = None
        if enable_revert:
            from paddlebox_tpu.train.rollback import PassGuard

            self._guard = PassGuard(self.table, trainer)
            self._guard.begin(self.ws.sorted_keys)
        return self.device_table

    def kick_writeback(self, trained_table) -> None:
        """Start the end-of-pass host writeback NOW, overlapped with
        whatever runs between training and ``end_pass`` (gate evaluation,
        verdict exchange, the next pass's staging): the boundary worker
        then JOINS this kick instead of writing back inline, so
        ``boundary.writeback_s`` records only the residual blocking tail
        and the hidden seconds flow into ``boundary.overlap_hidden_s``.

        Safe under an armed guard: rollback's PassGuard contract covers
        zero/partial/full writeback, so kicking before the verdict costs
        nothing — a rejected pass cancels the kick at a chunk boundary in
        ``revert_pass`` and the revert restores pre-pass rows either way.
        No-op when no pass is open, a kick is already pending, or the
        ``overlap_writeback`` flag is off."""
        if (
            trained_table is None
            or not self._in_pass
            or self.ws is None
            or not bool(config.get_flag("overlap_writeback"))
            or getattr(self, "_wb_kick", None) is not None
        ):
            return
        ws, table = self.ws, self.table
        kick = _WritebackKick(ws)

        def run_kick():
            t0 = time.perf_counter()
            try:
                with record_event("boundary.writeback_kick", "boundary"):
                    arr = _trained_to_host(trained_table, table.layout)
                    ws.writeback(arr, cancel=kick.cancel)
                kick.fut.set_result(time.perf_counter() - t0)
            except BaseException as e:
                kick.fut.set_exception(e)

        # non-daemon for the same reason as the end_pass worker: interpreter
        # exit joins an in-flight writeback instead of truncating it
        kick.thread = threading.Thread(target=run_kick, daemon=False)
        self._wb_kick = kick
        kick.thread.start()

    def _cancel_writeback_kick(self) -> None:
        """Stop a pending overlapped writeback at its next chunk boundary
        and join it — whatever landed is exactly what guard.revert()
        undoes. Swallows the cancellation (it is the requested outcome);
        real failures are counted, not raised: the revert that follows
        undoes their partial effects too."""
        kick = getattr(self, "_wb_kick", None)
        if kick is None:
            return
        from paddlebox_tpu.table.sparse_table import WritebackCancelled

        kick.cancel.set()
        try:
            kick.fut.result()
        except WritebackCancelled:
            STAT_ADD("data.revert_writeback_cancelled")
        except BaseException:
            STAT_ADD("data.revert_end_pass_errors")
        kick.thread.join()
        self._wb_kick = None

    def revert_pass(self) -> None:
        """Reject the current pass (Revert parity, fleet_wrapper.h:319-321,
        pslib __init__.py:673-690): every pass key's host row returns to its
        pre-pass value (undoing any partial/complete writeback), the dense
        side restores, and the in-memory data re-arms so ``begin_pass`` can
        retrain it from scratch."""
        self._cancel_writeback_kick()
        if self._end_pass_fut is not None:
            try:
                self.wait_end_pass()
            except Exception:
                # a failed publish is exactly what revert undoes — but it
                # is still an incident; revert erasing it would make the
                # retry loop's root cause invisible
                STAT_ADD("data.revert_end_pass_errors")
        guard = getattr(self, "_guard", None)
        if guard is None or not guard.armed:
            raise RuntimeError(
                "no armed rollback — begin_pass(enable_revert=True) first"
            )
        guard.revert()
        self._guard = None
        # cancel any staged next pass: join the feed stage first (it may
        # still be writing the staged slot), then drop it — a revert means
        # the retried pass re-derives everything downstream of it, and the
        # supervisor re-loads (or re-stages) pass N+1 afterwards
        if self._preload_thread is not None:
            try:
                self.wait_preload_done()
            except Exception:
                # a failed staged load is discarded with the stage; count
                # it so a flaky reader doesn't hide behind the revert
                STAT_ADD("data.revert_preload_errors")
        self.discard_staged()
        # new epoch for the retrain: the aborted attempt's in-flight
        # exchange frames (if any) must never reach the retried exchange
        self.pass_epoch += 1
        if self.transport is not None and hasattr(
            self.transport, "discard_epochs_below"
        ):
            self.transport.discard_epochs_below(self.pass_epoch)
        # fresh working set over the same in-memory records for the retrain
        ws = self._new_working_set()
        if self.store is not None:
            ws.add_keys(self.store.u64_values)
            self.store.invalidate_rows()
        else:
            for r in self._records:
                ws.add_keys(r.u64_values)
        self.ws = ws
        self.device_table = None
        self._in_pass = False
        self._auc_runner = None

    def end_pass(
        self,
        trained_table: Optional[np.ndarray] = None,
        need_save_delta: bool = False,
        delta_dir: Optional[str] = None,
        shrink: bool = True,
    ) -> dict:
        """Flush trained rows to the host store, decay/shrink, optional delta
        save (EndPass box_wrapper.cc:627 + SaveDelta :1316)."""
        self.end_pass_async(
            trained_table,
            need_save_delta=need_save_delta,
            delta_dir=delta_dir,
            shrink=shrink,
        )
        return self.wait_end_pass()

    def end_pass_async(
        self,
        trained_table: Optional[np.ndarray] = None,
        need_save_delta: bool = False,
        delta_dir: Optional[str] = None,
        shrink: bool = True,
    ) -> None:
        """EndPass in a background thread, overlapped with the next pass's
        ``set_date``/``load_into_memory``/``preload_into_memory``.

        The device->host pull of the trained table plus the host writeback,
        decay/shrink, delta save, and disk spill are the dominant
        between-pass cost; none of it touches what the next LOAD needs (the
        load only reads files and collects keys — the host table is first
        consulted again at ``begin_pass`` finalize, which joins this thread
        automatically). The same overlap the reference gets from BoxHelper's
        feed/end thread pair (box_wrapper.h:897-959). ``trained_table`` may
        be the live device array — the transfer happens on the worker.
        Results surface from ``wait_end_pass`` (or the next begin_pass)."""
        if not self._in_pass:
            raise RuntimeError("begin_pass first")
        self._raise_pending_flush_error()
        if need_save_delta and delta_dir is None:
            raise ValueError("need_save_delta requires delta_dir")
        ws, guard, table = self.ws, getattr(self, "_guard", None), self.table
        # consume a pending overlapped writeback for THIS working set: the
        # worker joins it instead of writing back inline. A kick for a
        # different ws (shouldn't happen — revert/begin clear it) is left
        # alone and the classic path runs.
        kick = getattr(self, "_wb_kick", None)
        if kick is not None and kick.ws is ws:
            self._wb_kick = None
        else:
            kick = None
        # device-carried boundary: retain the trained DEVICE table instead
        # of fetching it; the next finalize splices surviving rows
        # device-to-device and fetches only the departing slice (EndPass
        # HBM-cache-warm parity, box_wrapper.cc:627-651). Gated to the
        # single-device single-process path; a save/guard/delta in the way
        # flushes via table.drain_pending. An in-flight kick is already
        # writing the full table back, so carrying is off for this boundary.
        carrier = None
        carry_ok = (
            trained_table is not None
            and not isinstance(trained_table, np.ndarray)
            and getattr(trained_table, "ndim", 0) in (2, 3)
            and bool(config.get_flag("enable_carried_table"))
            and guard is None
            and kick is None
        )
        from paddlebox_tpu.table.dist_ws import DistributedWorkingSet
        from paddlebox_tpu.table.sparse_table import PassWorkingSet

        if isinstance(ws, PassWorkingSet) and carry_ok:
            import jax as _jax

            if (
                isinstance(trained_table, _jax.Array)
                and _jax.process_count() == 1
            ):
                from paddlebox_tpu.table.carrier import TableCarrier

                # decay is NOT pre-set: the worker's decay_and_shrink notes
                # it on every pending carrier under the maintenance lock,
                # so a concurrent drain can neither miss nor double it.
                # 3-D = single-host MESH table [ns, cap, W] (device-axis
                # sharded): rows stay in-shard across passes (key shard is
                # stable), so the splice's gathers/scatters are legal on
                # the sharded array — any reshard rides ICI, never the
                # host link
                carrier = TableCarrier(trained_table, ws, table.layout)
        elif isinstance(ws, DistributedWorkingSet):
            # multi-host: lockstep the carry decision over the transport
            # (like the resident gate) so every host takes the same
            # boundary. The allreduce runs UNCONDITIONALLY for a DWS pass
            # — a host that can't carry (flag off, guard armed, numpy
            # table) must still answer, or the hosts that can would hang.
            import jax as _jax

            self._carry_seq = getattr(self, "_carry_seq", 0) + 1
            local_ok = int(carry_ok and isinstance(trained_table, _jax.Array))
            agree = -ws.transport.allreduce_max(
                -local_ok, f"carry-gate:{self._carry_seq}"
            )
            if agree:
                from paddlebox_tpu.table.carrier import MultiHostCarrier

                # per-host carrier over this host's addressable shard
                # blocks; splice/departures/flush stay host-local because
                # key->shard->device pinning is pass-stable (writeback is
                # host-local for the same reason, dist_ws.py:20-22)
                carrier = MultiHostCarrier(
                    trained_table, ws.owned_shard_keys, table.layout,
                    ownership_epoch=ws.ownership.epoch,
                )
        if carrier is not None:
            table.add_pending_carrier(carrier)
            # the PREVIOUS boundary's carrier (if any) is superseded:
            # its carried keys live on in this carrier's table, its
            # departed keys were pushed at finalize
            prev = getattr(self, "_carrier", None)
            if prev is not None and not prev.flushed:
                prev.supersede()
            self._carrier = carrier
        # the pass state clears NOW so the next load starts immediately.
        # _guard intentionally STAYS set until the worker confirms, and a
        # worker FAILURE restores the cleared state — so a failed publish
        # (bad delta dir, full disk) leaves the pass open for a retried
        # end_pass, or revertible via revert_pass when a guard is armed;
        # begin_pass refuses to start a new pass over the unresolved one
        saved_state = (self.store, self._order, self._records)
        self._records = []
        self.store = None
        self._order = None
        self.ws = None
        self.device_table = None
        self._in_pass = False
        self._auc_runner = None  # pools reference this pass's records only

        prev_carrier = getattr(self, "_prev_boundary_carrier", None)
        self._prev_boundary_carrier = carrier

        def run():
            t_run = time.perf_counter()
            wb_s = 0.0
            try:
                fire("boundary.writeback")
                if prev_carrier is not None:
                    # the previous boundary's departing-slice push must land
                    # before this boundary's decay (a late push would
                    # overwrite decayed rows with un-decayed values)
                    prev_carrier.join_push()
                t_wb = time.perf_counter()
                if kick is not None:
                    # overlapped writeback: the kick thread has been pushing
                    # since the trained table landed — only the residual
                    # tail blocks this boundary, and the seconds the kick
                    # ran before this join were hidden behind the gate/
                    # verdict window (absorbed into overlap_hidden_s)
                    kick_secs = kick.fut.result()
                    kick.thread.join()
                    wb_s = time.perf_counter() - t_wb
                    hidden = max(0.0, kick_secs - wb_s)
                    with self._stage_lock:
                        self._stage_hidden_s += hidden
                    STAT_SET("boundary.writeback_hidden_s", hidden)
                    STAT_OBSERVE("boundary.writeback_hidden_s", hidden)
                    if prev_carrier is not None and not prev_carrier.flushed:
                        prev_carrier.supersede()
                elif trained_table is not None and carrier is None:
                    arr = _trained_to_host(trained_table, table.layout)
                    ws.writeback(arr)
                    if prev_carrier is not None and not prev_carrier.flushed:
                        # the full classic writeback covers everything a
                        # still-pending carrier owed (carried keys are this
                        # pass's rows; its departures just joined) — a later
                        # splice or drain of it would resurrect stale values
                        prev_carrier.supersede()
                    wb_s = time.perf_counter() - t_wb
                STAT_SET("boundary.writeback_s", wb_s)
                STAT_OBSERVE("boundary.writeback_s", wb_s)
                dropped = table.decay_and_shrink() if shrink else 0
                saved = table.save_delta(delta_dir) if need_save_delta else 0
                # enforce the host-RAM cap: evict cold rows to the disk tier
                # (LoadSSD2Mem inverse; next finalize promotes what it needs)
                if getattr(table, "mem_cap_rows", None) is not None:
                    table.maybe_spill()
                # per-pass table.tier.* gauges (occupancy, spill/promote flow)
                if hasattr(table, "publish_tier_stats"):
                    table.publish_tier_stats()
                # the pass is published: drop the rollback snapshot (Confirm)
                if guard is not None and guard.armed:
                    guard.confirm()
                if self._guard is guard:
                    self._guard = None
                return {
                    "dropped": dropped,
                    "delta_keys": saved,
                    "secs": time.perf_counter() - t_run,
                }
            except BaseException:
                # re-open the pass so the failure is recoverable; under
                # the pass lock so a preload thread publishing the next
                # pass can't interleave with the restore
                with self._pass_lock:
                    self.store, self._order, self._records = saved_state
                    self.ws = ws
                    self._in_pass = True
                raise

        from concurrent.futures import Future

        fut: Future = Future()

        def worker():
            try:
                with record_event("boundary.end_pass_worker", "boundary"):
                    fut.set_result(run())
            except BaseException as e:
                fut.set_exception(e)

        self._end_pass_fut = fut
        # non-daemon: interpreter exit JOINS an in-flight publish instead of
        # killing it mid-write (truncated delta files, lost writeback);
        # wait_end_pass joins the handle once the future settles
        self._end_pass_thread = threading.Thread(target=worker, daemon=False)
        self._end_pass_thread.start()

    def wait_end_pass(self) -> dict:
        """Join a pending end_pass_async; returns its result dict (or the
        last one again if already joined; {} if none ever ran).

        Also settles the boundary overlap accounting: worker seconds not
        spent blocking here ran behind training, and so did the feed
        stage's premerge/prefetch — their sum is ``boundary.overlap_hidden_s``."""
        fut = self._end_pass_fut
        if fut is not None:
            t0 = time.perf_counter()
            try:
                self._end_pass_result = fut.result()
            except BaseException:
                # never let a failed pass alias the previous pass's success
                self._end_pass_result = {}
                raise
            finally:
                self._end_pass_fut = None
                # the future settles inside the worker, so this join only
                # covers the record_event epilogue — but it retires the
                # handle instead of abandoning a zombie Thread object
                t = getattr(self, "_end_pass_thread", None)
                if t is not None:
                    t.join()
                    self._end_pass_thread = None
            blocked = time.perf_counter() - t0
            hidden = max(
                0.0, self._end_pass_result.get("secs", 0.0) - blocked
            )
            with self._stage_lock:
                stage_hidden, self._stage_hidden_s = self._stage_hidden_s, 0.0
            STAT_SET("boundary.overlap_hidden_s", hidden + stage_hidden)
            STAT_OBSERVE("boundary.overlap_hidden_s", hidden + stage_hidden)
        # surface an already-stored eager-flush failure HERE too: a run's
        # final pass has no next begin_pass to raise it, and exiting 0
        # with carried values still owed would hide the durability gap
        # (the failed carrier stays registered; drain_pending retries it).
        # Only a stored error raises — a still-running flush is joined at
        # the next boundary as before, preserving the overlap.
        err = getattr(self, "_eager_flush_error", None)
        if err is not None:
            self._eager_flush_error = None
            raise RuntimeError(
                "background carrier flush failed — carried values remain "
                "owed and will be retried by the next drain_pending"
            ) from err
        return getattr(self, "_end_pass_result", {})

    # ---- batch serving ---------------------------------------------------

    def memory_data_size(self) -> int:
        if self.store is not None:
            return len(self.store)
        return len(self._records)

    def num_batches(self, global_count: Optional[int] = None) -> int:
        """Minibatch count this pass. Lockstep across nodes: with a
        transport attached the local count is allreduce-max'd automatically
        (compute_thread_batch_nccl parity, data_set.cc:2069-2135) so every
        node runs the same count and mesh collectives never desync;
        ``global_count`` overrides with an externally agreed count."""
        if global_count is not None:
            return global_count
        n = self.memory_data_size()
        local = n // self.batch_size
        if not self.drop_remainder and n % self.batch_size:
            local += 1
        if self.transport is not None and self.transport.n_ranks > 1:
            # cache key must be identical on every rank (pass + load
            # generation, both advanced in lockstep) — keying on the LOCAL
            # count would let one rank skip the collective another enters
            key = (self.pass_id, getattr(self, "_load_gen", 0))
            cached = getattr(self, "_nb_lockstep", None)
            if cached is not None and cached[0] == key:
                return cached[1]
            agreed = self.transport.allreduce_max(
                local, f"nb:{key[0]}:{key[1]}"
            )
            self._nb_lockstep = (key, agreed)
            return agreed
        return local

    def batch_indices(self, n_batches: Optional[int] = None) -> Iterator[np.ndarray]:
        """Store-record indices of each minibatch (the fast-path analog of
        ``batches()``): the pre-partitioned ``batch_offsets_`` of the
        reference (PrepareTrain, data_set.cc:2155-2192) with the shuffle
        order applied as a permutation. Wraps around past the tail so every
        rank serves the same count (lockstep parity)."""
        n = self.num_batches() if n_batches is None else n_batches
        B = self.batch_size
        N = self.memory_data_size()
        if N == 0:
            if n > 0:
                raise RuntimeError(
                    f"asked for {n} batches but this node holds 0 records "
                    "(check file striping / shuffle routing)"
                )
            return
        for i in range(n):
            idx = np.arange(i * B, (i + 1) * B, dtype=np.int64) % N
            yield self._order[idx] if self._order is not None else idx

    def batches(self, n_batches: Optional[int] = None) -> Iterator[SlotBatch]:
        """Yield equal-size SlotBatches; wraps around if asked for more than
        the pass holds (tail re-split parity: devices stay in lockstep)."""
        n = self.num_batches() if n_batches is None else n_batches
        if self.memory_data_size() == 0:
            if n > 0:
                # yielding fewer batches than asked would desync mesh
                # collectives across ranks — fail loudly instead
                raise RuntimeError(
                    f"asked for {n} batches but this node holds 0 records "
                    "(check file striping / shuffle routing)"
                )
            return
        B = self.batch_size
        recs = self.records
        for i in range(n):
            batch = [recs[(i * B + j) % len(recs)] for j in range(B)]
            yield build_batch(batch, self.schema)
