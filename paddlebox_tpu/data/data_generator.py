"""User-side data generators for pipe_command preprocessing (P10).

Parity with ``paddle.fluid.incubate.data_generator`` (incubate/
data_generator/__init__.py:21-340): a user subclass defines
``generate_sample(line)`` returning an iterator of
``[(slot_name, [values...]), ...]`` samples (and optionally
``generate_batch(samples)``); ``run_from_stdin`` turns raw lines from stdin
into the slot text protocol on stdout —

    <num> <v0> <v1> ...   per slot, schema order

which is exactly what ``parse_line`` / BoxPSDataset's pipe_command path
consumes. The generator script *is* the pipe_command:

    pipe_command="python my_gen.py"  ->  reader | my_gen.py | parser

Slot order/type consistency across lines is enforced like the reference's
running ``proto_info`` check; empty value lists are rejected (the feed
requires a nonzero count — pad in the generator).
"""

from __future__ import annotations

import sys
from typing import Any, Callable, Iterable, List, Optional, Sequence, Tuple

Sample = Sequence[Tuple[str, Sequence[Any]]]


def _is_float(e) -> bool:
    """float-typed value (incl. numpy floating scalars; ints stay uint64)."""
    import numpy as np

    return isinstance(e, (float, np.floating))


class DataGenerator:
    """Base class: override ``generate_sample`` (and maybe ``generate_batch``)."""

    def __init__(self):
        self._proto_info: Optional[List[Tuple[str, str]]] = None
        self.batch_size_ = 32

    # ---- user hooks ------------------------------------------------------

    def generate_sample(self, line: Optional[str]):
        """Return an iterator factory over parsed samples for one raw line
        (None for run_from_memory)."""
        raise NotImplementedError(
            "implement generate_sample(line) -> callable yielding "
            "[(slot_name, [values...]), ...]"
        )

    def generate_batch(self, samples: List[Sample]):
        """Optional batch-level hook; default passes samples through."""

        def local_iter():
            for s in samples:
                yield s

        return local_iter

    def set_batch(self, batch_size: int) -> None:
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        self.batch_size_ = batch_size

    # ---- drivers ---------------------------------------------------------

    def run_from_stdin(self, stdin=None, stdout=None) -> int:
        """Read raw lines, emit slot-protocol lines. Returns lines written."""
        fin = stdin if stdin is not None else sys.stdin
        fout = stdout if stdout is not None else sys.stdout
        n = 0
        batch: List[Sample] = []
        for line in fin:
            it = self.generate_sample(line)
            for sample in it():
                if sample is None:
                    continue
                batch.append(sample)
                if len(batch) == self.batch_size_:
                    n += self._flush(batch, fout)
                    batch = []
        if batch:
            n += self._flush(batch, fout)
        return n

    def run_from_memory(self, stdout=None) -> int:
        """Generate without input lines (debug/bench parity)."""
        fout = stdout if stdout is not None else sys.stdout
        batch: List[Sample] = []
        n = 0
        for sample in self.generate_sample(None)():
            if sample is None:
                continue
            batch.append(sample)
            if len(batch) == self.batch_size_:
                n += self._flush(batch, fout)
                batch = []
        if batch:
            n += self._flush(batch, fout)
        return n

    def _flush(self, batch: List[Sample], fout) -> int:
        n = 0
        for sample in self.generate_batch(batch)():
            fout.write(self._gen_str(sample))
            n += 1
        return n

    def _gen_str(self, sample: Sample) -> str:
        raise NotImplementedError


class MultiSlotDataGenerator(DataGenerator):
    """Emits the `num v...` text protocol with slot-consistency checking."""

    def _gen_str(self, sample: Sample) -> str:
        if not isinstance(sample, (list, tuple)):
            raise ValueError(
                "a sample must be [(slot_name, [values...]), ...], got "
                f"{type(sample).__name__}"
            )
        # first sample fixes the slot order + types (proto_info parity)
        if self._proto_info is None:
            self._proto_info = []
            for name, elements in sample:
                if not elements:
                    raise ValueError(
                        f"slot {name!r} has no values — the feed needs a "
                        "nonzero count; pad in the generator"
                    )
                t = (
                    "float"
                    if any(_is_float(e) for e in elements)
                    else "uint64"
                )
                self._proto_info.append((name, t))
        else:
            if len(sample) != len(self._proto_info):
                raise ValueError(
                    f"sample has {len(sample)} slots, previous lines had "
                    f"{len(self._proto_info)}"
                )
        parts = []
        for (name, elements), (pname, ptype) in zip(sample, self._proto_info):
            if name != pname:
                raise ValueError(
                    f"slot order changed: got {name!r}, expected {pname!r}"
                )
            if not elements:
                raise ValueError(f"slot {name!r} has no values")
            is_float = any(_is_float(e) for e in elements)
            if is_float and ptype == "uint64":
                raise ValueError(
                    f"slot {name!r} switched from uint64 to float mid-stream"
                )
            parts.append(str(len(elements)))
            # repr keeps full float precision (the reference emits str(e))
            parts.extend(
                (repr(float(e)) if ptype == "float" else str(int(e)))
                for e in elements
            )
        return " ".join(parts) + "\n"
