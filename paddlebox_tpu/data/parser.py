"""Slot-format text line parser.

Line format (parity with SlotPaddleBoxDataFeed::ParseOneInstance,
data_feed.cc:2951-3061):

    [1 <ins_id>] [1 <logkey>] {<num> <v0> <v1> ...} per slot in schema order

- every slot present with its count first; count must be nonzero (pad in the
  data generator)
- uint64 slots drop 0-valued feasigns unless the slot is dense
- float slots drop |v| < 1e-6 unless dense
- logkey is a hex string: cmatch = [11:14), rank = [14:16), search_id = [16:32)
  (parser_log_key, data_feed.cc:2940-2948)

A record with zero remaining uint64 feasigns is rejected (returns None), same
as the reference's ``return (uint64_total_slot_num > 0)``.

Custom parsers: the reference loads user ``.so`` plugins via dlopen
(SlotInsParserMgr data_feed.cc:2594-2655). Here a plugin is any callable
``(line: str, schema) -> SlotRecord | None`` registered with
``register_parser``; the C++ fast path lives in utils/_native (same contract).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

from paddlebox_tpu.data.slot_record import SlotRecord
from paddlebox_tpu.data.slot_schema import SlotSchema

_parsers: Dict[str, Callable] = {}


def register_parser(name: str, fn: Callable) -> None:
    _parsers[name] = fn


def get_parser(name: str) -> Callable:
    return _parsers[name]


def parse_logkey(log_key: str):
    """-> (search_id, cmatch, rank). Hex sub-fields per the reference layout."""
    search_id = int(log_key[16:32], 16)
    cmatch = int(log_key[11:14], 16)
    rank = int(log_key[14:16], 16)
    return search_id, cmatch, rank


def parse_line(line: str, schema: SlotSchema) -> Optional[SlotRecord]:
    try:
        return _parse_line(line, schema)
    except IndexError:
        raise ValueError(f"truncated slot line (ran out of tokens): {line[:120]!r}")


def _parse_line(line: str, schema: SlotSchema) -> Optional[SlotRecord]:
    toks = line.split()
    pos = 0
    ins_id = ""
    search_id = cmatch = rank = 0
    if schema.parse_ins_id:
        if toks[pos] != "1":
            raise ValueError(f"expected ins_id count 1, got {toks[pos]}")
        ins_id = toks[pos + 1]
        pos += 2
    if schema.parse_logkey:
        if toks[pos] != "1":
            raise ValueError(f"expected logkey count 1, got {toks[pos]}")
        log_key = toks[pos + 1]
        search_id, cmatch, rank = parse_logkey(log_key)
        ins_id = log_key
        pos += 2

    u_vals: list = []
    u_offsets = np.zeros(schema.num_sparse + 1, dtype=np.uint32)
    f_vals: list = []
    f_offsets = np.zeros(schema.num_float + 1, dtype=np.uint32)
    u_slot = f_slot = 0
    for info in schema.slots:
        num = int(toks[pos])
        if num == 0:
            raise ValueError(
                "slot value count can not be zero; pad it in the data generator "
                f"(slot {info.name}, line {line[:80]!r})"
            )
        vals = toks[pos + 1 : pos + 1 + num]
        pos += 1 + num
        if not info.used:
            continue
        if info.type == "float":
            for t in vals:
                v = float(t)
                if abs(v) < 1e-6 and not info.dense:
                    continue
                f_vals.append(v)
            f_slot += 1
            f_offsets[f_slot] = len(f_vals)
        else:
            for t in vals:
                k = int(t)
                if k == 0 and not info.dense:
                    continue
                u_vals.append(k)
            u_slot += 1
            u_offsets[u_slot] = len(u_vals)

    if not u_vals:
        return None
    return SlotRecord(
        u64_values=np.array(u_vals, dtype=np.uint64),
        u64_offsets=u_offsets,
        f_values=np.array(f_vals, dtype=np.float32),
        f_offsets=f_offsets,
        ins_id=ins_id,
        search_id=search_id,
        cmatch=cmatch,
        rank=rank,
    )
