"""Slot-format text line parser.

Line format (parity with SlotPaddleBoxDataFeed::ParseOneInstance,
data_feed.cc:2951-3061):

    [1 <ins_id>] [1 <logkey>] {<num> <v0> <v1> ...} per slot in schema order

- every slot present with its count first; count must be nonzero (pad in the
  data generator)
- uint64 slots drop 0-valued feasigns unless the slot is dense
- float slots drop |v| < 1e-6 unless dense
- logkey is a hex string: cmatch = [11:14), rank = [14:16), search_id = [16:32)
  (parser_log_key, data_feed.cc:2940-2948)

A record with zero remaining uint64 feasigns is rejected (returns None), same
as the reference's ``return (uint64_total_slot_num > 0)``.

Custom parsers: the reference loads user ``.so`` plugins via dlopen
(SlotInsParserMgr data_feed.cc:2594-2655). Here a plugin is any callable
``(line: str, schema) -> SlotRecord | None`` registered with
``register_parser``; the C++ fast path lives in utils/_native (same contract).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

import numpy as np

from paddlebox_tpu.data.slot_record import SlotRecord
from paddlebox_tpu.data.slot_schema import SlotSchema
from paddlebox_tpu.utils.faultinject import fire

_parsers: Dict[str, Callable] = {}


def register_parser(name: str, fn: Callable) -> None:
    _parsers[name] = fn


def get_parser(name: str) -> Callable:
    return _parsers[name]


def _hex_field(log_key: str, name: str, lo: int, hi: int) -> int:
    try:
        return int(log_key[lo:hi], 16)
    except ValueError:
        raise ValueError(
            f"non-hex {name} field {log_key[lo:hi]!r} in log_key {log_key[:64]!r}"
        ) from None


def parse_logkey(log_key: str):
    """-> (search_id, cmatch, rank). Hex sub-fields per the reference layout.

    A short or non-hex key raises a ValueError naming the field and the
    offending value (quarantinable like any other parse error). The length
    floor matches the native tier (csrc/slot_parser.cc: > 16 hex chars), so
    both tiers reject the same keys.
    """
    if len(log_key) <= 16:
        raise ValueError(
            f"log_key too short: need > 16 hex chars, got "
            f"{len(log_key)} ({log_key!r})"
        )
    search_id = _hex_field(log_key, "search_id", 16, 32)
    cmatch = _hex_field(log_key, "cmatch", 11, 14)
    rank = _hex_field(log_key, "rank", 14, 16)
    return search_id, cmatch, rank


def parse_line(line: str, schema: SlotSchema) -> Optional[SlotRecord]:
    fire("parser.parse_line")
    try:
        return _parse_line(line, schema)
    except IndexError:
        raise ValueError(f"truncated slot line (ran out of tokens): {line[:120]!r}")


def _parse_line(line: str, schema: SlotSchema) -> Optional[SlotRecord]:
    toks = line.split()
    pos = 0
    ins_id = ""
    search_id = cmatch = rank = 0
    if schema.parse_ins_id:
        if toks[pos] != "1":
            raise ValueError(f"expected ins_id count 1, got {toks[pos]}")
        ins_id = toks[pos + 1]
        pos += 2
    if schema.parse_logkey:
        if toks[pos] != "1":
            raise ValueError(f"expected logkey count 1, got {toks[pos]}")
        log_key = toks[pos + 1]
        search_id, cmatch, rank = parse_logkey(log_key)
        ins_id = log_key
        pos += 2

    u_vals: list = []
    u_offsets = np.zeros(schema.num_sparse + 1, dtype=np.uint32)
    f_vals: list = []
    f_offsets = np.zeros(schema.num_float + 1, dtype=np.uint32)
    u_slot = f_slot = 0
    for info in schema.slots:
        num = int(toks[pos])
        if num == 0:
            raise ValueError(
                "slot value count can not be zero; pad it in the data generator "
                f"(slot {info.name}, line {line[:80]!r})"
            )
        vals = toks[pos + 1 : pos + 1 + num]
        pos += 1 + num
        if not info.used:
            continue
        if info.type == "float":
            for t in vals:
                v = float(t)
                if abs(v) < 1e-6 and not info.dense:
                    continue
                f_vals.append(v)
            f_slot += 1
            f_offsets[f_slot] = len(f_vals)
        else:
            for t in vals:
                k = int(t)
                if k == 0 and not info.dense:
                    continue
                u_vals.append(k)
            u_slot += 1
            u_offsets[u_slot] = len(u_vals)

    if not u_vals:
        return None
    return SlotRecord(
        u64_values=np.array(u_vals, dtype=np.uint64),
        u64_offsets=u_offsets,
        f_values=np.array(f_vals, dtype=np.float32),
        f_offsets=f_offsets,
        ins_id=ins_id,
        search_id=search_id,
        cmatch=cmatch,
        rank=rank,
    )


class ReplicaCacheLineParser:
    """Line parser for replica-cache datasets (B16 feed integration).

    Parity with SlotPaddleBoxDataFeedWithGpuReplicaCache
    (data_feed.cc:3198-3326): a line starting with ``#`` carries ``dim``
    floats appended to the cache (no record produced); every following
    normal line stores the latest cache row id as the single feasign of
    ``cache_slot`` (the reference hard-codes slot index 3; here it is named).
    The id slot's tokens in the text line are still consumed positionally.

    State is thread-local and reset per file (``begin_file``, invoked by the
    dataset reader): a cache line governs the records after it *within its
    file*; a record before any cache line in its file is an error.
    """

    def __init__(self, cache, cache_slot: str):
        self.cache = cache
        self.cache_slot = cache_slot
        self._tls = threading.local()

    def begin_file(self, path: str) -> None:
        self._tls.offset = None

    def __call__(self, line: str, schema: SlotSchema) -> Optional[SlotRecord]:
        if line.startswith("#"):
            # full token list: a dim mismatch in either direction must raise
            # (add_items validates), not silently truncate
            vals = np.array(line[1:].split(), dtype=np.float32)
            self._tls.offset = self.cache.add_items(vals)
            return None
        rec = parse_line(line, schema)
        if rec is None:
            return None
        offset = getattr(self._tls, "offset", None)
        if offset is None:
            raise ValueError(
                "record line before any '#' cache line in this file"
            )
        s = schema.sparse_slot_index(self.cache_slot)
        new_vals = {s: np.array([offset], dtype=np.uint64)}
        parts = []
        n_slots = len(rec.u64_offsets) - 1
        lens = np.empty(n_slots, dtype=np.int64)
        for i in range(n_slots):
            v = new_vals.get(i)
            if v is None:
                v = rec.slot_keys(i)
            parts.append(v)
            lens[i] = len(v)
        rec.u64_values = np.concatenate(parts).astype(np.uint64, copy=False)
        off = np.zeros(n_slots + 1, dtype=np.uint32)
        np.cumsum(lens, out=off[1:])
        rec.u64_offsets = off
        return rec
