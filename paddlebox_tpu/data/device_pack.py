"""Host-side batch packer: SlotBatch -> static-shape device arrays.

Analog of MiniBatchGpuPack + BuildSlotBatchGPU + the CopyKeys/dedup device
kernels (data_feed.h:1418-1580, box_wrapper_impl.h:103 DedupKeysAndFillIdx):
everything ragged or key-valued is resolved here on the host —

- keys -> pass-local global rows (PassWorkingSet.lookup)
- cross-slot dedup: unique rows + inverse indices
  (flag enable_pullpush_dedup_keys parity)
- segment ids (slot * batch + ins) for the fused seqpool
- padding to bucketed static lengths so XLA sees few distinct shapes

The device then runs only gather/scatter/segment-sum with static shapes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

from paddlebox_tpu import config
from paddlebox_tpu.data.slot_record import SlotBatch
from paddlebox_tpu.data.slot_schema import SlotSchema
from paddlebox_tpu.table.sparse_table import PassWorkingSet


def _round_bucket(n: int, quantum: int) -> int:
    return max(quantum, -(-n // quantum) * quantum)


@dataclass
class DeviceBatch:
    """Static-shape arrays consumed by the jitted train step."""

    batch_size: int
    num_slots: int
    uniq_rows: np.ndarray  # int32 [U_pad] table rows, deduped; pads -> padding row
    inverse: np.ndarray  # int32 [L_pad] flat key -> uniq index; pads -> U_pad-1
    segments: np.ndarray  # int32 [L_pad] slot*B+ins; pads -> S*B (trash segment)
    labels: np.ndarray  # f32 [B]
    dense: Optional[np.ndarray]  # f32 [B, dense_dim] or None
    n_keys: int  # true (unpadded) flat key count
    n_uniq: int  # true unique count

    def as_dict(self) -> Dict[str, np.ndarray]:
        d = {
            "uniq_rows": self.uniq_rows,
            "inverse": self.inverse,
            "segments": self.segments,
            "labels": self.labels,
        }
        if self.dense is not None:
            d["dense"] = self.dense
        return d


def pack_batch(
    batch: SlotBatch,
    ws: PassWorkingSet,
    schema: SlotSchema,
    dense_slot: Optional[str] = None,
    dense_dim: int = 0,
    label_slot: Optional[str] = None,
    bucket: Optional[int] = None,
    dedup: Optional[bool] = None,
) -> DeviceBatch:
    bucket = bucket or config.get_flag("batch_bucket_rounding")
    if dedup is None:
        dedup = config.get_flag("enable_pullpush_dedup_keys")
    B = batch.batch_size
    S = batch.num_sparse_slots

    rows = ws.lookup(batch.keys)  # int32 [L]
    segments = batch.segment_ids()  # int32 [L]
    L = len(rows)

    if dedup:
        uniq, inverse = np.unique(rows, return_inverse=True)
    else:
        uniq, inverse = rows, np.arange(L, dtype=np.int64)
    U = len(uniq)

    L_pad = _round_bucket(L, bucket)
    U_pad = _round_bucket(U + 1, bucket)  # +1 keeps one guaranteed pad slot

    uniq_p = np.full(U_pad, ws.padding_row, dtype=np.int32)
    uniq_p[:U] = uniq
    inv_p = np.full(L_pad, U_pad - 1, dtype=np.int32)
    inv_p[:L] = inverse
    seg_p = np.full(L_pad, S * B, dtype=np.int32)
    seg_p[:L] = segments

    label_name = label_slot or schema.label_slot
    if label_name is not None:
        li = schema.float_slot_index(label_name)
        labels = batch.dense_float_matrix(li, 1)[:, 0]
    else:
        labels = np.zeros(B, dtype=np.float32)

    dense = None
    if dense_slot is not None and dense_dim:
        di = schema.float_slot_index(dense_slot)
        dense = batch.dense_float_matrix(di, dense_dim)

    return DeviceBatch(
        batch_size=B,
        num_slots=S,
        uniq_rows=uniq_p,
        inverse=inv_p,
        segments=seg_p,
        labels=labels.astype(np.float32),
        dense=dense,
        n_keys=L,
        n_uniq=U,
    )
