"""Host-side batch packer: SlotBatch -> static-shape device arrays.

Analog of MiniBatchGpuPack + BuildSlotBatchGPU + the CopyKeys/dedup device
kernels (data_feed.h:1418-1580, box_wrapper_impl.h:103 DedupKeysAndFillIdx):
everything ragged or key-valued is resolved here on the host —

- keys -> pass-local global rows (PassWorkingSet.lookup)
- cross-slot dedup: unique rows + inverse indices
  (flag enable_pullpush_dedup_keys parity)
- segment ids (slot * batch + ins) for the fused seqpool
- padding to bucketed static lengths so XLA sees few distinct shapes

The device then runs only gather/scatter/segment-sum with static shapes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

from paddlebox_tpu import config
from paddlebox_tpu.data.slot_record import SlotBatch
from paddlebox_tpu.data.slot_schema import SlotSchema
from paddlebox_tpu.ops import wire_quant
from paddlebox_tpu.table.sparse_table import PassWorkingSet
from paddlebox_tpu.utils.faultinject import InjectedFault
from paddlebox_tpu.utils.faultinject import fire as _fault_fire
from paddlebox_tpu.utils.monitor import STAT_ADD


def _round_bucket(n: int, quantum: int) -> int:
    return max(quantum, -(-n // quantum) * quantum)


@dataclass
class DeviceBatch:
    """Static-shape arrays consumed by the jitted train step."""

    batch_size: int
    num_slots: int
    uniq_rows: np.ndarray  # int32 [U_pad] table rows, deduped; pads -> padding row
    inverse: np.ndarray  # int32 [L_pad] flat key -> uniq index; pads -> U_pad-1
    segments: np.ndarray  # int32 [L_pad] slot*B+ins; pads -> S*B (trash segment)
    labels: np.ndarray  # f32 [B]
    dense: Optional[np.ndarray]  # f32 [B, dense_dim] or None
    n_keys: int  # true (unpadded) flat key count
    n_uniq: int  # true unique count

    def as_dict(self) -> Dict[str, np.ndarray]:
        d = {
            "uniq_rows": self.uniq_rows,
            "inverse": self.inverse,
            "segments": self.segments,
            "labels": self.labels,
        }
        if self.dense is not None:
            d["dense"] = self.dense
        return d


def _extract_labels_dense(
    batch: SlotBatch,
    schema: SlotSchema,
    label_slot: Optional[str],
    dense_slot: Optional[str],
    dense_dim: int,
):
    """Shared label/dense-float extraction for both packers."""
    label_name = label_slot or schema.label_slot
    if label_name is not None:
        li = schema.float_slot_index(label_name)
        labels = batch.dense_float_matrix(li, 1)[:, 0]
    else:
        labels = np.zeros(batch.batch_size, dtype=np.float32)
    dense = None
    if dense_slot is not None and dense_dim:
        di = schema.float_slot_index(dense_slot)
        dense = batch.dense_float_matrix(di, dense_dim)
    return labels.astype(np.float32), dense


@dataclass
class ShardedDeviceBatch:
    """Static-shape arrays for the mesh train step; axis 0 = device.

    ``req_ranks[d, s]`` is the bucket of rank-within-shard requests device d
    sends shard s (pads -> cap-1, the padding row); ``inverse[d]`` maps the
    device's flat keys to bucket positions ``s*K + j``. The last slot of every
    bucket (j = K-1) is guaranteed padding, so pad inverse entries point at
    bucket position K-1 of shard 0.
    """

    local_batch: int
    num_slots: int
    req_ranks: np.ndarray  # int32 [n_dev, n_shards, K]
    inverse: np.ndarray  # int32 [n_dev, L_pad] flat key -> bucket pos
    segments: np.ndarray  # int32 [n_dev, L_pad]; pads -> S*local_batch
    labels: np.ndarray  # f32 [n_dev, local_batch]
    dense: Optional[np.ndarray]  # f32 [n_dev, local_batch, dense_dim]

    def as_dict(self) -> Dict[str, np.ndarray]:
        d = {
            "req_ranks": self.req_ranks,
            "inverse": self.inverse,
            "segments": self.segments,
            "labels": self.labels,
        }
        if self.dense is not None:
            d["dense"] = self.dense
        return d


def _route_sharded(
    rows: np.ndarray,
    segments: np.ndarray,
    B: int,
    S: int,
    ws: PassWorkingSet,
    n_devices: int,
    bucket: int,
    labels: np.ndarray,
    dense: Optional[np.ndarray],
    dense_dim: int,
    k_floor: int = 0,
    l_floor: int = 0,
) -> ShardedDeviceBatch:
    """Shared mesh routing: flat (rows, segments) -> per-device buckets.

    ``n_devices`` is the number of devices THIS pack serves (all of them
    single-host; this host's local devices multi-host), while routing
    targets all ``ws.n_mesh_shards`` global shards — a host packs its own
    records into [n_local, n_shards, K] request buckets and the mesh
    all_to_all delivers them."""
    ns = ws.n_mesh_shards
    if ns % n_devices:
        raise ValueError(
            f"{ns} working-set mesh shards not divisible by {n_devices} "
            "packed devices"
        )
    if B % n_devices:
        raise ValueError(f"batch {B} not divisible by {n_devices} devices")
    b = B // n_devices
    cap = ws.capacity
    ins = segments % B
    slot = segments // B
    dev = ins // b

    # hot-first bucket ordering for the adaptive ICI wire: the working set
    # publishes a per-row hotness bit (tier decayed-show >= ici_hot_show)
    # only when the adaptive wire is engaged, and the device side assigns
    # precision purely by slot index — so ordering each per-shard bucket
    # hot-first here IS the whole hot/cold partition. None (the default and
    # the ablation) keeps the historical stable-by-shard order bitwise.
    hot_rows = getattr(ws, "hot_rows", None)
    if hot_rows is not None:
        try:
            _fault_fire("wire.ici_pack")
        except InjectedFault:
            # recovery: this batch degrades to the uniform slot order — hot
            # keys ride the int8 region (correct, just un-prioritized)
            STAT_ADD("wire.ici_pack_errors", 1)
            hot_rows = None

    per_dev = []  # (uniq_rows, inverse, local_segments) per device
    max_L = 1
    max_bucket = 1
    for d in range(n_devices):
        sel = np.nonzero(dev == d)[0]
        uniq, inv = np.unique(rows[sel], return_inverse=True)
        local_seg = slot[sel] * b + (ins[sel] - d * b)
        per_dev.append((uniq, inv, local_seg))
        max_L = max(max_L, len(sel))
        if len(uniq):
            counts = np.bincount(uniq // cap, minlength=ns)
            max_bucket = max(max_bucket, int(counts.max()))

    # K-1 is always a pad slot; L_pad/K identical across devices so the mesh
    # program has one shape (compute_thread_batch_nccl lockstep parity,
    # data_set.cc:2069-2135); floors let a pass-scoped packer keep shapes
    # sticky across batches (one compiled program per pass). k_floor == -1
    # requests first-batch headroom (25%) so later batches rarely grow K.
    if k_floor == -1:
        K = _round_bucket(max_bucket + 1 + max(bucket, max_bucket // 4), bucket)
    else:
        K = max(_round_bucket(max_bucket + 1, bucket), k_floor)
    L_pad = max(_round_bucket(max_L, bucket), l_floor)

    req_ranks = np.full((n_devices, ns, K), cap - 1, dtype=np.int32)
    inverse = np.full((n_devices, L_pad), K - 1, dtype=np.int32)
    seg_out = np.full((n_devices, L_pad), S * b, dtype=np.int32)

    hot_overflow = 0
    H = wire_quant.ici_hot_slots(K) if hot_rows is not None else 0
    for d, (uniq, inv, local_seg) in enumerate(per_dev):
        shard_of = (uniq // cap).astype(np.int64)
        rank_of = (uniq % cap).astype(np.int64)
        if hot_rows is not None and len(uniq):
            # lexsort is stable with the LAST key primary: group by owner
            # shard, hot rows (cold=False) first within each bucket
            cold = ~hot_rows[uniq]
            order = np.lexsort((cold, shard_of))
            per_shard_hot = np.bincount(shard_of[~cold], minlength=ns)
            hot_overflow += int(np.maximum(per_shard_hot - H, 0).sum())
        else:
            order = np.argsort(shard_of, kind="stable")
        counts = np.bincount(shard_of, minlength=ns)
        # bucket position of each unique row: owner_shard*K + slot-in-bucket
        pos_in_bucket = np.empty(len(uniq), dtype=np.int64)
        start = 0
        for s in range(ns):
            c = int(counts[s])
            req_ranks[d, s, :c] = rank_of[order[start : start + c]]
            pos_in_bucket[order[start : start + c]] = s * K + np.arange(c)
            start += c
        inverse[d, : len(inv)] = pos_in_bucket[inv]
        seg_out[d, : len(local_seg)] = local_seg

    if hot_rows is not None and hot_overflow:
        # hot keys past the static bf16 bound ride int8 this batch —
        # harmless (graceful degrade), but a persistently nonzero counter
        # says ici_hot_frac is too small for the traffic's hot set
        STAT_ADD("wire.ici_hot_overflow_keys", hot_overflow)

    labels = labels.reshape(n_devices, b)
    if dense is not None:
        dense = dense.reshape(n_devices, b, dense_dim)

    return ShardedDeviceBatch(
        local_batch=b,
        num_slots=S,
        req_ranks=req_ranks,
        inverse=inverse,
        segments=seg_out,
        labels=labels,
        dense=dense,
    )


def route_serve_requests(
    owner: np.ndarray,
    local_rank: np.ndarray,
    n_devices: int,
    bucket: int,
    pad_rank: int,
):
    """Serve-tier hit keys -> static sharded-pull request buckets.

    ``owner[i]`` is the mesh shard holding hit key i, ``local_rank[i]`` its
    row within that shard's device block. Keys split round-robin across the
    ``n_devices`` requesting devices (one host request exercises every
    chip), then bucket per owner shard exactly like :func:`_route_sharded`:
    K rounds to ``bucket`` so the compiled collective family stays bounded,
    and slot K-1 of every bucket is guaranteed padding (-> ``pad_rank``,
    the tier's reserved zero row).

    Returns ``(req_ranks int32 [n_dev, n_dev, K], pos int64 [m], K)`` where
    ``pos[i]`` is key i's flat row in the pulled ``[n_dev, n_dev*K, width]``
    output (device-major, then bucket position s*K + j).
    """
    m = len(owner)
    if m == 0:
        K = bucket
        req = np.full((n_devices, n_devices, K), pad_rank, dtype=np.int32)
        return req, np.zeros(0, dtype=np.int64), K
    dev = np.arange(m, dtype=np.int64) % n_devices
    grp = dev * n_devices + owner
    order = np.argsort(grp, kind="stable")
    counts = np.bincount(grp, minlength=n_devices * n_devices)
    K = max(_round_bucket(int(counts.max()) + 1, bucket), bucket)
    starts = np.concatenate([[0], np.cumsum(counts)])
    slot = np.arange(m, dtype=np.int64) - starts[grp[order]]
    req = np.full((n_devices, n_devices, K), pad_rank, dtype=np.int32)
    req[dev[order], owner[order], slot] = local_rank[order]
    pos = np.empty(m, dtype=np.int64)
    pos[order] = dev[order] * (n_devices * K) + owner[order] * K + slot
    return req, pos, K


def pack_batch_sharded(
    batch: SlotBatch,
    ws: PassWorkingSet,
    schema: SlotSchema,
    n_devices: int,
    dense_slot: Optional[str] = None,
    dense_dim: int = 0,
    label_slot: Optional[str] = None,
    bucket: Optional[int] = None,
    k_floor: int = 0,
    l_floor: int = 0,
) -> ShardedDeviceBatch:
    """Split a global batch across the mesh and bucket keys by owner shard.

    The analog of the reference's per-GPU batch split (one BoxPSWorker per
    device over pre-partitioned offsets, data_set.cc:2155-2192) plus the
    host half of the inter-node key routing that the closed PullSparseGPU
    performs internally: every unique row is assigned to its owner shard's
    request bucket here, so the device side is pure all_to_all + gather.

    ``n_devices`` is the number of devices this batch feeds: all mesh
    devices single-host (== the working set's shard count; table shard axis
    == dp axis), or this host's LOCAL device block multi-host (the global
    shard count just has to divide by it). Batch size must divide evenly.
    """
    bucket = bucket or config.get_flag("batch_bucket_rounding")
    rows = ws.lookup(batch.keys)  # int32 [L] global rows (shard*cap + rank)
    segments = batch.segment_ids()  # int32 [L] slot*B + ins
    labels, dense = _extract_labels_dense(batch, schema, label_slot, dense_slot, dense_dim)
    return _route_sharded(
        rows,
        segments,
        batch.batch_size,
        batch.num_sparse_slots,
        ws,
        n_devices,
        bucket,
        labels,
        dense,
        dense_dim,
        k_floor=k_floor,
        l_floor=l_floor,
    )


def pack_batch(
    batch: SlotBatch,
    ws: PassWorkingSet,
    schema: SlotSchema,
    dense_slot: Optional[str] = None,
    dense_dim: int = 0,
    label_slot: Optional[str] = None,
    bucket: Optional[int] = None,
    dedup: Optional[bool] = None,
) -> DeviceBatch:
    bucket = bucket or config.get_flag("batch_bucket_rounding")
    if dedup is None:
        dedup = config.get_flag("enable_pullpush_dedup_keys")
    B = batch.batch_size
    S = batch.num_sparse_slots

    rows = ws.lookup(batch.keys)  # int32 [L]
    segments = batch.segment_ids()  # int32 [L]
    L = len(rows)

    if dedup:
        uniq, inverse = np.unique(rows, return_inverse=True)
    else:
        uniq, inverse = rows, np.arange(L, dtype=np.int64)
    U = len(uniq)

    L_pad = _round_bucket(L, bucket)
    U_pad = _round_bucket(U + 1, bucket)  # +1 keeps one guaranteed pad slot

    uniq_p = np.full(U_pad, ws.padding_row, dtype=np.int32)
    uniq_p[:U] = uniq
    inv_p = np.full(L_pad, U_pad - 1, dtype=np.int32)
    inv_p[:L] = inverse
    seg_p = np.full(L_pad, S * B, dtype=np.int32)
    seg_p[:L] = segments

    labels, dense = _extract_labels_dense(batch, schema, label_slot, dense_slot, dense_dim)

    return DeviceBatch(
        batch_size=B,
        num_slots=S,
        uniq_rows=uniq_p,
        inverse=inv_p,
        segments=seg_p,
        labels=labels,
        dense=dense,
        n_keys=L,
        n_uniq=U,
    )


class BatchPacker:
    """Pass-scoped fast packer over a ColumnarRecords store.

    Precomputes once per pass: key->row resolution for every key of the
    store (vectorized), whole-pass label/dense-feature matrices. Per batch,
    a single native call (csrc/batch_packer.cc) does the ragged row gather
    + first-occurrence dedup + segment ids — the MiniBatchGpuPack::
    pack_instance hot loop (data_feed.h:1418-1542) without any per-record
    Python. Falls back to vectorized numpy when the native lib is absent.

    Thread contract: pack()/pack_sharded() are safe to call from multiple
    packer threads (each thread gets its own native scratch handle).
    """

    def __init__(
        self,
        store,  # ColumnarRecords
        ws: PassWorkingSet,
        schema: SlotSchema,
        dense_slot: Optional[str] = None,
        dense_dim: int = 0,
        label_slot: Optional[str] = None,
        bucket: Optional[int] = None,
    ):
        import threading

        self.store = store
        self.ws = ws
        self.schema = schema
        self.bucket = bucket or config.get_flag("batch_bucket_rounding")
        self.dense_dim = dense_dim
        self._rows = store.resolve_rows(ws)
        self._key_counts = store.key_counts()
        label_name = label_slot or schema.label_slot
        if label_name is not None:
            li = schema.float_slot_index(label_name)
            self._labels = store.float_slot_matrix(li, 1)[:, 0].astype(np.float32)
        else:
            self._labels = np.zeros(len(store), np.float32)
        if dense_slot is not None and dense_dim:
            di = schema.float_slot_index(dense_slot)
            self._dense = store.float_slot_matrix(di, dense_dim)
        else:
            self._dense = None
        self._n_table_rows = ws.n_mesh_shards * ws.capacity
        self._tls = threading.local()
        self._use_native = config.get_flag("enable_native_parser")
        self._dedup = config.get_flag("enable_pullpush_dedup_keys")
        # sticky pad shapes: XLA compiles one program per distinct feed
        # shape, so per-batch rounding would trigger a recompile whenever the
        # unique-key count crosses a bucket boundary. Freeze L_pad/U_pad at
        # first use (with headroom) and only ever grow — the reused-pack-
        # buffer discipline of MiniBatchGpuPack (data_feed.h:1418-1542),
        # re-motivated by the compiler. Updates happen under _shape_lock
        # (prefetch packs from several threads; shapes must not diverge).
        self._shape_lock = threading.Lock()
        self._L_pad = 0  # pack(): whole-batch; pack_sharded(): per-device
        self._U_pad = 0
        self._K_pad = 0
        # every native handle ever spawned (any thread): close() frees the
        # per-thread O(n_table_rows) scratch eagerly instead of waiting for
        # executor threads to die and __del__ to fire
        self._all_native: list = []

    def freeze_shapes(self, batch_indices, n_devices: int = 0, transport=None) -> None:
        """Fix L_pad for a whole pass upfront so every batch compiles to ONE
        device program: L is exactly computable per batch from the record
        key counts (per device when ``n_devices`` > 0 — the sharded feed's
        L dimension is per-device). Call with the pass's batch partition
        before the first pack.

        With a ``transport`` both pads are allreduce-max'd across hosts and
        K (the per-shard request bucket) is frozen from an exact scan of
        every batch's per-(device, shard) unique-row counts, so every host
        compiles the SAME mesh program — collectives can never see
        mismatched shapes (lockstep parity, compute_thread_batch_nccl
        data_set.cc:2069-2135) — without inflating the all_to_all payload
        beyond what the pass actually needs."""
        lockstep = transport is not None and transport.n_ranks > 1
        max_L = 1
        max_bucket = 0
        for idx in batch_indices:
            idx = np.asarray(idx)
            counts = self._key_counts[idx]
            if n_devices:
                per_dev = counts.reshape(n_devices, -1).sum(axis=1)
                max_L = max(max_L, int(per_dev.max()))
            else:
                max_L = max(max_L, int(counts.sum()))
            if lockstep and n_devices:
                # exact per-(device, shard) request-bucket need of this batch
                from paddlebox_tpu.data.record_store import _ragged_indices

                cap = self.ws.capacity
                ns = self.ws.n_mesh_shards
                base = self.store.u64_base[idx]
                for d in range(n_devices):
                    sl = slice(d * (len(idx) // n_devices), (d + 1) * (len(idx) // n_devices))
                    rows = self._rows[_ragged_indices(base[sl], counts[sl])]
                    if len(rows):
                        uniq = np.unique(rows)
                        max_bucket = max(
                            max_bucket,
                            int(np.bincount(uniq // cap, minlength=ns).max()),
                        )
        if lockstep:
            max_L = transport.allreduce_max(max_L, "freeze-L")
        with self._shape_lock:
            self._L_pad = max(self._L_pad, _round_bucket(max_L, self.bucket))
            if lockstep and n_devices:
                # +1 reserves the pad slot; identical on every host after
                # the allreduce, and K <= L so _route_sharded's local
                # rounding can never exceed this floor
                k = transport.allreduce_max(max_bucket + 1, "freeze-K")
                self._K_pad = max(self._K_pad, _round_bucket(k, self.bucket))

    def _native(self):
        from paddlebox_tpu.utils import native

        p = getattr(self._tls, "packer", None)
        if p is None and self._use_native and native.available():
            p = native.NativePacker(
                self._rows,
                self.store.u64_base,
                self.store.u64_offsets,
                self.store.n_sparse,
                self._n_table_rows,
            )
            self._tls.packer = p
            with self._shape_lock:
                self._all_native.append(p)
        return p

    def _gather_flat(self, indices: np.ndarray):
        """(uniq[U], inverse[L], segments[L]) for the batch, unpadded."""
        indices = np.asarray(indices, dtype=np.int64)
        L = int(self._key_counts[indices].sum())
        p = self._native() if self._dedup else None
        if p is not None:
            return (*p.pack(indices, L), L)
        # numpy fallback: per-slot ragged gather (slot-major), then unique
        from paddlebox_tpu.data.record_store import _ragged_indices

        S = self.store.n_sparse
        B = len(indices)
        off = self.store.u64_offsets[indices].astype(np.int64)
        base = self.store.u64_base[indices]
        parts, segs = [], []
        for s in range(S):
            starts = base + off[:, s]
            lens = off[:, s + 1] - off[:, s]
            parts.append(self._rows[_ragged_indices(starts, lens)])
            segs.append(np.repeat(s * B + np.arange(B, dtype=np.int32), lens))
        rows = np.concatenate(parts) if parts else np.zeros(0, np.int32)
        segments = np.concatenate(segs) if segs else np.zeros(0, np.int32)
        if self._dedup:
            uniq, inverse = np.unique(rows, return_inverse=True)
        else:
            uniq, inverse = rows, np.arange(L, dtype=np.int64)
        return uniq.astype(np.int32), inverse.astype(np.int32), segments, L

    def pack(self, indices: np.ndarray) -> DeviceBatch:
        """Batch of store records ``indices`` -> single-device DeviceBatch."""
        uniq, inverse, segments, L = self._gather_flat(indices)
        B = len(indices)
        S = self.store.n_sparse
        U = len(uniq)
        with self._shape_lock:
            self._L_pad = max(self._L_pad, _round_bucket(L, self.bucket))
            if self._U_pad == 0:
                # generous first-batch headroom (25%) so later batches rarely
                # grow the shape; capped at L_pad+1 since U <= L always
                self._U_pad = _round_bucket(U + max(self.bucket, U // 4), self.bucket)
            else:
                self._U_pad = max(self._U_pad, _round_bucket(U + 1, self.bucket))
            self._U_pad = min(self._U_pad, _round_bucket(self._L_pad + 1, self.bucket))
            L_pad, U_pad = self._L_pad, self._U_pad
        uniq_p = np.full(U_pad, self.ws.padding_row, dtype=np.int32)
        uniq_p[:U] = uniq
        inv_p = np.full(L_pad, U_pad - 1, dtype=np.int32)
        inv_p[:L] = inverse
        seg_p = np.full(L_pad, S * B, dtype=np.int32)
        seg_p[:L] = segments
        return DeviceBatch(
            batch_size=B,
            num_slots=S,
            uniq_rows=uniq_p,
            inverse=inv_p,
            segments=seg_p,
            labels=self._labels[indices],
            dense=self._dense[indices] if self._dense is not None else None,
            n_keys=L,
            n_uniq=U,
        )

    def pack_sharded(self, indices: np.ndarray, n_devices: int) -> ShardedDeviceBatch:
        """Batch -> mesh-routed ShardedDeviceBatch (fast gather + routing)."""
        uniq, inverse, segments, L = self._gather_flat(indices)
        rows = uniq[inverse] if len(uniq) else np.zeros(0, np.int32)
        with self._shape_lock:
            k_floor, l_floor = self._K_pad or -1, self._L_pad
        out = _route_sharded(
            rows,
            segments,
            len(indices),
            self.store.n_sparse,
            self.ws,
            n_devices,
            self.bucket,
            self._labels[indices],
            self._dense[indices] if self._dense is not None else None,
            self.dense_dim,
            k_floor=k_floor,
            l_floor=l_floor,
        )
        with self._shape_lock:
            self._K_pad = max(self._K_pad, out.req_ranks.shape[2])
            self._L_pad = max(self._L_pad, out.inverse.shape[1])
        return out

    def close(self) -> None:
        """Free every native scratch handle this packer spawned, including
        ones created inside prefetch worker threads (close() may be called
        from a thread that never packed)."""
        with self._shape_lock:
            handles, self._all_native = self._all_native, []
        for p in handles:
            p.close()
        self._tls.packer = None
