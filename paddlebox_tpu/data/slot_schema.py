"""Slot schema — which feature slots exist, their types, and which are used.

Parity with the reference's DataFeedDesc slot list
(paddle/fluid/framework/data_feed.proto:17-38: name, type "uint64"/"float",
is_used, is_dense) and the derived all_slots_info_/used_slots_info_ tables the
readers build (data_feed.cc SlotPaddleBoxDataFeed::Init).

A sample line carries *all* slots in schema order; only ``used`` slots are
materialized into batches. ``dense`` float slots keep zero values (sparse
slots drop zeros / near-zeros at parse time).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence


@dataclass(frozen=True)
class SlotInfo:
    name: str
    type: str = "uint64"  # "uint64" | "float"
    used: bool = True
    dense: bool = False  # dense float slots keep zeros, have fixed dim
    dim: int = 1  # for dense float slots: expected width

    def __post_init__(self):
        if self.type not in ("uint64", "float"):
            raise ValueError(f"slot {self.name}: bad type {self.type}")


class SlotSchema:
    """Ordered slot list + derived index tables."""

    def __init__(
        self,
        slots: Sequence[SlotInfo],
        parse_ins_id: bool = False,
        parse_logkey: bool = False,
        label_slot: Optional[str] = None,
    ):
        self.slots: List[SlotInfo] = list(slots)
        self.parse_ins_id = parse_ins_id
        self.parse_logkey = parse_logkey
        self.label_slot = label_slot
        names = [s.name for s in self.slots]
        if len(set(names)) != len(names):
            raise ValueError("duplicate slot names")
        # used slots partitioned by type, preserving schema order
        self.used_sparse: List[SlotInfo] = [
            s for s in self.slots if s.used and s.type == "uint64"
        ]
        self.used_float: List[SlotInfo] = [
            s for s in self.slots if s.used and s.type == "float"
        ]
        self._sparse_idx = {s.name: i for i, s in enumerate(self.used_sparse)}
        self._float_idx = {s.name: i for i, s in enumerate(self.used_float)}
        if label_slot is not None and label_slot not in self._float_idx and label_slot not in self._sparse_idx:
            raise ValueError(f"label slot {label_slot} not a used slot")

    @property
    def num_sparse(self) -> int:
        return len(self.used_sparse)

    @property
    def num_float(self) -> int:
        return len(self.used_float)

    def sparse_slot_index(self, name: str) -> int:
        return self._sparse_idx[name]

    def float_slot_index(self, name: str) -> int:
        return self._float_idx[name]

    @staticmethod
    def ctr_schema(num_sparse: int, dense_dim: int = 13, with_label: bool = True) -> "SlotSchema":
        """Criteo-style convenience schema: label + dense floats + N sparse slots."""
        slots: List[SlotInfo] = []
        if with_label:
            slots.append(SlotInfo("label", type="float", dense=True, dim=1))
        if dense_dim:
            slots.append(SlotInfo("dense", type="float", dense=True, dim=dense_dim))
        for i in range(num_sparse):
            slots.append(SlotInfo(f"slot{i:03d}", type="uint64"))
        return SlotSchema(slots, label_slot="label" if with_label else None)
