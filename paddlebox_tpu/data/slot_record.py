"""Columnar sample records and ragged batches.

``SlotRecord`` mirrors the reference's compact sample representation
(SlotRecordObject + SlotValues{values, offsets}, data_feed.h:777-852): one
flat value array per type with per-slot offsets, instead of a vector of
per-slot vectors.

``SlotBatch`` is the batch-of-records columnar form the device consumes
(analog of the fused uint64/float tensors BuildSlotBatchGPU produces,
data_feed.cc:2404-2522): one flat key array in slot-major order plus a
``[n_slots, batch+1]`` offset matrix per type. All device-side sparse ops key
off this layout.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np

from paddlebox_tpu.data.slot_schema import SlotSchema


@dataclass
class SlotRecord:
    """One sample: flat per-type values + per-slot offsets (len n_slots+1)."""

    u64_values: np.ndarray  # uint64 [total_u64]
    u64_offsets: np.ndarray  # uint32 [n_used_sparse + 1]
    f_values: np.ndarray  # float32 [total_f]
    f_offsets: np.ndarray  # uint32 [n_used_float + 1]
    ins_id: str = ""
    search_id: int = 0
    cmatch: int = 0
    rank: int = 0

    def slot_keys(self, slot_idx: int) -> np.ndarray:
        return self.u64_values[self.u64_offsets[slot_idx] : self.u64_offsets[slot_idx + 1]]

    def slot_floats(self, slot_idx: int) -> np.ndarray:
        return self.f_values[self.f_offsets[slot_idx] : self.f_offsets[slot_idx + 1]]


@dataclass
class SlotBatch:
    """Columnar ragged minibatch, slot-major.

    keys[k] for k in [offsets[s, i], offsets[s, i+1]) are the uint64 feasigns
    of slot s, instance i. Same shape contract for floats.
    """

    batch_size: int
    keys: np.ndarray  # uint64 [total_keys], slot-major then ins-major
    key_offsets: np.ndarray  # int32 [n_sparse, batch+1], per-slot prefix sums
    float_values: np.ndarray  # float32 [total_floats]
    float_offsets: np.ndarray  # int32 [n_float, batch+1]
    ins_ids: Optional[List[str]] = None
    search_ids: Optional[np.ndarray] = None  # uint64 [batch]
    cmatch: Optional[np.ndarray] = None  # int32 [batch]
    rank: Optional[np.ndarray] = None  # int32 [batch]
    rank_offset: Optional[np.ndarray] = None  # int32 [batch, max_rank*2+1] (pv-merged join phase)

    @property
    def num_sparse_slots(self) -> int:
        return self.key_offsets.shape[0]

    @property
    def num_float_slots(self) -> int:
        return self.float_offsets.shape[0]

    def slot_lengths(self) -> np.ndarray:
        """[n_sparse, batch] per-(slot, ins) key counts."""
        return np.diff(self.key_offsets, axis=1)

    def dense_float_matrix(self, slot_idx: int, dim: int) -> np.ndarray:
        """[batch, dim] view of a dense float slot (constant length == dim)."""
        off = self.float_offsets[slot_idx]
        lens = np.diff(off)
        if not np.all(lens == dim):
            out = np.zeros((self.batch_size, dim), dtype=np.float32)
            for i in range(self.batch_size):
                v = self.float_values[off[i] : off[i + 1]][:dim]
                out[i, : len(v)] = v
            return out
        start, stop = off[0], off[-1]
        return self.float_values[start:stop].reshape(self.batch_size, dim)

    def segment_ids(self) -> np.ndarray:
        """int32 [total_keys]: flat (slot * batch + ins) segment id per key.

        This is the host-precomputed analog of the reference's key2slot device
        array (FillKey2Slot, box_wrapper.cu): it drives device-side segment
        pooling with zero device bookkeeping.
        """
        n_slots, bp1 = self.key_offsets.shape
        lens = np.diff(self.key_offsets, axis=1).reshape(-1)  # [n_slots*batch]
        seg = np.repeat(np.arange(n_slots * (bp1 - 1), dtype=np.int32), lens)
        return seg


def build_batch(records: Sequence[SlotRecord], schema: SlotSchema) -> SlotBatch:
    """Concatenate records into a slot-major columnar batch.

    Analog of PutToFeedVec/BuildSlotBatchGPU (data_feed.cc:2404-2522) minus the
    device copy — pure host numpy; device upload happens in the packer.
    """
    bs = len(records)
    ns, nf = schema.num_sparse, schema.num_float

    key_offsets = np.zeros((ns, bs + 1), dtype=np.int32)
    float_offsets = np.zeros((nf, bs + 1), dtype=np.int32)

    # first pass: lengths
    for i, rec in enumerate(records):
        u_lens = np.diff(rec.u64_offsets)
        f_lens = np.diff(rec.f_offsets)
        key_offsets[:, i + 1] = u_lens
        float_offsets[:, i + 1] = f_lens
    # prefix-sum rows, then make slot-major global offsets
    np.cumsum(key_offsets, axis=1, out=key_offsets)
    np.cumsum(float_offsets, axis=1, out=float_offsets)
    slot_key_base = np.concatenate([[0], np.cumsum(key_offsets[:, -1])]).astype(np.int64)
    slot_f_base = np.concatenate([[0], np.cumsum(float_offsets[:, -1])]).astype(np.int64)

    keys = np.empty(int(slot_key_base[-1]), dtype=np.uint64)
    floats = np.empty(int(slot_f_base[-1]), dtype=np.float32)
    for i, rec in enumerate(records):
        for s in range(ns):
            v = rec.slot_keys(s)
            dst = slot_key_base[s] + key_offsets[s, i]
            keys[dst : dst + len(v)] = v
        for s in range(nf):
            v = rec.slot_floats(s)
            dst = slot_f_base[s] + float_offsets[s, i]
            floats[dst : dst + len(v)] = v
    # rebase offsets to global (slot-major) coordinates
    key_offsets += slot_key_base[:-1, None].astype(np.int32)
    float_offsets += slot_f_base[:-1, None].astype(np.int32)

    has_meta = schema.parse_ins_id or schema.parse_logkey
    return SlotBatch(
        batch_size=bs,
        keys=keys,
        key_offsets=key_offsets,
        float_values=floats,
        float_offsets=float_offsets,
        ins_ids=[r.ins_id for r in records] if has_meta else None,
        search_ids=np.array([r.search_id for r in records], dtype=np.uint64) if has_meta else None,
        cmatch=np.array([r.cmatch for r in records], dtype=np.int32) if has_meta else None,
        rank=np.array([r.rank for r in records], dtype=np.int32) if has_meta else None,
    )
