"""Columnar record store: the whole pass as a handful of flat arrays.

The reference keeps pass data as pooled ``SlotRecord`` objects
(``SlotObjPool``, data_feed.h:934-1050) because its per-record work happens
in C++ threads. Here the same columnar idea goes further: the pass IS the
arrays — ``u64_values``/``f_values`` flats plus per-record offset tables —
and every pass-wide operation (working-set key collection, key->row
resolution, label extraction, shuffling, batch packing) is one vectorized
or native call over them. No per-record Python objects exist on the hot
path; ``record(i)`` materializes a ``SlotRecord`` view only for the compat
paths (pv merge, AucRunner, cross-node routing).

Key→row resolution is pass-scoped: after ``PassWorkingSet.finalize`` the
mapping key->table row is frozen, so ``resolve_rows`` translates the whole
store ONCE (vectorized searchsorted); batches then gather int32 rows and
never touch uint64 keys again (the host analog of the reference's device
CopyKeys + DedupKeysAndFillIdx, box_wrapper_impl.h:25-162).
"""

from __future__ import annotations

import struct
from typing import List, Optional, Sequence

import numpy as np

from paddlebox_tpu.data.slot_record import SlotRecord
from paddlebox_tpu.data.slot_schema import SlotSchema


class ColumnarRecords:
    """Immutable columnar batch-of-all-records for one pass (one node)."""

    __slots__ = (
        "u64_values", "u64_offsets", "u64_base",
        "f_values", "f_offsets", "f_base",
        "search_ids", "cmatch", "rank",
        "ins_id_off", "ins_id_chars",
        "_rows", "_rows_ws_id",
    )

    def __init__(
        self,
        u64_values: np.ndarray,   # uint64 [total_u64]
        u64_offsets: np.ndarray,  # uint32 [n, n_sparse+1] record-local
        u64_base: np.ndarray,     # int64 [n]
        f_values: np.ndarray,     # float32 [total_f]
        f_offsets: np.ndarray,    # uint32 [n, n_float+1]
        f_base: np.ndarray,       # int64 [n]
        search_ids: Optional[np.ndarray] = None,  # uint64 [n]
        cmatch: Optional[np.ndarray] = None,      # int32 [n]
        rank: Optional[np.ndarray] = None,        # int32 [n]
        ins_id_off: Optional[np.ndarray] = None,  # int64 [n+1] byte offsets
        ins_id_chars: bytes = b"",
    ):
        self.u64_values = u64_values
        self.u64_offsets = u64_offsets
        self.u64_base = u64_base
        self.f_values = f_values
        self.f_offsets = f_offsets
        self.f_base = f_base
        n = len(u64_base)
        self.search_ids = search_ids if search_ids is not None else np.zeros(n, np.uint64)
        self.cmatch = cmatch if cmatch is not None else np.zeros(n, np.int32)
        self.rank = rank if rank is not None else np.zeros(n, np.int32)
        self.ins_id_off = ins_id_off
        self.ins_id_chars = ins_id_chars
        self._rows: Optional[np.ndarray] = None  # int32 [total_u64]
        self._rows_ws_id: Optional[int] = None

    # ---- basics ----------------------------------------------------------

    def __len__(self) -> int:
        return len(self.u64_base)

    @property
    def n_sparse(self) -> int:
        return self.u64_offsets.shape[1] - 1

    @property
    def n_float(self) -> int:
        return self.f_offsets.shape[1] - 1

    def key_counts(self) -> np.ndarray:
        """int64 [n]: total feasign count per record."""
        return self.u64_offsets[:, -1].astype(np.int64)

    def ins_id(self, i: int) -> str:
        if self.ins_id_off is None:
            return ""
        a, b = int(self.ins_id_off[i]), int(self.ins_id_off[i + 1])
        return self.ins_id_chars[a:b].decode(errors="replace")

    def record(self, i: int) -> SlotRecord:
        """Materialize one record as (view-backed) SlotRecord — compat path."""
        ub, fb = int(self.u64_base[i]), int(self.f_base[i])
        return SlotRecord(
            u64_values=self.u64_values[ub : ub + int(self.u64_offsets[i, -1])],
            u64_offsets=self.u64_offsets[i],
            f_values=self.f_values[fb : fb + int(self.f_offsets[i, -1])],
            f_offsets=self.f_offsets[i],
            ins_id=self.ins_id(i),
            search_id=int(self.search_ids[i]),
            cmatch=int(self.cmatch[i]),
            rank=int(self.rank[i]),
        )

    def records(self) -> List[SlotRecord]:
        return [self.record(i) for i in range(len(self))]

    # ---- construction ----------------------------------------------------

    @classmethod
    def empty(cls, n_sparse: int, n_float: int) -> "ColumnarRecords":
        return cls(
            np.zeros(0, np.uint64), np.zeros((0, n_sparse + 1), np.uint32),
            np.zeros(0, np.int64), np.zeros(0, np.float32),
            np.zeros((0, n_float + 1), np.uint32), np.zeros(0, np.int64),
            ins_id_off=np.zeros(1, np.int64),
        )

    @classmethod
    def from_records(
        cls, records: Sequence[SlotRecord], schema: SlotSchema
    ) -> "ColumnarRecords":
        """Vectorized concat of SlotRecords (fallback-parser / router path)."""
        n = len(records)
        Su, Sf = schema.num_sparse, schema.num_float
        if n == 0:
            return cls.empty(Su, Sf)
        u_off = np.stack([r.u64_offsets for r in records]).astype(np.uint32)
        f_off = np.stack([r.f_offsets for r in records]).astype(np.uint32)
        u_base = np.concatenate([[0], np.cumsum(u_off[:, -1])]).astype(np.int64)
        f_base = np.concatenate([[0], np.cumsum(f_off[:, -1])]).astype(np.int64)
        u_vals = (
            np.concatenate([r.u64_values for r in records])
            if u_base[-1]
            else np.zeros(0, np.uint64)
        )
        f_vals = (
            np.concatenate([r.f_values for r in records])
            if f_base[-1]
            else np.zeros(0, np.float32)
        )
        has_meta = schema.parse_ins_id or schema.parse_logkey
        ins_off = None
        chars = b""
        if has_meta:
            ids = [r.ins_id.encode() for r in records]
            lens = np.array([len(b) for b in ids], np.int64)
            ins_off = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
            chars = b"".join(ids)
        return cls(
            u_vals.astype(np.uint64), u_off, u_base[:-1],
            f_vals.astype(np.float32), f_off, f_base[:-1],
            search_ids=np.array([r.search_id for r in records], np.uint64),
            cmatch=np.array([r.cmatch for r in records], np.int32),
            rank=np.array([r.rank for r in records], np.int32),
            ins_id_off=ins_off, ins_id_chars=chars,
        )

    @classmethod
    def concat(cls, parts: Sequence["ColumnarRecords"]) -> "ColumnarRecords":
        parts = [p for p in parts if len(p)]
        if not parts:
            raise ValueError("concat of zero non-empty parts (use empty())")
        if len(parts) == 1:
            return parts[0]
        u_vals = np.concatenate([p.u64_values for p in parts])
        f_vals = np.concatenate([p.f_values for p in parts])
        u_off = np.concatenate([p.u64_offsets for p in parts])
        f_off = np.concatenate([p.f_offsets for p in parts])
        ub, fb, off_u, off_f = [], [], 0, 0
        for p in parts:
            ub.append(p.u64_base + off_u)
            fb.append(p.f_base + off_f)
            off_u += len(p.u64_values)
            off_f += len(p.f_values)
        have_ids = all(p.ins_id_off is not None for p in parts)
        ins_off = None
        chars = b""
        if have_ids:
            io, base = [np.zeros(1, np.int64)], 0
            pieces = []
            for p in parts:
                io.append(p.ins_id_off[1:] + base)
                base += p.ins_id_off[-1]
                pieces.append(p.ins_id_chars)
            chars = b"".join(pieces)
            ins_off = np.concatenate(io)
        return cls(
            u_vals, u_off, np.concatenate(ub), f_vals, f_off, np.concatenate(fb),
            search_ids=np.concatenate([p.search_ids for p in parts]),
            cmatch=np.concatenate([p.cmatch for p in parts]),
            rank=np.concatenate([p.rank for p in parts]),
            ins_id_off=ins_off, ins_id_chars=bytes(chars),
        )

    def select(self, indices: np.ndarray) -> "ColumnarRecords":
        """New store holding ``indices``' records (vectorized ragged gather).

        Used for physical shuffles and cross-node routing — the per-record
        list-append of the reference's ShuffleData (data_set.cc:1772-1791)
        becomes one gather per array.
        """
        indices = np.asarray(indices, dtype=np.int64)
        u_lens = self.u64_offsets[indices, -1].astype(np.int64)
        f_lens = self.f_offsets[indices, -1].astype(np.int64)
        u_idx = _ragged_indices(self.u64_base[indices], u_lens)
        f_idx = _ragged_indices(self.f_base[indices], f_lens)
        ins_off = None
        chars = b""
        if self.ins_id_off is not None:
            starts = self.ins_id_off[indices]
            lens = (self.ins_id_off[indices + 1] - starts).astype(np.int64)
            cidx = _ragged_indices(starts, lens)
            chars = np.frombuffer(self.ins_id_chars, np.uint8)[cidx].tobytes()
            ins_off = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
        return ColumnarRecords(
            self.u64_values[u_idx], self.u64_offsets[indices],
            np.concatenate([[0], np.cumsum(u_lens[:-1])]).astype(np.int64)
            if len(indices) else np.zeros(0, np.int64),
            self.f_values[f_idx], self.f_offsets[indices],
            np.concatenate([[0], np.cumsum(f_lens[:-1])]).astype(np.int64)
            if len(indices) else np.zeros(0, np.int64),
            search_ids=self.search_ids[indices],
            cmatch=self.cmatch[indices],
            rank=self.rank[indices],
            ins_id_off=ins_off, ins_id_chars=chars,
        )

    # ---- wire format (cross-process shuffle / working-set exchange) ------
    #
    # v2: one fixed header + raw column blocks in declared order. Column
    # dtypes are pinned by the class contract, so the header only needs
    # the shape scalars — no zip container, no per-array .npy headers, no
    # CRC duplication (the transport frame CRC already covers the bytes).
    # v1 (np.savez) payloads are still decoded: they start with the zip
    # local-file magic "PK\x03\x04", which can never collide with _WIRE_MAGIC.

    _WIRE_MAGIC = b"PBCR"
    _WIRE_VERSION = 2
    # magic, version, has_ins, n_sparse, n_float, n, n_u64, n_f, ins_chars
    _WIRE_HDR = struct.Struct("<4sBBHHQQQQ")

    def to_bytes(self) -> bytes:
        """Serialize for the host transport (compact v2: header + raw
        column blocks; versioned, self-describing, no pickle)."""
        has_ins = self.ins_id_off is not None
        parts = [
            self._WIRE_HDR.pack(
                self._WIRE_MAGIC, self._WIRE_VERSION, int(has_ins),
                self.n_sparse, self.n_float, len(self),
                len(self.u64_values), len(self.f_values),
                len(self.ins_id_chars) if has_ins else 0,
            ),
            np.ascontiguousarray(self.u64_values, np.uint64).tobytes(),
            np.ascontiguousarray(self.u64_offsets, np.uint32).tobytes(),
            np.ascontiguousarray(self.u64_base, np.int64).tobytes(),
            np.ascontiguousarray(self.f_values, np.float32).tobytes(),
            np.ascontiguousarray(self.f_offsets, np.uint32).tobytes(),
            np.ascontiguousarray(self.f_base, np.int64).tobytes(),
            np.ascontiguousarray(self.search_ids, np.uint64).tobytes(),
            np.ascontiguousarray(self.cmatch, np.int32).tobytes(),
            np.ascontiguousarray(self.rank, np.int32).tobytes(),
        ]
        if has_ins:
            parts.append(np.ascontiguousarray(self.ins_id_off, np.int64).tobytes())
            parts.append(bytes(self.ins_id_chars))
        return b"".join(parts)

    @classmethod
    def from_bytes(cls, data: bytes) -> "ColumnarRecords":
        if data[:4] == cls._WIRE_MAGIC:
            return cls._from_bytes_v2(data)
        if data[:4] == b"PK\x03\x04":  # legacy np.savez container
            import io

            z = np.load(io.BytesIO(data))
            ins_off = z["ins_id_off"] if "ins_id_off" in z.files else None
            chars = z["ins_id_chars"].tobytes() if "ins_id_chars" in z.files else b""
            return cls(
                z["u64_values"], z["u64_offsets"], z["u64_base"],
                z["f_values"], z["f_offsets"], z["f_base"],
                search_ids=z["search_ids"], cmatch=z["cmatch"], rank=z["rank"],
                ins_id_off=ins_off, ins_id_chars=chars,
            )
        raise ValueError(
            f"not a ColumnarRecords wire payload (magic {data[:4]!r})"
        )

    @classmethod
    def _from_bytes_v2(cls, data: bytes) -> "ColumnarRecords":
        hdr = cls._WIRE_HDR
        if len(data) < hdr.size:
            raise ValueError("ColumnarRecords v2 payload shorter than header")
        magic, ver, has_ins, n_sparse, n_float, n, n_u64, n_f, n_chars = (
            hdr.unpack_from(data)
        )
        if ver != cls._WIRE_VERSION:
            raise ValueError(f"ColumnarRecords wire version {ver} unsupported")
        # one writable buffer: slices below are views into it, matching the
        # fresh-array semantics of the npz path (slots_shuffle mutates
        # u64_values in place on the eval path)
        buf = bytearray(data)
        off = [hdr.size]

        def block(dtype, count):
            dt = np.dtype(dtype)
            end = off[0] + dt.itemsize * count
            if end > len(buf):
                raise ValueError(
                    "ColumnarRecords v2 payload truncated: header declares "
                    f"more column bytes than the {len(buf)}-byte buffer holds"
                )
            a = np.frombuffer(buf, dt, count=count, offset=off[0])
            off[0] = end
            return a

        u64_values = block(np.uint64, n_u64)
        u64_offsets = block(np.uint32, n * (n_sparse + 1)).reshape(n, n_sparse + 1)
        u64_base = block(np.int64, n)
        f_values = block(np.float32, n_f)
        f_offsets = block(np.uint32, n * (n_float + 1)).reshape(n, n_float + 1)
        f_base = block(np.int64, n)
        search_ids = block(np.uint64, n)
        cmatch = block(np.int32, n)
        rank = block(np.int32, n)
        ins_off = None
        chars = b""
        if has_ins:
            ins_off = block(np.int64, n + 1)
            chars = bytes(block(np.uint8, n_chars))
        if off[0] != len(buf):
            raise ValueError(
                f"ColumnarRecords v2 payload holds {len(buf) - off[0]} "
                "trailing bytes beyond the declared columns"
            )
        return cls(
            u64_values, u64_offsets, u64_base, f_values, f_offsets, f_base,
            search_ids=search_ids, cmatch=cmatch, rank=rank,
            ins_id_off=ins_off, ins_id_chars=chars,
        )

    # ---- pass-scoped precomputation -------------------------------------

    def resolve_rows(self, ws) -> np.ndarray:
        """int32 pass-local row per key, whole store at once (cached).

        One vectorized lookup per pass replaces a per-batch key search —
        the decisive host-side win over re-resolving every batch.
        """
        if self._rows is not None and self._rows_ws_id == id(ws):
            return self._rows
        self._rows = (
            ws.lookup(self.u64_values)
            if len(self.u64_values)
            else np.zeros(0, np.int32)
        )
        self._rows_ws_id = id(ws)
        return self._rows

    def invalidate_rows(self) -> None:
        """Call after mutating keys in place (slots_shuffle eval path)."""
        self._rows = None
        self._rows_ws_id = None

    def float_slot_matrix(
        self, slot_idx: int, dim: int, indices: Optional[np.ndarray] = None
    ) -> np.ndarray:
        """[n, dim] dense view of a float slot (labels / dense features)."""
        if indices is None:
            indices = np.arange(len(self), dtype=np.int64)
        starts = self.f_base[indices] + self.f_offsets[indices, slot_idx].astype(np.int64)
        lens = (
            self.f_offsets[indices, slot_idx + 1] - self.f_offsets[indices, slot_idx]
        ).astype(np.int64)
        if np.all(lens == dim):
            idx = starts[:, None] + np.arange(dim, dtype=np.int64)[None, :]
            return self.f_values[idx].astype(np.float32, copy=False)
        from paddlebox_tpu.utils import native

        if native.available():
            return native.gather_f32_slot(
                self.f_values, self.f_base, self.f_offsets, indices, slot_idx, dim
            )
        out = np.zeros((len(indices), dim), np.float32)
        for i in range(len(indices)):
            c = min(int(lens[i]), dim)
            out[i, :c] = self.f_values[starts[i] : starts[i] + c]
        return out


def _ragged_indices(starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Flat gather indices for variable-length runs [starts[i], +lens[i])."""
    total = int(lens.sum())
    if total == 0:
        return np.zeros(0, np.int64)
    idx = np.ones(total, dtype=np.int64)
    nz = lens > 0
    # positions where a new run begins get start - (prev_start + prev_len) + 1
    run_starts = starts[nz]
    run_lens = lens[nz]
    run_ends = np.cumsum(run_lens)[:-1]
    idx[0] = run_starts[0]
    idx[run_ends] = run_starts[1:] - (run_starts[:-1] + run_lens[:-1]) + 1
    np.cumsum(idx, out=idx)
    return idx
