"""pbox-lint — project-specific static analysis for paddlebox_tpu.

Stdlib-only (``ast`` + ``re``); deliberately importable without jax so the
CLI (tools/run_lint.py) and CI can run it on any box. Rule catalog lives
in docs/STATIC_ANALYSIS.md.
"""

from .callgraph import MAIN, CallGraph, FuncNode, get_callgraph
from .core import (
    DEFAULT_PROFILES,
    ERROR,
    WARNING,
    Finding,
    LintResult,
    ModuleCtx,
    Rule,
    apply_baseline,
    apply_profiles,
    iter_py_files,
    lint_paths,
    load_baseline,
    save_baseline,
)
from .protocol import (
    ProtocolModel,
    ProtoSite,
    extract_protocol,
    get_protocol,
    patterns_may_match,
)
from .rules_distributed import DistributedDisciplineRule
from .rules_exceptions import ExceptionFlowRule
from .rules_faultflow import FaultSiteCoverageRule
from .rules_io import DurableWriteRule
from .rules_jit import JitPurityRule
from .rules_locks import LockDisciplineRule
from .rules_registry import RegistryConsistencyRule
from .rules_resources import ResourceLifecycleRule
from .rules_stats import StatNameRule
from .rules_threads import RaceDetectorRule

ALL_RULES = [
    JitPurityRule,
    LockDisciplineRule,
    RegistryConsistencyRule,
    DurableWriteRule,
    StatNameRule,
    RaceDetectorRule,
    ExceptionFlowRule,
    FaultSiteCoverageRule,
    DistributedDisciplineRule,
    ResourceLifecycleRule,
]


def default_rules():
    """Fresh instances of every rule (rules hold per-run state)."""
    return [cls() for cls in ALL_RULES]


__all__ = [
    "ALL_RULES",
    "DEFAULT_PROFILES",
    "ERROR",
    "MAIN",
    "WARNING",
    "CallGraph",
    "Finding",
    "FuncNode",
    "LintResult",
    "ModuleCtx",
    "Rule",
    "apply_baseline",
    "apply_profiles",
    "default_rules",
    "get_callgraph",
    "iter_py_files",
    "lint_paths",
    "load_baseline",
    "save_baseline",
    "DistributedDisciplineRule",
    "DurableWriteRule",
    "ExceptionFlowRule",
    "FaultSiteCoverageRule",
    "JitPurityRule",
    "LockDisciplineRule",
    "ProtoSite",
    "ProtocolModel",
    "RaceDetectorRule",
    "RegistryConsistencyRule",
    "ResourceLifecycleRule",
    "StatNameRule",
    "extract_protocol",
    "get_protocol",
    "patterns_may_match",
]
