"""pbox-lint — project-specific static analysis for paddlebox_tpu.

Stdlib-only (``ast`` + ``re``); deliberately importable without jax so the
CLI (tools/run_lint.py) and CI can run it on any box. Rule catalog lives
in docs/STATIC_ANALYSIS.md.
"""

from .core import (
    ERROR,
    WARNING,
    Finding,
    LintResult,
    ModuleCtx,
    Rule,
    apply_baseline,
    iter_py_files,
    lint_paths,
    load_baseline,
    save_baseline,
)
from .rules_io import DurableWriteRule
from .rules_jit import JitPurityRule
from .rules_locks import LockDisciplineRule
from .rules_registry import RegistryConsistencyRule
from .rules_stats import StatNameRule

ALL_RULES = [
    JitPurityRule,
    LockDisciplineRule,
    RegistryConsistencyRule,
    DurableWriteRule,
    StatNameRule,
]


def default_rules():
    """Fresh instances of every rule (rules hold per-run state)."""
    return [cls() for cls in ALL_RULES]


__all__ = [
    "ALL_RULES",
    "ERROR",
    "WARNING",
    "Finding",
    "LintResult",
    "ModuleCtx",
    "Rule",
    "apply_baseline",
    "default_rules",
    "iter_py_files",
    "lint_paths",
    "load_baseline",
    "save_baseline",
    "DurableWriteRule",
    "JitPurityRule",
    "LockDisciplineRule",
    "RegistryConsistencyRule",
    "StatNameRule",
]
