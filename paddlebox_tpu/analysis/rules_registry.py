"""REG003 — flag and faultinject-site registry consistency (project-wide).

The flag registry (paddlebox_tpu/config.py) raises ``KeyError`` on an
undefined ``get_flag``/``set_flag`` — but only when the code path actually
runs, which for error-handling and rarely-enabled paths can be days into a
soak. The fault-injection harness (utils/faultinject.py) is worse: firing
an unknown site is a silent no-op, so a typo'd site string makes a chaos
test pass vacuously. Both are catchable at lint time:

- ERROR: ``get_flag("x")``/``set_flag("x")`` with no ``define_flag("x")``
  anywhere in the scanned set.
- WARNING: ``define_flag("x")`` never read via ``get_flag("x")`` — dead
  knob (or a knob only tests poke, which deserves a look either way).
- ERROR: ``fire("site")`` / ``fail_*("site", ...)`` with a site string not
  in ``faultinject.KNOWN_SITES`` (the declared catalog; the rule reads the
  tuple straight out of the AST, so catalog and check can't drift).

Dynamic (non-literal) names are skipped — the registry module's own
``get_flag(n)`` loops are unknowable statically; the literal discipline
everywhere else is exactly what makes this rule cheap and exact.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, ModuleCtx, Rule, call_name, literal_str_arg

_FIRE_FUNCS = {"fire", "_fault_fire"}
_RULE_FACTORIES = {"fail_nth", "fail_once", "fail_always", "fail_prob"}


def _known_sites(modules: Sequence[ModuleCtx]) -> Optional[Set[str]]:
    """KNOWN_SITES tuple parsed from utils/faultinject.py, if scanned."""
    for ctx in modules:
        if not ctx.path.endswith("utils/faultinject.py"):
            continue
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign):
                names = [
                    t.id for t in stmt.targets if isinstance(t, ast.Name)
                ]
                if "KNOWN_SITES" in names and isinstance(
                    stmt.value, (ast.Tuple, ast.List, ast.Set)
                ):
                    return {
                        e.value
                        for e in stmt.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                    }
    return None


class RegistryConsistencyRule(Rule):
    id = "REG003"
    doc = "flag get/set vs define_flag, faultinject sites vs KNOWN_SITES"

    def finalize(self, modules: Sequence[ModuleCtx]) -> List[Finding]:
        defines: Dict[str, Tuple[ModuleCtx, int]] = {}
        reads: Set[str] = set()
        uses: List[Tuple[str, ModuleCtx, int, str]] = []  # (name, ctx, line, fn)
        fires: List[Tuple[str, ModuleCtx, int]] = []
        for ctx in modules:
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = call_name(node)
                if fn == "define_flag":
                    name = literal_str_arg(node)
                    if name is not None and name not in defines:
                        defines[name] = (ctx, node.lineno)
                elif fn in ("get_flag", "set_flag"):
                    name = literal_str_arg(node)
                    if name is not None:
                        uses.append((name, ctx, node.lineno, fn))
                        if fn == "get_flag":
                            reads.add(name)
                elif fn in _FIRE_FUNCS or fn in _RULE_FACTORIES:
                    site = literal_str_arg(node)
                    if site is not None:
                        fires.append((site, ctx, node.lineno))

        findings: List[Finding] = []
        for name, ctx, line, fn in uses:
            if name not in defines:
                f = self.finding(
                    ctx, line,
                    f'{fn}("{name}") but no define_flag("{name}") anywhere '
                    "in the scanned set — raises KeyError when this path runs",
                )
                if f is not None:
                    findings.append(f)
        for name, (ctx, line) in sorted(defines.items()):
            if name not in reads:
                f = self.finding(
                    ctx, line,
                    f'define_flag("{name}") is never read via get_flag — '
                    "dead knob (wire it up or delete it)",
                    severity="warning",
                )
                if f is not None:
                    findings.append(f)
        sites = _known_sites(modules)
        if sites is not None:
            for site, ctx, line in fires:
                if site not in sites:
                    f = self.finding(
                        ctx, line,
                        f'faultinject site "{site}" is not in '
                        "faultinject.KNOWN_SITES — firing it is a silent "
                        "no-op in every chaos schedule",
                    )
                    if f is not None:
                        findings.append(f)
        return findings
