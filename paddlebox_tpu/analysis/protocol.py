"""Protocol vocabulary extraction for pbox-verify.

The elastic control plane talks through tagged PBTX frames: point-to-point
``send``/``recv`` pairs, collective rounds (``allgather``/``alltoall``/
``allreduce_max``/``barrier``), the verdict wrapper
(``EpochCoordinator.exchange_verdict``), the membership convergence loop
(``agree_membership``) and the epoch floor (``discard_epochs_below``).
This pass statically recovers that vocabulary from the real code so the
distributed-discipline rule (DST009) and the model checker
(tools/proto_check.py) can be checked *against the code*, not against a
hand-maintained list that drifts.

Every tag expression is resolved to a **pattern**: constant parts stay
literal, runtime parts (f-string fields, unresolvable names) become
``*``.  ``f"migrate:{seq}:{lo}-{hi}@e{epoch}"`` extracts as
``migrate:*:*-*@e*``; ``"barrier:" + tag`` as ``barrier:*``.  Resolution
follows module-level string constants (``_JOIN_ANNOUNCE_TAG``) and
single-assignment locals (``tag = f"{_JOIN_OFFER_TAG}:{tp.rank}"`` two
lines above the ``recv``), which covers every tag site in the package.

Two patterns *may match* when the literal head of one (text up to the
first ``*``) is a prefix of the other's — deliberately over-matching, so
the black-holed-frame check under-reports rather than cries wolf.  A tag
expression that resolves to nothing literal at all (a bare parameter,
``sock.recv(1024)``'s byte count) yields an *opaque* site: opaque recvs
conservatively satisfy any send, opaque sends are never reported.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import ModuleCtx

# op -> (argument index of the tag, direction)
#   "out"  — the site emits frames with this tag
#   "in"   — the site consumes frames with this tag
#   "both" — collective: every rank sends and receives under the tag
_TAG_OPS: Dict[str, Tuple[int, str]] = {
    "send": (1, "out"),
    "recv": (0, "in"),
    "recv_first": (0, "in"),
    "pending_sources": (0, "in"),
    "allgather": (1, "both"),
    "alltoall": (1, "both"),
    "allreduce_max": (1, "both"),
    "barrier": (0, "both"),
    "exchange_verdict": (0, "both"),
}

_COLLECTIVE_OPS = frozenset(
    ("allgather", "alltoall", "allreduce_max", "barrier",
     "exchange_verdict", "agree_membership")
)

# prefixes of the control-plane vocabulary: any string literal with one of
# these heads counts as protocol vocabulary even when it reaches the
# transport through a helper parameter (e.g. the ctl:load / ctl:jload
# f-strings handed to the shard-load gather)
CONTROL_PREFIXES = ("ctl:", "migrate:", "barrier:", "shuffle:", "serve:")

STAR = "*"


@dataclass(frozen=True)
class ProtoSite:
    """One protocol call site: a tagged transport op, a membership round,
    or an epoch gate."""

    module: str
    line: int
    op: str  # key of _TAG_OPS, or "agree_membership" / "epoch_gate"
    direction: str  # "out" | "in" | "both" | "gate"
    pattern: str  # tag pattern with runtime parts as "*"; "" for gates
    opaque: bool = False  # True when nothing literal could be recovered
    fatal: bool = False  # exchange_verdict(..., fatal=True) commit points
    has_fingerprint: bool = False  # tag/key embeds a .fingerprint() call

    @property
    def has_epoch(self) -> bool:
        return "@e" in self.pattern

    @property
    def is_collective(self) -> bool:
        return self.op in _COLLECTIVE_OPS


def literal_head(pattern: str) -> str:
    """Constant prefix of a pattern (text before the first ``*``)."""
    i = pattern.find(STAR)
    return pattern if i < 0 else pattern[:i]


def patterns_may_match(a: str, b: str) -> bool:
    """Conservative unification: literal patterns must be equal; once a
    wildcard is involved, the literal heads must be prefix-compatible.
    Errs toward matching (DST009 under-reports black holes)."""
    if STAR not in a and STAR not in b:
        return a == b
    ha, hb = literal_head(a), literal_head(b)
    return ha.startswith(hb) or hb.startswith(ha)


# ---- tag expression resolution ---------------------------------------------


def _module_str_consts(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Constant):
            v = stmt.value.value
            if isinstance(v, str):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = v
    return out


def _local_assigns(fn: ast.AST) -> Dict[str, List[ast.AST]]:
    """name -> value exprs assigned to it anywhere in ``fn`` (excluding
    nested defs, whose locals are their own)."""
    out: Dict[str, List[ast.AST]] = {}

    def walk(node: ast.AST, top: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if not top:
                    continue
                walk(child, False)
                continue
            if isinstance(child, ast.Assign) and len(child.targets) == 1:
                t = child.targets[0]
                if isinstance(t, ast.Name):
                    out.setdefault(t.id, []).append(child.value)
            walk(child, top)

    # fn itself is the def whose body we want; nested defs are skipped
    for stmt in getattr(fn, "body", []):
        walk(stmt, True)
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            t = stmt.targets[0]
            if isinstance(t, ast.Name):
                out.setdefault(t.id, []).append(stmt.value)
    return out


class _Resolver:
    """Resolves a tag expression to a pattern string, or None when the
    expression is definitely not a string (numeric recv byte counts)."""

    def __init__(self, consts: Dict[str, str], local_env: Dict[str, List[ast.AST]]):
        self.consts = consts
        self.local_env = local_env

    def resolve(self, expr: ast.AST, depth: int = 0) -> Optional[str]:
        if depth > 6:
            return STAR
        if isinstance(expr, ast.Constant):
            return expr.value if isinstance(expr.value, str) else None
        if isinstance(expr, ast.JoinedStr):
            parts: List[str] = []
            for v in expr.values:
                if isinstance(v, ast.Constant):
                    parts.append(str(v.value))
                elif isinstance(v, ast.FormattedValue):
                    inner = self.resolve(v.value, depth + 1)
                    parts.append(inner if inner not in (None, "") else STAR)
                else:
                    parts.append(STAR)
            return "".join(parts)
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            left = self.resolve(expr.left, depth + 1)
            right = self.resolve(expr.right, depth + 1)
            if left is None and right is None:
                return None
            return (left or STAR) + (right or STAR)
        if isinstance(expr, ast.Name):
            if expr.id in self.consts:
                return self.consts[expr.id]
            vals = self.local_env.get(expr.id, [])
            if len(vals) == 1:
                return self.resolve(vals[0], depth + 1) or STAR
            return STAR
        # attributes, calls, subscripts: runtime values
        return STAR


def _has_fingerprint(
    expr: ast.AST, res: Optional["_Resolver"] = None, depth: int = 0
) -> bool:
    """True when a ``.fingerprint()`` call flows into ``expr`` — directly,
    or (like pattern resolution) via a single-assignment local."""
    if depth > 4:
        return False
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr == "fingerprint":
                return True
        if res is not None and isinstance(node, ast.Name):
            vals = res.local_env.get(node.id, [])
            if len(vals) == 1 and _has_fingerprint(vals[0], res, depth + 1):
                return True
    return False


# ---- extraction -------------------------------------------------------------


@dataclass
class ProtocolModel:
    """The extracted vocabulary plus the send/recv matching table."""

    sites: List[ProtoSite] = field(default_factory=list)
    # control-prefixed string literals seen anywhere (op="tag_literal"):
    # vocabulary that reaches the transport through helper parameters
    literal_tags: List[ProtoSite] = field(default_factory=list)

    def tag_patterns(self) -> Set[str]:
        return {s.pattern for s in self.sites if s.pattern and not s.opaque}

    def control_patterns(self) -> Set[str]:
        """Every control-vocabulary pattern: direct tag-op sites plus
        control-prefixed literals routed through helpers."""
        out = {
            p for p in self.tag_patterns()
            if literal_head(p).startswith(CONTROL_PREFIXES)
        }
        out.update(s.pattern for s in self.literal_tags)
        return out

    def sites_in(self, module: str) -> List[ProtoSite]:
        return [s for s in self.sites if s.module == module]

    def send_sites(self) -> List[ProtoSite]:
        return [s for s in self.sites if s.direction == "out"]

    def recv_sites(self) -> List[ProtoSite]:
        return [s for s in self.sites if s.direction == "in"]

    def collective_sites(self) -> List[ProtoSite]:
        return [s for s in self.sites if s.is_collective]

    def epoch_gates(self) -> List[ProtoSite]:
        return [s for s in self.sites if s.op == "epoch_gate"]

    def receivers_for(self, send: ProtoSite) -> List[ProtoSite]:
        """Recv-side sites whose pattern may match this send's."""
        out: List[ProtoSite] = []
        for s in self.recv_sites():
            if s.opaque or patterns_may_match(send.pattern, s.pattern):
                out.append(s)
        return out

    def unmatched_sends(self) -> List[ProtoSite]:
        """Point-to-point sends with no possible receiver anywhere in the
        scanned set — black-holed frames.  Opaque sends are skipped (we
        could not read their tag, so we cannot call them unmatched)."""
        return [
            s for s in self.send_sites()
            if not s.opaque and not self.receivers_for(s)
        ]

    def covers_tag(self, tag: str) -> bool:
        """True when a concrete runtime tag is within the extracted
        vocabulary (some non-opaque pattern or control literal matches)."""
        pats = self.tag_patterns() | {s.pattern for s in self.literal_tags}
        return any(patterns_may_match(tag, p) for p in pats)


def extract_protocol(modules: Sequence[ModuleCtx]) -> ProtocolModel:
    model = ProtocolModel()
    for ctx in modules:
        consts = _module_str_consts(ctx.tree)
        # walk per-function so locals resolve against the right scope;
        # module-level calls resolve against constants only
        funcs = [
            n for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        covered: Set[int] = set()
        # ast.walk is breadth-first, so reversing visits nested defs before
        # their hosts — a call inside a nested def must resolve against the
        # nested scope's locals, not the host's
        for fn in reversed(funcs):
            env = _local_assigns(fn)
            res = _Resolver(consts, env)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and id(node) not in covered:
                    site = _site_for_call(ctx, node, res)
                    if site is not None:
                        covered.add(id(node))
                        model.sites.append(site)
        res = _Resolver(consts, {})
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and id(node) not in covered:
                site = _site_for_call(ctx, node, res)
                if site is not None:
                    model.sites.append(site)
        # secondary sweep: control-prefixed literals anywhere in the module
        # (tags handed to helpers as parameters never hit a tag op directly)
        inside_fstring = {
            id(v) for node in ast.walk(ctx.tree)
            if isinstance(node, ast.JoinedStr) for v in node.values
        }
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Constant, ast.JoinedStr)):
                if id(node) in inside_fstring:
                    continue  # fragments report through their JoinedStr
                pat = res.resolve(node)
                if pat and literal_head(pat).startswith(CONTROL_PREFIXES):
                    model.literal_tags.append(ProtoSite(
                        module=ctx.path, line=getattr(node, "lineno", 0),
                        op="tag_literal", direction="lit", pattern=pat,
                    ))
    model.sites.sort(key=lambda s: (s.module, s.line, s.op))
    model.literal_tags.sort(key=lambda s: (s.module, s.line, s.pattern))
    return model


def _call_tail(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _site_for_call(
    ctx: ModuleCtx, node: ast.Call, res: _Resolver
) -> Optional[ProtoSite]:
    name = _call_tail(node)
    if name is None:
        return None
    if name == "agree_membership":
        return ProtoSite(
            module=ctx.path, line=node.lineno, op=name, direction="both",
            pattern="ctl:member:*",
        )
    if name == "discard_epochs_below":
        return ProtoSite(
            module=ctx.path, line=node.lineno, op="epoch_gate",
            direction="gate", pattern="",
        )
    if name not in _TAG_OPS:
        return None
    idx, direction = _TAG_OPS[name]
    if len(node.args) <= idx:
        tag_expr = None
        for kw in node.keywords:
            if kw.arg == "tag" or (name == "exchange_verdict" and kw.arg == "key"):
                tag_expr = kw.value
        if tag_expr is None:
            return None
    else:
        tag_expr = node.args[idx]
    pattern = res.resolve(tag_expr)
    if pattern is None:
        return None  # definitely not a string tag (socket.recv byte count)
    fatal = False
    if name == "exchange_verdict":
        if len(node.args) > 3 and isinstance(node.args[3], ast.Constant):
            fatal = bool(node.args[3].value)
        for kw in node.keywords:
            if kw.arg == "fatal" and isinstance(kw.value, ast.Constant):
                fatal = bool(kw.value.value)
        # the wrapper builds f"ctl:verdict:{key}@e{epoch}" around the key
        pattern = f"ctl:verdict:{pattern}@e{STAR}"
    if name == "barrier":
        pattern = "barrier:" + pattern
    opaque = literal_head(pattern) == "" and pattern.replace(STAR, "") == ""
    return ProtoSite(
        module=ctx.path, line=node.lineno, op=name, direction=direction,
        pattern=pattern, opaque=opaque, fatal=fatal,
        has_fingerprint=_has_fingerprint(tag_expr, res),
    )


_CACHE: Dict[int, ProtocolModel] = {}


def get_protocol(modules: Sequence[ModuleCtx]) -> ProtocolModel:
    """Build (or reuse) the extraction for this exact module list —
    mirrors get_callgraph's one-live-graph cache."""
    key = hash(tuple(id(m) for m in modules))
    model = _CACHE.get(key)
    if model is None:
        _CACHE.clear()
        model = extract_protocol(modules)
        _CACHE[key] = model
    return model
