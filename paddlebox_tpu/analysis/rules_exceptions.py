"""EXC007 — exception-flow gate: no silent swallows of broad excepts.

The repo's headline guarantee is bitwise-equal recovery; its failure
paths raise TYPED errors (``HostCodecError``, ``SpillIOError``,
``DataPoisonedError``, ``TransportTimeout``, ``PeerDeadError``,
``VersionMismatchError``, ``DeltaLineageError``, ...).  A broad
``except Exception:``/``except OSError:`` between the raise and the
supervisor turns any of them into silence: the pass "succeeds", the soak
stays green, and the divergence surfaces days later as a parity failure
nobody can bisect.  Two checks:

- **error — silent swallow**: an ``except`` clause catching ``Exception``,
  ``BaseException``, ``OSError`` (or bare ``except:``) whose body neither
  *re-raises* (any ``raise``), *counts* (``STAT_ADD``/``STAT_SET``),
  *records* (a call whose name looks like logging/incident machinery:
  ``log*``/``warn*``/``*record*``/``*instant*``/``*alarm*``/``print``),
  nor *stores the exception for later* — an assignment whose right side
  uses the bound name (``except X as e: self._exc = e``), a
  ``fut.set_exception(e)`` handoff, or any call taking the bound name as
  an argument (``errors.append((r, e))``) all keep the error alive — they
  are deferred re-raises, not swallows.  Handling by narrowing
  (``except HostCodecError:``) never fires — the rule only polices the
  catch-alls.
- **warning — unhandled typed error**: a package-defined ``*Error`` class
  that is raised somewhere in the scanned set but never named in ANY
  ``except`` clause or ``pytest.raises(...)`` assertion (package or
  tests): every path that can see it is a broad catch-all, so its type
  carries no information to any handler.

Suppress with ``# pbox-lint: disable=EXC007`` on the ``except`` line only
where the swallow is the contract (e.g. ``__del__`` close paths) — and
say why in the comment.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, ModuleCtx, Rule, call_name

_BROAD = {"Exception", "BaseException", "OSError", "EnvironmentError", "IOError"}
_COUNT_FUNCS = {"STAT_ADD", "STAT_SET"}
_RECORD_RE = re.compile(
    r"^(log|warn|print$|debug$|info$|exception$|critical$)|record|incident|"
    r"instant|alarm|fail$|abort",
    re.IGNORECASE,
)


def _broad_names(h: ast.ExceptHandler) -> List[str]:
    """The broad type names this handler catches ([] when it is narrow)."""
    if h.type is None:
        return ["<bare except>"]
    exprs = (
        list(h.type.elts) if isinstance(h.type, ast.Tuple) else [h.type]
    )
    out: List[str] = []
    for e in exprs:
        name = e.attr if isinstance(e, ast.Attribute) else (
            e.id if isinstance(e, ast.Name) else None
        )
        if name in _BROAD:
            out.append(name)
    return out


def _handler_is_accounted(h: ast.ExceptHandler) -> bool:
    bound = h.name  # "e" in `except X as e:` (None when unbound)
    for node in ast.walk(h):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name is None:
                continue
            if name in _COUNT_FUNCS or name == "set_exception":
                return True
            if _RECORD_RE.search(name):
                return True
        if bound and isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            value = getattr(node, "value", None)
            if value is not None and any(
                isinstance(n, ast.Name) and n.id == bound
                for n in ast.walk(value)
            ):
                return True  # exception stored for a later re-raise
        if bound and isinstance(node, ast.Call) and any(
            isinstance(n, ast.Name) and n.id == bound
            for a in node.args + [kw.value for kw in node.keywords]
            for n in ast.walk(a)
        ):
            # the exception object is handed onward (errors.append((r, e)),
            # q.put(e), repr(e) into a collector) — a deferred surface,
            # not a swallow
            return True
    return False


class ExceptionFlowRule(Rule):
    id = "EXC007"
    doc = "broad except must re-raise, count, or record; typed errors handled"

    def check_module(self, ctx: ModuleCtx) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            broad = _broad_names(node)
            if not broad:
                continue
            if _handler_is_accounted(node):
                continue
            f = self.finding(
                ctx,
                node,
                f"broad `except {broad[0]}` silently swallows — re-raise, "
                "count a STAT_ADD, or record an incident (typed errors "
                "like TransportTimeout/HostCodecError die invisibly here)",
            )
            if f is not None:
                findings.append(f)
        return findings

    def finalize(self, modules: Sequence[ModuleCtx]) -> List[Finding]:
        # typed *Error classes defined inside the package
        defined: Dict[str, Tuple[ModuleCtx, int]] = {}
        raised: Set[str] = set()
        handled: Set[str] = set()
        have_tests = any(m.path.startswith("tests/") for m in modules)
        for ctx in modules:
            in_pkg = ctx.path.startswith("paddlebox_tpu/")
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.ClassDef):
                    if in_pkg and node.name.endswith("Error"):
                        defined.setdefault(node.name, (ctx, node.lineno))
                elif isinstance(node, ast.Raise) and node.exc is not None:
                    exc = node.exc
                    if isinstance(exc, ast.Call):
                        exc = exc.func
                    name = exc.attr if isinstance(exc, ast.Attribute) else (
                        exc.id if isinstance(exc, ast.Name) else None
                    )
                    if name:
                        raised.add(name)
                elif isinstance(node, ast.ExceptHandler) and node.type is not None:
                    exprs = (
                        list(node.type.elts)
                        if isinstance(node.type, ast.Tuple)
                        else [node.type]
                    )
                    for e in exprs:
                        name = e.attr if isinstance(e, ast.Attribute) else (
                            e.id if isinstance(e, ast.Name) else None
                        )
                        if name:
                            handled.add(name)
                elif isinstance(node, ast.Call) and call_name(node) == "raises":
                    # pytest.raises(X) asserts on the type by name — that
                    # IS handling it (the usual place typed errors are
                    # pinned down)
                    for e in node.args:
                        exprs = (
                            list(e.elts) if isinstance(e, ast.Tuple) else [e]
                        )
                        for x in exprs:
                            name = x.attr if isinstance(x, ast.Attribute) else (
                                x.id if isinstance(x, ast.Name) else None
                            )
                            if name:
                                handled.add(name)
        if not have_tests:
            # without the test tree in the module set, "never handled"
            # cannot be concluded — most typed errors are asserted on
            # exactly there
            return []
        findings: List[Finding] = []
        for name, (ctx, line) in sorted(defined.items()):
            if name in raised and name not in handled:
                f = self.finding(
                    ctx, line,
                    f"typed error {name} is raised but never handled by "
                    "name anywhere in the scanned set — only broad "
                    "catch-alls ever see it, so its type is dead "
                    "information (catch it somewhere or delete the class)",
                    severity="warning",
                )
                if f is not None:
                    findings.append(f)
        return findings
