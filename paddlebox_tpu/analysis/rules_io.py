"""IO004 — durable-write discipline.

PR 1's crash-window work made every checkpoint artifact go through either
the ``utils/fs`` retry/dispatch tier (``fs_open_write`` /
``fs_open_write_retry``) or the atomic tmp+``os.replace`` publish path.
A raw ``open(path, "w")`` write inside the package regresses exactly that:
no remote dispatch, no retry-until-open, and a crash mid-write leaves a
torn file under the final name.

The rule flags every builtin ``open()`` call whose literal mode writes
(``w``/``a``/``x``/``+``). The fs module itself implements the wrappers —
its own opens carry inline ``# pbox-lint: disable=IO004`` suppressions,
which doubles as the documentation that they are the allowed floor.
Non-literal modes are skipped (unknowable statically); third-party writers
(``np.savez`` given a *path*) are out of scope — hand them a file object
from ``fs.atomic_write`` instead.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, ModuleCtx, Rule


def _write_mode(node: ast.Call) -> str:
    mode = None
    if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
        mode = node.args[1].value
    for kw in node.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    if isinstance(mode, str) and any(c in mode for c in "wax+"):
        return mode
    return ""


class DurableWriteRule(Rule):
    id = "IO004"
    doc = "raw open() writes must go through utils/fs wrappers"

    def check_module(self, ctx: ModuleCtx) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if not (isinstance(node.func, ast.Name) and node.func.id == "open"):
                continue
            mode = _write_mode(node)
            if not mode:
                continue
            f = self.finding(
                ctx, node,
                f'raw open(..., "{mode}") write — route through utils/fs '
                "(fs_open_write[_retry] for streams, atomic_write for "
                "publish-on-success artifacts)",
            )
            if f is not None:
                findings.append(f)
        return findings
