"""IO004 — durable-write discipline.

PR 1's crash-window work made every checkpoint artifact go through either
the ``utils/fs`` retry/dispatch tier (``fs_open_write`` /
``fs_open_write_retry``) or the atomic tmp+``os.replace`` publish path.
A raw ``open(path, "w")`` write inside the package regresses exactly that:
no remote dispatch, no retry-until-open, and a crash mid-write leaves a
torn file under the final name.

The rule flags every builtin ``open()`` call whose literal mode writes
(``w``/``a``/``x``/``+``). The fs module itself implements the wrappers —
its own opens carry inline ``# pbox-lint: disable=IO004`` suppressions,
which doubles as the documentation that they are the allowed floor.
Non-literal modes are skipped (unknowable statically); third-party writers
(``np.savez`` given a *path*) are out of scope — hand them a file object
from ``fs.atomic_write`` instead.

One exemption: writes inside a function that takes a pytest tmp-dir
fixture (``tmp_path``/``tmpdir``/their ``_factory`` forms) are ephemeral
by construction — the directory dies with the test, so there is no crash
window to protect. Fixture-writer helpers that take a plain ``path``
argument do NOT qualify (the rule cannot see the caller); suppress those
inline with a justification instead.
"""

from __future__ import annotations

import ast
from typing import List, Set, Tuple

from .core import Finding, ModuleCtx, Rule

_TMP_FIXTURES = {"tmp_path", "tmpdir", "tmp_path_factory", "tmpdir_factory"}


def _tmp_fixture_spans(tree: ast.Module) -> List[Tuple[int, int]]:
    """(lineno, end_lineno) of every function taking a pytest tmp fixture."""
    spans: List[Tuple[int, int]] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args: Set[str] = {
                a.arg for a in node.args.args + node.args.kwonlyargs
            }
            if args & _TMP_FIXTURES:
                spans.append(
                    (node.lineno, getattr(node, "end_lineno", node.lineno))
                )
    return spans


def _write_mode(node: ast.Call) -> str:
    mode = None
    if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
        mode = node.args[1].value
    for kw in node.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    if isinstance(mode, str) and any(c in mode for c in "wax+"):
        return mode
    return ""


class DurableWriteRule(Rule):
    id = "IO004"
    doc = "raw open() writes must go through utils/fs wrappers"

    def check_module(self, ctx: ModuleCtx) -> List[Finding]:
        findings: List[Finding] = []
        tmp_spans = _tmp_fixture_spans(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if not (isinstance(node.func, ast.Name) and node.func.id == "open"):
                continue
            mode = _write_mode(node)
            if not mode:
                continue
            if any(lo <= node.lineno <= hi for lo, hi in tmp_spans):
                continue  # pytest tmp dir: ephemeral, no crash window
            f = self.finding(
                ctx, node,
                f'raw open(..., "{mode}") write — route through utils/fs '
                "(fs_open_write[_retry] for streams, atomic_write for "
                "publish-on-success artifacts)",
            )
            if f is not None:
                findings.append(f)
        return findings
