"""pbox-lint core: rule engine, findings, inline suppressions, baseline.

A zero-dependency AST linter for project-specific invariants the Python
runtime never checks (jit trace purity, lock discipline, flag/stat
registries, durable-write rules). Architecture:

- :class:`Rule` subclasses visit one parsed module at a time
  (``check_module``) and may aggregate across the whole scanned set
  (``finalize``) for project-wide invariants (e.g. every ``get_flag`` name
  must have a ``define_flag`` somewhere in the package).
- Findings carry (rule, severity, path, line, message). Identity for
  baseline matching is (rule, path, message) — line numbers drift with
  unrelated edits, messages are stable because they name the symbol.
- ``# pbox-lint: disable=RULE[,RULE2]`` (or ``disable=all``) on the
  flagged line suppresses findings from that line; on a comment-only
  line it suppresses the line below (room for the justification).
- A checked-in baseline (tools/lint_baseline.json) grandfathers known
  findings: the gate fails only on NEW errors, so the linter can be
  enforced as a tier-1 test without a flag-day cleanup.

This package must stay importable with the standard library only — the
CLI (tools/run_lint.py) loads it by path so linting never pays the
package's jax import.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

ERROR = "error"
WARNING = "warning"

_SUPPRESS_RE = re.compile(r"#\s*pbox-lint:\s*disable=([A-Za-z0-9_,]+)")


@dataclass(frozen=True)
class Finding:
    rule: str
    severity: str
    path: str  # repo-root-relative, posix separators
    line: int
    message: str

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: stable across line-number drift."""
        return (self.rule, self.path, self.message)

    def as_dict(self) -> Dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} [{self.severity}] {self.message}"


@dataclass
class ModuleCtx:
    """One parsed module plus everything rules need to report on it."""

    path: str  # repo-root-relative
    abspath: str
    source: str
    tree: ast.Module
    lines: List[str] = field(default_factory=list)
    # line number -> set of rule ids suppressed there ("all" wildcards)
    suppressions: Dict[int, set] = field(default_factory=dict)
    # False for context-only modules: whole-program rules resolve through
    # them (call graph, registries) but findings anchored there are
    # dropped — the mechanism behind `run_lint.py --changed`
    report: bool = True

    @classmethod
    def parse(cls, abspath: str, relpath: str) -> "ModuleCtx":
        with open(abspath, "r") as f:
            source = f.read()
        tree = ast.parse(source, filename=relpath)
        lines = source.splitlines()
        sup: Dict[int, set] = {}
        for i, text in enumerate(lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                # a directive on a comment-only line governs the NEXT line
                # (the justified-suppression idiom); inline directives
                # govern their own line
                line = i + 1 if text.lstrip().startswith("#") else i
                sup.setdefault(line, set()).update(rules)
        return cls(
            path=relpath, abspath=abspath, source=source, tree=tree,
            lines=lines, suppressions=sup,
        )

    def suppressed(self, rule: str, line: int) -> bool:
        rules = self.suppressions.get(line)
        return bool(rules) and (rule in rules or "all" in rules)


class Rule:
    """Base rule: subclass, set ``id``/``severity``, implement
    ``check_module`` and/or ``finalize``."""

    id: str = "RULE000"
    severity: str = ERROR
    doc: str = ""

    def check_module(self, ctx: ModuleCtx) -> List[Finding]:
        return []

    def finalize(self, modules: Sequence[ModuleCtx]) -> List[Finding]:
        """Project-wide pass after every module was seen."""
        return []

    def finding(
        self, ctx: ModuleCtx, node_or_line, message: str,
        severity: Optional[str] = None,
    ) -> Optional[Finding]:
        line = getattr(node_or_line, "lineno", node_or_line)
        if not ctx.report:
            return None
        if ctx.suppressed(self.id, line):
            return None
        return Finding(
            rule=self.id,
            severity=severity or self.severity,
            path=ctx.path,
            line=int(line),
            message=message,
        )


# ---- helpers shared by rules ----------------------------------------------


def call_name(node: ast.Call) -> Optional[str]:
    """Trailing name of the called function: ``open`` / ``config.get_flag``
    -> ``get_flag`` / ``jax.jit`` -> ``jit``."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def dotted_name(node: ast.AST) -> Optional[str]:
    """Full dotted path of a Name/Attribute chain (``jax.lax.psum``)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def literal_str_arg(node: ast.Call, index: int = 0) -> Optional[str]:
    if len(node.args) > index and isinstance(node.args[index], ast.Constant):
        v = node.args[index].value
        if isinstance(v, str):
            return v
    return None


def iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            for fn in sorted(files):
                if fn.endswith(".py"):
                    out.append(os.path.join(root, fn))
    return out


# ---- engine ----------------------------------------------------------------


@dataclass
class LintResult:
    findings: List[Finding]
    parse_errors: List[Finding]

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == WARNING]


def lint_paths(
    paths: Sequence[str],
    rules: Sequence[Rule],
    root: Optional[str] = None,
    context_paths: Sequence[str] = (),
    profiles: Optional[Dict[str, Sequence[str]]] = None,
) -> LintResult:
    """Lint every .py under ``paths`` with ``rules``. ``root`` anchors the
    relative paths used in findings (defaults to CWD).

    ``context_paths`` are parsed and fed to every rule so whole-program
    passes (call graph, registries, fault-site coverage) resolve over the
    full set, but findings anchored in them are dropped — the machinery
    behind ``--changed`` incremental runs.

    ``profiles`` maps a path prefix to rule ids DISABLED under it (e.g.
    ``{"tests/": ("JIT001", "THR006")}``); see DEFAULT_PROFILES.
    """
    root = os.path.abspath(root or os.getcwd())
    modules: List[ModuleCtx] = []
    parse_errors: List[Finding] = []
    seen_report: set = set()
    for report, group in ((True, paths), (False, context_paths)):
        for abspath in iter_py_files(group):
            abspath = os.path.abspath(abspath)
            rel = os.path.relpath(abspath, root).replace(os.sep, "/")
            if report:
                seen_report.add(rel)
            elif rel in seen_report:
                continue  # report wins when a file is in both sets
            try:
                ctx = ModuleCtx.parse(abspath, rel)
                ctx.report = report
                modules.append(ctx)
            except SyntaxError as e:
                if not report:
                    continue  # context modules fail soft
                parse_errors.append(
                    Finding(
                        rule="PARSE",
                        severity=ERROR,
                        path=rel,
                        line=int(e.lineno or 0),
                        message=f"syntax error: {e.msg}",
                    )
                )
    findings: List[Finding] = []
    for rule in rules:
        for ctx in modules:
            findings.extend(f for f in rule.check_module(ctx) if f is not None)
        findings.extend(f for f in rule.finalize(modules) if f is not None)
    if profiles:
        findings = apply_profiles(findings, profiles)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return LintResult(findings=findings, parse_errors=parse_errors)


# Per-root rule profiles for the default three-root scan: tests spawn
# threads with intentional shared state (harness fixtures), call jit only
# through the package, and exercise the flag/fault-site registry machinery
# with synthetic names (REG003's contract is about package code firing
# real sites), so those rules would drown signal there; likewise test
# fixtures build deliberately half-torn protocol and resource scenarios
# (unanswered collectives, threads the test itself owns), so the
# distributed-discipline and lifecycle rules (DST009/RES010) gate package
# and tools code only; everything IO/stat/exception-shaped stays on
# everywhere.
DEFAULT_PROFILES: Dict[str, Sequence[str]] = {
    "tests/": ("JIT001", "THR006", "REG003", "DST009", "RES010"),
}


def apply_profiles(
    findings: Sequence[Finding], profiles: Dict[str, Sequence[str]]
) -> List[Finding]:
    """Drop findings whose rule is disabled for their path's root."""
    out: List[Finding] = []
    for f in findings:
        disabled = False
        for prefix, rules in profiles.items():
            if f.path.startswith(prefix) and f.rule in rules:
                disabled = True
                break
        if not disabled:
            out.append(f)
    return out


# ---- baseline ---------------------------------------------------------------

BASELINE_VERSION = 1


def load_baseline(path: str) -> Dict[Tuple[str, str, str], int]:
    """Baseline file -> {(rule, path, message): grandfathered count}."""
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    out: Dict[Tuple[str, str, str], int] = {}
    for e in data.get("findings", []):
        key = (e["rule"], e["path"], e["message"])
        out[key] = out.get(key, 0) + int(e.get("count", 1))
    return out


def save_baseline(path: str, findings: Sequence[Finding]) -> None:
    """Write the baseline for ``findings`` (errors only — warnings never
    gate, so grandfathering them would only hide them)."""
    counts: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        if f.severity == ERROR:
            counts[f.key()] = counts.get(f.key(), 0) + 1
    entries = [
        {"rule": k[0], "path": k[1], "message": k[2], "count": n}
        for k, n in sorted(counts.items())
    ]
    # lint tooling output, not a durable training artifact: a torn baseline
    # just re-runs --update-baseline  # pbox-lint: disable=IO004
    with open(path, "w") as f:  # pbox-lint: disable=IO004
        json.dump({"version": BASELINE_VERSION, "findings": entries}, f, indent=2)
        f.write("\n")


def apply_baseline(
    findings: Sequence[Finding], baseline: Dict[Tuple[str, str, str], int]
) -> Tuple[List[Finding], List[Finding], List[Tuple[str, str, str]]]:
    """Split ``findings`` into (new, grandfathered) and list stale baseline
    keys (grandfathered findings that no longer fire — candidates for
    shrinking the baseline). Only errors consume baseline budget."""
    budget = dict(baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        if f.severity == ERROR and budget.get(f.key(), 0) > 0:
            budget[f.key()] -= 1
            old.append(f)
        else:
            new.append(f)
    stale = [k for k, n in sorted(budget.items()) if n > 0]
    return new, old, stale
