"""RES010 — resource lifecycle: threads joined, sockets shut down, handles closed.

Encodes the teardown invariants the elastic planes learned the hard way
(docs/STATIC_ANALYSIS.md "Resource lifecycle"):

- **threads**: every ``threading.Thread(...)`` must either be
  ``daemon=True`` or reach a ``.join()`` on the name/attribute it is
  bound to.  A non-daemon thread nobody joins turns interpreter exit
  into an unbounded wait and hides the errors the target raised; a
  fire-and-forget ``Thread(...).start()`` is flagged outright.
- **sockets**: a *listening or accepted* socket must see ``shutdown()``
  before ``close()`` — the PR 16 rejoin invariant: a bare ``close()`` on
  a dead incarnation's server/reader socket neither sends FIN nor wakes
  a blocked reader, so the successor's frames are silently eaten.
  Connect-side and bind-only (port-pick) sockets have no blocked peer
  and are out of scope.
- **executors**: a ``ThreadPoolExecutor``/``ProcessPoolExecutor`` must be
  used as a context manager or reach ``.shutdown()`` on its binding.
- **files**: an ``open()`` result bound to a name outside a ``with``
  must reach ``.close()`` on that binding (IO004 owns the durability of
  *write* paths; this arm owns the descriptor itself).

The analysis is module-scoped and name-based: a resource bound to
``x``/``self.x`` is satisfied by ``x.join()``, ``self.x.join()``, a
loop ``for t in xs: t.join()`` over its list, or an alias
(``t = self.x`` / ``t = getattr(self, "x", None)``).  A resource handed
to another function or returned is not tracked (under-reporting, never
false alarms); deliberate fire-and-forget threads carry a justified
``# pbox-lint: disable=RES010`` instead.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, ModuleCtx, Rule

_EXECUTORS = {"ThreadPoolExecutor", "ProcessPoolExecutor"}
_SOCKET_MAKERS = {"socket", "create_server"}


def _call_tail(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _receiver_key(expr: ast.AST) -> Optional[Tuple[str, str]]:
    """("name", x) for ``x.meth()``, ("attr", x) for ``<any>.x.meth()``."""
    if isinstance(expr, ast.Name):
        return ("name", expr.id)
    if isinstance(expr, ast.Attribute):
        return ("attr", expr.attr)
    return None


class _ModuleScan:
    """One pass over a module collecting method receivers, aliases and
    with-statement context expressions."""

    def __init__(self, tree: ast.Module):
        self.parent: Dict[int, ast.AST] = {}
        self.with_ctx: Set[int] = set()
        # method name -> receiver keys it was called on
        self.called_on: Dict[str, Set[Tuple[str, str]]] = {}
        # local name -> attr tails it aliases (v = self.x / getattr(o, "x"));
        # a multi-map: the same local name in different functions may alias
        # different attributes
        self.alias_attr: Dict[str, Set[str]] = {}
        # loop var -> iterated name/attr key
        self.loop_src: Dict[str, Tuple[str, str]] = {}
        # names receiving call args of close-like helpers (_close_sock(s))
        self.closed_via_helper: Set[str] = set()

        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parent[id(child)] = node
            if isinstance(node, ast.With):
                for item in node.items:
                    self.with_ctx.add(id(item.context_expr))
            elif isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute):
                    key = _receiver_key(node.func.value)
                    if key is not None:
                        self.called_on.setdefault(
                            node.func.attr, set()).add(key)
                tail = _call_tail(node)
                if tail and "close" in tail.lower():
                    for a in node.args:
                        if isinstance(a, ast.Name):
                            self.closed_via_helper.add(a.id)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    v = node.value
                    if isinstance(v, ast.Attribute):
                        self.alias_attr.setdefault(t.id, set()).add(v.attr)
                    elif (
                        isinstance(v, ast.Call)
                        and isinstance(v.func, ast.Name)
                        and v.func.id == "getattr"
                        and len(v.args) >= 2
                        and isinstance(v.args[1], ast.Constant)
                        and isinstance(v.args[1].value, str)
                    ):
                        self.alias_attr.setdefault(t.id, set()).add(
                            v.args[1].value)
            elif isinstance(node, ast.For):
                if isinstance(node.target, ast.Name):
                    key = _receiver_key(node.iter)
                    if key is not None:
                        self.loop_src[node.target.id] = key

    def receivers_of(self, method: str) -> Set[Tuple[str, str]]:
        """Receiver keys ``method`` is called on, expanded through aliases
        and loop variables: ``t.join()`` where ``t = getattr(o, "x")``
        also satisfies ("attr", "x"); ``for t in xs: t.join()`` satisfies
        ("name", "xs") / ("attr", "xs")."""
        base = set(self.called_on.get(method, ()))
        out = set(base)
        for kind, name in base:
            if kind != "name":
                continue
            for attr in self.alias_attr.get(name, ()):
                out.add(("attr", attr))
            if name in self.loop_src:
                src = self.loop_src[name]
                out.add(src)
                out.add(("attr", src[1]))
        return out

    def binding_of(self, call: ast.Call) -> Optional[Tuple[str, str]]:
        """Climb parents to the binding of a creation call: through
        IfExp/comprehensions/list displays to an Assign target, or an
        ``xs.append(...)`` receiver.  None when untrackable."""
        node: ast.AST = call
        for _ in range(8):
            p = self.parent.get(id(node))
            if p is None:
                return None
            if isinstance(p, ast.Assign):
                for t in p.targets:
                    key = _receiver_key(t)
                    if key is not None:
                        return key
                    if isinstance(t, ast.Tuple):
                        for e in t.elts:
                            if isinstance(e, ast.Name) and not e.id.startswith("_"):
                                return ("name", e.id)
                return None
            if isinstance(p, ast.Call) and isinstance(p.func, ast.Attribute) \
                    and p.func.attr == "append":
                return _receiver_key(p.func.value)
            if isinstance(
                p,
                (ast.IfExp, ast.ListComp, ast.GeneratorExp, ast.SetComp,
                 ast.List, ast.Tuple, ast.comprehension, ast.Starred),
            ):
                node = p
                continue
            return None
        return None

    def started_inline(self, call: ast.Call) -> bool:
        """True for ``Thread(...).start()`` — created and fired unbound."""
        p = self.parent.get(id(call))
        return isinstance(p, ast.Attribute) and p.attr == "start"


def _kw_true(call: ast.Call, name: str) -> bool:
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


class ResourceLifecycleRule(Rule):
    id = "RES010"
    doc = "threads joined or daemon; listening sockets shutdown-before-close; executors/files closed"

    def check_module(self, ctx: ModuleCtx) -> List[Finding]:
        scan = _ModuleScan(ctx.tree)
        findings: List[Finding] = []
        joined = scan.receivers_of("join")
        shut = scan.receivers_of("shutdown")
        closed = scan.receivers_of("close")
        listened = scan.receivers_of("listen")

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = _call_tail(node)

            if tail == "Thread":
                if _kw_true(node, "daemon"):
                    continue
                if scan.started_inline(node):
                    f = self.finding(
                        ctx, node,
                        "non-daemon Thread(...).start() is never joinable — "
                        "bind it and join, set daemon=True, or justify with "
                        "a RES010 suppression",
                    )
                    if f is not None:
                        findings.append(f)
                    continue
                key = scan.binding_of(node)
                if key is not None and key not in joined:
                    f = self.finding(
                        ctx, node,
                        f'non-daemon thread bound to "{key[1]}" is never '
                        "joined in this module — interpreter exit blocks on "
                        "it and its errors are lost",
                    )
                    if f is not None:
                        findings.append(f)

            elif tail in _EXECUTORS:
                if id(node) in scan.with_ctx:
                    continue
                key = scan.binding_of(node)
                if key is None:
                    f = self.finding(
                        ctx, node,
                        f"{tail} is neither a context manager nor bound for "
                        "shutdown() — worker threads outlive the work",
                    )
                    if f is not None:
                        findings.append(f)
                elif key not in shut:
                    f = self.finding(
                        ctx, node,
                        f'executor bound to "{key[1]}" never reaches '
                        "shutdown() in this module — worker threads leak "
                        "past the work that spawned them",
                    )
                    if f is not None:
                        findings.append(f)

            elif tail == "accept" or (
                tail in _SOCKET_MAKERS and isinstance(node.func, ast.Attribute)
            ):
                key = scan.binding_of(node)
                if key is None:
                    continue
                peered = tail != "socket" or key in listened
                is_closed = (
                    key in closed
                    or (key[0] == "name" and key[1] in scan.closed_via_helper)
                )
                if peered and is_closed and key not in shut:
                    what = (
                        "accepted socket" if tail == "accept"
                        else "listening socket"
                    )
                    f = self.finding(
                        ctx, node,
                        f'{what} bound to "{key[1]}" is closed without '
                        "shutdown() — a bare close neither sends FIN nor "
                        "wakes a blocked reader, so a peer of a dead "
                        "incarnation silently eats the successor's frames "
                        "(the transport.py teardown invariant)",
                    )
                    if f is not None:
                        findings.append(f)

            elif tail == "open" and isinstance(node.func, ast.Name):
                if id(node) in scan.with_ctx:
                    continue
                key = scan.binding_of(node)
                if key is None:
                    continue  # anonymous/one-expression opens: refcount-scoped
                if key not in closed and not (
                    key[0] == "name" and key[1] in scan.closed_via_helper
                ):
                    f = self.finding(
                        ctx, node,
                        f'file handle bound to "{key[1]}" never reaches '
                        "close() in this module — the descriptor leaks on "
                        "normal exit paths",
                    )
                    if f is not None:
                        findings.append(f)
        return findings
