"""THR002 — lock discipline over `# guarded-by:` annotated state.

A lightweight static race detector. Shared mutable state is annotated at
its initialization site with a trailing comment naming the lock that
guards it:

    self._params = [...]        # guarded-by: _lock         (instance attr)
    _stats: Dict[str, int] = {} # guarded-by: _lock         (module global)

The rule then checks every OTHER access in the module:

- an annotated instance attribute (``self.X`` in methods of the owning
  class, including closures defined inside them) must be read/written
  inside a ``with self.<lock>:`` block;
- an annotated module global must be accessed inside ``with <lock>:``.

Severity is graded by a thread-reachability approximation: functions
reachable (intra-module call graph) from a ``threading.Thread(target=...)``
or ``executor.submit(fn)`` entry point get ERROR (two sides of a real
race: the entry runs concurrently with everything), everything else gets
WARNING (the annotation's contract is still violated, but no in-module
thread proves concurrency). Initialization sites are exempt:
``__init__``/``__post_init__`` for instance attrs, module top-level for
globals.

Locks must be held via ``with``; manual acquire()/release() is not
recognized (and is itself the failure-prone pattern the rule nudges away
from).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, ModuleCtx, Rule

_GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")

_INIT_METHODS = {"__init__", "__post_init__"}


def _guard_on_line(ctx: ModuleCtx, line: int) -> Optional[str]:
    if 1 <= line <= len(ctx.lines):
        m = _GUARD_RE.search(ctx.lines[line - 1])
        if m:
            return m.group(1)
    return None


class _FuncInfo:
    """One function/method/nested-def node plus ownership metadata."""

    def __init__(self, node, cls: Optional[str], qualname: str):
        self.node = node
        self.cls = cls  # owning class name (None for module functions)
        self.qualname = qualname
        self.calls: Set[Tuple[Optional[str], str]] = set()  # (cls-or-None, name)


def _collect_functions(tree: ast.Module) -> List[_FuncInfo]:
    """Every def in the module with its owning class (methods keep their
    class; defs nested in methods inherit it — they close over self)."""
    out: List[_FuncInfo] = []

    def walk(node, cls: Optional[str], prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, child.name, f"{prefix}{child.name}.")
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(_FuncInfo(child, cls, f"{prefix}{child.name}"))
                walk(child, cls, f"{prefix}{child.name}.")
            else:
                walk(child, cls, prefix)

    walk(tree, None, "")
    return out


def _direct_children_defs(fn_node) -> Set[int]:
    """ids of def nodes nested anywhere inside ``fn_node`` (excl. itself)."""
    out: Set[int] = set()
    for n in ast.walk(fn_node):
        if n is not fn_node and isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            out.add(id(n))
    return out


class LockDisciplineRule(Rule):
    id = "THR002"
    doc = "guarded-by lock discipline (static race detector)"

    def check_module(self, ctx: ModuleCtx) -> List[Finding]:
        funcs = _collect_functions(ctx.tree)
        node_to_info = {id(f.node): f for f in funcs}

        # ---- 1. collect annotations -----------------------------------
        # (cls, attr) -> lock attr name; and module global -> lock name
        attr_guards: Dict[Tuple[str, str], str] = {}
        global_guards: Dict[str, str] = {}
        for f in funcs:
            if f.cls is None:
                continue
            for stmt in ast.walk(f.node):
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                lock = _guard_on_line(ctx, stmt.lineno)
                if lock is None:
                    continue
                targets = (
                    stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                )
                for t in targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        attr_guards[(f.cls, t.attr)] = lock
        for stmt in ctx.tree.body:  # module top level only
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                lock = _guard_on_line(ctx, stmt.lineno)
                if lock is None:
                    continue
                targets = (
                    stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                )
                for t in targets:
                    if isinstance(t, ast.Name):
                        global_guards[t.id] = lock
        # class-level annotated attrs (rare): ClassDef body assigns
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                for stmt in node.body:
                    if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                        lock = _guard_on_line(ctx, stmt.lineno)
                        if lock is None:
                            continue
                        targets = (
                            stmt.targets
                            if isinstance(stmt, ast.Assign)
                            else [stmt.target]
                        )
                        for t in targets:
                            if isinstance(t, ast.Name):
                                attr_guards[(node.name, t.id)] = lock
        if not attr_guards and not global_guards:
            return []

        # ---- 2. thread entries + call graph ---------------------------
        entries: Set[int] = set()

        def resolve(cls: Optional[str], name: str) -> List[_FuncInfo]:
            hits = [f for f in funcs if f.node.name == name and f.cls == cls]
            return hits or [f for f in funcs if f.node.name == name]

        for f in funcs:
            for n in ast.walk(f.node):
                if not isinstance(n, ast.Call):
                    continue
                fname = (
                    n.func.attr
                    if isinstance(n.func, ast.Attribute)
                    else (n.func.id if isinstance(n.func, ast.Name) else None)
                )
                cands: List[ast.AST] = []
                if fname == "Thread":
                    for kw in n.keywords:
                        if kw.arg == "target":
                            cands.append(kw.value)
                elif fname == "submit" and n.args:
                    cands.append(n.args[0])
                for c in cands:
                    if isinstance(c, ast.Name):
                        for hit in resolve(f.cls, c.id):
                            entries.add(id(hit.node))
                    elif (
                        isinstance(c, ast.Attribute)
                        and isinstance(c.value, ast.Name)
                        and c.value.id == "self"
                    ):
                        for hit in resolve(f.cls, c.attr):
                            entries.add(id(hit.node))

        nested_of = {id(f.node): _direct_children_defs(f.node) for f in funcs}
        for f in funcs:
            for n in ast.walk(f.node):
                if not isinstance(n, ast.Call):
                    continue
                if isinstance(n.func, ast.Name):
                    f.calls.add((f.cls, n.func.id))
                elif isinstance(n.func, ast.Attribute) and isinstance(
                    n.func.value, ast.Name
                ):
                    if n.func.value.id == "self":
                        f.calls.add((f.cls, n.func.attr))

        reachable: Set[int] = set()
        frontier = list(entries)
        while frontier:
            nid = frontier.pop()
            if nid in reachable:
                continue
            reachable.add(nid)
            info = node_to_info.get(nid)
            if info is None:
                continue
            # a nested def runs on the same thread as its host when called
            for child in nested_of.get(nid, ()):
                if child not in reachable:
                    frontier.append(child)
            for cls, name in info.calls:
                for hit in resolve(cls, name):
                    if id(hit.node) not in reachable:
                        frontier.append(id(hit.node))

        # ---- 3. scan accesses -----------------------------------------
        findings: List[Finding] = []
        rule = self

        class Scanner(ast.NodeVisitor):
            def __init__(self, info: _FuncInfo):
                self.info = info
                self.held: List[str] = []

            def _check_attr(self, node: ast.Attribute) -> None:
                if not (
                    isinstance(node.value, ast.Name) and node.value.id == "self"
                ):
                    return
                cls = self.info.cls
                if cls is None:
                    return
                lock = attr_guards.get((cls, node.attr))
                if lock is None:
                    return
                if self.info.node.name in _INIT_METHODS:
                    return
                want = f"self.{lock}"
                if want in self.held:
                    return
                sev = "error" if id(self.info.node) in reachable else "warning"
                f = rule.finding(
                    ctx,
                    node,
                    f"self.{node.attr} is guarded-by {lock} but accessed "
                    f"outside `with {want}:` in {self.info.qualname}"
                    + (
                        " (reachable from a thread entry point)"
                        if sev == "error"
                        else ""
                    ),
                    severity=sev,
                )
                if f is not None:
                    findings.append(f)

            def _check_global(self, node: ast.Name) -> None:
                lock = global_guards.get(node.id)
                if lock is None:
                    return
                if lock in self.held:
                    return
                sev = "error" if id(self.info.node) in reachable else "warning"
                f = rule.finding(
                    ctx,
                    node,
                    f"module global {node.id} is guarded-by {lock} but "
                    f"accessed outside `with {lock}:` in {self.info.qualname}"
                    + (
                        " (reachable from a thread entry point)"
                        if sev == "error"
                        else ""
                    ),
                    severity=sev,
                )
                if f is not None:
                    findings.append(f)

            def visit_With(self, node: ast.With) -> None:
                names = []
                for item in node.items:
                    try:
                        names.append(ast.unparse(item.context_expr))
                    # unparse failure just drops one lock name from the
                    # held-set  # pbox-lint: disable=EXC007
                    except Exception:  # pragma: no cover
                        pass
                self.held.extend(names)
                self.generic_visit(node)
                del self.held[len(self.held) - len(names):]

            def visit_Attribute(self, node: ast.Attribute) -> None:
                self._check_attr(node)
                self.generic_visit(node)

            def visit_Name(self, node: ast.Name) -> None:
                self._check_global(node)

            def visit_FunctionDef(self, node) -> None:
                pass  # nested defs scanned as their own _FuncInfo

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Lambda(self, node) -> None:
                pass  # deferred execution: lock context unknowable

        for f in funcs:
            sc = Scanner(f)
            for stmt in f.node.body:
                sc.visit(stmt)
        return findings
