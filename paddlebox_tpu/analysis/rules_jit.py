"""JIT001 — trace purity inside jitted/shard_mapped functions.

A function whose body runs under `jax.jit` / `pjit` / `shard_map` executes
at TRACE time: host syncs (`.item()`, `float()`/`int()` on traced values,
`np.asarray` of a tracer, `jax.device_get`), wall-clock reads, and Python
`if` branching on traced values either crash (ConcretizationTypeError) or —
worse — silently bake one trace-time value into the compiled program and
desync the sparse hot path (the Parallax/SparCML failure class: one stray
host sync serializes the whole async pipeline).

Detection, entirely static:

- *Jitted* functions are (a) defs decorated with `jit`/`pjit`/`shard_map`
  (dotted or wrapped in `functools.partial(jax.jit, ...)`), and (b) defs or
  lambdas referenced by name as the first argument of a `jit`/`pjit`/
  `shard_map` call in the same module. A def returned by a maker and jitted
  in ANOTHER module is not resolved (documented approximation).
- *Traced names* are the jitted function's parameters minus
  `static_argnames`/`static_argnums`, propagated through simple assignments
  (`y = f(x)` taints `y` if `x` is tainted).
- Flagged: `.item()` anywhere; `jax.device_get`; `time.time()`/
  `time.perf_counter()`/`time.monotonic()`; `float()`/`int()`/`bool()`/
  `np.asarray`/`np.array` over an expression mentioning a traced name; a
  Python `if` whose test mentions a traced name. Shape/structure reads
  (`.shape`, `.ndim`, `.dtype`, `len()`, `isinstance()`, `x is None`,
  `"k" in feed`) are trace-static and exempt.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import Finding, ModuleCtx, Rule, call_name, dotted_name

_JIT_NAMES = {"jit", "pjit", "shard_map"}
_CLOCK_CALLS = {"time.time", "time.perf_counter", "time.monotonic"}
_NP_ROOTS = {"np", "numpy", "onp"}
_CAST_FUNCS = {"float", "int", "bool"}
# attribute reads that are static at trace time even on a tracer
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval"}
_STATIC_FUNCS = {"isinstance", "len", "getattr", "hasattr", "type", "id"}


def _is_jit_ref(node: ast.AST) -> bool:
    """True if ``node`` names jit/pjit/shard_map (possibly dotted)."""
    name = dotted_name(node)
    if name is None:
        return False
    return name.split(".")[-1] in _JIT_NAMES


def _unwrap_partial(call: ast.Call) -> Optional[ast.AST]:
    """functools.partial(jax.jit, ...) -> jax.jit."""
    name = dotted_name(call.func)
    if name and name.split(".")[-1] == "partial" and call.args:
        return call.args[0]
    return None


def _static_params(call_or_dec: Optional[ast.Call], fn: ast.AST) -> Set[str]:
    """Parameter names excluded from tracing via static_argnames/nums."""
    out: Set[str] = set()
    if call_or_dec is None:
        return out
    args = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for kw in call_or_dec.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    out.add(n.value)
        elif kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    if 0 <= n.value < len(args):
                        out.add(args[n.value])
    return out


def _mentions_tainted(node: ast.AST, tainted: Set[str]) -> bool:
    """Does ``node`` read a traced name in a trace-DYNAMIC position?

    Skips subtrees whose value is static at trace time: `.shape`-like
    attribute reads, `len()`/`isinstance()` calls, `x is None` / `k in d`
    comparisons.
    """

    def walk(n: ast.AST) -> bool:
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            return False
        if isinstance(n, ast.Call):
            cn = call_name(n)
            if cn in _STATIC_FUNCS:
                return False
        if isinstance(n, ast.Compare):
            ops_static = all(
                isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                for op in n.ops
            )
            if ops_static:
                return False
        if isinstance(n, ast.Name) and n.id in tainted:
            return True
        return any(walk(c) for c in ast.iter_child_nodes(n))

    return walk(node)


class _BodyScanner(ast.NodeVisitor):
    """Walks one jitted function body collecting purity violations."""

    def __init__(self, rule: "JitPurityRule", ctx: ModuleCtx, tainted: Set[str]):
        self.rule = rule
        self.ctx = ctx
        self.tainted = set(tainted)
        self.findings: List[Finding] = []

    def _emit(self, node: ast.AST, msg: str) -> None:
        f = self.rule.finding(self.ctx, node, msg)
        if f is not None:
            self.findings.append(f)

    # taint propagation through simple assignments
    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        if _mentions_tainted(node.value, self.tainted):
            for t in node.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        self.tainted.add(n.id)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.generic_visit(node)
        if isinstance(node.target, ast.Name) and _mentions_tainted(
            node.value, self.tainted
        ):
            self.tainted.add(node.target.id)

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        full = dotted_name(node.func)
        name = call_name(node)
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item":
            self._emit(
                node,
                "host sync: .item() inside a jitted function forces a "
                "device round-trip at trace time",
            )
            return
        if full is not None:
            if full.endswith("device_get") and (
                full.split(".")[0] in ("jax",) or full == "device_get"
            ):
                self._emit(
                    node, "host sync: jax.device_get() inside a jitted function"
                )
                return
            if full in _CLOCK_CALLS:
                self._emit(
                    node,
                    f"impure: {full}() reads the host clock at trace time — "
                    "the value is baked into the compiled program",
                )
                return
            root = full.split(".")[0]
            if (
                root in _NP_ROOTS
                and name in ("asarray", "array")
                and node.args
                and _mentions_tainted(node.args[0], self.tainted)
            ):
                self._emit(
                    node,
                    f"host sync: {full}() materializes a traced value on "
                    "host — use jnp inside jit",
                )
                return
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in _CAST_FUNCS
            and node.args
            and _mentions_tainted(node.args[0], self.tainted)
        ):
            self._emit(
                node,
                f"host sync: {node.func.id}() on a traced value forces "
                "concretization inside jit",
            )

    def visit_If(self, node: ast.If) -> None:
        if _mentions_tainted(node.test, self.tainted):
            self._emit(
                node,
                "traced-value branch: Python `if` on a traced value inside "
                "jit — use jnp.where / lax.cond",
            )
        self.generic_visit(node)


class JitPurityRule(Rule):
    id = "JIT001"
    doc = "trace purity inside jax.jit/pjit/shard_map functions"

    def check_module(self, ctx: ModuleCtx) -> List[Finding]:
        # name -> def nodes (module-wide, scope-approximate)
        defs: dict = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)

        jitted: List[tuple] = []  # (fn node, jit call node or None)
        seen: Set[int] = set()

        def mark(fn: ast.AST, call: Optional[ast.Call]) -> None:
            if id(fn) not in seen:
                seen.add(id(fn))
                jitted.append((fn, call))

        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _is_jit_ref(dec):
                        mark(node, None)
                    elif isinstance(dec, ast.Call):
                        inner = _unwrap_partial(dec)
                        if _is_jit_ref(dec.func) or (
                            inner is not None and _is_jit_ref(inner)
                        ):
                            mark(node, dec)
            elif isinstance(node, ast.Call) and _is_jit_ref(node.func):
                if node.args:
                    target = node.args[0]
                    if isinstance(target, ast.Call):
                        unwrapped = _unwrap_partial(target)
                        if unwrapped is not None and isinstance(
                            unwrapped, ast.Name
                        ):
                            target = unwrapped
                    if isinstance(target, ast.Lambda):
                        mark(target, node)
                    elif isinstance(target, ast.Name):
                        for fn in defs.get(target.id, []):
                            mark(fn, node)

        findings: List[Finding] = []
        for fn, call in jitted:
            args = fn.args
            params = {
                a.arg
                for a in (
                    args.posonlyargs + args.args + args.kwonlyargs
                    + ([args.vararg] if args.vararg else [])
                    + ([args.kwarg] if args.kwarg else [])
                )
            }
            params -= {"self", "cls"}
            params -= _static_params(call, fn)
            scanner = _BodyScanner(self, ctx, params)
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                scanner.visit(stmt)
            findings.extend(scanner.findings)
        return findings
