"""DST009 — distributed discipline for the tagged-frame control plane.

Three statically checkable ways a PBTX protocol change deadlocks or
splits the fleet, all of which the elastic soaks only catch *after* a
hang (docs/STATIC_ANALYSIS.md "Protocol verification"):

- **rank-conditional collective**: a collective round (``allgather``/
  ``alltoall``/``allreduce_max``/``barrier``/``exchange_verdict``/
  ``agree_membership``) reached under an ``if`` whose test mentions a
  rank identity, with no matching collective on the other arm.  Ranks
  taking the other arm never enter the round: the entering ranks block
  until the transport timeout.  Collectives must run unconditionally or
  symmetrically on every arm (the package's own idiom — see the
  ``carry-gate`` comment in data/dataset.py: "must still answer, or the
  hosts that can would hang").
- **black-holed frame**: a point-to-point ``send`` whose tag pattern no
  ``recv``/``pending_sources`` site in the whole scanned set can match.
  The frame sits in the receiver's pending map forever (or trips the
  stale-epoch floor); the payload is silently lost.
- **verdict discipline**: a verdict round whose tag lacks the ``@e``
  epoch component would be answerable by frames from a previous
  incarnation (split-brain risk); a *commit-point* verdict
  (``exchange_verdict(..., fatal=True)`` — the all-or-die map flips)
  whose key lacks a ``fingerprint()`` component would let ranks whose
  bases diverged commit the same epoch number over different maps — the
  exact hole the PR 16 fingerprint-tagged verdicts closed.

Resolution rides analysis/protocol.py: runtime tag components are ``*``
wildcards and matching is prefix-conservative, so every check here
under-reports rather than inventing deadlocks.  A tag the extractor
cannot read at all (opaque) satisfies any send and is never reported
itself.  Rank-conditional detection matches an exact ``rank`` name or
attribute in the branch test (``tp.rank == 0``, ``if rank:``) —
``n_ranks`` comparisons and early-``return`` guard styles are out of
scope and stay on the model checker (tools/proto_check.py).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

from .core import Finding, ModuleCtx, Rule
from .protocol import (
    _COLLECTIVE_OPS,
    _TAG_OPS,
    ProtoSite,
    get_protocol,
    patterns_may_match,
)

_COLLECTIVE_CALL_NAMES = frozenset(_COLLECTIVE_OPS)


def _is_rank_conditional(test: ast.AST) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id == "rank":
            return True
        if isinstance(node, ast.Attribute) and node.attr == "rank":
            return True
    return False


def _collectives_under(arm: Sequence[ast.stmt]) -> List[ast.Call]:
    """Collective call sites syntactically inside an If arm, excluding
    nested def bodies (a def under a branch is not a call)."""
    out: List[ast.Call] = []

    def walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(child, ast.Call):
                f = child.func
                name = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None)
                if name in _COLLECTIVE_CALL_NAMES:
                    out.append(child)
            walk(child)

    for stmt in arm:
        walk(stmt)
    return out


class DistributedDisciplineRule(Rule):
    id = "DST009"
    doc = "collectives must be rank-symmetric; sends need receivers; verdicts need epoch+fingerprint"

    def finalize(self, modules: Sequence[ModuleCtx]) -> List[Finding]:
        model = get_protocol(modules)
        by_path: Dict[str, ModuleCtx] = {m.path: m for m in modules}
        site_at: Dict[Tuple[str, int, str], ProtoSite] = {
            (s.module, s.line, s.op): s for s in model.sites
        }
        findings: List[Finding] = []

        # ---- black-holed frames -------------------------------------------
        for s in model.unmatched_sends():
            ctx = by_path.get(s.module)
            if ctx is None:
                continue
            f = self.finding(
                ctx, s.line,
                f'send tag "{s.pattern}" has no matching recv/'
                "pending_sources site anywhere in the scanned set — the "
                "frame is black-holed in the receiver's pending map",
            )
            if f is not None:
                findings.append(f)

        # ---- verdict discipline -------------------------------------------
        for s in model.collective_sites():
            ctx = by_path.get(s.module)
            if ctx is None or s.opaque:
                continue
            if "verdict" in s.pattern and not s.has_epoch:
                f = self.finding(
                    ctx, s.line,
                    f'verdict round "{s.pattern}" carries no @e epoch '
                    "component — frames from a dead incarnation could "
                    "answer it (split-brain risk)",
                )
                if f is not None:
                    findings.append(f)
            if s.op == "exchange_verdict" and s.fatal and not s.has_fingerprint:
                f = self.finding(
                    ctx, s.line,
                    f'commit-point verdict "{s.pattern}" (fatal=True) has '
                    "no map fingerprint() component in its key — diverged "
                    "bases could commit the same epoch over different maps",
                )
                if f is not None:
                    findings.append(f)

        # ---- rank-conditional collectives ---------------------------------
        for ctx in modules:
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.If):
                    continue
                if not _is_rank_conditional(node.test):
                    continue
                body_c = _collectives_under(node.body)
                else_c = _collectives_under(node.orelse)
                for here, there, arm in (
                    (body_c, else_c, "true"),
                    (else_c, body_c, "false"),
                ):
                    for call in here:
                        f_ = call.func
                        op = f_.attr if isinstance(f_, ast.Attribute) else (
                            f_.id if isinstance(f_, ast.Name) else "")
                        site = site_at.get((ctx.path, call.lineno, op))
                        pattern = site.pattern if site else None
                        if self._arm_matches(
                            ctx, there, pattern, site_at
                        ):
                            continue
                        tag = f' tag "{pattern}"' if pattern else ""
                        f = self.finding(
                            ctx, call,
                            f"collective {op}(){tag} runs only on the "
                            f"{arm} arm of a rank-conditional branch — "
                            "ranks taking the other arm never enter the "
                            "round and the callers block until timeout "
                            "(static deadlock)",
                        )
                        if f is not None:
                            findings.append(f)
        return findings

    def _arm_matches(
        self,
        ctx: ModuleCtx,
        other_arm: List[ast.Call],
        pattern: Optional[str],
        site_at: Dict[Tuple[str, int, str], ProtoSite],
    ) -> bool:
        """True when the other arm holds a collective that could pair with
        this one (same/compatible tag, or either side unresolvable)."""
        for call in other_arm:
            f = call.func
            op = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            site = site_at.get((ctx.path, call.lineno, op))
            other = site.pattern if site else None
            if pattern is None or other is None:
                return True  # conservative: unreadable tags may pair
            if patterns_may_match(pattern, other):
                return True
        return False
