"""FLT008 — chaos coverage: fault sites must be fired AND test-referenced.

``utils/faultinject.KNOWN_SITES`` is the declared catalog of recovery
seams; REG003 already rejects *firing* a site that is not declared.  This
rule closes the other direction — a DECLARED site can rot into a dead
string two ways:

- **error — dead site**: no ``fire("site")``/``_fault_fire("site")`` call
  with that literal anywhere in the package (outside faultinject.py
  itself).  The catalog advertises a seam the runtime no longer has;
  every chaos schedule arming it passes vacuously.
- **error — untested site**: no ``tests/test_*.py`` file references the
  site string at all.  The seam exists but nothing exercises it, so the
  recovery path it guards is one refactor away from silently breaking.
  (A plain substring scan of test sources is deliberate: parametrize
  lists, helper tables, and f-string schedules all count as coverage.)

Both checks anchor on the ``KNOWN_SITES`` tuple entry so the finding
names the exact line to fix.  The test-reference half only runs when the
scanned set actually contains test modules (``tools/run_lint.py`` scans
``paddlebox_tpu/ tools/ tests/`` by default); likewise the fired half
needs the package tree.  Firing through a variable
(``fire(SITE)``) is invisible to this rule — use literals at fire sites,
exactly as REG003 already demands.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from .core import Finding, ModuleCtx, Rule, call_name, literal_str_arg

_FIRE_FUNCS = {"fire", "_fault_fire"}
_FAULTINJECT = "utils/faultinject.py"


def _site_lines(ctx: ModuleCtx) -> Dict[str, int]:
    """site -> lineno of its KNOWN_SITES tuple element."""
    for stmt in ctx.tree.body:
        if isinstance(stmt, ast.Assign):
            names = [t.id for t in stmt.targets if isinstance(t, ast.Name)]
            if "KNOWN_SITES" in names and isinstance(
                stmt.value, (ast.Tuple, ast.List, ast.Set)
            ):
                return {
                    e.value: e.lineno
                    for e in stmt.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                }
    return {}


class FaultSiteCoverageRule(Rule):
    id = "FLT008"
    doc = "KNOWN_SITES entries must be fired by package code and test-referenced"

    def finalize(self, modules: Sequence[ModuleCtx]) -> List[Finding]:
        fi_ctx: Optional[ModuleCtx] = None
        for ctx in modules:
            if ctx.path.endswith(_FAULTINJECT):
                fi_ctx = ctx
                break
        if fi_ctx is None:
            return []
        sites = _site_lines(fi_ctx)
        if not sites:
            return []

        pkg_modules = [
            m
            for m in modules
            if m.path.split("/")[0] not in ("tests", "tools")
            and not m.path.endswith(_FAULTINJECT)
        ]
        test_modules = [
            m
            for m in modules
            if m.path.startswith("tests/") and m.path.split("/")[-1].startswith("test_")
        ]

        fired: Set[str] = set()
        for ctx in pkg_modules:
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Call) and call_name(node) in _FIRE_FUNCS:
                    site = literal_str_arg(node)
                    if site is not None:
                        fired.add(site)

        findings: List[Finding] = []
        for site, line in sorted(sites.items()):
            if pkg_modules and site not in fired:
                f = self.finding(
                    fi_ctx, line,
                    f'fault site "{site}" is declared in KNOWN_SITES but '
                    "never fired by package code — dead seam, every chaos "
                    "schedule arming it passes vacuously",
                )
                if f is not None:
                    findings.append(f)
            if test_modules and not any(
                site in m.source for m in test_modules
            ):
                f = self.finding(
                    fi_ctx, line,
                    f'fault site "{site}" is not referenced by any '
                    "tests/test_* file — the recovery path it guards has "
                    "no chaos coverage",
                )
                if f is not None:
                    findings.append(f)
        return findings
