"""Whole-program call graph + thread-entrypoint graph for pbox-lint.

PR 2's THR002 grades findings by an *intra-module* thread-reachability
approximation; the threaded planes added since (transport sender/reader/
heartbeat, serving follower/batcher, async dense, boundary prefetch) call
across module boundaries, so the flow-sensitive rules (THR006, and any
future one that needs "who runs this?") build on this pass instead.

The pass resolves, over the FULL scanned module set:

- every function/method/nested def as a :class:`FuncNode` with its owning
  class and module;
- an interprocedural call graph.  Resolution is deliberately conservative
  and name-based (no type inference):

    * ``f()``        -> def ``f`` in the same module, else any module-level
                        def ``f`` in the scanned set;
    * ``self.m()``   -> method ``m`` of the caller's class (class name
                        matched across modules, so mixins resolve);
    * ``obj.m()``    -> method ``m`` ONLY when exactly one class in the
                        scanned set defines it (unique-name resolution;
                        ambiguous names like ``get``/``close`` would
                        overlink the graph into uselessness);

- *thread entry points*: each ``threading.Thread(target=X)`` and
  ``executor.submit(X, ...)`` creation site mints a distinct thread label
  ``"path:lineno(target)"``.  A target spun in a loop (pollers, heartbeat)
  is still one label — the label means "an instance of this thread kind",
  and two *kinds* touching the same state is already a race;
- a ``runs_on`` set per function: the thread labels whose entry reaches it
  through the call graph, plus the synthetic label ``MAIN`` when the
  function is also reachable from non-thread code (module top level, a
  def nobody in the scanned set calls — i.e. API surface driven by the
  user's thread — or any function only reachable from those);
- ``locks_held_in``: the set of lock names guaranteed held on EVERY path
  from an entry to the function (meet-over-paths with set intersection),
  seeded from ``with <lock>:`` blocks around call sites.  Only context
  managers whose expression looks lock-like (``lock``/``mutex``/``cond``/
  ``sem``, case-insensitive) count — ``with inject(...)`` or file handles
  never satisfy a lock requirement.

Everything here is a static approximation; the docstrings of the rules
that consume it state which side (over- or under-) each choice errs on.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .core import ModuleCtx

MAIN = "<main>"

_LOCKISH_RE = re.compile(r"lock|mutex|cond|sem", re.IGNORECASE)


def _is_lockish(expr_text: str) -> bool:
    return bool(_LOCKISH_RE.search(expr_text))


@dataclass
class FuncNode:
    """One def (function, method, or nested def) in the scanned set."""

    module: str  # ModuleCtx.path
    cls: Optional[str]  # owning class name (nested defs inherit it)
    name: str
    qualname: str  # "module.py::Class.method" / "module.py::fn.inner"
    node: ast.AST = field(repr=False)
    host: Optional[int] = None  # id() of the enclosing def, for nested defs
    # resolved out-edges: (callee id, locks held at the call site)
    out: List[Tuple[int, FrozenSet[str]]] = field(default_factory=list)
    runs_on: Set[str] = field(default_factory=set)
    locks_held_in: FrozenSet[str] = frozenset()

    @property
    def key(self) -> int:
        return id(self.node)


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    # unparse failure only degrades lock-name resolution, never a training
    # path  # pbox-lint: disable=EXC007
    except Exception:  # pragma: no cover - malformed synthetic nodes only
        return ""


class _FuncCollector:
    """Collects every def with ownership, mirroring rules_locks' walk but
    keeping nested-def host links (a nested def runs on its host's
    thread when called locally)."""

    def __init__(self, ctx: ModuleCtx):
        self.ctx = ctx
        self.funcs: List[FuncNode] = []

    def collect(self) -> List[FuncNode]:
        self._walk(self.ctx.tree, None, "", None)
        return self.funcs

    def _walk(self, node, cls, prefix, host) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                self._walk(child, child.name, f"{prefix}{child.name}.", host)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = FuncNode(
                    module=self.ctx.path,
                    cls=cls,
                    name=child.name,
                    qualname=f"{self.ctx.path}::{prefix}{child.name}",
                    node=child,
                    host=host,
                )
                self.funcs.append(fn)
                self._walk(child, cls, f"{prefix}{child.name}.", id(child))
            else:
                self._walk(child, cls, prefix, host)


@dataclass
class ThreadEntry:
    label: str  # "path:lineno(target_name)"
    target_ids: List[int]  # resolved FuncNode keys


class CallGraph:
    """The resolved whole-program graph; built once per lint run and shared
    by every rule that needs thread or lock flow."""

    def __init__(self, modules: Sequence[ModuleCtx]):
        self.modules = list(modules)
        self.funcs: List[FuncNode] = []
        for ctx in self.modules:
            self.funcs.extend(_FuncCollector(ctx).collect())
        self.by_key: Dict[int, FuncNode] = {f.key: f for f in self.funcs}
        # resolution indexes
        self._module_defs: Dict[Tuple[str, str], List[FuncNode]] = {}
        self._methods: Dict[Tuple[str, str], List[FuncNode]] = {}  # (cls, name)
        self._by_name: Dict[str, List[FuncNode]] = {}
        for f in self.funcs:
            if f.cls is None and f.host is None:
                self._module_defs.setdefault((f.module, f.name), []).append(f)
            if f.cls is not None:
                self._methods.setdefault((f.cls, f.name), []).append(f)
            self._by_name.setdefault(f.name, []).append(f)
        self.entries: List[ThreadEntry] = []
        self._callers: Dict[int, List[int]] = {}
        self._build_edges()
        self._find_entries()
        self._propagate_threads()
        self._propagate_locks()

    # ---- resolution --------------------------------------------------------

    def resolve_call(self, caller: FuncNode, call: ast.Call) -> List[FuncNode]:
        fn = call.func
        if isinstance(fn, ast.Name):
            return self._resolve_name(caller, fn.id)
        if isinstance(fn, ast.Attribute):
            if isinstance(fn.value, ast.Name) and fn.value.id in ("self", "cls"):
                return self._resolve_method(caller.cls, fn.attr)
            return self._resolve_unique_method(fn.attr)
        return []

    def _resolve_name(self, caller: FuncNode, name: str) -> List[FuncNode]:
        local = self._module_defs.get((caller.module, name))
        if local:
            return local
        # nested defs of the caller's own scope (closure calls)
        nested = [
            f
            for f in self.funcs
            if f.module == caller.module and f.name == name and f.host is not None
        ]
        if nested:
            return nested
        return [
            f
            for f in self._by_name.get(name, [])
            if f.cls is None and f.host is None
        ]

    def _resolve_method(self, cls: Optional[str], name: str) -> List[FuncNode]:
        if cls is not None:
            hits = self._methods.get((cls, name))
            if hits:
                return hits
        return self._resolve_unique_method(name)

    def _resolve_unique_method(self, name: str) -> List[FuncNode]:
        if name.startswith("__"):
            return []
        hits = [
            f for (_, n), fs in self._methods.items() if n == name for f in fs
        ]
        owning = {f.cls for f in hits}
        if len(owning) == 1:
            return hits
        return []

    # ---- graph construction ------------------------------------------------

    def _build_edges(self) -> None:
        for f in self.funcs:
            held: List[str] = []

            def visit(node: ast.AST) -> None:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if node is not f.node:
                        return  # nested defs get their own edges
                if isinstance(node, ast.With):
                    names = [
                        _unparse(item.context_expr.func)
                        if isinstance(item.context_expr, ast.Call)
                        else _unparse(item.context_expr)
                        for item in node.items
                    ]
                    lockish = [n for n in names if n and _is_lockish(n)]
                    held.extend(lockish)
                    for child in ast.iter_child_nodes(node):
                        visit(child)
                    del held[len(held) - len(lockish):]
                    return
                if isinstance(node, ast.Call):
                    for callee in self.resolve_call(f, node):
                        f.out.append((callee.key, frozenset(held)))
                        self._callers.setdefault(callee.key, []).append(f.key)
                for child in ast.iter_child_nodes(node):
                    visit(child)

            for stmt in getattr(f.node, "body", []):
                visit(stmt)
            # a nested def is conservatively assumed to run where its host
            # runs (local call or callback on the same thread)
            if f.host is not None and f.host in self.by_key:
                host = self.by_key[f.host]
                host.out.append((f.key, frozenset()))
                self._callers.setdefault(f.key, []).append(host.key)

    def _resolve_target(self, caller: FuncNode, t: ast.AST) -> List[FuncNode]:
        if isinstance(t, ast.Name):
            return self._resolve_name(caller, t.id)
        if isinstance(t, ast.Attribute):
            if isinstance(t.value, ast.Name) and t.value.id in ("self", "cls"):
                return self._resolve_method(caller.cls, t.attr)
            return self._resolve_unique_method(t.attr)
        if isinstance(t, ast.Lambda):
            return []  # lambda bodies are scanned via the host function
        return []

    def _find_entries(self) -> None:
        for f in self.funcs:
            for node in ast.walk(f.node):
                if not isinstance(node, ast.Call):
                    continue
                fname = (
                    node.func.attr
                    if isinstance(node.func, ast.Attribute)
                    else (node.func.id if isinstance(node.func, ast.Name) else None)
                )
                targets: List[ast.AST] = []
                if fname == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            targets.append(kw.value)
                elif fname == "submit" and node.args:
                    targets.append(node.args[0])
                for t in targets:
                    resolved = self._resolve_target(f, t)
                    if not resolved:
                        continue
                    label = (
                        f"{f.module}:{node.lineno}"
                        f"({_unparse(t) or 'target'})"
                    )
                    self.entries.append(
                        ThreadEntry(label=label, target_ids=[r.key for r in resolved])
                    )

    def _propagate_threads(self) -> None:
        # 1. each thread label floods its reachable set
        thread_reached: Set[int] = set()
        for entry in self.entries:
            frontier = list(entry.target_ids)
            seen: Set[int] = set()
            while frontier:
                k = frontier.pop()
                if k in seen:
                    continue
                seen.add(k)
                fn = self.by_key.get(k)
                if fn is None:
                    continue
                fn.runs_on.add(entry.label)
                for callee, _ in fn.out:
                    if callee not in seen:
                        frontier.append(callee)
            thread_reached |= seen

        # 2. MAIN floods from non-thread roots: every def that (a) nobody
        # in the scanned set calls and is not a thread target (API surface
        # the user drives), or (b) is called from module top level.  A def
        # reached ONLY as a thread target does not seed MAIN.
        thread_targets = {k for e in self.entries for k in e.target_ids}
        roots: List[int] = []
        for f in self.funcs:
            if f.key in thread_targets:
                continue
            if f.host is not None:
                continue  # nested defs run where their host runs
            if not self._callers.get(f.key):
                roots.append(f.key)
        self._main_roots: Set[int] = set(roots)
        frontier = roots
        seen_main: Set[int] = set()
        while frontier:
            k = frontier.pop()
            if k in seen_main:
                continue
            seen_main.add(k)
            fn = self.by_key.get(k)
            if fn is None:
                continue
            fn.runs_on.add(MAIN)
            for callee, _ in fn.out:
                if callee not in seen_main:
                    frontier.append(callee)

    def _propagate_locks(self) -> None:
        """Meet-over-paths: a lock counts as held *in* a function only when
        every resolved call edge into it (from an already-constrained
        caller) holds that lock.  Entries and MAIN roots start with
        nothing held."""
        UNIVERSE = None  # sentinel: unconstrained (no path seen yet)
        held: Dict[int, Optional[FrozenSet[str]]] = {
            f.key: UNIVERSE for f in self.funcs
        }
        # seed ONLY true roots (thread targets + the MAIN flood roots) with
        # nothing held — seeding every MAIN-running function would zero the
        # meet for callees whose every call site holds a lock
        entry_keys = {k for e in self.entries for k in e.target_ids}
        for f in self.funcs:
            if f.key in entry_keys or f.key in self._main_roots:
                held[f.key] = frozenset()
        changed = True
        iters = 0
        while changed and iters < 50:
            changed = False
            iters += 1
            for f in self.funcs:
                base = held[f.key]
                if base is UNIVERSE:
                    continue
                for callee, at_site in f.out:
                    incoming = frozenset(base | at_site)
                    cur = held.get(callee, UNIVERSE)
                    new = incoming if cur is UNIVERSE else (cur & incoming)
                    if new != cur:
                        held[callee] = new
                        changed = True
        for f in self.funcs:
            h = held[f.key]
            f.locks_held_in = frozenset() if h is None else h

    # ---- queries -----------------------------------------------------------

    def func_at(self, module: str, node: ast.AST) -> Optional[FuncNode]:
        return self.by_key.get(id(node))

    def functions_in(self, module: str) -> List[FuncNode]:
        return [f for f in self.funcs if f.module == module]


_CACHE: Dict[int, CallGraph] = {}


def get_callgraph(modules: Sequence[ModuleCtx]) -> CallGraph:
    """Build (or reuse) the graph for this exact module list — several
    rules share one lint run's graph, and the build is the expensive part
    of whole-program linting."""
    key = hash(tuple(id(m) for m in modules))
    cg = _CACHE.get(key)
    if cg is None:
        _CACHE.clear()  # one live graph: runs never interleave
        cg = CallGraph(modules)
        _CACHE[key] = cg
    return cg
