"""THR006 — whole-program race detector over UNANNOTATED shared state.

THR002 (rules_locks) checks lock discipline around state the author
*annotated* with ``# guarded-by:`` — by construction it cannot see the
races nobody thought about.  THR006 closes that hole with the call graph
(analysis/callgraph): it flags every mutation of unannotated
``self.*``/module-global state that happens in a function whose
``runs_on`` set names **two or more threads** (the main thread counts),
when **no lock is guaranteed held on any path** to the mutation.

Fires when ALL of:

- the mutated state is an instance attribute initialized in the owning
  class (``self.x = ...`` in ``__init__``/``__post_init__`` or a
  class-body assign) or a module-global assigned at top level;
- the state has NO ``# guarded-by:`` annotation anywhere it is
  initialized (annotated state is THR002's contract) and NO
  ``# synchronized-by: <mechanism>`` annotation — the escape hatch for
  state synchronized WITHOUT a lock (thread-join handoffs like the
  preload double buffer: writer thread finishes, consumer joins it, the
  join is the happens-before edge).  ``synchronized-by`` documents the
  mechanism at the init site and exempts the attribute here while staying
  invisible to THR002 (which would otherwise demand a ``with`` block that
  does not exist);
- the mutation site's function is reachable from >= 2 distinct thread
  labels (each ``Thread(target=...)``/``executor.submit`` creation site
  is a label; ``MAIN`` is the synthetic label for code the user's thread
  drives);
- no lock is held: the function's ``locks_held_in`` (meet over all call
  paths) is empty AND the mutation is not inside a lock-like ``with``
  block in the function body.

Mutations are: assignment / augmented assignment, ``del``, subscript
stores, and calls of known mutating methods (``append``/``update``/
``pop``/...).  Exemptions that keep the rule quiet where a race is
impossible or the object synchronizes itself:

- ``__init__``/``__post_init__``/``__del__`` bodies (happens-before
  thread spawn / teardown);
- attributes initialized to synchronization or queue primitives
  (``Lock``/``Condition``/``Event``/``Queue``/``deque``/...): their
  methods carry their own synchronization;
- single-thread functions (``runs_on`` of 0 or 1 labels) — no
  concurrency, no race.

Known approximations: name-based call resolution can over-link (a false
``runs_on`` label -> false positive, suppress with justification) and a
function never called in the scanned set but invoked via getattr from a
thread is under-linked (false negative).  Reader-side races (unlocked
read racing a locked write) are out of scope — annotate the state
``# guarded-by:`` and THR002 takes over both sides.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import MAIN, CallGraph, FuncNode, get_callgraph, _is_lockish, _unparse
from .core import Finding, ModuleCtx, Rule

_GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
_SYNC_RE = re.compile(r"#\s*synchronized-by:\s*(\S.+)")
_INIT_METHODS = {"__init__", "__post_init__"}
_EXEMPT_METHODS = {"__init__", "__post_init__", "__del__"}

# attribute types whose instances synchronize their own mutations
_SYNC_PRIMITIVES = {
    "Lock", "RLock", "Condition", "Event", "Semaphore", "BoundedSemaphore",
    "Barrier", "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue", "deque",
}

_MUTATING_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "setdefault", "pop", "popleft", "popitem", "remove",
    "discard", "clear", "sort", "reverse",
}


def _init_value_is_sync(value: ast.AST) -> bool:
    if isinstance(value, ast.Call):
        name = value.func
        attr = (
            name.attr
            if isinstance(name, ast.Attribute)
            else (name.id if isinstance(name, ast.Name) else None)
        )
        return attr in _SYNC_PRIMITIVES
    return False


class _StateCatalog:
    """(class, attr) and (module, global) states with annotation flags."""

    def __init__(self) -> None:
        # (cls, attr) -> (annotated, self_sync)
        self.attrs: Dict[Tuple[str, str], Tuple[bool, bool]] = {}
        # (module, name) -> (annotated, self_sync)
        self.globals: Dict[Tuple[str, str], Tuple[bool, bool]] = {}

    @staticmethod
    def _merge(old: Optional[Tuple[bool, bool]], new: Tuple[bool, bool]):
        if old is None:
            return new
        return (old[0] or new[0], old[1] or new[1])

    def collect(self, modules: Sequence[ModuleCtx], cg: CallGraph) -> None:
        for ctx in modules:
            # module globals at top level
            for stmt in ctx.tree.body:
                if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    annotated = _guard_on_line(ctx, stmt.lineno) is not None
                    value = stmt.value
                    sync = value is not None and _init_value_is_sync(value)
                    targets = (
                        stmt.targets
                        if isinstance(stmt, ast.Assign)
                        else [stmt.target]
                    )
                    for t in targets:
                        if isinstance(t, ast.Name):
                            key = (ctx.path, t.id)
                            self.globals[key] = self._merge(
                                self.globals.get(key), (annotated, sync)
                            )
            # class bodies + __init__/__post_init__ self-assigns
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for stmt in node.body:
                    if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                        annotated = _guard_on_line(ctx, stmt.lineno) is not None
                        value = stmt.value
                        sync = value is not None and _init_value_is_sync(value)
                        targets = (
                            stmt.targets
                            if isinstance(stmt, ast.Assign)
                            else [stmt.target]
                        )
                        for t in targets:
                            if isinstance(t, ast.Name):
                                key = (node.name, t.id)
                                self.attrs[key] = self._merge(
                                    self.attrs.get(key), (annotated, sync)
                                )
        for fn in cg.funcs:
            if fn.cls is None or fn.name not in _INIT_METHODS:
                continue
            ctx = _ctx_for(modules, fn.module)
            if ctx is None:
                continue
            for stmt in ast.walk(fn.node):
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                    continue
                annotated = _guard_on_line(ctx, stmt.lineno) is not None
                value = getattr(stmt, "value", None)
                sync = value is not None and _init_value_is_sync(value)
                targets = (
                    stmt.targets
                    if isinstance(stmt, ast.Assign)
                    else [stmt.target]
                )
                for t in targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        key = (fn.cls, t.attr)
                        self.attrs[key] = self._merge(
                            self.attrs.get(key), (annotated, sync)
                        )


def _guard_on_line(ctx: ModuleCtx, line: int) -> Optional[str]:
    """The annotation text when the init line carries ``guarded-by`` (lock
    discipline, THR002 enforces) or ``synchronized-by`` (documented
    non-lock mechanism, exempt here)."""
    if 1 <= line <= len(ctx.lines):
        text = ctx.lines[line - 1]
        m = _GUARD_RE.search(text) or _SYNC_RE.search(text)
        if m:
            return m.group(1)
    return None


def _ctx_for(modules: Sequence[ModuleCtx], path: str) -> Optional[ModuleCtx]:
    for ctx in modules:
        if ctx.path == path:
            return ctx
    return None


class _Mutation:
    __slots__ = ("node", "kind", "state_key", "is_global")

    def __init__(self, node: ast.AST, kind: str, state_key, is_global: bool):
        self.node = node
        self.kind = kind  # "assign" | "del" | "call"
        self.state_key = state_key
        self.is_global = is_global


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _collect_mutations(
    fn: FuncNode, catalog: _StateCatalog
) -> List[_Mutation]:
    """Mutation sites in ``fn``'s own body (nested defs excluded — they
    are their own FuncNodes)."""
    out: List[_Mutation] = []

    def target_state(t: ast.AST):
        """(state_key, is_global) for an assignment/del target (possibly
        through one subscript level: self.x[k] = v mutates self.x)."""
        base = t
        if isinstance(base, ast.Subscript):
            base = base.value
        attr = _self_attr(base)
        if attr is not None and fn.cls is not None:
            key = (fn.cls, attr)
            if key in catalog.attrs:
                return key, False
        if isinstance(base, ast.Name):
            key = (fn.module, base.id)
            if key in catalog.globals:
                # plain rebinding of a local shadows the global unless
                # `global` was declared; subscript stores always hit it
                if isinstance(t, ast.Subscript) or base.id in _global_decls(fn):
                    return key, True
        return None, False

    def visit(node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node is not fn.node:
                return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                key, is_glob = target_state(t)
                if key is not None:
                    out.append(_Mutation(node, "assign", key, is_glob))
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                key, is_glob = target_state(t)
                if key is not None:
                    out.append(_Mutation(node, "del", key, is_glob))
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _MUTATING_METHODS:
                base = f.value
                attr = _self_attr(base)
                if attr is not None and fn.cls is not None:
                    key = (fn.cls, attr)
                    if key in catalog.attrs:
                        out.append(_Mutation(node, "call", key, False))
                elif isinstance(base, ast.Name):
                    key = (fn.module, base.id)
                    if key in catalog.globals:
                        out.append(_Mutation(node, "call", key, True))
        for child in ast.iter_child_nodes(node):
            visit(child)

    for stmt in getattr(fn.node, "body", []):
        visit(stmt)
    return out


def _global_decls(fn: FuncNode) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Global):
            out.update(node.names)
    return out


def _locks_at_site(fn: FuncNode, site: ast.AST) -> bool:
    """True when ``site`` sits inside a lock-like ``with`` block of
    ``fn``'s body."""
    found = [False]

    def visit(node: ast.AST, held: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node is not fn.node:
                return
        if node is site and held:
            found[0] = True
            return
        if isinstance(node, ast.With):
            lockish = any(
                _is_lockish(_unparse(item.context_expr)) for item in node.items
            )
            for child in ast.iter_child_nodes(node):
                visit(child, held or lockish)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in getattr(fn.node, "body", []):
        visit(stmt, False)
    return found[0]


class RaceDetectorRule(Rule):
    id = "THR006"
    doc = "whole-program race detector over unannotated shared state"

    def finalize(self, modules: Sequence[ModuleCtx]) -> List[Finding]:
        cg = get_callgraph(modules)
        catalog = _StateCatalog()
        catalog.collect(modules, cg)
        findings: List[Finding] = []
        for fn in cg.funcs:
            if fn.name in _EXEMPT_METHODS:
                continue
            if len(fn.runs_on) < 2:
                continue
            ctx = _ctx_for(modules, fn.module)
            if ctx is None:
                continue
            for mut in _collect_mutations(fn, catalog):
                annotated, self_sync = (
                    catalog.globals[mut.state_key]
                    if mut.is_global
                    else catalog.attrs[mut.state_key]
                )
                if annotated or self_sync:
                    continue
                if fn.locks_held_in:
                    continue  # every path in already holds a lock
                if _locks_at_site(fn, mut.node):
                    continue
                state = (
                    f"module global {mut.state_key[1]}"
                    if mut.is_global
                    else f"self.{mut.state_key[1]} "
                    f"(class {mut.state_key[0]})"
                )
                threads = ", ".join(sorted(fn.runs_on))
                f = self.finding(
                    ctx,
                    mut.node,
                    f"{state} is mutated in {fn.qualname} which runs on "
                    f">=2 threads [{threads}] with no lock held on the "
                    "path and no guarded-by annotation — add a lock, "
                    "annotate `# guarded-by:`, or justify a suppression",
                )
                if f is not None:
                    findings.append(f)
        return findings
