"""MON005 — stat-name hygiene.

Dashboards and soak tooling enumerate the monitor registry by name; that
only works if every ``STAT_ADD``/``STAT_SET``/``STAT_OBSERVE`` site uses
a string literal
from the flat ``[a-z0-9_.]+`` namespace. An f-string name mints an
unbounded metric family nothing can enumerate ahead of time; an uppercase
or hyphenated name breaks the dashboards' parsing convention.

- ERROR: first argument is not a string literal.
- ERROR: literal doesn't fullmatch ``[a-z0-9_.]+``.

``STAT_GET``/``STAT_RESET`` are exempt: programmatic sweeps over
``all_stats()`` legitimately pass computed names there.
"""

from __future__ import annotations

import ast
import re
from typing import List

from .core import Finding, ModuleCtx, Rule, call_name

_NAME_RE = re.compile(r"[a-z0-9_.]+")
_STAT_FUNCS = {"STAT_ADD", "STAT_SET", "STAT_OBSERVE"}


class StatNameRule(Rule):
    id = "MON005"
    doc = "STAT_ADD/STAT_SET/STAT_OBSERVE names must be enumerable literals"

    def check_module(self, ctx: ModuleCtx) -> List[Finding]:
        if ctx.path.endswith("utils/monitor.py"):
            return []  # the registry's own defs/internals
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node) not in _STAT_FUNCS:
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if not _NAME_RE.fullmatch(arg.value):
                    f = self.finding(
                        ctx, node,
                        f'stat name "{arg.value}" must match [a-z0-9_.]+ '
                        "(dashboard enumeration convention)",
                    )
                    if f is not None:
                        findings.append(f)
            else:
                f = self.finding(
                    ctx, node,
                    "stat name must be a string literal — dynamic names "
                    "mint an unenumerable metric family",
                )
                if f is not None:
                    findings.append(f)
        return findings
