"""Quantized wire formats for host<->device and inter-chip value traffic.

The reference ships compressed pull records over its wires — the
Quant/ShowClk pull-value family (FeaturePullValueGpuQuant dispatch,
box_wrapper.cc:419-437) packs embeddings as int16 with a scale, because the
PS lives on the host and every batch's values cross PCIe. This framework's
architecture removed the per-batch value wire entirely (the pass table lives
in HBM; per-batch feed is index-only), so quantization applies where values
still move:

- the pass-boundary wire (table/carrier.py: new-key upload, departing-slice
  fetch, flush, classic device writeback) over a bandwidth-limited
  host<->TPU transport — full TABLE ROWS, handled by the layout-aware
  ``send_rows_*``/``fetch_rows_*`` API below;
- the ICI all_to_all payloads of the sharded pull/push
  (parallel/sharded_pullpush.py) on multi-chip meshes — handled inline by a
  bf16 cast at the collective.

Formats (``wire_dtype`` / ``ici_wire_dtype`` flags, defined in config.py so
they exist before this module loads; default fp32 = exact):
- ``bf16``: drop 16 mantissa bits; ~3 significant digits — comfortably
  inside CTR embedding noise, exactly half the bytes.
- ``int8``: the EMBED VALUE region (embed_w + embedx + expand — contiguous
  columns [embed_w_col, embed_g2_col)) is int8 with per-row max-abs scales,
  like the reference's int16 quant pull; the heterogeneous remainder
  (show/clk counters, conv/pcoc extras, adagrad g2) rides bf16 — a shared
  row scale would let a show=1000 counter zero out 0.01-magnitude
  embeddings. Scales are PER BLOCK within the region — (embed_w+embedx)
  and expand quantize independently, mirroring how the reference types
  each value family separately (box_wrapper.cc:419-437): the expand block
  trains on different gradients and can sit orders of magnitude away from
  embedx, and one shared scale would quantize the smaller block to noise.

Host-side casts use ml_dtypes (numpy bf16 support ships with jax).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

import ml_dtypes

from paddlebox_tpu import config  # flags wire_dtype / ici_wire_dtype live there
from paddlebox_tpu.utils.monitor import STAT_ADD

BF16 = ml_dtypes.bfloat16

_MODES = ("fp32", "bf16", "int8")

# the ICI wire additionally understands the frequency-adaptive mixed mode
# (hot rows bf16, cold tail int8 — see ici_effective_mode below); the
# boundary row wire does not, because boundary rows already ride the
# layout-aware per-block int8 format and cross once per pass, not per batch
_ICI_MODES = _MODES + ("adaptive",)


def _check(mode: str) -> str:
    if mode not in _MODES:
        raise ValueError(f"wire dtype {mode!r} not in {_MODES}")
    return mode


def check_ici(mode: str) -> str:
    if mode not in _ICI_MODES:
        raise ValueError(f"ici wire dtype {mode!r} not in {_ICI_MODES}")
    return mode


def ici_effective_mode() -> str:
    """Resolve the ICI wire mode the collective should actually run.

    ``ici_wire_adaptive=False`` is the ablation master switch: it degrades
    ``adaptive`` all the way to fp32 (not to a uniform quant mode) so the
    off-leg is bitwise-identical to the pre-adaptive default wire."""
    mode = check_ici(str(config.get_flag("ici_wire_dtype")))
    if mode != "adaptive":
        return mode
    if not config.get_flag("ici_wire_adaptive"):
        return "fp32"
    return "adaptive"


def ici_adaptive_engaged() -> bool:
    """True iff the adaptive hot/cold wire is actually live (mode resolves
    to adaptive after the ablation gate) — the single predicate every
    hotness-plumbing site gates on, so turning the gate off also turns off
    the hot-first packer reorder and the working-set hotness round."""
    return ici_effective_mode() == "adaptive"


def ici_hot_slots(K: int) -> int:
    """Static per-bucket hot-slot count for bucket capacity K (the first H
    slots of each per-shard request bucket ride bf16)."""
    frac = float(config.get_flag("ici_hot_frac"))
    return int(min(K, max(0, round(frac * K))))


def _embed_span(layout) -> Tuple[int, int]:
    """[start, stop) of the contiguous embed-value region in a table row."""
    return layout.embed_w_col, layout.embed_g2_col


def _embed_blocks(layout) -> Tuple[Tuple[int, int], ...]:
    """Independently-scaled sub-blocks tiling the embed-value region:
    (embed_w + embedx) and, when present, the expand embedding — separate
    value families with separate gradient flows, so separate quant scales
    (the reference types each pull-value family on its own,
    box_wrapper.cc:419-437)."""
    a, b = _embed_span(layout)
    if layout.expand_dim:
        return ((a, layout.expand_col), (layout.expand_col, b))
    return ((a, b),)


# ---- table-row wire (boundary transfers) ------------------------------------
#
# A "wire handle" is a dict of arrays (device or host) that crosses the wire
# as-is; the matching finish/receive call reassembles fp32 rows on the other
# side. Splitting start/finish lets an async sender dispatch the device-side
# casts immediately (so they read current values) while the blocking
# transfer happens on a worker thread.


def fetch_rows_start(arr, layout, mode: str):
    """Device fp32 [n, width] -> wire handle of device arrays (D2H side).

    Dispatches the quantizing casts now; nothing blocks until
    ``fetch_rows_finish`` pulls the handle to the host."""
    import jax.numpy as jnp

    mode = _check(mode)
    # bytes-on-wire accounting at the choke point every boundary D2H routes
    # through (carrier departing-slice fetch, flush, classic writeback) —
    # the measurement the quantized-wire roadmap claim is graded against
    STAT_ADD("wire.fetch_rows_total", arr.shape[0])
    STAT_ADD("wire.fetch_bytes_total", row_wire_nbytes(arr.shape[0], layout, mode))
    STAT_ADD(
        "wire.fetch_fp32_bytes_total", row_wire_nbytes(arr.shape[0], layout, "fp32")
    )
    if mode == "fp32":
        return {"mode": mode, "raw": arr}
    if mode == "bf16":
        return {"mode": mode, "raw": arr.astype(jnp.bfloat16)}
    a, b = _embed_span(layout)
    qs, scales = [], []
    for ba, bb in _embed_blocks(layout):
        blk = arr[:, ba:bb]
        s = jnp.maximum(jnp.abs(blk).max(axis=1), 1e-12) / 127.0
        qs.append(jnp.clip(jnp.rint(blk / s[:, None]), -127, 127).astype(jnp.int8))
        scales.append(s)
    return {
        "mode": mode,
        "q": jnp.concatenate(qs, axis=1) if len(qs) > 1 else qs[0],
        "scale": jnp.stack(scales, axis=1).astype(jnp.float32),  # [n, n_blocks]
        "head": arr[:, :a].astype(jnp.bfloat16),
        "tail": arr[:, b:].astype(jnp.bfloat16),
    }


def fetch_rows_finish(handle, layout) -> np.ndarray:
    """Blocking D2H of a wire handle -> host fp32 [n, width]."""
    mode = handle["mode"]
    if mode == "fp32":
        return np.asarray(handle["raw"])
    if mode == "bf16":
        return np.asarray(handle["raw"]).astype(np.float32)
    a, b = _embed_span(layout)
    q = np.asarray(handle["q"]).astype(np.float32)
    scale = np.asarray(handle["scale"])  # [n, n_blocks]
    head = np.asarray(handle["head"]).astype(np.float32)
    tail = np.asarray(handle["tail"]).astype(np.float32)
    out = np.empty((q.shape[0], layout.width), dtype=np.float32)
    out[:, :a] = head
    for bi, (ba, bb) in enumerate(_embed_blocks(layout)):
        out[:, ba:bb] = q[:, ba - a : bb - a] * scale[:, bi : bi + 1]
    out[:, b:] = tail
    return out


def fetch_rows(arr, layout, mode: str) -> np.ndarray:
    """One-shot device fp32 rows -> host fp32 rows over the quantized wire."""
    return fetch_rows_finish(fetch_rows_start(arr, layout, mode), layout)


def send_rows(arr: np.ndarray, layout, mode: str):
    """Host fp32 [n, width] -> device fp32 [n, width] over the quantized
    wire (H2D side: casts happen host-side so only the small payload
    crosses; the device reassembles)."""
    import jax.numpy as jnp

    mode = _check(mode)
    # H2D twin of the fetch_rows_start accounting (carrier new-key upload,
    # dist_ws block upload)
    STAT_ADD("wire.send_rows_total", arr.shape[0])
    STAT_ADD("wire.send_bytes_total", row_wire_nbytes(arr.shape[0], layout, mode))
    STAT_ADD(
        "wire.send_fp32_bytes_total", row_wire_nbytes(arr.shape[0], layout, "fp32")
    )
    if mode == "fp32":
        return jnp.asarray(arr)
    if mode == "bf16":
        return jnp.asarray(arr.astype(BF16)).astype(jnp.float32)
    a, b = _embed_span(layout)
    out = jnp.empty((arr.shape[0], layout.width), dtype=jnp.float32)
    out = out.at[:, :a].set(
        jnp.asarray(arr[:, :a].astype(BF16)).astype(jnp.float32)
    )
    for ba, bb in _embed_blocks(layout):
        blk = arr[:, ba:bb]
        scale = np.maximum(np.abs(blk).max(axis=1), 1e-12) / 127.0
        q = np.clip(np.rint(blk / scale[:, None]), -127, 127).astype(np.int8)
        out = out.at[:, ba:bb].set(
            jnp.asarray(q).astype(jnp.float32)
            * jnp.asarray(scale.astype(np.float32))[:, None]
        )
    out = out.at[:, b:].set(
        jnp.asarray(arr[:, b:].astype(BF16)).astype(jnp.float32)
    )
    return out


def row_wire_nbytes(n: int, layout, mode: str) -> int:
    """Bytes crossing the wire for n table rows under a mode."""
    mode = _check(mode)
    w = layout.width
    if mode == "fp32":
        return n * w * 4
    if mode == "bf16":
        return n * w * 2
    a, b = _embed_span(layout)
    n_blocks = len(_embed_blocks(layout))
    # int8 region + bf16 rest + one fp32 scale per block
    return n * ((b - a) + (w - (b - a)) * 2 + 4 * n_blocks)


def ici_wire_nbytes(
    n: int, K: int, W: int, head: int, n_sections: int, mode: str, hot_slots: int = 0
) -> int:
    """Bytes crossing ICI for an [n, K, W] all_to_all record block.

    ``head`` columns are always exact fp32 (counts for pull, show/clk for
    push); the remaining W-head value columns ride the mode's format.
    int8 records carry one fp32 max-abs scale per (record, section).
    ``adaptive`` splits each K-bucket at ``hot_slots``: the first H slots
    bf16, the rest int8 — degenerating to the uniform modes at H=0 / H=K
    exactly as the collective itself does."""
    mode = check_ici(mode)
    q_cols = W - head
    if mode == "fp32":
        return n * K * W * 4
    if mode == "bf16":
        return n * K * (head * 4 + q_cols * 2)
    if mode == "int8":
        return n * K * (head * 4 + q_cols + 4 * n_sections)
    H = int(hot_slots)
    if H <= 0:
        return ici_wire_nbytes(n, K, W, head, n_sections, "int8")
    if H >= K:
        return ici_wire_nbytes(n, K, W, head, n_sections, "bf16")
    return n * (
        K * head * 4 + H * q_cols * 2 + (K - H) * (q_cols + 4 * n_sections)
    )
