"""Device-side sparse pull/push over the pass working-set table.

TPU-native replacement for the reference's pull/push hot path
(PullSparseCase/PushSparseGradCase, box_wrapper_impl.h:25-253, kernels in
box_wrapper.cu): keys were already remapped host-side to dense row ids, so

- pull  = gather rows + embedx activity gating + scale     (static shapes)
- push  = vectorized sparse-AdaGrad column math + one scatter back

Both run *inside* the jitted train step; the optimizer lives on device, not
in a parameter server. The table row layout is ``ValueLayout``:
``[show, clk, extras..., embed_w, embedx[D], embed_g2, embedx_g2]``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddlebox_tpu import config
from paddlebox_tpu.table.optimizers import SparseOptimizerConfig
from paddlebox_tpu.table.value_layout import FeatureType, ValueLayout


def _impl_for(op: str, table: jnp.ndarray, n_idx: int, unique_rows: bool = True) -> str:
    """KernelPlan lookup for one op instance (ops/kernel_plan.py): per-shape
    pallas-vs-native routing, resolved at trace time from the committed plan
    artifact (or the builtin defaults, which honor ``use_pallas_sparse``)."""
    from paddlebox_tpu.ops.kernel_plan import current_backend, get_plan

    return get_plan().select(
        op,
        current_backend(),
        table.shape[0],
        table.shape[1],
        n_idx,
        unique_rows=unique_rows,
    )


def _gather_rows(table: jnp.ndarray, rows: jnp.ndarray) -> jnp.ndarray:
    """Row gather: XLA take, or the Pallas row-DMA kernel when planned."""
    if _impl_for("pull", table, rows.shape[0]) == "pallas":
        from paddlebox_tpu.ops.pallas_kernels import pull_rows_pallas

        return pull_rows_pallas(table, rows)
    return jnp.take(table, rows, axis=0)


def embedx_active_mask(
    layout: ValueLayout, show: jnp.ndarray, embedx_threshold: float
) -> jnp.ndarray:
    """Activation mask for the embedx block, from the key's show count.

    Row-level threshold gate (the closed lib's ``embedding_size > 0``
    signal, box_wrapper.cu:54-63) — or, for FeatureType.VARIABLE, the
    graded per-column unlock (column j needs show >= threshold *
    2^quarter(j)): cold keys expose a short vector, hot keys the full one
    (B3 VARIABLE; dim policy re-derived openly, see
    value_layout.FeatureType). Shared by pull AND push so locked dims can
    neither be seen nor trained.
    """
    if layout.feature_type is FeatureType.VARIABLE:
        D = layout.embedx_dim
        quarter = jnp.arange(D, dtype=jnp.int32) * 4 // max(D, 1)
        need = embedx_threshold * jnp.exp2(quarter.astype(jnp.float32))
        return show[:, None] >= need[None, :]
    return (show >= embedx_threshold)[:, None]


def pull_sparse_rows(
    table: jnp.ndarray,  # [rows, width]
    rows: jnp.ndarray,  # int32 [U] (deduped, padded with the padding row)
    layout: ValueLayout,
    embedx_threshold: float,
    scale: float = 1.0,
) -> jnp.ndarray:
    """Gather pull records [U, pull_width] = [show, clk, .., embed_w, embedx].

    embedx columns are zeroed per ``embedx_active_mask``: for keys whose
    show count has not reached the activation threshold — the open analog
    of the closed lib's ``embedding_size > 0`` signal consumed by PullCopy
    (box_wrapper.cu:54-63) — or, on VARIABLE layouts, per-column as the
    graded dims unlock.
    """
    picked = _gather_rows(table, rows)  # [U, width]
    cvm_block = picked[:, : layout.cvm_offset]
    embedx = picked[:, layout.embedx_col : layout.embedx_col + layout.embedx_dim]
    active = embedx_active_mask(layout, picked[:, layout.SHOW], embedx_threshold)
    embedx = jnp.where(active, embedx * scale, 0.0)
    return jnp.concatenate([cvm_block, embedx], axis=1)


def pull_sparse_rows_extended(
    table: jnp.ndarray,  # [rows, width]
    rows: jnp.ndarray,  # int32 [U]
    layout: ValueLayout,
    embedx_threshold: float,
    scale: float = 1.0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(pull records [U, pull_width], expand embeddings [U, expand_dim]).

    The pull_box_extended_sparse analog (pull_box_extended_sparse_op.h:26-95):
    each key yields its normal record plus a second, independently trained
    expand embedding (same activation gating).
    """
    if layout.expand_dim == 0:
        raise ValueError("layout has no expand block (expand_embed_dim == 0)")
    picked = _gather_rows(table, rows)
    cvm_block = picked[:, : layout.cvm_offset]
    show = picked[:, layout.SHOW]
    # embedx follows the layout's gating (incl. VARIABLE graded dims);
    # the expand block stays row-level gated — its dims are an independent
    # second embedding, not a prefix-extensible vector
    active = embedx_active_mask(layout, show, embedx_threshold)
    row_active = (show >= embedx_threshold)[:, None]
    embedx = picked[:, layout.embedx_col : layout.embedx_col + layout.embedx_dim]
    embedx = jnp.where(active, embedx * scale, 0.0)
    expand = picked[:, layout.expand_col : layout.expand_col + layout.expand_dim]
    expand = jnp.where(row_active, expand * scale, 0.0)
    return jnp.concatenate([cvm_block, embedx], axis=1), expand


def push_sparse_rows(
    table: jnp.ndarray,  # [rows, width]
    rows: jnp.ndarray,  # int32 [U] deduped rows (padding row allowed)
    grads: jnp.ndarray,  # [U, pull_width] d(loss)/d(pull record)
    show_counts: jnp.ndarray,  # f32 [U] occurrences of the key in this batch
    clk_counts: jnp.ndarray,  # f32 [U] summed clicks over those occurrences
    layout: ValueLayout,
    opt: SparseOptimizerConfig,
    lr_scale: jnp.ndarray | float = 1.0,  # scalar or [U] slot-lr multiplier
) -> jnp.ndarray:
    """Apply sparse AdaGrad + counter updates; returns the new table.

    Mirrors the closed PushSparseGPU contract (push record = show, clk,
    grads; box_wrapper.cu PushCopy fills show/clk from the batch) with the
    optimizer semantics documented in table/optimizers.py.
    """
    old = _gather_rows(table, rows)  # [U, width]
    new_rows = sparse_update_rows(
        old, grads, show_counts, clk_counts, layout, opt, lr_scale
    )
    # dedup'd rows are unique (pad-row repeats write identical contents), so
    # the pallas per-row SET == scatter-add of deltas; without dedup the
    # plan clamps to native (unique_rows=False makes pallas ineligible)
    unique_rows = bool(config.get_flag("enable_pullpush_dedup_keys"))
    if _impl_for("push", table, rows.shape[0], unique_rows=unique_rows) == "pallas":
        from paddlebox_tpu.ops.pallas_kernels import write_rows_pallas

        return write_rows_pallas(table, rows, new_rows)
    # Scatter the *delta* with add-semantics: with host dedup rows are unique
    # and this equals a set; without dedup (enable_pullpush_dedup_keys=0) a
    # key occurring in several slots contributes each occurrence's update
    # deterministically (sequential-push semantics) instead of last-write-wins.
    return table.at[rows].add(new_rows - old)


def sparse_update_rows(
    old: jnp.ndarray,  # [U, width] current rows
    grads: jnp.ndarray,  # [U, pull_width] d(loss)/d(pull record)
    show_counts: jnp.ndarray,  # f32 [U]
    clk_counts: jnp.ndarray,  # f32 [U]
    layout: ValueLayout,
    opt: SparseOptimizerConfig,
    lr_scale: jnp.ndarray | float = 1.0,
) -> jnp.ndarray:
    """Row-wise sparse optimizer math shared by the single-device scatter path
    and the sharded owner-side merge path (rows with all-zero records are
    identity: g2 += 0, step 0, counters += 0).

    ``grads`` may be [U, pull_width] or [U, pull_width + expand_dim] — the
    extended form (pull_sparse_rows_extended) appends expand-embedding grads,
    updated with their own adagrad g2 scalar (static shapes: the branch
    resolves at trace time).
    """
    co, D = layout.cvm_offset, layout.embedx_dim
    with_expand = grads.shape[1] == layout.extended_push_width and layout.expand_dim > 0

    show = old[:, layout.SHOW] + show_counts
    clk = old[:, layout.CLK] + clk_counts

    # --- embed_w (+ any conv/pcoc extras: cols 2..cvm_offset) scalar adagrad.
    # grads[:, :2] correspond to the show/clk passthrough columns of the pull
    # record; they receive CVM-transform gradients in principle, but counters
    # are PS statistics, not weights — the reference likewise ignores them.
    w_grad = grads[:, 2:co]  # [U, co-2] (embed_w last)
    g2_e = old[:, layout.embed_g2_col] + jnp.sum(w_grad * w_grad, axis=1)
    scale_e = jnp.sqrt(opt.initial_g2sum / (opt.initial_g2sum + g2_e))
    step_e = (opt.embed_lr * lr_scale * scale_e)[:, None] * w_grad
    new_w = old[:, 2:co] - step_e
    new_w = jnp.clip(new_w, -opt.weight_bounds, opt.weight_bounds)

    # --- embedx vector adagrad with one shared g2 scalar (mean energy).
    # The activation mask MUST match the pull's (incl. VARIABLE graded
    # dims): grads are taken w.r.t. the pulled record, so a locked dim's
    # gradient is nonzero even though the model saw a zero — without the
    # mask it would train on phantom inputs and inflate g2.
    x_grad = grads[:, co : co + D]
    x_active = embedx_active_mask(layout, old[:, layout.SHOW], opt.embedx_threshold)
    x_grad = jnp.where(x_active, x_grad, 0.0)
    g2_x = old[:, layout.embedx_g2_col] + jnp.mean(x_grad * x_grad, axis=1)
    scale_x = jnp.sqrt(opt.initial_g2sum / (opt.initial_g2sum + g2_x))
    new_x = old[:, co : co + D] - (opt.embedx_lr * lr_scale * scale_x)[:, None] * x_grad
    new_x = jnp.clip(new_x, -opt.weight_bounds, opt.weight_bounds)

    cols = [show[:, None], clk[:, None], new_w, new_x]
    if layout.expand_dim:
        E = layout.expand_dim
        ec = layout.expand_col
        if with_expand:
            # expand is row-level gated (an independent second embedding,
            # not a prefix-extensible vector) — mirrors the extended pull
            row_active = (old[:, layout.SHOW] >= opt.embedx_threshold)[:, None]
            e_grad = grads[:, co + D : co + D + E]
            e_grad = jnp.where(row_active, e_grad, 0.0)
        else:  # plain push on an expand-capable layout: expand untouched
            e_grad = jnp.zeros((old.shape[0], E), old.dtype)
        g2_p = old[:, layout.expand_g2_col] + jnp.mean(e_grad * e_grad, axis=1)
        scale_p = jnp.sqrt(opt.initial_g2sum / (opt.initial_g2sum + g2_p))
        new_p = old[:, ec : ec + E] - (opt.embedx_lr * lr_scale * scale_p)[:, None] * e_grad
        cols.append(jnp.clip(new_p, -opt.weight_bounds, opt.weight_bounds))
        cols += [g2_e[:, None], g2_x[:, None], g2_p[:, None]]
    else:
        cols += [g2_e[:, None], g2_x[:, None]]
    return jnp.concatenate(cols, axis=1)
