from paddlebox_tpu.ops.pull_push import pull_sparse_rows, push_sparse_rows
from paddlebox_tpu.ops.seqpool_cvm import fused_seqpool_cvm, cvm_transform

__all__ = [
    "pull_sparse_rows",
    "push_sparse_rows",
    "fused_seqpool_cvm",
    "cvm_transform",
]
