from paddlebox_tpu.ops.ctr_ops import batch_fc, fused_concat, rank_attention
from paddlebox_tpu.ops.pull_push import (
    pull_sparse_rows,
    pull_sparse_rows_extended,
    push_sparse_rows,
)
from paddlebox_tpu.ops.seqpool_cvm import (
    cvm_transform,
    cvm_with_conv_transform,
    cvm_with_pcoc_transform,
    fused_seqpool_cvm,
    fused_seqpool_cvm_with_conv,
    fused_seqpool_cvm_with_diff_thres,
    fused_seqpool_cvm_with_pcoc,
)

__all__ = [
    "pull_sparse_rows",
    "pull_sparse_rows_extended",
    "push_sparse_rows",
    "fused_seqpool_cvm",
    "fused_seqpool_cvm_with_conv",
    "fused_seqpool_cvm_with_diff_thres",
    "fused_seqpool_cvm_with_pcoc",
    "cvm_transform",
    "cvm_with_conv_transform",
    "cvm_with_pcoc_transform",
    "rank_attention",
    "batch_fc",
    "fused_concat",
]
