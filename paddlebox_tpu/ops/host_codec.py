"""Host-wire codecs: delta+varint key streams, narrow-int row ids, chunked
zlib frames.

The device plane already compresses its traffic (``ops/wire_quant.py`` rows,
the bf16/int8 ICI all_to_all in ``parallel/sharded_pullpush.py``); this
module is the HOST plane's counterpart — the open rebuild of the byte
formats the reference's closed ``boxps::PaddleShuffler`` key-exchange tier
ships between nodes. Three codecs, all pure numpy, all round-trip exact:

- **Sorted-u64 delta+varint** (``encode_sorted_u64``): the working-set
  exchange moves *sorted unique* uint64 feasign streams. Gaps between
  consecutive keys are tiny compared to the absolute 64-bit values (CTR
  sign spaces are dense), so delta + LEB128 varint lands at ~1-2 bytes/key
  instead of 8 — the SparCML observation that sparse-stream *index*
  compression is the dominant win for this exchange shape. Non-monotonic
  input is rejected at encode time; a decoded stream that wraps uint64 is
  rejected at decode time, so a malformed buffer can never round-trip
  silently.
- **Narrow-int row ids** (``encode_row_ids``): global rows are
  ``shard * capacity + rank`` — bounded by ``n_mesh_shards * capacity``,
  which in practice fits uint32 (often uint16). The encoder picks the
  narrowest width that holds the declared bound and *asserts* every value
  fits, so an overflow is a loud codec error, never a truncated id.
- **Chunked zlib frame** (``compress_chunked``): a generic byte-stream
  codec for the transport's frame payloads (shuffle chunks, anything
  opaque). Input is compressed in bounded chunks so peak codec RAM stays
  ~chunk-sized on both ends; the header pins the exact raw length and every
  chunk's compressed length, so truncation and length lies are caught
  before (or during) inflate and surface as :class:`HostCodecError`.

``parallel/transport.py`` (PBTX v3) frames these on the wire — the codec
byte in the frame header says how the payload is encoded, the frame CRC32
covers the *compressed* body so corruption is caught before inflate, and
the ``wire.host_*`` counters at that choke point are the measurement the
ROADMAP item 2 host-wire claim is graded against.
"""

from __future__ import annotations

import struct
import zlib

import numpy as np


class HostCodecError(ValueError):
    """Malformed host-wire codec input — rejected, never silently decoded."""


# ---------------------------------------------------------------------------
# sorted uint64 streams: delta + LEB128 varint
# ---------------------------------------------------------------------------

_U64_HDR = struct.Struct("<Q")  # value count

_SEVEN = np.uint64(7)
_LOW7 = np.uint64(0x7F)


def _varint_encode(vals: np.ndarray) -> np.ndarray:
    """uint64 values -> LEB128 byte stream (vectorized; <=10 passes)."""
    n = len(vals)
    if n == 0:
        return np.zeros(0, np.uint8)
    # bytes per value: ceil(bit_length / 7), minimum 1
    nb = np.ones(n, np.int64)
    v = vals >> _SEVEN
    while v.any():
        nb += v > 0
        v >>= _SEVEN
    starts = np.zeros(n, np.int64)
    np.cumsum(nb[:-1], out=starts[1:])
    out = np.zeros(int(nb.sum()), np.uint8)
    cur = vals
    j = 0
    while True:
        m = nb > j
        if not m.any():
            break
        more = nb[m] > j + 1
        out[starts[m] + j] = (cur[m] & _LOW7).astype(np.uint8) | (
            more.astype(np.uint8) << 7
        )
        cur = cur >> _SEVEN
        j += 1
    return out


def _varint_decode(buf: np.ndarray, n: int) -> np.ndarray:
    """LEB128 byte stream -> exactly ``n`` uint64 values (vectorized)."""
    if n == 0:
        if len(buf):
            raise HostCodecError(
                f"varint stream: header says 0 values but {len(buf)} "
                "payload bytes follow"
            )
        return np.zeros(0, np.uint64)
    if len(buf) == 0:
        raise HostCodecError(f"varint stream truncated: 0 bytes for {n} values")
    ends = (buf & 0x80) == 0  # bytes without a continuation bit terminate
    n_vals = int(ends.sum())
    if n_vals != n or not ends[-1]:
        raise HostCodecError(
            f"varint stream holds {n_vals} terminated values, header says "
            f"{n} (truncated or corrupt)"
        )
    group_starts = np.zeros(n, np.int64)
    group_starts[1:] = np.nonzero(ends)[0][:-1] + 1
    gid = np.zeros(len(buf), np.int64)
    gid[1:] = np.cumsum(ends[:-1])
    within = np.arange(len(buf), dtype=np.int64) - group_starts[gid]
    if int(within.max()) > 9:
        raise HostCodecError("varint longer than 10 bytes cannot fit uint64")
    # the 10th byte carries bits [63, 70): anything above bit 63 overflows
    if np.any((within == 9) & ((buf & 0x7F) > 1)):
        raise HostCodecError("varint value overflows uint64")
    contrib = (buf.astype(np.uint64) & _LOW7) << (
        _SEVEN * within.astype(np.uint64)
    )
    # per-group bit fields are disjoint, so the reduceat sum is exact
    return np.add.reduceat(contrib, group_starts)


def encode_sorted_u64(keys: np.ndarray) -> bytes:
    """Sorted (non-decreasing) uint64 stream -> delta+varint bytes."""
    keys = np.ascontiguousarray(keys, dtype=np.uint64)
    n = len(keys)
    if n == 0:
        return _U64_HDR.pack(0)
    if n > 1 and np.any(keys[1:] < keys[:-1]):
        raise HostCodecError(
            "encode_sorted_u64 requires a non-decreasing key stream"
        )
    deltas = np.empty(n, np.uint64)
    deltas[0] = keys[0]
    np.subtract(keys[1:], keys[:-1], out=deltas[1:])
    return _U64_HDR.pack(n) + _varint_encode(deltas).tobytes()


def decode_sorted_u64(data: bytes) -> np.ndarray:
    """Inverse of :func:`encode_sorted_u64`; rejects malformed buffers."""
    if len(data) < _U64_HDR.size:
        raise HostCodecError(
            f"key stream shorter than its {_U64_HDR.size}-byte header"
        )
    (n,) = _U64_HDR.unpack_from(data)
    buf = np.frombuffer(data, np.uint8, offset=_U64_HDR.size)
    deltas = _varint_decode(buf, n)
    keys = np.cumsum(deltas, dtype=np.uint64)
    # deltas are non-negative, so any decrease means the cumsum wrapped
    # uint64 — a malformed stream, not a representable key set
    if len(keys) > 1 and np.any(keys[1:] < keys[:-1]):
        raise HostCodecError("key stream overflows uint64 (corrupt deltas)")
    return keys


# ---------------------------------------------------------------------------
# self-describing key-stream wrapper (raw ablation interoperates with codec)
# ---------------------------------------------------------------------------

KEYS_RAW = 0  # marker + raw little-endian uint64 bytes
KEYS_DELTA = 1  # marker + delta+varint


def encode_key_stream(keys: np.ndarray, codec: bool) -> bytes:
    """One sorted-u64 payload for the working-set exchange. The leading
    marker byte makes the format self-describing, so a codec-on rank and a
    raw-ablation rank decode each other's frames identically."""
    keys = np.ascontiguousarray(keys, dtype=np.uint64)
    if codec:
        return bytes([KEYS_DELTA]) + encode_sorted_u64(keys)
    return bytes([KEYS_RAW]) + keys.tobytes()


def decode_key_stream(data: bytes) -> np.ndarray:
    if len(data) < 1:
        raise HostCodecError("key stream payload missing its marker byte")
    marker, body = data[0], data[1:]
    if marker == KEYS_DELTA:
        return decode_sorted_u64(body)
    if marker == KEYS_RAW:
        if len(body) % 8:
            raise HostCodecError(
                f"raw key stream length {len(body)} is not a multiple of 8"
            )
        return np.frombuffer(body, dtype=np.uint64)
    raise HostCodecError(f"unknown key stream marker {marker}")


# ---------------------------------------------------------------------------
# row ids: narrowest unsigned width that holds the declared bound
# ---------------------------------------------------------------------------

_ROW_HDR = struct.Struct("<BQ")  # itemsize, count
_ROW_DTYPES = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


def row_id_dtype(max_value: int):
    """Narrowest unsigned dtype holding ``[0, max_value]``."""
    for dt in (np.uint8, np.uint16, np.uint32, np.uint64):
        if max_value <= int(np.iinfo(dt).max):
            return dt
    raise HostCodecError(f"row id bound {max_value} exceeds uint64")


def encode_row_ids(rows: np.ndarray, max_value: int) -> bytes:
    """Global row ids -> narrow-int bytes. ``max_value`` is the declared
    inclusive bound (``n_mesh_shards * capacity - 1``); any value outside
    ``[0, max_value]`` is an overflow and raises rather than truncating."""
    rows = np.ascontiguousarray(rows)
    if len(rows):
        lo, hi = int(rows.min()), int(rows.max())
        if lo < 0 or hi > max_value:
            raise HostCodecError(
                f"row id range [{lo}, {hi}] outside declared bound "
                f"[0, {max_value}]"
            )
    arr = rows.astype(row_id_dtype(max_value))
    return _ROW_HDR.pack(arr.dtype.itemsize, len(arr)) + arr.tobytes()


def decode_row_ids(data: bytes) -> np.ndarray:
    """Inverse of :func:`encode_row_ids`; always returns int64."""
    if len(data) < _ROW_HDR.size:
        raise HostCodecError(
            f"row id payload shorter than its {_ROW_HDR.size}-byte header"
        )
    width, n = _ROW_HDR.unpack_from(data)
    if width not in _ROW_DTYPES:
        raise HostCodecError(f"row id width {width} not in {{1,2,4,8}}")
    body = len(data) - _ROW_HDR.size
    if body != width * n:
        raise HostCodecError(
            f"row id payload holds {body} bytes, header says {n} x {width}"
        )
    return np.frombuffer(
        data, _ROW_DTYPES[width], count=n, offset=_ROW_HDR.size
    ).astype(np.int64)


# ---------------------------------------------------------------------------
# chunked zlib frames (opaque byte payloads: shuffle chunks etc.)
# ---------------------------------------------------------------------------

_ZFRAME_HDR = struct.Struct("<QII")  # raw_len, chunk_bytes, n_chunks
_ZCHUNK_LEN = struct.Struct("<I")

DEFAULT_CHUNK_BYTES = 1 << 20


def compress_chunked(
    data: bytes, level: int = 1, chunk_bytes: int = DEFAULT_CHUNK_BYTES
) -> bytes:
    """zlib-compress ``data`` in bounded chunks. The header records the
    exact raw length and per-chunk compressed lengths, so the decoder can
    bound every read and verify every inflated size."""
    if chunk_bytes <= 0:
        raise HostCodecError(f"chunk_bytes must be positive, got {chunk_bytes}")
    chunks = [
        zlib.compress(data[i : i + chunk_bytes], level)
        for i in range(0, len(data), chunk_bytes)
    ]
    return b"".join(
        [_ZFRAME_HDR.pack(len(data), chunk_bytes, len(chunks))]
        + [_ZCHUNK_LEN.pack(len(c)) for c in chunks]
        + chunks
    )


def decompress_chunked(data: bytes) -> bytes:
    """Inverse of :func:`compress_chunked`; truncation, length lies, and
    corrupt deflate streams all raise :class:`HostCodecError`."""
    if len(data) < _ZFRAME_HDR.size:
        raise HostCodecError(
            f"zlib frame shorter than its {_ZFRAME_HDR.size}-byte header"
        )
    raw_len, chunk_bytes, n_chunks = _ZFRAME_HDR.unpack_from(data)
    if chunk_bytes <= 0:
        raise HostCodecError(f"zlib frame declares chunk_bytes {chunk_bytes}")
    expect_chunks = max(0, -(-raw_len // chunk_bytes))
    if n_chunks != expect_chunks:
        raise HostCodecError(
            f"zlib frame declares {n_chunks} chunks for {raw_len} raw bytes "
            f"at {chunk_bytes}/chunk (expected {expect_chunks})"
        )
    off = _ZFRAME_HDR.size
    lens = []
    for _ in range(n_chunks):
        if off + _ZCHUNK_LEN.size > len(data):
            raise HostCodecError("zlib frame truncated inside its chunk table")
        (clen,) = _ZCHUNK_LEN.unpack_from(data, off)
        lens.append(clen)
        off += _ZCHUNK_LEN.size
    if off + sum(lens) != len(data):
        raise HostCodecError(
            f"zlib frame holds {len(data) - off} chunk bytes, chunk table "
            f"says {sum(lens)}"
        )
    out = []
    for i, clen in enumerate(lens):
        want = min(chunk_bytes, raw_len - i * chunk_bytes)
        try:
            raw = zlib.decompress(data[off : off + clen])
        except zlib.error as e:
            raise HostCodecError(f"corrupt zlib chunk {i}: {e}") from e
        if len(raw) != want:
            raise HostCodecError(
                f"zlib chunk {i} inflated to {len(raw)} bytes, expected {want}"
            )
        out.append(raw)
        off += clen
    return b"".join(out)
