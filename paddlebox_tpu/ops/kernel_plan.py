"""Per-shape kernel implementation selection for the sparse hot path.

The pull/push hot path has two implementations per op — XLA's native
gather/scatter lowering and the hand-tuned Pallas row-DMA kernels
(ops/pallas_kernels.py) — and the winner is SHAPE-DEPENDENT: the measured
v5p numbers (pallas_kernels.py docstring) have XLA winning at the CTR
flagship shape while per-row DMA amortizes better at wide rows, and the
scatter-sweep non-monotonicity (tools/op_probe.py, SCATTER_NOTES) says the
crossover moves with table width. A single hand-picked heuristic (the old
``_use_pallas``: one bool flag + alignment check) can't express that, so
selection is a REGISTRY lookup instead:

    (op, backend, shape bucket: table rows x width x batch-unique-keys)
        -> implementation {"native", "pallas"}

Plans load from a JSON artifact (``kernel_plan_path`` flag; the committed
default is ``tools/kernel_plan.json``, regenerated from op_probe sweep
artifacts by ``tools/tune_kernels.py``) with deterministic built-in
defaults when no artifact exists. Row and unique-key counts bucket to
ceil-log2 so a plan entry covers a 2x shape band — the same pad-bucket
granularity the batch packer already quantizes to (``batch_bucket_rounding``
keeps repeated shapes compile-cache-stable, so per-bucket choice is also
per-compilation choice).

Correctness constraints are enforced HERE, not trusted to the artifact: a
plan may *prefer* pallas, but selection clamps to native unless the backend
is TPU, the width is lane-aligned, the index count is block-aligned, and
(push only) rows are unique — a hand-edited artifact can never route an
ineligible shape into a kernel that would miscompile.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from paddlebox_tpu.utils.monitor import STAT_ADD

# Mosaic alignment facts the Pallas kernels require (pallas_kernels.py
# imports these back, so the eligibility clamp and the kernels themselves
# can never disagree): rows must be DMA-sliceable out of a lane-tiled HBM
# memref (width % LANE == 0) and the grid unrolls BLK rows per step.
PALLAS_LANE = 128
PALLAS_BLK = 8

OPS = ("pull", "push")
IMPLS = ("native", "pallas")

PLAN_VERSION = 1


def log2_bucket(n: int) -> int:
    """Ceil-log2 shape bucket: all n in (2^(k-1), 2^k] share bucket k."""
    n = int(n)
    if n <= 1:
        return 0
    return (n - 1).bit_length()


def current_backend() -> str:
    """The default jax backend name, or "none" before/without one."""
    try:
        import jax

        return jax.default_backend()
    # absence probe: "none" IS the answer (dispatch falls back to XLA ops)
    # pbox-lint: disable=EXC007
    except Exception:  # pragma: no cover - no backend at all
        return "none"


# lookup probe order per (op, backend): exact bucket first, then wildcard
# uniq, wildcard rows, width-only, and finally the (op, backend) catch-all
_PROBE_ORDER = (
    (True, True, True),
    (True, True, False),
    (True, False, True),
    (True, False, False),
    (False, False, False),
)


@dataclass(frozen=True)
class PlanEntry:
    """One routing decision. ``None`` fields are wildcards."""

    op: str
    backend: str
    impl: str
    width: Optional[int] = None
    rows_log2: Optional[int] = None
    uniq_log2: Optional[int] = None
    why: str = ""

    def key(self) -> Tuple:
        return (self.op, self.backend, self.width, self.rows_log2, self.uniq_log2)

    def as_dict(self) -> Dict:
        d = {"op": self.op, "backend": self.backend, "impl": self.impl}
        for f in ("width", "rows_log2", "uniq_log2"):
            v = getattr(self, f)
            if v is not None:
                d[f] = v
        if self.why:
            d["why"] = self.why
        return d


@dataclass
class KernelPlan:
    """Immutable-after-construction (op, backend, shape-bucket) -> impl map.

    ``fallback`` is the impl preferred when no entry matches — "native" by
    default; ``default_plan`` maps the legacy ``use_pallas_sparse`` flag to
    a pallas fallback so the old opt-in keeps working bit-for-bit.
    """

    entries: List[PlanEntry] = field(default_factory=list)
    fallback: str = "native"
    source: str = "builtin-default"

    def __post_init__(self):
        if self.fallback not in IMPLS:
            raise ValueError(f"fallback {self.fallback!r} not in {IMPLS}")
        self._index: Dict[Tuple, str] = {}
        for e in self.entries:
            if e.op not in OPS:
                raise ValueError(f"plan entry op {e.op!r} not in {OPS}")
            if e.impl not in IMPLS:
                raise ValueError(f"plan entry impl {e.impl!r} not in {IMPLS}")
            k = e.key()
            if k in self._index:
                raise ValueError(f"duplicate plan entry for {k}")
            self._index[k] = e.impl

    # ---- selection -------------------------------------------------------

    def preferred(
        self, op: str, backend: str, n_rows: int, width: int, n_idx: int
    ) -> str:
        """Registry answer BEFORE the eligibility clamp (artifact intent)."""
        r, u = log2_bucket(n_rows), log2_bucket(n_idx)
        for use_w, use_r, use_u in _PROBE_ORDER:
            k = (
                op,
                backend,
                width if use_w else None,
                r if use_r else None,
                u if use_u else None,
            )
            impl = self._index.get(k)
            if impl is not None:
                return impl
        return self.fallback

    def select(
        self,
        op: str,
        backend: str,
        n_rows: int,
        width: int,
        n_idx: int,
        unique_rows: bool = True,
    ) -> str:
        """Implementation for one op instance; deterministic in its inputs.

        Runs at trace time (shapes are static), so the returned choice is
        baked into the compiled program — one selection per compilation,
        not per step.
        """
        if op not in OPS:
            raise ValueError(f"unknown op {op!r}; known: {OPS}")
        impl = self.preferred(op, backend, n_rows, width, n_idx)
        if impl == "pallas" and not pallas_eligible(
            op, backend, width, n_idx, unique_rows
        ):
            STAT_ADD("kernel_plan.pallas_clamped")
            impl = "native"
        STAT_ADD("kernel_plan.selects")
        if impl == "pallas":
            STAT_ADD("kernel_plan.selects_pallas")
        return impl

    # ---- (de)serialization ----------------------------------------------

    def to_json(self) -> Dict:
        return {
            "version": PLAN_VERSION,
            "fallback": self.fallback,
            "source": self.source,
            "entries": [e.as_dict() for e in self.entries],
        }

    @classmethod
    def from_json(cls, doc: Dict, source: str = "json") -> "KernelPlan":
        if int(doc.get("version", PLAN_VERSION)) != PLAN_VERSION:
            raise ValueError(
                f"kernel plan version {doc.get('version')} != {PLAN_VERSION}"
            )
        entries = [
            PlanEntry(
                op=e["op"],
                backend=e["backend"],
                impl=e["impl"],
                width=e.get("width"),
                rows_log2=e.get("rows_log2"),
                uniq_log2=e.get("uniq_log2"),
                why=e.get("why", ""),
            )
            for e in doc.get("entries", [])
        ]
        return cls(
            entries=entries,
            fallback=doc.get("fallback", "native"),
            source=doc.get("source", source),
        )

    def save(self, path: str) -> None:
        from paddlebox_tpu.utils.fs import atomic_write

        with atomic_write(path, "w") as f:
            json.dump(self.to_json(), f, indent=1, sort_keys=True)
            f.write("\n")

    @classmethod
    def load(cls, path: str) -> "KernelPlan":
        with open(path) as f:
            doc = json.load(f)
        plan = cls.from_json(doc)
        # operational provenance: artifacts that embed plan.source must say
        # which FILE routed the run; the file's own "source" field keeps the
        # generation story (tune_kernels invocation) inside the artifact
        plan.source = path
        return plan


def pallas_eligible(
    op: str, backend: str, width: int, n_idx: int, unique_rows: bool = True
) -> bool:
    """Hard constraints for routing into the Pallas kernels (see module
    docstring; these are correctness bounds, not preferences)."""
    if backend != "tpu":
        return False
    if width % PALLAS_LANE != 0 or n_idx % PALLAS_BLK != 0:
        return False
    if op == "push" and not unique_rows:
        # the pallas writeback is per-row SET: duplicates with differing
        # contents would be last-write-wins instead of merged
        return False
    return True


def default_plan() -> KernelPlan:
    """Deterministic built-in plan.

    Maps the legacy ``use_pallas_sparse`` opt-in onto the registry: flag on
    -> prefer pallas everywhere it is eligible (the old gate's exact
    semantics, alignment clamp included); flag off -> native everywhere.
    """
    from paddlebox_tpu import config

    prefer_pallas = bool(config.get_flag("use_pallas_sparse"))
    return KernelPlan(
        entries=[],
        fallback="pallas" if prefer_pallas else "native",
        source="builtin-default"
        + (":use_pallas_sparse" if prefer_pallas else ""),
    )


# ---- process-wide cached plan ------------------------------------------
#
# Selection runs on the jit trace path, so the plan must be a cheap dict
# lookup: resolve (flag -> file -> plan) once and cache until the flag or
# the opt-in changes. invalidate_plan() drops the cache (tests, re-tune).

_lock = threading.Lock()
_cached: Optional[Tuple[Tuple, KernelPlan]] = None  # guarded-by: _lock


def _default_artifact_path() -> str:
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    return os.path.join(repo, "tools", "kernel_plan.json")


def resolve_plan_path(flag_value: str) -> Optional[str]:
    """kernel_plan_path flag -> artifact path or None (builtin defaults).

    "auto" uses the committed tools/kernel_plan.json when present; "" / "off"
    forces the builtin defaults; anything else is an explicit path and must
    exist — a typo'd path silently falling back would un-tune the hot path.
    """
    v = (flag_value or "").strip()
    if v in ("", "off", "none"):
        return None
    if v == "auto":
        p = _default_artifact_path()
        return p if os.path.exists(p) else None
    if not os.path.exists(v):
        raise FileNotFoundError(
            f"kernel_plan_path={v!r} does not exist (use 'auto' or 'off' "
            "for defaults)"
        )
    return v


def get_plan() -> KernelPlan:
    """The active plan (cached; keyed on the path flag + pallas opt-in)."""
    from paddlebox_tpu import config

    global _cached
    key = (
        str(config.get_flag("kernel_plan_path")),
        bool(config.get_flag("use_pallas_sparse")),
    )
    with _lock:
        if _cached is not None and _cached[0] == key:
            return _cached[1]
    path = resolve_plan_path(key[0])
    plan = KernelPlan.load(path) if path is not None else default_plan()
    with _lock:
        _cached = (key, plan)
    return plan


def invalidate_plan() -> None:
    """Drop the cached plan (next get_plan() re-resolves flag + file)."""
    global _cached
    with _lock:
        _cached = None
