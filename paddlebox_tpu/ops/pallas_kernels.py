"""Pallas TPU kernels for the sparse pull/push hot path.

The reference's hot path is hand-written CUDA (PullCopy/PushCopy and the
dedup scatter-gather family, box_wrapper.cu:31-800). On TPU the equivalent
ops are row gathers/writebacks over the pass working-set array; XLA's
take/scatter lowerings are the baseline, and these Pallas kernels are the
hand-tuned alternative doing **explicit row DMA**: the row-id vector is
scalar-prefetched (PrefetchScalarGridSpec), the table stays unblocked in
HBM (memory_space=ANY), and each grid step issues ``make_async_copy`` for a
block of rows — all copies in flight concurrently before one wait
(box_wrapper.cu's coalesced gather, TPU idiom).

Mosaic constrains *blocked* specs to (8, 128)-aligned tiles, which a
(1, width) row gather can't satisfy — manual DMA from ANY space has no such
constraint, so arbitrary row widths work.

Integration: ops/pull_push.py routes through these when
``config.get_flag("use_pallas_sparse")`` is on, the backend is TPU, and the
table width is lane-aligned (W % 128 == 0 — Mosaic cannot slice narrower
rows out of a lane-tiled HBM memref); CPU tests run interpret mode.

Measured (v5p single chip, R=1M x W=128, U=160k rows): XLA take 2.8 ms vs
this kernel 9.2 ms; scatter-set 7.4 ms. XLA's native gather wins at CTR
shapes, so the flag DEFAULTS OFF and the kernels stand as correct,
benchmarked infrastructure for wider-row layouts where per-row DMA
amortizes better — re-measure before enabling in production.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_BLK = 8  # rows per grid step (also the out-block sublane size)
LANE = 128  # Mosaic lane width: table rows must be a multiple to DMA-slice


def _gather_kernel(rows_ref, table_ref, out_ref, sems):
    i = pl.program_id(0)
    for j in range(_BLK):  # static unroll: _BLK concurrent row DMAs
        r = rows_ref[i * _BLK + j]
        pltpu.make_async_copy(table_ref.at[r], out_ref.at[j], sems.at[j]).start()
    for j in range(_BLK):
        r = rows_ref[i * _BLK + j]
        pltpu.make_async_copy(table_ref.at[r], out_ref.at[j], sems.at[j]).wait()


@functools.partial(jax.jit, static_argnames=("interpret",))
def pull_rows_pallas(
    table: jnp.ndarray,  # [R, W] f32
    rows: jnp.ndarray,  # [U] int32 row ids (duplicates fine); U % 8 == 0
    interpret: bool = False,
) -> jnp.ndarray:
    """Gather ``table[rows]`` -> [U, W] via explicit HBM->VMEM row DMAs."""
    U = rows.shape[0]
    R, W = table.shape
    if U % _BLK != 0:
        raise ValueError(
            f"U={U} must be a multiple of {_BLK} (pad with the padding row)"
        )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(U // _BLK,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],  # whole table, HBM
        out_specs=pl.BlockSpec((_BLK, W), lambda i, rows_ref: (i, 0)),
        scratch_shapes=[pltpu.SemaphoreType.DMA((_BLK,))],
    )
    return pl.pallas_call(
        _gather_kernel,
        out_shape=jax.ShapeDtypeStruct((U, W), table.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(rows.astype(jnp.int32), table)


def _writeback_kernel(rows_ref, table_in_ref, new_rows_ref, out_ref, sems):
    del table_in_ref  # aliased with out_ref; untouched rows pass through
    i = pl.program_id(0)
    for j in range(_BLK):
        r = rows_ref[i * _BLK + j]
        pltpu.make_async_copy(new_rows_ref.at[j], out_ref.at[r], sems.at[j]).start()
    for j in range(_BLK):
        r = rows_ref[i * _BLK + j]
        pltpu.make_async_copy(new_rows_ref.at[j], out_ref.at[r], sems.at[j]).wait()


@functools.partial(jax.jit, static_argnames=("interpret",))
def write_rows_pallas(
    table: jnp.ndarray,  # [R, W] f32 (in-place via pallas aliasing when the
    # caller's enclosing jit donates it; no eager-level donation here)
    rows: jnp.ndarray,  # [U] int32 row ids; U % 8 == 0
    new_rows: jnp.ndarray,  # [U, W] updated row contents
    interpret: bool = False,
) -> jnp.ndarray:
    """Write updated rows back into the table (PushCopy writeback analog).

    Rows must be unique EXCEPT for repeats carrying byte-identical contents
    (the packer's padding-row repeats) — the push path merges real
    duplicates first (PushMergeCopy parity), so per-row set semantics is
    exact. The table aliases in/out: untouched rows never move.
    """
    U, W = new_rows.shape
    if U % _BLK != 0:
        raise ValueError(f"U={U} must be a multiple of {_BLK}")
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(U // _BLK,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # table (aliased out)
            pl.BlockSpec((_BLK, W), lambda i, rows_ref: (i, 0)),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((_BLK,))],
    )
    return pl.pallas_call(
        _writeback_kernel,
        out_shape=jax.ShapeDtypeStruct(table.shape, table.dtype),
        grid_spec=grid_spec,
        input_output_aliases={1: 0},  # table (first arg after scalars) -> out
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
        interpret=interpret,
    )(rows.astype(jnp.int32), table, new_rows)


def backend_is_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False
