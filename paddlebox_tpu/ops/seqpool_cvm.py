"""Fused sequence-pool + CVM over ragged slot batches.

Parity with the reference's fused_seqpool_cvm op family
(operators/fused/fused_seqpool_cvm_op.cu): per (slot, instance) sum-pool of
the pulled key records, then the CVM transform on the leading show/click
columns:

    out[0] = log(show_sum + 1)
    out[1] = log(clk_sum + 1) - log(show_sum + 1)        (join phase, use_cvm)
    out[2:] passthrough
  or, update phase (use_cvm=False): strip the first two columns
  (FusedCVMKernelNoCVM, fused_seqpool_cvm_op.cu:166-182).

Options mirrored: pad_value, need_filter (drop keys failing
(show-clk)*show_coeff + clk*clk_coeff >= threshold, :90-118), clk_filter
(join with show only, :145-164), quant_ratio (round(v*q)/q, :60-88),
embed_threshold_filter variant (`_with_diff_thres`).

The ragged pooling is a segment-sum over host-precomputed segment ids
(slot * batch + ins), which XLA lowers to a single scatter-add — the
device-side bookkeeping the reference does in CUDA lives in the host packer
here. Autodiff provides the backward (the reference hand-writes it).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def cvm_transform(pooled: jnp.ndarray, use_cvm: bool = True) -> jnp.ndarray:
    """CVM on pooled records [..., width]: show/clk -> log CTR features.

    Parity: cvm_op (operators/cvm_op.h:26-38) and FusedCVMKernelWithCVM.
    """
    show = pooled[..., 0:1]
    clk = pooled[..., 1:2]
    log_show = jnp.log(show + 1.0)
    log_clk = jnp.log(clk + 1.0)
    if use_cvm:
        return jnp.concatenate([log_show, log_clk - log_show, pooled[..., 2:]], axis=-1)
    return pooled[..., 2:]


def cvm_with_conv_transform(
    pooled: jnp.ndarray, use_cvm: bool = True, show_filter: bool = False
) -> jnp.ndarray:
    """CVM for CONV layouts [show, clk, conv, ...] (cvm_offset 4 family).

    Parity with FusedCVMWithConvKernelNormal / WithOutShow
    (fused_seqpool_cvm_with_conv_op.cu:55-110):
      out = [log(show+1), log(clk+1), log(conv+1) - log(clk+1), rest]
      show_filter drops the show column (join-with-show-only mode).
    """
    if not use_cvm:
        return pooled[..., 3:]
    log_show = jnp.log(pooled[..., 0:1] + 1.0)
    log_clk = jnp.log(pooled[..., 1:2] + 1.0)
    log_conv = jnp.log(pooled[..., 2:3] + 1.0)
    cols = [log_show, log_clk, log_conv - log_clk, pooled[..., 3:]]
    if show_filter:
        cols = cols[1:]
    return jnp.concatenate(cols, axis=-1)


def cvm_with_pcoc_transform(
    pooled: jnp.ndarray, pclk_num: int = 3, use_cvm: bool = True
) -> jnp.ndarray:
    """CVM for PCOC layouts [show, clk, join_show, join_clk, pclk*, ...]
    (cvm_offset 2 + 2 + pclk_num).

    Parity with FusedCVMWithPCOCKernelWithCVM
    (fused_seqpool_cvm_with_pcoc_op.cu:120-155):
      out[0]              = log(show+1)
      out[1]              = log(clk+1) - log(show+1)
      out[2 : 2+p]        = log(pclk_k+1) - log(join_show+1)
      out[2+p : 2+2p]     = log(pclk_k+1) - log(join_clk+1)
      rest                  passthrough (the embedx block)
    """
    cvm_in = 4 + pclk_num
    if not use_cvm:
        return pooled[..., cvm_in:]
    log_show = jnp.log(pooled[..., 0:1] + 1.0)
    log_clk = jnp.log(pooled[..., 1:2] + 1.0)
    log_jshow = jnp.log(pooled[..., 2:3] + 1.0)
    log_jclk = jnp.log(pooled[..., 3:4] + 1.0)
    log_pclk = jnp.log(pooled[..., 4:cvm_in] + 1.0)
    return jnp.concatenate(
        [
            log_show,
            log_clk - log_show,
            log_pclk - log_jshow,
            log_pclk - log_jclk,
            pooled[..., cvm_in:],
        ],
        axis=-1,
    )


def _seqpool(
    records: jnp.ndarray,
    segments: jnp.ndarray,
    num_slots: int,
    batch_size: int,
    pad_value: float,
    need_filter: bool,
    show_coeff: float,
    clk_coeff: float,
    threshold,  # float, or per-slot [num_slots] vector (diff_thres variant)
    quant_ratio: Optional[int],
    cvm_cols: int = 2,
) -> jnp.ndarray:
    """Shared sum-pool half: filter/quant at key level, then segment-sum.
    Returns [num_slots, batch, width]."""
    vals = records
    if need_filter:
        # key-level filter on raw show/clk (SeqPoolKernelEmbedQuantFilter;
        # per-slot thresholds = FusedSeqpoolKernelDiffThresFilter,
        # fused_seqpool_cvm_with_diff_thres_op.cu:92-118)
        score = (vals[:, 0] - vals[:, 1]) * show_coeff + vals[:, 1] * clk_coeff
        thr = jnp.asarray(threshold, jnp.float32)
        if thr.ndim == 1:
            slot_of_key = jnp.minimum(segments // batch_size, num_slots - 1)
            thr = thr[slot_of_key]
        keep = score >= thr
        vals = jnp.where(keep[:, None], vals, 0.0)
    if quant_ratio:
        q = float(quant_ratio)
        head = vals[:, :cvm_cols]
        tail = jnp.round(vals[:, cvm_cols:] * q) / q
        vals = jnp.concatenate([head, tail], axis=1)

    num_segments = num_slots * batch_size
    pooled = jax.ops.segment_sum(vals, segments, num_segments=num_segments + 1)
    pooled = pooled[:num_segments].reshape(num_slots, batch_size, -1)
    if pad_value != 0.0:
        # slots with zero keys for an instance pool to pad_value, not 0
        ones = jax.ops.segment_sum(
            jnp.ones((records.shape[0],), records.dtype),
            segments,
            num_segments=num_segments + 1,
        )[:num_segments].reshape(num_slots, batch_size)
        pooled = jnp.where((ones == 0)[..., None], pad_value, pooled)
    return pooled


def fused_seqpool_cvm(
    records: jnp.ndarray,  # [L, width] pulled per-key records (flat, padded)
    segments: jnp.ndarray,  # int32 [L] = slot * batch + ins; pads -> num_segments
    num_slots: int,
    batch_size: int,
    use_cvm: bool = True,
    pad_value: float = 0.0,
    need_filter: bool = False,
    show_coeff: float = 0.2,
    clk_coeff: float = 1.0,
    threshold: float = 0.96,
    quant_ratio: Optional[int] = None,
    clk_filter: bool = False,
) -> jnp.ndarray:
    """-> [batch, num_slots, out_width] pooled + CVM'd slot features.

    ``segments`` may contain the value ``num_slots * batch_size`` for padded
    entries; those rows fall into a trash segment that is dropped.
    """
    pooled = _seqpool(
        records, segments, num_slots, batch_size, pad_value,
        need_filter, show_coeff, clk_coeff, threshold, quant_ratio,
    )
    out = cvm_transform(pooled, use_cvm=use_cvm)
    if use_cvm and clk_filter:
        # join with show only: drop the click column (col 1)
        out = jnp.concatenate([out[..., 0:1], out[..., 2:]], axis=-1)
    return jnp.transpose(out, (1, 0, 2))  # -> [batch, slots, width]


def fused_seqpool_cvm_with_diff_thres(
    records: jnp.ndarray,
    segments: jnp.ndarray,
    num_slots: int,
    batch_size: int,
    threshold_vec,  # [num_slots] per-slot filter thresholds
    use_cvm: bool = True,
    pad_value: float = 0.0,
    show_coeff: float = 0.2,
    clk_coeff: float = 1.0,
    quant_ratio: Optional[int] = None,
    clk_filter: bool = False,
) -> jnp.ndarray:
    """Per-slot-threshold variant (fused_seqpool_cvm_with_diff_thres_op.cu):
    identical to fused_seqpool_cvm but the key filter compares against the
    key's slot's threshold."""
    return fused_seqpool_cvm(
        records, segments, num_slots, batch_size,
        use_cvm=use_cvm, pad_value=pad_value, need_filter=True,
        show_coeff=show_coeff, clk_coeff=clk_coeff,
        threshold=threshold_vec, quant_ratio=quant_ratio, clk_filter=clk_filter,
    )


def fused_seqpool_cvm_with_conv(
    records: jnp.ndarray,  # [L, width] CONV layout: [show, clk, conv, embedx...]
    segments: jnp.ndarray,
    num_slots: int,
    batch_size: int,
    use_cvm: bool = True,
    pad_value: float = 0.0,
    show_filter: bool = False,
) -> jnp.ndarray:
    """CONV (q-value) variant -> [batch, slots, out_width]
    (fused_seqpool_cvm_with_conv_op.cu; cvm_offset 4, box_wrapper.h:526)."""
    pooled = _seqpool(
        records, segments, num_slots, batch_size, pad_value,
        False, 0.0, 0.0, 0.0, None, cvm_cols=3,
    )
    out = cvm_with_conv_transform(pooled, use_cvm=use_cvm, show_filter=show_filter)
    return jnp.transpose(out, (1, 0, 2))


def fused_seqpool_cvm_with_pcoc(
    records: jnp.ndarray,  # [L, width] PCOC layout (cvm_offset 4 + pclk_num)
    segments: jnp.ndarray,
    num_slots: int,
    batch_size: int,
    pclk_num: int = 3,
    use_cvm: bool = True,
    pad_value: float = 0.0,
    quant_ratio: Optional[int] = None,
) -> jnp.ndarray:
    """PCOC variant -> [batch, slots, out_width]
    (fused_seqpool_cvm_with_pcoc_op.cu; cvm_offset 8 = 4 + 3 pclk + embed_w
    packing per box_wrapper.h:524)."""
    pooled = _seqpool(
        records, segments, num_slots, batch_size, pad_value,
        False, 0.0, 0.0, 0.0, quant_ratio, cvm_cols=4 + pclk_num,
    )
    out = cvm_with_pcoc_transform(pooled, pclk_num=pclk_num, use_cvm=use_cvm)
    return jnp.transpose(out, (1, 0, 2))
