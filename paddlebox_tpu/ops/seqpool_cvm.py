"""Fused sequence-pool + CVM over ragged slot batches.

Parity with the reference's fused_seqpool_cvm op family
(operators/fused/fused_seqpool_cvm_op.cu): per (slot, instance) sum-pool of
the pulled key records, then the CVM transform on the leading show/click
columns:

    out[0] = log(show_sum + 1)
    out[1] = log(clk_sum + 1) - log(show_sum + 1)        (join phase, use_cvm)
    out[2:] passthrough
  or, update phase (use_cvm=False): strip the first two columns
  (FusedCVMKernelNoCVM, fused_seqpool_cvm_op.cu:166-182).

Options mirrored: pad_value, need_filter (drop keys failing
(show-clk)*show_coeff + clk*clk_coeff >= threshold, :90-118), clk_filter
(join with show only, :145-164), quant_ratio (round(v*q)/q, :60-88),
embed_threshold_filter variant (`_with_diff_thres`).

The ragged pooling is a segment-sum over host-precomputed segment ids
(slot * batch + ins), which XLA lowers to a single scatter-add — the
device-side bookkeeping the reference does in CUDA lives in the host packer
here. Autodiff provides the backward (the reference hand-writes it).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def cvm_transform(pooled: jnp.ndarray, use_cvm: bool = True) -> jnp.ndarray:
    """CVM on pooled records [..., width]: show/clk -> log CTR features.

    Parity: cvm_op (operators/cvm_op.h:26-38) and FusedCVMKernelWithCVM.
    """
    show = pooled[..., 0:1]
    clk = pooled[..., 1:2]
    log_show = jnp.log(show + 1.0)
    log_clk = jnp.log(clk + 1.0)
    if use_cvm:
        return jnp.concatenate([log_show, log_clk - log_show, pooled[..., 2:]], axis=-1)
    return pooled[..., 2:]


def fused_seqpool_cvm(
    records: jnp.ndarray,  # [L, width] pulled per-key records (flat, padded)
    segments: jnp.ndarray,  # int32 [L] = slot * batch + ins; pads -> num_segments
    num_slots: int,
    batch_size: int,
    use_cvm: bool = True,
    pad_value: float = 0.0,
    need_filter: bool = False,
    show_coeff: float = 0.2,
    clk_coeff: float = 1.0,
    threshold: float = 0.96,
    quant_ratio: Optional[int] = None,
    clk_filter: bool = False,
) -> jnp.ndarray:
    """-> [batch, num_slots, out_width] pooled + CVM'd slot features.

    ``segments`` may contain the value ``num_slots * batch_size`` for padded
    entries; those rows fall into a trash segment that is dropped.
    """
    vals = records
    if need_filter:
        # key-level filter on raw show/clk (SeqPoolKernelEmbedQuantFilter)
        keep = (vals[:, 0] - vals[:, 1]) * show_coeff + vals[:, 1] * clk_coeff >= threshold
        vals = jnp.where(keep[:, None], vals, 0.0)
    if quant_ratio:
        q = float(quant_ratio)
        head = vals[:, :2]
        tail = jnp.round(vals[:, 2:] * q) / q
        vals = jnp.concatenate([head, tail], axis=1)

    num_segments = num_slots * batch_size
    pooled = jax.ops.segment_sum(vals, segments, num_segments=num_segments + 1)
    pooled = pooled[:num_segments].reshape(num_slots, batch_size, -1)
    if pad_value != 0.0:
        # slots with zero keys for an instance pool to pad_value, not 0
        ones = jax.ops.segment_sum(
            jnp.ones((records.shape[0],), records.dtype), segments, num_segments=num_segments + 1
        )[:num_segments].reshape(num_slots, batch_size)
        pooled = jnp.where((ones == 0)[..., None], pad_value, pooled)

    out = cvm_transform(pooled, use_cvm=use_cvm)
    if use_cvm and clk_filter:
        # join with show only: drop the click column (col 1)
        out = jnp.concatenate([out[..., 0:1], out[..., 2:]], axis=-1)
    return jnp.transpose(out, (1, 0, 2))  # -> [batch, slots, width]
