"""CTR-specific dense ops: rank_attention, batch_fc, fused_concat.

These are the remaining B13 ops (SURVEY.md): position-aware attention over
pv-merged ad lists and per-"channel" batched FC. The reference hand-writes
CUDA forward+backward for each (operators/rank_attention_op.cu + .cu.h,
batch_fc_op.cu, fused/fused_concat_op.cu); here each forward is a
gather + einsum that XLA fuses and batches onto the MXU, and autodiff
produces the (gather/scatter-transposed) backward.
"""

from __future__ import annotations

import jax.numpy as jnp


def rank_attention(
    x: jnp.ndarray,  # [B, F] per-ad input features
    rank_offset: jnp.ndarray,  # int32 [B, 2*max_rank+1]
    rank_param: jnp.ndarray,  # [max_rank*max_rank*F, C] position-pair blocks
    max_rank: int = 3,
) -> jnp.ndarray:
    """Position-pair attention over pv-grouped ads -> [B, C].

    Semantics (rank_attention.cu.h:27-112 expand kernels; python wrapper
    contrib/layers/nn.py:1337):

    - ``rank_offset[i, 0]``        = 1-based rank of ad i in its pv (0 = no pv)
    - ``rank_offset[i, 2k+1]``     = 1-based rank of the k-th peer ad (0 = absent)
    - ``rank_offset[i, 2k+2]``     = row of that peer in ``x``
    - ``rank_param`` reshaped [max_rank(own), max_rank(peer), F, C]: a weight
      block per (own-rank, peer-rank) pair.

        out[i] = Σ_k  x[peer_k(i)] @ rank_param[own(i), peer_rank_k(i)]

    Absent peers and rankless instances contribute zero, exactly like the
    reference's zero-filled input_help/param_help expansion.
    """
    B, F = x.shape
    C = rank_param.shape[-1]
    param = rank_param.reshape(max_rank, max_rank, F, C)

    own = rank_offset[:, 0] - 1  # [B] -1 = invalid
    peer_rank = rank_offset[:, 1::2] - 1  # [B, R]
    peer_idx = rank_offset[:, 2::2]  # [B, R]
    valid = (own[:, None] >= 0) & (peer_rank >= 0)  # [B, R]

    x_exp = x[jnp.clip(peer_idx, 0, B - 1)]  # [B, R, F]
    x_exp = jnp.where(valid[..., None], x_exp, 0.0)
    blocks = param[jnp.clip(own, 0, max_rank - 1)[:, None],
                   jnp.clip(peer_rank, 0, max_rank - 1)]  # [B, R, F, C]
    blocks = jnp.where(valid[..., None, None], blocks, 0.0)
    return jnp.einsum("brf,brfc->bc", x_exp, blocks)


def batch_fc(
    x: jnp.ndarray,  # [B, batchcount * in_feat]
    w: jnp.ndarray,  # [in_feat, batchcount * out_feat]
    bias: jnp.ndarray,  # [batchcount * out_feat]
    batchcount: int,
) -> jnp.ndarray:
    """Per-channel FC -> [B, batchcount * out_feat].

    Channel k maps x[:, k*in : (k+1)*in] through w[:, k*out : (k+1)*out]
    plus bias — the reference's strided BatchedGEMM + row-add
    (batch_fc_op.cu:121-188). One einsum keeps all channels in a single
    MXU-batched matmul.
    """
    B = x.shape[0]
    in_feat = x.shape[1] // batchcount
    out_feat = w.shape[1] // batchcount
    xb = x.reshape(B, batchcount, in_feat)
    wb = w.reshape(in_feat, batchcount, out_feat)
    out = jnp.einsum("bki,iko->bko", xb, wb)
    return (out + bias.reshape(1, batchcount, out_feat)).reshape(B, -1)


def fused_concat(
    xs,  # sequence of [B, D] tensors (equal D)
    offset: int,
    length: int,
) -> jnp.ndarray:
    """Concat columns [offset, offset+length) of every input -> [B, n*length]
    (fused_concat_op.cu:207-260). The typical use slices the embedx block out
    of several pulled slot tensors in one op."""
    return jnp.concatenate([x[:, offset : offset + length] for x in xs], axis=1)
