"""Device-carried pass table: keep trained rows in HBM across passes.

The classic pass boundary is symmetric and expensive on a bandwidth-limited
host<->TPU transport: EndPass fetches the WHOLE trained table to the host
(writeback), and the next finalize uploads the WHOLE new table back — yet in
CTR streams consecutive passes share most of their keys (the reference keeps
its HBM cache warm across passes for exactly this reason, EndPass
box_wrapper.cc:627-651). The carrier exploits the overlap:

- at ``end_pass`` the trained DEVICE array is retained (no D2H);
- at the next finalize, rows whose keys survive into the new working set are
  SPLICED device-to-device into the new pass table (with the boundary's
  show/clk decay applied on device), rows whose keys leave are fetched and
  pushed to the host store (D2H of only the departing slice), and only
  genuinely new keys pull host rows and upload (H2D of only the new slice);
- the host store lags by at most the carried rows; every save/export path
  drains pending carriers first (``HostSparseTable.drain_pending``), so
  anything durable still sees the trained values.

Semantic deltas vs the classic boundary, both bounded and documented:
- shrink: a carried key is exempt from the boundary's cold-key drop while it
  stays carried (it is by definition active in the next pass; the host row
  it would have been judged by is stale anyway). With shrink_threshold=0 the
  paths are bit-equivalent.
- durability: between boundary and flush, the host store holds pre-pass
  values for carried keys. ``flush`` (directly, or via drain_pending from
  any save) restores full host fidelity.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np


class TableCarrier:
    """One pass's trained device table, pending splice-or-flush.

    Built at ``end_pass`` (no transfer), consumed by the next finalize
    (splice) and/or ``flush`` (full writeback). The carrier stays alive
    after the splice so a mid-pass save can still flush everything the host
    is owed (= this table's values; the NEXT pass's training is on its own
    live array and is owed nothing until its own end_pass).
    """

    def __init__(self, dev_flat, ws, layout, decay: Optional[float] = None):
        # dev_flat: jax [rows, width] — the single-device trained table, or
        # a single-host mesh table [ns, cap, W] flattened (stays sharded;
        # global row ids = shard*cap + rank index it directly)
        if dev_flat.ndim == 3:
            dev_flat = dev_flat.reshape(-1, dev_flat.shape[-1])
        self.dev_flat = dev_flat
        self.ws = ws
        self.layout = layout
        # accumulated show/clk decay owed to carried rows: each host-side
        # decay_and_shrink that runs while this carrier is pending calls
        # note_decay (HostSparseTable.decay_and_shrink does it under the
        # maintenance lock, so a carrier can never miss or double-count a
        # boundary). An eval pass keeping a carrier alive across TWO
        # boundaries accumulates two decays, exactly like its host rows
        # would have.
        self._decay_accum = 1.0 if decay is None else float(decay)
        self._flushed = False
        # in-flight background departure push. The lock covers the handle
        # only (install/claim/peek); waiting on the future itself happens
        # outside it, so wait_push (boundary prefetch thread) and
        # join_push (end_pass worker) can block concurrently.
        self._push_lock = threading.Lock()
        self._push_fut = None  # guarded-by: _push_lock
        # ws-order positions already handed back to the host (departures):
        # flush must not re-push them — once a key departs, the host row is
        # live again (later passes may train it) and a re-push of this
        # carrier's older value would overwrite that
        self._departed: Optional[np.ndarray] = None

    @property
    def flushed(self) -> bool:
        return self._flushed

    def note_decay(self, rate: float) -> None:
        """Record one boundary's show/clk decay (applied at splice/flush)."""
        self._decay_accum *= float(rate)

    def supersede(self) -> None:
        """A newer full writeback (classic end_pass or a successor carrier)
        covers every value this carrier owed: join the in-flight departure
        push, release the HBM reference, and go inert."""
        self.join_push()
        self._flushed = True
        self.dev_flat = None

    def _decay_mult(self) -> Optional[np.ndarray]:
        if self._decay_accum == 1.0:
            return None
        lay = self.layout
        mult = np.ones(lay.width, dtype=np.float32)
        mult[lay.SHOW] = self._decay_accum
        mult[lay.CLK] = self._decay_accum
        return mult

    def rows_for(self, positions: np.ndarray):
        """Device rows (decayed) for ws-order key positions [k] — stays on
        device; the caller splices it into the next pass table."""
        import jax.numpy as jnp

        vals = self.dev_flat[self.ws.row_of_sorted[positions]]
        mult = self._decay_mult()
        if mult is not None:
            vals = vals * jnp.asarray(mult)[None, :]
        return vals

    def fetch_for(self, positions: np.ndarray) -> np.ndarray:
        """Host copy (decayed) of ws-order key positions — the departing
        slice's D2H. Honors the ``wire_dtype`` flag: bf16/int8 shrinks the
        bytes on the transport (Quant pull-value parity,
        box_wrapper.cc:419-437)."""
        from paddlebox_tpu import config
        from paddlebox_tpu.ops.wire_quant import fetch_rows

        return fetch_rows(
            self.rows_for(positions),
            self.layout,
            str(config.get_flag("wire_dtype")),
        )

    def push_departures_async(self, table, keys: np.ndarray, positions) -> None:
        """Push the departing slice on a background thread: the D2H (the
        expensive part on a tunneled transport) overlaps the next pass's
        load/train instead of stalling the boundary. The device gather
        dispatches NOW (so it reads this table's values, not anything
        later); only the host fetch + push run on the worker. Joined by
        flush(), and by the next end_pass before host decay (a late push
        landing after a decay would un-decay those rows)."""
        from concurrent.futures import Future

        from paddlebox_tpu import config
        from paddlebox_tpu.ops.wire_quant import (
            fetch_rows_finish,
            fetch_rows_start,
        )

        mode = str(config.get_flag("wire_dtype"))
        # quantizing casts dispatch NOW (they must read this table's
        # values); only the blocking D2H + push run on the worker
        handle = fetch_rows_start(self.rows_for(positions), self.layout, mode)
        pos = np.asarray(positions)
        self._departed = (
            pos if self._departed is None else np.union1d(self._departed, pos)
        )
        fut: Future = Future()

        def work():
            try:
                table.push(keys, fetch_rows_finish(handle, self.layout))
                fut.set_result(len(keys))
            except BaseException as e:
                fut.set_exception(e)

        # non-daemon so interpreter exit joins an in-flight push; join_push
        # retires the handle once the future settles
        th = threading.Thread(target=work, daemon=False)
        th.start()
        with self._push_lock:
            self._push_fut = (fut, pos)
            self._push_thread = th

    def join_push(self) -> None:
        """Wait for an in-flight departure push (idempotent).

        A FAILED push un-departs its positions: the host never received
        those rows, so they must stay owed — a later flush() retry
        re-pushes them (drain_pending keeps this carrier registered on
        failure). Without this, the departed-exclusion in flush would
        silently drop exactly the rows whose push failed."""
        with self._push_lock:
            fut_pos, self._push_fut = self._push_fut, None
            th = getattr(self, "_push_thread", None)
            self._push_thread = None
        if fut_pos is not None:
            fut, pos = fut_pos
            try:
                fut.result()
            except BaseException:
                self._departed = (
                    np.setdiff1d(self._departed, pos)
                    if self._departed is not None
                    else None
                )
                raise
            finally:
                if th is not None:
                    th.join()

    def wait_push(self) -> None:
        """Block until any in-flight departure push lands, WITHOUT
        consuming the handle or its failure.

        The boundary prefetch must not read a departing key's pre-push
        host row, so it waits here first — but error handling (un-depart +
        raise) belongs to join_push on the end_pass path, so a failure is
        swallowed and stays armed. (A failed push fails the boundary
        there, and the supervisor's revert discards the staged prefetch.)
        """
        with self._push_lock:
            fut_pos = self._push_fut
        if fut_pos is not None:
            try:
                fut_pos[0].result()
            # deferred handling by design (docstring): the failure stays
            # armed in the future and join_push raises + un-departs it
            # pbox-lint: disable=EXC007
            except BaseException:
                pass

    def flush(self, table) -> int:
        """Push every carried key's (decayed) value to the host store.

        Idempotent; returns keys written. Called by drain_pending from any
        save/export path, by rollback arming, and at close/day boundaries."""
        self.join_push()
        if self._flushed or self.ws is None or self.ws.n_keys == 0:
            self._flushed = True
            self.dev_flat = None
            return 0
        pos = np.arange(self.ws.n_keys)
        if self._departed is not None:
            pos = np.setdiff1d(pos, self._departed, assume_unique=True)
        # chunked: one full-table gather + host copy at once would double
        # peak memory exactly at the save points where a snapshot copy is
        # already resident; fixed-size chunks bound the transient
        chunk = 2_000_000
        for lo in range(0, len(pos), chunk):
            p = pos[lo : lo + chunk]
            table.push(self.ws.sorted_keys[p], self.fetch_for(p))
        self._flushed = True
        self.dev_flat = None  # release the HBM reference
        return len(pos)


class _ShardView:
    """Key->row view over ONE device's shard block of a multi-host pass
    table: duck-types the ``ws`` surface TableCarrier reads (sorted_keys /
    row_of_sorted / n_keys). Rows are LOCAL to the device block
    (local_shard * cap + rank)."""

    def __init__(self, keys_per_shard, cap: int):
        ks, rows = [], []
        for j, k in enumerate(keys_per_shard):
            ks.append(k)
            rows.append(j * cap + np.arange(len(k), dtype=np.int64))
        keys = (
            np.concatenate(ks) if ks else np.zeros(0, np.uint64)
        )
        lrows = (
            np.concatenate(rows) if rows else np.zeros(0, np.int64)
        )
        order = np.argsort(keys)
        self.sorted_keys = keys[order]
        self.row_of_sorted = lrows[order]
        self.n_keys = len(keys)


class MultiHostCarrier:
    """Per-host device-carried pass table over a DistributedWorkingSet.

    The reference's EndPass keeps the HBM cache warm on EVERY node
    (box_wrapper.cc:627-651); here the same holds because ownership is
    structurally local: key -> mesh shard is a stable hash and shards pin
    to devices, so a key that survives into the next pass lands on the
    SAME device, and a key that departs is owed to THIS host's table slice
    (DistributedWorkingSet writeback is host-local by construction,
    dist_ws.py:20-22). The global trained table therefore decomposes into
    one independent TableCarrier per local device (its addressable shard
    block), each splicing / fetching / flushing purely locally — no
    cross-host traffic, no collective at the boundary.

    Registry-facing surface (flushed / note_decay / flush / supersede /
    join_push) delegates to the per-device carriers, so
    ``HostSparseTable.drain_pending`` and the decay bookkeeping treat this
    exactly like a single-host carrier.
    """

    def __init__(self, global_table, owned_shard_keys, layout,
                 ownership_epoch: int = 0):
        # global_table: jax [ns, cap, W] sharded on axis 0 over the mesh;
        # only this process's addressable shard blocks are touched.
        # owned_shard_keys: the ending pass's per-local-shard key lists
        # (DistributedWorkingSet.owned_shard_keys) — snapshotted into
        # per-device _ShardViews; the working set itself is NOT retained.
        # ownership_epoch pins the shard->host placement this snapshot was
        # taken under: a later finalize under a DIFFERENT epoch must not
        # splice these blocks (the ranges re-homed) — it flushes instead
        # (DistributedWorkingSet.finalize checks the pin).
        self.layout = layout
        self.ownership_epoch = int(ownership_epoch)
        self.sharding = global_table.sharding
        self.ns, self.cap, self.width = global_table.shape
        shards = sorted(
            global_table.addressable_shards,
            key=lambda s: s.index[0].start or 0,
        )
        if not shards:
            raise ValueError("no addressable shards on this process")
        self.shards_per_dev = shards[0].data.shape[0]
        self.devices = [s.data.devices().pop() for s in shards]
        # shard j of this host's owned_shard_keys belongs to device
        # j // shards_per_dev at block-local shard j % shards_per_dev
        self.parts = []
        spd = self.shards_per_dev
        for d, s in enumerate(shards):
            view = _ShardView(
                owned_shard_keys[d * spd : (d + 1) * spd], self.cap
            )
            dev_flat = s.data.reshape(spd * self.cap, self.width)
            self.parts.append(TableCarrier(dev_flat, view, layout))

    @property
    def flushed(self) -> bool:
        return all(c.flushed for c in self.parts)

    def note_decay(self, rate: float) -> None:
        for c in self.parts:
            c.note_decay(rate)

    def supersede(self) -> None:
        for c in self.parts:
            c.supersede()

    def join_push(self) -> None:
        # join ALL in-flight pushes even if one raises, then surface the
        # first failure (its positions are un-departed by TableCarrier)
        err = None
        for c in self.parts:
            try:
                c.join_push()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                err = err or e
        if err is not None:
            raise err

    def wait_push(self) -> None:
        for c in self.parts:
            c.wait_push()

    def flush(self, table) -> int:
        n = 0
        for c in self.parts:
            n += c.flush(table)
        return n
