from paddlebox_tpu.table.value_layout import ValueLayout, FeatureType
from paddlebox_tpu.table.sparse_table import HostSparseTable, PassWorkingSet
from paddlebox_tpu.table.optimizers import SparseOptimizerConfig

__all__ = [
    "ValueLayout",
    "FeatureType",
    "HostSparseTable",
    "PassWorkingSet",
    "SparseOptimizerConfig",
]
