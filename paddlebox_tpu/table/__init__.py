from paddlebox_tpu.table.value_layout import ValueLayout, FeatureType
from paddlebox_tpu.table.sparse_table import (
    HostSparseTable,
    PassWorkingSet,
    SpillIOError,
)
from paddlebox_tpu.table.optimizers import SparseOptimizerConfig
from paddlebox_tpu.table.replica_cache import (
    InputTable,
    ReplicaCache,
    pull_cache_value,
)

__all__ = [
    "ValueLayout",
    "FeatureType",
    "HostSparseTable",
    "PassWorkingSet",
    "SpillIOError",
    "SparseOptimizerConfig",
    "ReplicaCache",
    "InputTable",
    "pull_cache_value",
]
