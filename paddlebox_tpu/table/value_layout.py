"""Feature value layouts.

Parity with the reference's FeaturePullValueGpu/FeaturePushValueGpu template
grid (box_wrapper.cc:400-530 dispatches over embedx_dim × expand_dim ×
feature_type; the struct fields are visible through the copy kernels in
box_wrapper.cu:31-140: [show, clk, embed_w, embedx...] with
cvm_offset selecting how many leading floats flow to the model):

- PLAIN / QUANT / SHOW_CLK : cvm_offset 3  (show, clk, embed_w)
- CONV ("q value")         : cvm_offset 4  (box_wrapper.h:526)
- PCOC                     : cvm_offset 8  (box_wrapper.h:524)
- SHARE_EMBEDDING          : cvm_offset expand_embed_dim + 2 (box_wrapper.h:521)

Here the layout is a plain column map over one fp32 row per key, shared by
the host store and the device pass table:

    [show, clk, cvm_extra..., embed_w, embedx[D], embed_g2, embedx_g2]

The *pull* slice the model sees is the first ``cvm_offset + D`` columns
(hidden = cvm_offset + embedx_dim, matching CheckEmbedSizeIsValid,
box_wrapper.cc:442). Optimizer state (g2 sums) trails and never leaves the
table.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class FeatureType(enum.Enum):
    PLAIN = "plain"
    QUANT = "quant"
    SHOW_CLK = "show_clk"
    CONV = "conv"
    PCOC = "pcoc"
    SHARE_EMBEDDING = "share_embedding"
    # var-dim embeddings (box_wrapper.cc:419-437 selects a VARIABLE layout;
    # the per-key dim policy lives in the closed lib). Open re-expression:
    # a key's effective embedx dim unlocks in quarters as its show count
    # crosses doubling thresholds — embedx_threshold*1/2/4/8 for
    # 1/4, 1/2, 3/4, full dim — so cold keys spend HBM bandwidth on short
    # vectors and hot keys get the full embedding. Same row width; the
    # masking happens in the pull (ops/pull_push.py).
    VARIABLE = "variable"


_CVM_OFFSET = {
    FeatureType.PLAIN: 3,
    FeatureType.QUANT: 3,
    FeatureType.SHOW_CLK: 3,
    FeatureType.CONV: 4,
    FeatureType.PCOC: 8,
    FeatureType.VARIABLE: 3,
}

# embedx dims the reference compiles kernels for (box_wrapper.cc:444-457);
# informative only — any D works here since XLA specializes at trace time.
REFERENCE_EMBEDX_DIMS = (0, 8, 16, 32, 64, 128, 256, 280)
REFERENCE_EXPAND_DIMS = (0, 8, 64)


@dataclass(frozen=True)
class ValueLayout:
    embedx_dim: int = 8
    expand_embed_dim: int = 0
    feature_type: FeatureType = FeatureType.PLAIN

    @property
    def cvm_offset(self) -> int:
        if self.feature_type == FeatureType.SHARE_EMBEDDING:
            return self.expand_embed_dim + 2
        return _CVM_OFFSET[self.feature_type]

    # --- column indices ---
    SHOW = 0
    CLK = 1

    @property
    def embed_w_col(self) -> int:
        # embed_w is the last of the cvm block (after show/clk and any
        # conv/pcoc extras)
        return self.cvm_offset - 1

    @property
    def embedx_col(self) -> int:
        return self.cvm_offset

    @property
    def expand_col(self) -> int:
        """First column of the expand-embedding block (B12 extended pull:
        pull_box_extended_sparse returns (emb, expand_emb) per slot). Empty
        unless expand_embed_dim > 0 with a non-SHARE_EMBEDDING type —
        SHARE_EMBEDDING folds its expand dims into the cvm block instead."""
        return self.cvm_offset + self.embedx_dim

    @property
    def expand_dim(self) -> int:
        if self.feature_type == FeatureType.SHARE_EMBEDDING:
            return 0
        return self.expand_embed_dim

    @property
    def embed_g2_col(self) -> int:
        return self.cvm_offset + self.embedx_dim + self.expand_dim

    @property
    def embedx_g2_col(self) -> int:
        return self.embed_g2_col + 1

    @property
    def expand_g2_col(self) -> int:
        if self.expand_dim == 0:
            raise ValueError("layout has no expand block")
        return self.embed_g2_col + 2

    @property
    def width(self) -> int:
        """Total fp32 columns per key in the table (incl. optimizer state)."""
        return (
            self.cvm_offset
            + self.embedx_dim
            + self.expand_dim
            + 2
            + (1 if self.expand_dim else 0)
        )

    @property
    def pull_width(self) -> int:
        """Columns the model sees per key (= hidden size of pull tensors)."""
        return self.cvm_offset + self.embedx_dim

    @property
    def push_width(self) -> int:
        """Per-key push record: [show, clk, grads for cvm-extras+embed_w+embedx].

        Mirrors FeaturePushValueGpu (show, clk, embed_g, embedx_g[D]).
        """
        return self.cvm_offset + self.embedx_dim

    @property
    def extended_push_width(self) -> int:
        """Extended push record: push_width + expand grads appended
        (FeaturePushValueGpu expand variants, box_wrapper.cc:466-530)."""
        return self.push_width + self.expand_dim
