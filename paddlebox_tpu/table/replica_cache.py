"""Full-replica device caches: ReplicaCache + string-keyed InputTable (B16).

Parity targets (box_wrapper.h:140-248):

- ``GpuReplicaCache``: host threads accumulate fixed-dim float rows during
  data load (``AddItems`` returns the row id, which replaces the feasign in
  the parsed record); ``ToHBM`` replicates the whole table to every device;
  the ``pull_cache_value`` op then gathers rows by id inside the step. The
  cache is pass-scoped — BoxWrapper creates one per pass
  (box_wrapper.cc:585-607) — and suits small/dense-ish side embeddings where
  full replication beats sharded pull.

- ``InputTable``: string key -> row of floats, CPU-resident, with a reserved
  default row 0 (key "-") returned on miss (miss counter kept). The
  reference's LookupInput is itself a host gather (D2H keys -> memcpy rows
  -> H2D, box_wrapper.h:217-232), so a host-side ``lookup_input`` plus an
  optional device replica is strictly faster than parity.

TPU shape: ``to_device`` returns one jnp array; under a mesh pass a MeshPlan
and it is placed replicated (every chip holds the full table — the XLA
analog of the per-GPU cudaMemcpy loop in ToHBM). Row ids travel through the
normal uint64 slot pipeline, so batches need no new plumbing.

Note: the reference's InputTable stores *element* offsets into one flat
float vector (key_offset_[key] = table_.size()); row ids are the same
information divided by dim, kept as rows here for direct gather use.
"""

from __future__ import annotations

import threading
from typing import List, Optional

import numpy as np

from paddlebox_tpu.utils.monitor import STAT_GET, STAT_SET

try:  # jax only needed for to_device / device gathers
    import jax
    import jax.numpy as jnp
# optional-dependency gate: host-only mode keeps the numpy rows
# pbox-lint: disable=EXC007
except Exception:  # pragma: no cover
    jax = jnp = None


class ReplicaCache:
    """GpuReplicaCache analog: append-only host rows -> replicated device array."""

    def __init__(self, dim: int):
        self.dim = dim
        self._rows: List[np.ndarray] = []  # guarded-by: _lock
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:  # load threads append concurrently (AddItems parity)
            return len(self._rows)

    def add_items(self, emb) -> int:
        """Append one row; returns its id (AddItems parity, thread-safe).

        Strictly one row: a ``[1, dim]`` input squeezes, anything else
        multi-dimensional is rejected HERE with both shapes named. (The
        old ``reshape(-1)`` silently flattened e.g. a ``[2, dim/2]`` block
        into one wrong row, deferring the crash — or worse, the wrong
        gather — to scoring time.)"""
        row = np.asarray(emb, dtype=np.float32)
        if row.ndim == 2 and row.shape[0] == 1:
            row = row[0]
        if row.ndim != 1:
            raise ValueError(
                f"add_items wants one row of shape ({self.dim},), got shape "
                f"{row.shape} — use add_batch for [n, dim] blocks"
            )
        if row.shape[0] != self.dim:
            raise ValueError(f"row dim {row.shape[0]} != cache dim {self.dim}")
        with self._lock:
            self._rows.append(row)
            return len(self._rows) - 1

    def add_batch(self, rows) -> np.ndarray:
        """Append a ``[n, dim]`` block in one locked operation; returns the
        assigned row ids (int64 [n]). The bulk path the serving scoring
        table uses to materialize a snapshot without n lock round-trips."""
        block = np.asarray(rows, dtype=np.float32)
        if block.ndim != 2:
            raise ValueError(
                f"add_batch wants a [n, {self.dim}] block, got shape "
                f"{block.shape} — use add_items for single rows"
            )
        if block.shape[1] != self.dim:
            raise ValueError(
                f"add_batch got dim-mismatched rows: shape {block.shape} "
                f"vs cache dim {self.dim}"
            )
        block = np.ascontiguousarray(block)
        with self._lock:
            start = len(self._rows)
            self._rows.extend(block)  # row views share the block's buffer
            return np.arange(start, start + len(block), dtype=np.int64)

    def host_array(self) -> np.ndarray:
        with self._lock:
            if not self._rows:
                return np.zeros((0, self.dim), dtype=np.float32)
            return np.stack(self._rows)

    def to_device(self, plan=None) -> "jnp.ndarray":
        """Replicate to device(s) (ToHBM parity). With a MeshPlan the array
        is placed replicated across the mesh."""
        host = self.host_array()
        if plan is not None:
            from paddlebox_tpu.parallel.mesh import put_replicated

            return put_replicated(plan, host)
        return jnp.asarray(host)

    def mem_used_mb(self) -> float:
        with self._lock:
            return len(self._rows) * self.dim * 4 / 1024.0 / 1024.0

    def publish_serve_stats(self) -> None:
        """Export size under the serving dashboard namespace. Called by the
        scoring table on every version commit, so ``serve.replica_rows`` /
        ``serve.replica_mem_mb`` always describe the cache backing the
        CURRENTLY served version."""
        with self._lock:
            n = len(self._rows)
        STAT_SET("serve.replica_rows", n)
        STAT_SET("serve.replica_mem_mb", n * self.dim * 4 / 1024.0 / 1024.0)
        # cumulative lookup misses snapshotted at each commit: the delta
        # between two commits is the miss volume the OUTGOING version
        # served, which is what a per-version miss-rate dashboard needs
        STAT_SET("serve.key_misses_at_commit", float(STAT_GET("serve.key_misses")))
        # same snapshot for the device hot tier's fallback volume, so the
        # per-version dashboards split "not hot enough for the tier" from
        # "never published" without differencing two raw counters
        STAT_SET(
            "serve.device_tier_misses_at_commit",
            float(STAT_GET("serve.device_tier_misses")),
        )


def pull_cache_value(cache: "jnp.ndarray", ids: "jnp.ndarray") -> "jnp.ndarray":
    """Gather cache rows by id — the pull_cache_value op
    (pull_box_sparse_op.h:55-73 -> GpuReplicaCache::PullCacheValue)."""
    return jnp.take(cache, ids.astype(jnp.int32), axis=0)


class InputTable:
    """String-keyed side-input table with default row 0 on miss."""

    DEFAULT_KEY = "-"

    def __init__(self, dim: int):
        self.dim = dim
        self._key_row = {}  # guarded-by: _lock
        self._rows: List[np.ndarray] = []  # guarded-by: _lock
        self._lock = threading.Lock()
        self._miss = 0  # guarded-by: _lock
        self.add_index_data(self.DEFAULT_KEY, np.zeros(dim, np.float32))

    def __len__(self) -> int:
        with self._lock:
            return len(self._key_row)

    @property
    def miss(self) -> int:
        with self._lock:  # ordered against parse-thread get_index_offset
            return self._miss

    def add_index_data(self, key: str, vec) -> int:
        row = np.asarray(vec, dtype=np.float32).reshape(-1)
        if row.shape[0] != self.dim:
            raise ValueError(f"row dim {row.shape[0]} != table dim {self.dim}")
        with self._lock:
            if key in self._key_row:  # last write wins, stable row id
                rid = self._key_row[key]
                self._rows[rid] = row
                return rid
            rid = len(self._rows)
            self._key_row[key] = rid
            self._rows.append(row)
            return rid

    def get_index_offset(self, key: str) -> int:
        """Row id for ``key``; 0 (default row) and miss++ when absent
        (GetIndexOffset parity). Called at parse/pack time so only int ids
        reach the device pipeline."""
        with self._lock:
            rid = self._key_row.get(key)
            if rid is None:
                self._miss += 1
                return 0
            return rid

    def lookup_input(self, ids: np.ndarray) -> np.ndarray:
        """Host gather of rows by id (LookupInput parity — the reference's
        version is a host gather with device copies around it)."""
        with self._lock:
            table = np.stack(self._rows) if self._rows else np.zeros((0, self.dim), np.float32)
        return table[np.asarray(ids, dtype=np.int64)]

    def to_device(self, plan=None) -> "jnp.ndarray":
        """Device replica for in-step gathers via pull_cache_value."""
        with self._lock:
            host = np.stack(self._rows)
        if plan is not None:
            from paddlebox_tpu.parallel.mesh import put_replicated

            return put_replicated(plan, host)
        return jnp.asarray(host)

    def mem_used_mb(self) -> float:
        with self._lock:
            return len(self._rows) * self.dim * 4 / 1024.0 / 1024.0
