"""Sparse optimizer semantics for the embedding table.

The reference's sparse optimizers live inside the closed libbox_ps.so /
libps.so; the observable contract (value layouts B3, lr_map plumbing
box_wrapper.cc:1234-1241, pslib public accessor configs) is re-derived here:

- per-key scalar AdaGrad on embed_w: g2sum accumulates the squared grad;
  step size = lr * sqrt(initial_g2sum / (initial_g2sum + g2sum))
  (pslib "sparse adagrad" shape: step decays with accumulated energy)
- per-key scalar AdaGrad on the embedx vector, with the *mean* squared grad
  accumulated so one g2 scalar serves the whole vector (keeps table width
  D+cvm+2, matching the single embedx_g2sum in pslib value accessors)
- embedx is gated: inactive until the key's show count reaches
  ``embedx_threshold`` (pslib embedx_threshold; observable in PullCopy's
  ``embedding_size > 0`` branch, box_wrapper.cu:54-63)
- show/clk counters: push adds per-key occurrence counts and click counts;
  pass-boundary decay show *= decay, clk *= decay (pslib show_click_decay_rate)
- slot-wise learning-rate map: slot id -> lr multiplier
  (initialize_gpu_and_load_model lr_map, box_wrapper.cc:1234-1241)

All of this runs **inside the jitted train step** as vectorized column math on
the pass working-set array — the TPU-native replacement for the PS-side
optimizer.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass(frozen=True)
class SparseOptimizerConfig:
    embed_lr: float = 0.05
    embedx_lr: float = 0.05
    initial_g2sum: float = 3.0
    initial_range: float = 1e-4  # embed_w / embedx init uniform(-r, r)
    embedx_threshold: float = 10.0  # show count gating embedx activity
    show_clk_decay: float = 0.98  # per-pass decay on counters
    shrink_threshold: float = 1.0  # drop keys whose decayed show falls below
    weight_bounds: float = 10.0  # |w| clip after update (pslib weight_bounds)
    slot_lr_map: Optional[Dict[int, float]] = None  # slot -> lr multiplier

    def lr_for_slot(self, slot: int) -> float:
        if self.slot_lr_map is None:
            return 1.0
        return self.slot_lr_map.get(slot, 1.0)
