"""Open sparse table: host tiered store + pass-scoped device working set.

The reference links a closed ``libbox_ps.so`` whose observable surface is
BeginFeedPass/EndFeedPass/BeginPass/EndPass/PullSparseGPU/PushSparseGPU/
SaveBase/SaveDelta (box_wrapper.cc:580-1331). This module implements that
surface openly, re-shaped for TPU:

- ``HostSparseTable``: the full 1e9..1e11-key store, sharded by key hash
  across ``n_shards``. Native-backed (csrc/host_table.cc): the RAM tier is
  a C++ open-addressing store, and when constructed with ``spill_dir`` /
  ``mem_cap_rows`` cold rows are evicted to per-shard disk files and
  promoted lazily with catch-up decay — the mem/SSD tiers of BoxPS
  (LoadSSD2Mem, box_wrapper.cc:1325).

- ``PassWorkingSet``: the HBM tier. During load, every feasign of the pass is
  fed in (PSAgent::AddKeys parity, data_set.cc:1647); ``finalize`` dedups,
  pulls rows from the host store, and lays them out as a dense
  ``[n_mesh_shards, capacity, width]`` fp32 array to be placed in device HBM
  sharded over the mesh. Keys map to (mesh_shard, row) by hash, so the
  device-side pull/push is a static-shape gather/scatter and the multi-chip
  routing is a fixed all_to_all — the TPU-native analog of
  PullSparseGPU/PushSparseGPU.

- lookup: batch keys -> dense row ids happens host-side at pack time
  (vectorized searchsorted over the pass's sorted key table), so no hash
  tables ever live on device.

Each mesh shard reserves its last row as the padding row (zero, never written
back): batch padding and dropped-grad scatter both target it.
"""

from __future__ import annotations

import functools
import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu import config
from paddlebox_tpu.table.optimizers import SparseOptimizerConfig
from paddlebox_tpu.table.value_layout import ValueLayout
from paddlebox_tpu.utils.faultinject import InjectedFault, fire as _fault_fire
from paddlebox_tpu.utils.fs import atomic_write
from paddlebox_tpu.utils.monitor import STAT_ADD, STAT_OBSERVE, STAT_SET
from paddlebox_tpu.utils.trace import record_event

config.define_flag(
    "boundary_merge_threads", 4,
    "threads for the chunked pass-boundary key merge; <=1 falls back to "
    "the serial np.unique(np.concatenate(...))",
)
config.define_flag(
    "spill_policy", "freq",
    "victim selection for the RAM->disk cap sweep (maybe_spill): 'freq' "
    "ranks rows by coldness — lowest decayed show first, oldest "
    "last-touched epoch breaking ties — honoring spill_pin_show / "
    "spill_admit_show and balancing the sweep across shards; 'fifo' is "
    "the legacy creation-order sweep (untouched rows first), kept as the "
    "A/B baseline",
)
config.define_flag(
    "spill_pin_show", 0.0,
    "freq policy pin threshold: rows whose decayed show is >= this are "
    "never spilled while any colder victim exists in their shard "
    "(0 disables pinning)",
)
config.define_flag(
    "writeback_threads", 4,
    "writer-pool size for the end-of-pass host-table writeback "
    "(PassWorkingSet.writeback -> pbx_table_push_mt): each worker owns a "
    "disjoint set of shards, bitwise-equal to the serial path at every "
    "value; <=1 is the legacy serial ablation (plain table.push)",
)
config.define_flag(
    "writeback_chunk_keys", 2_000_000,
    "keys per writeback chunk: the trained rows are gathered and pushed "
    "chunk by chunk so the next chunk's gather overlaps the in-flight "
    "push, and a revert can cancel between chunks (rollback's "
    "partial-writeback contract covers whatever landed)",
)
config.define_flag(
    "spill_admit_show", 0.0,
    "freq policy admission threshold: at sweep time every row whose "
    "decayed show is under this is written disk-first instead of holding "
    "a RAM slot until pure cap pressure evicts it — pair with "
    "cache_threshold(rate) to target a resident fraction (0 disables "
    "admission)",
)

_HASH_MULT = np.uint64(0x9E3779B97F4A7C15)

# below this many total keys the serial merge wins (thread dispatch costs
# more than the merge itself)
_MERGE_SERIAL_FLOOR = 262_144


def merge_unique_keys(
    chunks: Sequence[np.ndarray], threads: int = 1
) -> np.ndarray:
    """Sorted-unique union of sorted-unique uint64 chunks.

    Bitwise-identical to ``np.unique(np.concatenate(chunks))`` (the tests
    assert this), but large merges run over deterministic key ranges in a
    thread pool: pivots are quantiles of a sorted strided sample of the
    chunks, every chunk is sliced at those pivots with searchsorted, each
    range unions its slices independently, and the per-range results
    concatenate back in ascending range order.

    A single non-empty chunk is returned AS-IS (no copy): the boundary
    prefetch's validity check is an O(1) identity test against the array a
    premerge() stored, and this fast path is what preserves that identity
    through finalize's re-merge of the singleton chunk list.
    """
    chunks = [c for c in chunks if len(c)]
    if not chunks:
        return np.zeros(0, dtype=np.uint64)
    if len(chunks) == 1:
        return chunks[0]
    total = sum(len(c) for c in chunks)
    threads = int(threads)
    if threads <= 1 or total < _MERGE_SERIAL_FLOOR:
        return np.unique(np.concatenate(chunks))
    n_ranges = min(threads, 16)
    sample = np.sort(
        np.concatenate([c[:: max(1, len(c) // 64)] for c in chunks])
    )
    pivots = sample[(np.arange(1, n_ranges) * len(sample)) // n_ranges]
    bounds = [np.searchsorted(c, pivots, side="left") for c in chunks]

    def _one_range(r: int) -> np.ndarray:
        parts = []
        for ci, c in enumerate(chunks):
            lo = int(bounds[ci][r - 1]) if r else 0
            hi = int(bounds[ci][r]) if r < n_ranges - 1 else len(c)
            if hi > lo:
                parts.append(c[lo:hi])
        if not parts:
            return np.zeros(0, dtype=np.uint64)
        return np.unique(np.concatenate(parts))

    with ThreadPoolExecutor(
        max_workers=n_ranges, thread_name_prefix="key-merge"
    ) as ex:
        ranges = [r for r in ex.map(_one_range, range(n_ranges)) if len(r)]
    if not ranges:
        return np.zeros(0, dtype=np.uint64)
    return np.concatenate(ranges)


@functools.lru_cache(maxsize=8)
def _sharded_zeros_fn(rows: int, width: int, sharding):
    """Compiled born-sharded zeros builder, cached by (shape, sharding) —
    jit caches by function identity, so a fresh lambda per pass boundary
    would re-trace+compile the allocation every boundary."""
    import jax
    import jax.numpy as jnp

    return jax.jit(
        lambda: jnp.zeros((rows, width), dtype=jnp.float32),
        out_shardings=sharding,
    )


def _sharded_zeros(rows: int, width: int, sharding):
    return _sharded_zeros_fn(rows, width, sharding)()


def key_to_shard(keys: np.ndarray, n_shards: int) -> np.ndarray:
    """Mesh/host shard of each key: multiplicative hash then modulo.

    Feasigns are already hashes in production, but cheap mixing keeps
    adversarial/test keys balanced too.
    """
    with np.errstate(over="ignore"):
        mixed = keys.astype(np.uint64) * _HASH_MULT
    return (mixed >> np.uint64(33)).astype(np.int64) % n_shards


class SpillIOError(IOError):
    """Typed disk-tier failure from the spill entry points.

    The native store returns -1 (tier disabled) / -2 (IO failure) from
    ``spill_cold`` / ``compact_spill``; before this type those codes could
    flow upward as plain ints and read as "spilled -2 rows". Carries the
    failing op and raw code; every raise is counted under the
    ``table.spill_errors`` stat.
    """

    def __init__(self, op: str, rc: int, detail: str = ""):
        msg = f"spill tier {op} failed rc={rc}"
        if detail:
            msg = f"{msg} ({detail})"
        super().__init__(msg)
        self.op = op
        self.rc = rc


class WritebackCancelled(RuntimeError):
    """A chunked writeback was cancelled at a chunk boundary (revert path).

    Not an error: the chunks already pushed are exactly the partial
    writeback rollback's PassGuard contract covers ("safe after zero,
    partial, or full writeback"), so the canceller reverts and retries.
    Carries how far the writeback got for the revert log."""

    def __init__(self, done_keys: int, total_keys: int):
        super().__init__(
            f"writeback cancelled at chunk boundary "
            f"({done_keys}/{total_keys} keys pushed)"
        )
        self.done_keys = done_keys
        self.total_keys = total_keys


# flag value -> native policy code (csrc/host_table.cc kSpillFifo/kSpillFreq)
_SPILL_POLICY_CODES = {"fifo": 0, "freq": 1}


class _Shard:
    """One lock-protected hash shard of the host store."""

    __slots__ = ("index", "values", "lock", "touched", "width")

    def __init__(self, width: int):
        self.index: Dict[int, int] = {}
        self.values = np.zeros((0, width), dtype=np.float32)
        self.lock = threading.Lock()
        self.touched: set = set()
        self.width = width

    def _grow(self, need: int) -> None:
        cap = len(self.values)
        if need <= cap:
            return
        new_cap = max(1024, cap * 2, need)
        nv = np.zeros((new_cap, self.width), dtype=np.float32)
        nv[:cap] = self.values
        self.values = nv


class HostSparseTable:
    """Host sharded key -> fp32 row store: the mem + disk tiers of BoxPS.

    Backed by the native C++ store (csrc/host_table.cc) when the toolchain
    is available: batch pull/push run with the GIL released and thread
    across shards, and cold rows spill to per-shard disk files under
    ``spill_dir`` with lazy promotion + catch-up decay (``LoadSSD2Mem``
    parity, box_wrapper.cc:1325). Falls back to a pure-Python dict store
    (no spill) when g++ is unavailable or ``PBOX_NATIVE_TABLE=0``.

    ``mem_cap_rows`` bounds the RAM tier: ``maybe_spill()`` (called by the
    dataset at pass end) evicts cold rows to disk until under the cap.
    """

    def __init__(
        self,
        layout: ValueLayout,
        opt: SparseOptimizerConfig = SparseOptimizerConfig(),
        n_shards: Optional[int] = None,
        seed: int = 0,
        spill_dir: Optional[str] = None,
        mem_cap_rows: Optional[int] = None,
    ):
        if n_shards is None:
            # flag default (6 bits) keeps the historical 64-shard layout
            n_shards = 1 << config.get_flag("sparse_table_shard_bits")
        self.layout = layout
        self.opt = opt
        self.n_shards = n_shards
        self.mem_cap_rows = mem_cap_rows
        self._native = None
        if os.environ.get("PBOX_NATIVE_TABLE", "1") != "0":
            try:
                from paddlebox_tpu.utils import native as _native_mod

                if _native_mod.available():
                    lay = layout
                    n_emb = lay.embedx_dim + lay.expand_dim
                    init_cols = np.concatenate(
                        [
                            [lay.embed_w_col],
                            np.arange(lay.embedx_col, lay.embedx_col + n_emb),
                        ]
                    ).astype(np.int32)
                    if spill_dir:
                        os.makedirs(spill_dir, exist_ok=True)
                    self._native = _native_mod.NativeHostStore(
                        n_shards, lay.width, lay.SHOW, lay.CLK, seed,
                        init_cols, opt.initial_range, spill_dir,
                    )
            except Exception:
                # silent fallback to the Python store loses native batch
                # pull/push AND the disk tier — a box training 10x slower
                # with no signal is the worst failure mode this init has
                STAT_ADD("table.native_init_failures")
                self._native = None
        if self._native is None and spill_dir is not None:
            raise RuntimeError(
                "disk spill requires the native table store "
                "(g++ build failed or PBOX_NATIVE_TABLE=0)"
            )
        self._shards = (
            [] if self._native else [_Shard(layout.width) for _ in range(n_shards)]
        )
        self._rng = np.random.default_rng(seed)
        self._size = 0
        self._size_lock = threading.Lock()
        # device-carried pass tables owing this store a writeback (see
        # table/carrier.py); every durable read path drains them first.
        # _maintenance_lock orders carrier flushes against decay_and_shrink
        # so a carried row's show/clk decay is applied exactly once per
        # boundary no matter when a save drains.
        self._pending_carriers: List = []
        self._maintenance_lock = threading.Lock()
        # pass-boundary decay counter, stamped into every save's meta: a
        # key untouched since its last save still DECAYS at later
        # boundaries, so a resume must catch those rows up (load applies
        # rate**(file_epoch - table_epoch) to existing rows before each
        # delta lands) — else resumed counters run high and everything
        # show-gated (embedx unlock, shrink, cache thresholds) drifts
        self.decay_epochs = 0

    def add_pending_carrier(self, carrier) -> None:
        """Register a TableCarrier whose values the host store is owed."""
        with self._maintenance_lock:
            self._pending_carriers = [
                c for c in self._pending_carriers if not c.flushed
            ]
            self._pending_carriers.append(carrier)

    def drain_pending(self) -> int:
        """Flush every registered carrier (idempotent); returns keys written.

        Called by save/export paths so durable artifacts always include
        device-carried training. A flush that raises must NOT drop the
        failed (or the not-yet-reached) carriers from the registry —
        otherwise a later save_base/save_delta would silently write a
        checkpoint missing device-carried training."""
        with self._maintenance_lock:
            carriers, self._pending_carriers = self._pending_carriers, []
            n = 0
            try:
                while carriers:
                    c = carriers[0]
                    n += c.flush(self)
                    carriers.pop(0)
            finally:
                if carriers:  # failed + unflushed: keep them owed
                    self._pending_carriers = carriers + self._pending_carriers
        return n

    @property
    def native(self) -> bool:
        return self._native is not None

    @property
    def mem_rows(self) -> int:
        return self._native.mem_rows if self._native else self._size

    @property
    def disk_rows(self) -> int:
        return self._native.disk_rows if self._native else 0

    def spill_cold(self, max_mem_rows: int) -> int:
        """Evict cold rows to disk until RAM tier <= max_mem_rows.

        Victim selection follows the ``spill_policy`` flag: ``freq`` ranks
        by coldness (lowest decayed show, then oldest last-touched epoch)
        with the ``spill_pin_show`` / ``spill_admit_show`` thresholds
        active; ``fifo`` is the legacy creation-order sweep. Raises
        :class:`SpillIOError` (counted under ``table.spill_errors``) when
        the disk tier is disabled or a shard file write fails.
        """
        if self._native is None:
            raise RuntimeError("spill requires the native table store")
        policy = str(config.get_flag("spill_policy"))
        code = _SPILL_POLICY_CODES.get(policy)
        if code is None:
            raise ValueError(
                f"unknown spill_policy {policy!r} (expected 'freq' or 'fifo')"
            )
        try:
            _fault_fire("spill.io")
        except InjectedFault as e:
            STAT_ADD("table.spill_errors", 1)
            raise SpillIOError("spill_cold", -2, str(e)) from e
        # separate site for the double-buffered stage writer: an injected
        # failure here models the staged fwrite handoff dying mid-sweep
        # (native rc -2 from the flusher thread) without shifting spill.io
        # hit counts for plans armed against the sweep entry itself
        try:
            _fault_fire("spill.stage_flush")
        except InjectedFault as e:
            STAT_ADD("table.spill_errors", 1)
            raise SpillIOError("stage_flush", -2, str(e)) from e
        n = self._native.spill_cold(
            max_mem_rows,
            policy=code,
            pin_show=float(config.get_flag("spill_pin_show")),
            admit_show=float(config.get_flag("spill_admit_show")),
        )
        if n < 0:
            STAT_ADD("table.spill_errors", 1)
            raise SpillIOError(
                "spill_cold", n,
                "disk tier disabled (no spill_dir)" if n == -1
                else "shard spill-file write failed",
            )
        return n

    def maybe_spill(self) -> int:
        """Enforce ``mem_cap_rows`` if configured (pass-end hook)."""
        if self.mem_cap_rows is None or self._native is None:
            return 0
        return self.spill_cold(self.mem_cap_rows)

    def compact_spill(self) -> int:
        """Reclaim dead spill-file space (records superseded by promotes).

        spill_cold compacts a shard automatically once dead records
        outnumber live ones; this forces it everywhere — call at day
        boundaries. Returns live records kept; raises SpillIOError on a
        shard rewrite failure (the failed shard keeps its old file)."""
        if self._native is None:
            return 0
        n = self._native.compact_spill()
        if n == -1:  # tier disabled: nothing to reclaim
            return 0
        if n < 0:
            STAT_ADD("table.spill_errors", 1)
            raise SpillIOError("compact_spill", n, "shard rewrite failed")
        return n

    def spill_stats(self) -> tuple:
        """(live_records, dead_records, file_bytes) of the disk tier."""
        if self._native is None:
            return (0, 0, 0)
        return self._native.spill_stats()

    def tier_stats(self) -> dict:
        """Tiered-store occupancy + cumulative flow counters.

        Totals over all shards for each field of
        ``native.TIER_STAT_FIELDS`` (mem_rows, disk_rows, spilled_total,
        promoted_total, admitted_disk_first, lazy_shrunk, dead_records,
        spill_bytes), the per-shard maxima of the two occupancy columns
        (skew telltales), and the full per-shard vectors under
        ``"per_shard"``. The Python fallback reports mem occupancy only.
        """
        from paddlebox_tpu.utils.native import TIER_STAT_FIELDS

        if self._native is not None:
            per = self._native.tier_stats()
        else:
            per = np.zeros((self.n_shards, len(TIER_STAT_FIELDS)), np.int64)
            for i, sh in enumerate(self._shards):
                with sh.lock:
                    per[i, 0] = len(sh.index)
        out = {f: int(per[:, i].sum()) for i, f in enumerate(TIER_STAT_FIELDS)}
        out["mem_rows_max_shard"] = int(per[:, 0].max()) if len(per) else 0
        out["disk_rows_max_shard"] = int(per[:, 1].max()) if len(per) else 0
        out["per_shard"] = {
            f: per[:, i].tolist() for i, f in enumerate(TIER_STAT_FIELDS)
        }
        return out

    def publish_tier_stats(self) -> dict:
        """Export :meth:`tier_stats` totals as ``table.tier.*`` STAT gauges
        (per-shard vectors stay in the returned dict — stat names must be
        literals, so shard-indexed gauges are out by design)."""
        st = self.tier_stats()
        STAT_SET("table.tier.mem_rows", st["mem_rows"])
        STAT_SET("table.tier.disk_rows", st["disk_rows"])
        STAT_SET("table.tier.spilled_total", st["spilled_total"])
        STAT_SET("table.tier.promoted_total", st["promoted_total"])
        STAT_SET("table.tier.admitted_disk_first", st["admitted_disk_first"])
        STAT_SET("table.tier.lazy_shrunk", st["lazy_shrunk"])
        STAT_SET("table.tier.dead_records", st["dead_records"])
        STAT_SET("table.tier.spill_bytes", st["spill_bytes"])
        STAT_SET("table.tier.mem_rows_max_shard", st["mem_rows_max_shard"])
        STAT_SET("table.tier.disk_rows_max_shard", st["disk_rows_max_shard"])
        if self._native is not None:
            # where the writeback/spill IO time went: the gather-vs-fwrite
            # split of the double-buffered stage writers plus the push
            # pre-pass header reads (cumulative, from the native tier)
            io = self._native.io_stats()
            STAT_SET("table.writeback.spill_gather_s",
                     io["spill_gather_ns"] / 1e9)
            STAT_SET("table.writeback.spill_fwrite_s",
                     io["spill_fwrite_ns"] / 1e9)
            STAT_SET("table.writeback.prepass_read_s",
                     io["prepass_read_ns"] / 1e9)
            STAT_SET("table.writeback.stage_flushes", io["stage_flushes"])
            STAT_SET("table.writeback.stage_bytes", io["stage_bytes"])
        return st

    def __len__(self) -> int:
        if self._native is not None:
            return len(self._native)
        return self._size

    def keys(self) -> np.ndarray:
        """All keys currently stored (mem + disk tiers), unsorted.
        Keys-only exports on both backends: no value-matrix copies, no
        disk reads."""
        if self._native is not None:
            parts = [self._native.shard_keys(s) for s in range(self.n_shards)]
        else:
            parts = []
            for sh in self._shards:
                with sh.lock:
                    parts.append(
                        np.fromiter(
                            sh.index.keys(), dtype=np.uint64, count=len(sh.index)
                        )
                    )
        return np.concatenate(parts) if parts else np.zeros(0, np.uint64)

    def _init_rows(self, n: int) -> np.ndarray:
        lay = self.layout
        rows = np.zeros((n, lay.width), dtype=np.float32)
        r = self.opt.initial_range
        rows[:, lay.embed_w_col] = self._rng.uniform(-r, r, size=n)
        n_emb = lay.embedx_dim + lay.expand_dim  # expand block trails embedx
        rows[:, lay.embedx_col : lay.embedx_col + n_emb] = self._rng.uniform(
            -r, r, size=(n, n_emb)
        )
        return rows

    def pull_or_create(self, keys: np.ndarray) -> np.ndarray:
        """Rows for unique ``keys`` (creating missing ones). [n, width]."""
        if self._native is not None:
            return self._native.pull_or_create(keys)
        out = np.empty((len(keys), self.layout.width), dtype=np.float32)
        shard_ids = key_to_shard(keys, self.n_shards)
        created = 0
        for s in range(self.n_shards):
            sel = np.nonzero(shard_ids == s)[0]
            if len(sel) == 0:
                continue
            shard = self._shards[s]
            with shard.lock:
                idx = shard.index
                # pure-Python fallback path (native store unavailable):
                # .tolist() converts uint64->int in C so dict lookups stay
                # as cheap as the interpreter allows
                klist = keys[sel].tolist()
                get = idx.get
                rows = np.fromiter(
                    (get(k, -1) for k in klist), dtype=np.int64, count=len(klist)
                )
                miss = np.nonzero(rows < 0)[0]
                if len(miss):
                    base = len(idx)
                    shard._grow(base + len(miss))
                    init = self._init_rows(len(miss))
                    new_rows = base + np.arange(len(miss))
                    for mj, j in zip(new_rows, miss):
                        idx[klist[j]] = int(mj)
                    shard.values[new_rows] = init
                    rows[miss] = new_rows
                    created += len(miss)
                out[sel] = shard.values[rows]
        if created:
            with self._size_lock:
                self._size += created
        return out

    def shows_peek(self, keys: np.ndarray) -> np.ndarray:
        """Decayed show counts for ``keys`` without creating, promoting or
        touching anything. f32 [n]; keys on the disk tier or absent read 0.

        This is the hotness source of the adaptive ICI wire (a key is hot
        when its decayed show clears ``ici_hot_show``): a pure mem-tier
        peek, because spill policy only evicts cold rows — a hot key that
        somehow sits on disk just rides int8 until its next pull, which is
        the graceful-degrade contract anyway. Keeping the read side-effect
        free means the wire heuristic can never perturb tier state."""
        if self._native is not None:
            return self._native.shows_peek(keys)
        out = np.zeros(len(keys), dtype=np.float32)
        shard_ids = key_to_shard(keys, self.n_shards)
        show_col = self.layout.SHOW
        for s in range(self.n_shards):
            sel = np.nonzero(shard_ids == s)[0]
            if len(sel) == 0:
                continue
            shard = self._shards[s]
            with shard.lock:
                get = shard.index.get
                klist = keys[sel].tolist()
                rows = np.fromiter(
                    (get(k, -1) for k in klist), dtype=np.int64, count=len(klist)
                )
                hit = rows >= 0
                if hit.any():
                    out[sel[hit]] = shard.values[rows[hit], show_col]
        return out

    def prefetch_rows(self, keys: np.ndarray) -> Tuple[np.ndarray, int]:
        """Pull/create rows for a STAGED next pass; returns (rows, epoch).

        Held under the maintenance lock so the row snapshot and the decay
        epoch stamp agree — no concurrent ``decay_and_shrink`` (an
        overlapped end_pass worker's) or carrier drain can land between
        the pull and the stamp. The boundary consumer then compensates
        exactly ``decay_epochs - epoch`` decays onto the prefetched rows;
        rows created here have show=clk=0, so the extra decays are bitwise
        no-ops on them.
        """
        with self._maintenance_lock:
            return self.pull_or_create(keys), self.decay_epochs

    def push(self, keys: np.ndarray, rows: np.ndarray) -> None:
        """Write back full rows for existing keys (end-of-pass flush)."""
        if self._native is not None:
            self._native.push(keys, rows)
            return
        shard_ids = key_to_shard(keys, self.n_shards)
        created = 0
        for s in range(self.n_shards):
            sel = np.nonzero(shard_ids == s)[0]
            if len(sel) == 0:
                continue
            shard = self._shards[s]
            t_shard = time.perf_counter()
            with shard.lock:
                idx = shard.index
                klist = keys[sel].tolist()
                get = idx.get
                trows = np.fromiter(
                    (get(k, -1) for k in klist), dtype=np.int64, count=len(klist)
                )
                miss = np.nonzero(trows < 0)[0]
                if len(miss):
                    base = len(idx)
                    shard._grow(base + len(miss))
                    new_rows = base + np.arange(len(miss))
                    for mj, j in zip(new_rows, miss):
                        idx[klist[j]] = int(mj)
                    trows[miss] = new_rows
                    created += len(miss)
                shard.values[trows] = rows[sel]
                shard.touched.update(klist)
            # per-shard writeback time distribution: skew across shards
            # is the writeback wall the ROADMAP finalize item chases
            STAT_OBSERVE(
                "table.push_shard_s", time.perf_counter() - t_shard
            )
        if created:
            with self._size_lock:
                self._size += created

    def push_writeback(self, keys: np.ndarray, rows: np.ndarray,
                       threads: int) -> None:
        """One writer-pool chunk of the end-of-pass writeback.

        Routes through ``pbx_table_push_mt`` (bitwise-equal to ``push`` at
        every thread count) and feeds the per-shard wall seconds into the
        ``table.writeback.shard_s`` histogram. Fires the
        ``table.writeback_worker`` fault site; any failure — injected or a
        real worker rc — surfaces as the typed :class:`SpillIOError`,
        counted under ``table.spill_errors``.
        """
        try:
            _fault_fire("table.writeback_worker")
        except InjectedFault as e:
            STAT_ADD("table.spill_errors", 1)
            raise SpillIOError("writeback_worker", -2, str(e)) from e
        if self._native is None:
            self.push(keys, rows)
            return
        try:
            shard_s = self._native.push_mt(keys, rows, threads)
        except SpillIOError:
            raise
        except IOError as e:
            STAT_ADD("table.spill_errors", 1)
            raise SpillIOError("writeback_push", -2, str(e)) from e
        for v in shard_s:
            STAT_OBSERVE("table.writeback.shard_s", float(v))

    def decay_and_shrink(self) -> int:
        """Pass-boundary maintenance: decay show/clk, drop cold keys.

        Returns number of keys dropped. (pslib show_click_decay_rate + shrink
        threshold semantics; reference surfaces this as table shrink,
        fleet_wrapper.h:258-310.)

        Pending device-carried tables (whose rows this decay cannot reach)
        get the boundary's decay NOTED instead — they apply it at
        splice/flush time. Held under the maintenance lock so a concurrent
        drain either lands fully before (then its pushed rows decay here,
        classic push-then-decay order) or fully after (then the flush
        carries the noted decay) — never half."""
        with self._maintenance_lock:
            live = [c for c in self._pending_carriers if not c.flushed]
            for c in live:
                c.note_decay(self.opt.show_clk_decay)
            self._pending_carriers = live
            self.decay_epochs += 1
            return self._decay_and_shrink_locked()

    def _decay_and_shrink_locked(
        self, decay: Optional[float] = None, threshold: Optional[float] = None
    ) -> int:
        lay, opt = self.layout, self.opt
        decay = opt.show_clk_decay if decay is None else decay
        threshold = opt.shrink_threshold if threshold is None else threshold
        if self._native is not None:
            return self._native.decay_and_shrink(decay, threshold)
        dropped = 0
        for shard in self._shards:
            with shard.lock:
                n = len(shard.index)
                if n == 0:
                    continue
                vals = shard.values[:n]
                vals[:, lay.SHOW] *= decay
                vals[:, lay.CLK] *= decay
                keep = vals[:, lay.SHOW] >= threshold
                if keep.all():
                    continue
                keys_arr = np.empty(n, dtype=np.uint64)
                rows_arr = np.empty(n, dtype=np.int64)
                for i, (k, r) in enumerate(shard.index.items()):
                    keys_arr[i] = k
                    rows_arr[i] = r
                order = np.argsort(rows_arr)
                keys_arr, rows_arr = keys_arr[order], rows_arr[order]
                kept = keep[rows_arr]
                new_vals = vals[rows_arr[kept]]
                dropped += int((~kept).sum())
                shard.index = {int(k): i for i, k in enumerate(keys_arr[kept])}
                shard.values = np.zeros(
                    (max(1024, len(shard.index)), lay.width), dtype=np.float32
                )
                shard.values[: len(shard.index)] = new_vals
        with self._size_lock:
            self._size -= dropped
        return dropped

    # --- persistence: base + delta model publishing (SaveBase/SaveDelta parity,
    # box_wrapper.cc:1288-1331) ---

    def _snapshot_shard(self, s: int, only_touched: bool, clear_touched: bool = True):
        """Atomically snapshot (keys, values) of a shard and clear touched.

        The snapshot+clear happens under the shard lock so a concurrent
        push() either lands in this snapshot or stays marked touched for the
        next delta — no update can fall between and be lost.
        ``clear_touched=False`` gives a read-only peek (cache/whitelist/
        keys() exports).
        """
        if self._native is not None:
            return self._native.snapshot_shard(s, only_touched, clear_touched)
        shard = self._shards[s]
        with shard.lock:
            if only_touched:
                items = [(k, shard.index[k]) for k in shard.touched if k in shard.index]
            else:
                items = list(shard.index.items())
            keys = np.array([k for k, _ in items], dtype=np.uint64)
            vals = (
                shard.values[[r for _, r in items]]
                if items
                else np.zeros((0, self.layout.width), dtype=np.float32)
            )
            if clear_touched:
                shard.touched.clear()
        return keys, vals

    def save_base(self, path: str) -> None:
        self.drain_pending()
        os.makedirs(path, exist_ok=True)
        # the epoch stamp and the row snapshots must agree: hold the
        # maintenance lock across stamp + snapshots so an overlapped
        # end_pass_async worker's decay_and_shrink lands entirely before
        # or after this save. Compression/IO happens OUTSIDE the lock —
        # a minutes-long compressed write must not stall pass-boundary
        # maintenance (the transient snapshot copy is the price).
        with self._maintenance_lock:
            meta = {
                "n_shards": self.n_shards,
                "width": self.layout.width,
                "embedx_dim": self.layout.embedx_dim,
                "kind": "base",
                "decay_epoch": self.decay_epochs,
            }
            snaps = [
                self._snapshot_shard(s, only_touched=False)
                for s in range(self.n_shards)
            ]
        with atomic_write(os.path.join(path, "meta.json")) as f:
            json.dump(meta, f)
        for s, (keys, vals) in enumerate(snaps):
            np.savez_compressed(
                os.path.join(path, f"shard-{s:05d}.npz"),
                keys=keys, values=vals,
            )

    def save_delta(self, path: str, clear_touched: bool = True) -> int:
        """Write only keys touched since the last save; returns count.

        ``clear_touched=False`` keeps the touched set intact so the caller
        can defer the clear (via :meth:`clear_touched`) until the written
        delta is durable — a crashed-and-retried save then re-snapshots the
        same keys instead of publishing an empty delta.
        """
        self.drain_pending()
        os.makedirs(path, exist_ok=True)
        total = 0
        with self._maintenance_lock:  # stamp/snapshot atomicity (see save_base)
            epoch = self.decay_epochs
            snaps = [
                self._snapshot_shard(s, only_touched=True,
                                     clear_touched=clear_touched)
                for s in range(self.n_shards)
            ]
        for s, (keys, vals) in enumerate(snaps):
            total += len(keys)
            np.savez_compressed(
                os.path.join(path, f"shard-{s:05d}.npz"),
                keys=keys, values=vals,
            )
        with atomic_write(os.path.join(path, "meta.json")) as f:
            json.dump(
                {
                    "n_shards": self.n_shards,
                    "kind": "delta",
                    "decay_epoch": epoch,
                },
                f,
            )
        return total

    def clear_touched(self) -> None:
        """Drop the touched-keys set on every shard.

        Pairs with ``save_delta(..., clear_touched=False)``: the checkpoint
        layer snapshots without clearing, publishes durably, commits the
        cursor, and only THEN clears — so a crash anywhere inside the save
        leaves the touched set armed for the retry. Call only at a
        quiescent point (no concurrent pushes), or updates between the
        snapshot and this clear would drop out of the next delta.
        """
        if self._native is not None:
            self._native.clear_touched()
            return
        for shard in self._shards:
            with shard.lock:
                shard.touched.clear()

    def cache_threshold(self, cache_rate: float = 0.1) -> float:
        """Show-count threshold whose admitted fraction is CLOSEST to
        ``cache_rate`` (get_cache_threshold parity, pslib __init__.py:411).

        Computed over the exact show distribution, so heavy ties (many
        cold keys sharing tiny counts) can't silently blow the cache up to
        the whole table — the closest achievable fraction wins. The native
        store exports only the show column per shard; the Python fallback
        reads one column from its shard arrays."""
        if not 0.0 < cache_rate <= 1.0:
            raise ValueError(f"cache_rate must be in (0, 1], got {cache_rate}")
        shows = []
        for s in range(self.n_shards):
            if self._native is not None:
                col = self._native.shard_shows(s)
            else:
                shard = self._shards[s]
                with shard.lock:
                    col = shard.values[: len(shard.index), self.layout.SHOW].copy()
            if len(col):
                shows.append(col)
        if not shows:
            return 0.0
        allshow = np.concatenate(shows)
        uniq, counts = np.unique(allshow, return_counts=True)  # ascending
        admitted = np.cumsum(counts[::-1])[::-1] / len(allshow)  # frac >= uniq[i]
        return float(uniq[int(np.argmin(np.abs(admitted - cache_rate)))])

    def _filtered_save(self, path: str, mask_fn, meta: dict) -> int:
        """Shared filtered snapshot-to-dir writer (cache/whitelist saves).
        Stamp + snapshots are atomic under the maintenance lock (same
        discipline as save_base); filtering/compression run outside it."""
        self.drain_pending()
        os.makedirs(path, exist_ok=True)
        with self._maintenance_lock:
            meta = {**meta, "decay_epoch": self.decay_epochs}
            snaps = [
                self._snapshot_shard(s, only_touched=False, clear_touched=False)
                for s in range(self.n_shards)
            ]
        total = 0
        for s, (keys, vals) in enumerate(snaps):
            keep = mask_fn(keys, vals)
            keys, vals = keys[keep], vals[keep]
            total += len(keys)
            np.savez_compressed(
                os.path.join(path, f"shard-{s:05d}.npz"), keys=keys, values=vals
            )
        with atomic_write(os.path.join(path, "meta.json")) as f:
            json.dump({"n_shards": self.n_shards, **meta}, f)
        return total

    def save_cache(self, path: str, threshold: float) -> int:
        """Write the hot subset (show >= threshold) for serving
        (cache_shuffle/save_cache_model parity, pslib __init__.py:416).
        Like the reference (which brackets threshold+shuffle in worker
        barriers), quiesce pushes across threshold+save for an exact cut.
        Same dir format as base/delta; returns the feasign count."""
        return self._filtered_save(
            path,
            lambda keys, vals: vals[:, self.layout.SHOW] >= threshold,
            {"kind": "cache", "threshold": threshold},
        )

    def save_with_whitelist(self, path: str, whitelist: np.ndarray) -> int:
        """Write only the whitelisted keys that exist in the table
        (save_model_with_whitelist parity, pslib __init__.py:351-384)."""
        wl = np.unique(np.asarray(whitelist, dtype=np.uint64))
        return self._filtered_save(
            path, lambda keys, vals: np.isin(keys, wl), {"kind": "whitelist"}
        )

    def load(self, path: str) -> None:
        """Load a base dir, then optionally apply deltas via ``apply_delta``.

        Epoch catch-up: each file is stamped with the table's decay epoch
        at save time; when a file from a LATER epoch lands, the rows
        already in the table first receive the decays they lived through
        (``rate**(file_epoch - table_epoch)``) — exactly the history a key
        untouched since an earlier save experienced. Files without the
        stamp (older checkpoints) load as before."""
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        if meta["n_shards"] != self.n_shards:
            raise ValueError("shard count mismatch on load")
        file_epoch = int(meta.get("decay_epoch", self.decay_epochs))
        if meta.get("kind", "base") == "base":
            # a base load STARTS a lineage: epochs are only comparable
            # within one save lineage, so the table adopts the base's stamp
            # outright (catching 'up' across unrelated lineages would
            # crush or inflate counters arbitrarily)
            self.decay_epochs = file_epoch
        elif file_epoch > self.decay_epochs:
            if len(self):
                d = float(self.opt.show_clk_decay) ** (
                    file_epoch - self.decay_epochs
                )
                if d < 1.0:
                    # threshold 0: pure decay, no drops. (The native spill
                    # tier's per-record catch-up uses the last rate seen; a
                    # load into a table with live spill files is atypical.)
                    with self._maintenance_lock:
                        self._decay_and_shrink_locked(d, 0.0)
            self.decay_epochs = file_epoch
        for s in range(self.n_shards):
            data = np.load(os.path.join(path, f"shard-{s:05d}.npz"))
            keys, vals = data["keys"], data["values"]
            if len(keys):
                self.push(keys, vals)
            if self._native is None:
                self._shards[s].touched.clear()
        if self._native is not None:
            self._native.clear_touched()

    apply_delta = load  # a delta dir has the same format; push() upserts


def _rows_with_prefetch(
    table: HostSparseTable, keys: np.ndarray, prefetch
) -> np.ndarray:
    """Host rows for sorted unique ``keys``, serving staged-prefetch hits
    and pulling only the remainder.

    Prefetched rows receive the decays the host applied since the staged
    pull (``decay_epochs - epoch`` of them). Bitwise-equal to a fresh
    ``pull_or_create``: rows the prefetch CREATED have show=clk=0 so the
    catch-up decays are no-ops, and rows that already existed are — by the
    feed stage's exclusion of the live pass's keys — untouched by any
    writeback between the staged pull and now.
    """
    if prefetch is None:
        return table.pull_or_create(keys)
    pf_keys, pf_rows = prefetch["keys"], prefetch["rows"]
    lay = table.layout
    out = np.empty((len(keys), lay.width), dtype=np.float32)
    if len(pf_keys):
        pos = np.searchsorted(pf_keys, keys)
        pos = np.minimum(pos, len(pf_keys) - 1)
        hit = pf_keys[pos] == keys
    else:
        hit = np.zeros(len(keys), dtype=bool)
    if hit.any():
        rows = pf_rows[pos[hit]]  # fancy index: a fresh copy, safe to mutate
        d = table.decay_epochs - prefetch["epoch"]
        if d > 0:
            dec = np.float32(table.opt.show_clk_decay)
            for _ in range(d):
                rows[:, lay.SHOW] *= dec
                rows[:, lay.CLK] *= dec
        out[hit] = rows
    miss = ~hit
    if miss.any():
        out[miss] = table.pull_or_create(keys[miss])
    return out


class PassWorkingSet:
    """The HBM tier: dense pass-local table built from the pass's unique keys.

    Life cycle (BeginFeedPass .. EndPass parity):
      add_keys (during load, many threads) -> finalize() -> device array up
      -> train steps gather/scatter rows -> writeback(updated_array) -> host.
    """

    def __init__(self, n_mesh_shards: int = 1):
        self.n_mesh_shards = n_mesh_shards
        self._key_chunks: List[np.ndarray] = []
        self._lock = threading.Lock()
        self._finalized = False
        # set by finalize():
        self.sorted_keys: Optional[np.ndarray] = None  # uint64 [n]
        self.row_of_sorted: Optional[np.ndarray] = None  # int64 [n] global rows
        self.capacity = 0  # rows per mesh shard (incl. padding row)
        self.n_keys = 0
        # bool [n_mesh_shards*capacity] hotness bits for the adaptive ICI
        # wire (None = adaptive off/ablated: the packer keeps the uniform
        # slot order bitwise). Set by finalize() when the wire is engaged.
        self.hot_rows: Optional[np.ndarray] = None

    def add_keys(self, keys: np.ndarray) -> None:
        """Feed feasigns seen in loaded records (PSAgent::AddKeys parity)."""
        if self._finalized:
            raise RuntimeError("working set already finalized")
        if len(keys):
            with self._lock:
                self._key_chunks.append(np.unique(keys.astype(np.uint64)))

    def premerge(self, threads: int = 1) -> np.ndarray:
        """Collapse the accumulated key chunks to the merged array NOW.

        The boundary feed stage calls this while the PREVIOUS pass trains,
        so finalize() later re-merges a singleton chunk list through the
        no-copy fast path of :func:`merge_unique_keys` — the object
        returned here is the SAME object finalize sees, which is what lets
        a staged host prefetch validate itself with an O(1) identity test.
        ``add_keys`` after premerge still works (the merged array becomes
        one chunk among others) but voids that identity, so a stale
        prefetch is dropped rather than consumed.
        """
        if self._finalized:
            raise RuntimeError("working set already finalized")
        with self._lock:
            merged = merge_unique_keys(self._key_chunks, threads)
            self._key_chunks = [merged] if len(merged) else []
        return merged

    def finalize(
        self, table: HostSparseTable, round_to: int = 512, carrier=None,
        prefetch=None,
    ) -> np.ndarray:
        """Dedup keys, pull host rows, lay out [n_mesh_shards, cap, width].

        The returned array is what gets device_put with a mesh sharding on
        axis 0. Row (s, cap-1) of every shard is the reserved padding row.

        With ``carrier`` (the previous pass's TableCarrier), the boundary
        goes delta-only: keys present in both passes splice device-to-device
        from the carried trained table (one decay applied on device), keys
        that left the stream are fetched and pushed to the host store (D2H
        of the departing slice only), and only NEW keys pull host rows and
        upload. Returns a jax array in that case. The reference keeps its
        HBM cache warm across passes the same way (EndPass
        box_wrapper.cc:627-651).

        ``prefetch`` is the staged host-pull dict built by the dataset's
        boundary feed stage ({src, keys, rows, epoch}); it is consumed
        only if its ``src`` is the very array this finalize merges
        (identity check), else silently dropped."""
        t0 = time.perf_counter()
        with self._lock, record_event("boundary.dedup", "boundary"):
            all_keys = merge_unique_keys(
                self._key_chunks,
                int(config.get_flag("boundary_merge_threads")),
            )
            self._key_chunks = []
        STAT_SET("boundary.dedup_s", time.perf_counter() - t0)
        if prefetch is not None and prefetch.get("src") is not all_keys:
            prefetch = None  # keys landed after the staged premerge: stale
        self.n_keys = len(all_keys)
        ns = self.n_mesh_shards
        shard_ids = key_to_shard(all_keys, ns)
        counts = np.bincount(shard_ids, minlength=ns)
        # +1 reserves the padding row; round for stable compiled shapes
        cap = int(counts.max()) + 1 if len(all_keys) else 1
        cap = -(-cap // round_to) * round_to
        self.capacity = cap

        # stable order: group by shard, rank within shard — vectorized
        # (rank of key i = position of i within its shard's sorted group)
        order = np.argsort(shard_ids, kind="stable")
        rank_in_shard = np.empty(len(all_keys), dtype=np.int64)
        starts = np.repeat(np.cumsum(counts) - counts, counts)
        rank_in_shard[order] = np.arange(len(all_keys), dtype=np.int64) - starts
        global_rows = shard_ids * cap + rank_in_shard

        self.sorted_keys = all_keys  # np.unique output is sorted
        self.row_of_sorted = global_rows
        self._finalized = True
        self._table = table

        if carrier is not None and not carrier.flushed and carrier.ws.n_keys:
            # spliced boundary: resident keys' live shows sit on device, so
            # hotness reads the host mem tier instead (possibly one pass
            # stale — fine for a precision heuristic, and side-effect free)
            if self._ici_adaptive():
                self._set_hot_rows(global_rows, table.shows_peek(all_keys))
            return self._finalize_spliced(
                table, carrier, all_keys, global_rows, ns, cap, prefetch
            )
        t0 = time.perf_counter()
        with record_event("boundary.pull", "boundary"):
            rows = (
                _rows_with_prefetch(table, all_keys, prefetch)
                if len(all_keys)
                else np.zeros((0, table.layout.width), dtype=np.float32)
            )
        STAT_SET("boundary.pull_s", time.perf_counter() - t0)
        if self._ici_adaptive() and len(all_keys):
            # the classic pull already materialized every row: its decayed
            # show column is the exact, free hotness source
            self._set_hot_rows(global_rows, rows[:, table.layout.SHOW])
        dev = np.zeros((ns, cap, table.layout.width), dtype=np.float32)
        dev.reshape(ns * cap, -1)[global_rows] = rows
        return dev

    @staticmethod
    def _ici_adaptive() -> bool:
        from paddlebox_tpu.ops import wire_quant  # lazy: avoids import cycle

        return wire_quant.ici_adaptive_engaged()

    def _set_hot_rows(self, global_rows: np.ndarray, shows: np.ndarray) -> None:
        """Publish per-row hotness bits for the adaptive ICI wire."""
        thr = float(config.get_flag("ici_hot_show"))
        hot = np.zeros(self.n_mesh_shards * self.capacity, dtype=bool)
        hot[global_rows] = np.asarray(shows, dtype=np.float32) >= thr
        self.hot_rows = hot
        STAT_SET("wire.ici_hot_keys", int(hot.sum()))

    def _finalize_spliced(
        self, table, carrier, all_keys, global_rows, ns, cap, prefetch=None
    ):
        """Delta boundary: splice carried rows on device, push departures,
        upload only new keys. Returns the [ns, cap, width] jax array.

        The host pull of the new keys runs on a worker thread so it
        overlaps the device-side allocation + common splice; the two
        scatters hit disjoint row sets, so running the common splice first
        is bitwise-identical to the old new-then-common order."""
        import jax.numpy as jnp

        old_keys = carrier.ws.sorted_keys
        # both sides sorted: positions of the intersection in each
        pos_in_old = np.searchsorted(old_keys, all_keys)
        pos_in_old = np.minimum(pos_in_old, len(old_keys) - 1)
        common = old_keys[pos_in_old] == all_keys  # mask over all_keys
        common_old = pos_in_old[common]
        # departing = old keys NOT in the new set
        in_new = np.zeros(len(old_keys), dtype=bool)
        in_new[common_old] = True
        leave_pos = np.nonzero(~in_new)[0]
        if len(leave_pos):
            # departing slice: D2H + host push overlap the next pass
            # (joined before any decay or durable read)
            carrier.push_departures_async(
                table, old_keys[leave_pos], leave_pos
            )
        new_mask = ~common
        new_keys = all_keys[new_mask]
        W = table.layout.width

        # single-writer result cell; the join below is the only reader
        pull = {"rows": None, "err": None, "secs": 0.0}

        def _pull_new():
            t0 = time.perf_counter()
            try:
                with record_event("boundary.pull", "boundary"):
                    pull["rows"] = _rows_with_prefetch(
                        table, new_keys, prefetch
                    )
            except BaseException as e:  # joined + re-raised below
                pull["err"] = e
            pull["secs"] = time.perf_counter() - t0

        puller = None
        if len(new_keys):
            puller = threading.Thread(
                target=_pull_new, name="boundary-pull", daemon=True
            )
            puller.start()

        # allocate the destination BORN under the carried table's sharding
        # (jit + out_shardings): an eager zeros (even one fed to
        # device_put) would first materialize the full next-pass table
        # unsharded on the default device — an HBM spike of full-table
        # size at exactly the boundary the carrier exists to slim down.
        # On a single device this degenerates to a plain allocation.
        t0 = time.perf_counter()
        with record_event("boundary.splice", "boundary"):
            dev = _sharded_zeros(ns * cap, W, carrier.dev_flat.sharding)
            if common.any():
                dev = dev.at[jnp.asarray(global_rows[common])].set(
                    carrier.rows_for(common_old)
                )
        STAT_SET("boundary.splice_s", time.perf_counter() - t0)
        if puller is not None:
            puller.join()
            if pull["err"] is not None:
                raise pull["err"]
            STAT_SET("boundary.pull_s", pull["secs"])
            from paddlebox_tpu import config as _config
            from paddlebox_tpu.ops.wire_quant import send_rows

            up = send_rows(
                pull["rows"], table.layout, str(_config.get_flag("wire_dtype"))
            )
            dev = dev.at[jnp.asarray(global_rows[new_mask])].set(up)
        return dev.reshape(ns, cap, W)

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        """Batch keys -> global row ids (int32). Keys must be in the pass."""
        if len(self.sorted_keys) == 0:
            if len(keys):
                raise KeyError(
                    f"{len(keys)} batch keys but the pass working set is empty"
                )
            return np.zeros(0, np.int32)
        pos = np.searchsorted(self.sorted_keys, keys.astype(np.uint64))
        pos = np.minimum(pos, len(self.sorted_keys) - 1)
        if not np.all(self.sorted_keys[pos] == keys):
            missing = keys[self.sorted_keys[pos] != keys]
            raise KeyError(
                f"{len(missing)} batch keys not in pass working set (e.g. {missing[:5]})"
            )
        return self.row_of_sorted[pos].astype(np.int32)

    @property
    def padding_row(self) -> int:
        """Global row id safe for batch padding (shard 0's reserved row)."""
        return self.capacity - 1

    def writeback(
        self,
        device_array: np.ndarray,
        cancel: Optional[threading.Event] = None,
    ) -> None:
        """Flush trained rows back to the host store (EndPass parity).

        With ``writeback_threads`` > 1 and the native store available, the
        push is chunked (``writeback_chunk_keys``) through the explicit
        writer pool: chunk k+1's row gather runs while chunk k's push is
        in flight on a single-slot pipeline, and ``cancel`` (checked at
        chunk boundaries) lets a revert stop mid-writeback — whatever
        landed is exactly the partial writeback rollback's PassGuard
        contract covers. ``writeback_threads <= 1`` is the legacy serial
        path, bit for bit. Either way the host table ends bitwise-equal:
        chunks split a sorted unique key batch, so per-shard batch order
        and every row write are identical to the one-shot push.

        Emits the ``table.writeback.*`` stat family: total push seconds,
        per-chunk gather/wait seconds, pool size, chunk count, and the
        seconds the pipeline hid (push busy time that overlapped gathers).
        """
        if self.n_keys == 0:
            return
        flat = np.asarray(device_array).reshape(-1, device_array.shape[-1])
        threads = int(config.get_flag("writeback_threads"))
        if threads <= 1 or not getattr(self._table, "native", False):
            self._table.push(self.sorted_keys, flat[self.row_of_sorted])
            return
        chunk = max(1, int(config.get_flag("writeback_chunk_keys")))
        n = len(self.sorted_keys)
        t_all = time.perf_counter()
        wait_s = 0.0
        busy_s = 0.0
        n_chunks = 0
        pending = None

        def _push_chunk(ck: np.ndarray, cr: np.ndarray) -> float:
            t0 = time.perf_counter()
            self._table.push_writeback(ck, cr, threads)
            return time.perf_counter() - t0

        with ThreadPoolExecutor(max_workers=1) as ex:
            for lo in range(0, n, chunk):
                if cancel is not None and cancel.is_set():
                    # the in-flight chunk (if any) completes on executor
                    # shutdown; nothing past it starts
                    raise WritebackCancelled(lo, n)
                hi = min(n, lo + chunk)
                t0 = time.perf_counter()
                cr = np.ascontiguousarray(flat[self.row_of_sorted[lo:hi]])
                gather_s = time.perf_counter() - t0
                STAT_OBSERVE("table.writeback.gather_s", gather_s)
                if pending is not None:
                    t0 = time.perf_counter()
                    busy_s += pending.result()
                    w = time.perf_counter() - t0
                    wait_s += w
                    STAT_OBSERVE("table.writeback.chunk_wait_s", w)
                pending = ex.submit(_push_chunk, self.sorted_keys[lo:hi], cr)
                n_chunks += 1
            t0 = time.perf_counter()
            busy_s += pending.result()
            w = time.perf_counter() - t0
            wait_s += w
            STAT_OBSERVE("table.writeback.chunk_wait_s", w)
        total_s = time.perf_counter() - t_all
        STAT_SET("table.writeback.threads", threads)
        STAT_SET("table.writeback.chunks", n_chunks)
        STAT_SET("table.writeback.wait_s", wait_s)
        STAT_SET("table.writeback.push_s", total_s)
        STAT_OBSERVE("table.writeback.push_s", total_s)
        # push busy time the single-slot pipeline hid behind row gathers
        STAT_SET("table.writeback.hidden_s", max(0.0, busy_s - wait_s))
