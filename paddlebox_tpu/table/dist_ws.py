"""Multi-host pass working set: host-sharded table ownership + key exchange.

The reference's pass open (`BeginFeedPass`, box_wrapper.cc:580) hands every
feasign of the pass to the closed boxps lib, which shards keys across MPI
nodes and stages each node's slice into its GPUs. This module is that tier
in the open: mesh shards partition keys (`key_to_shard(key, n_mesh)`), each
host OWNS the contiguous shard range of its local devices, and a two-round
host exchange builds the pass:

  round 1 (request):  every host all-to-alls the pass keys it saw to the
                      keys' owner hosts;
  round 2 (reply):    each owner dedups, assigns ranks (ascending key order
                      per shard — identical layout to the single-process
                      PassWorkingSet), pulls/creates rows in its LOCAL
                      HostSparseTable slice, and replies to each requester
                      with the global row ids of the keys it asked about.

Capacity is allreduce-max'd so every host compiles the same shapes
(lockstep parity, compute_thread_batch_nccl data_set.cc:2069-2135), and
writeback is purely local: a host's trained device slice lands in its own
host table — no cross-host traffic at pass end.

Both rounds encode through ``ops/host_codec.py``: request key streams are
delta+varint under the ``host_wire_codec`` flag (sorted unique uint64 →
~1-2 bytes/key; marker byte keeps raw/codec ranks interoperable), and row
replies always ride the narrow-int codec (width picked from the
``n_mesh_shards * capacity`` bound, overflow is a loud codec error).
``wire.ws_req_*`` / ``wire.ws_rep_*`` counters record raw-vs-encoded bytes
per round — the per-round ratios chaos_probe's distributed soak reports.
"""

from __future__ import annotations

import threading
from typing import List, Optional

import numpy as np

from paddlebox_tpu import config
from paddlebox_tpu.ops import host_codec
from paddlebox_tpu.parallel.membership import OwnershipMap
from paddlebox_tpu.table.sparse_table import (
    HostSparseTable,
    key_to_shard,
    merge_unique_keys,
)
from paddlebox_tpu.utils.monitor import STAT_ADD


class DistributedWorkingSet:
    """Pass working set across hosts; same pack-time surface as
    PassWorkingSet (n_mesh_shards / capacity / padding_row / lookup)."""

    def __init__(
        self, transport, n_mesh_shards: int, pass_id: int = 0, epoch: int = 0,
        ownership: Optional[OwnershipMap] = None,
    ):
        self.transport = transport
        self.n_mesh_shards = n_mesh_shards
        n_hosts = transport.n_ranks
        # ownership is an explicit versioned map (largest-remainder
        # contiguous ranges), not rank arithmetic: uneven splits are fine
        # and the live set may be smaller than the endpoint list after a
        # membership shrink. Default reproduces the historical even split.
        if ownership is None:
            ownership = OwnershipMap.even(n_mesh_shards, n_hosts)
        if ownership.n_mesh_shards != n_mesh_shards:
            raise ValueError(
                f"ownership map covers {ownership.n_mesh_shards} shards, "
                f"pass has {n_mesh_shards}"
            )
        if not ownership.is_live(transport.rank):
            raise ValueError(
                f"rank {transport.rank} is not live in {ownership!r}"
            )
        self.ownership = ownership
        lo, hi = ownership.range_of(transport.rank)
        self.shard_lo = lo
        self.shards_per_host = hi - lo  # THIS rank's owned count (uneven ok)
        self.pass_id = pass_id
        # pass-retry epoch: tags carry ``@e<epoch>`` so the transport can
        # discard a reverted attempt's frames instead of feeding them to
        # the retried exchange (see TcpTransport.discard_epochs_below)
        self.epoch = epoch
        self._key_chunks: List[np.ndarray] = []
        self._lock = threading.Lock()
        self._finalized = False
        # set by finalize():
        self.sorted_keys: Optional[np.ndarray] = None  # referenced keys
        self.row_of_sorted: Optional[np.ndarray] = None
        self.capacity = 0
        self.n_keys = 0  # locally referenced
        self.owned_shard_keys: Optional[List[np.ndarray]] = None
        # bool [n_mesh_shards*capacity] hotness bits for the adaptive ICI
        # wire (None = off/ablated); set by finalize via the gated ws-hot
        # round — owners read their local tier, requesters get one bit per
        # requested key
        self.hot_rows: Optional[np.ndarray] = None

    def add_keys(self, keys: np.ndarray) -> None:
        if self._finalized:
            raise RuntimeError("working set already finalized")
        if len(keys):
            with self._lock:
                self._key_chunks.append(np.unique(keys.astype(np.uint64)))

    def premerge(self, threads: int = 1) -> np.ndarray:
        """Collapse accumulated key chunks now (boundary feed stage); the
        later finalize re-merges the singleton list via the no-copy fast
        path (see PassWorkingSet.premerge)."""
        if self._finalized:
            raise RuntimeError("working set already finalized")
        with self._lock:
            merged = merge_unique_keys(self._key_chunks, threads)
            self._key_chunks = [merged] if len(merged) else []
        return merged

    def _owner_host(self, keys: np.ndarray) -> np.ndarray:
        return self.ownership.owner_of_shard(
            key_to_shard(keys, self.n_mesh_shards)
        )

    def finalize(
        self, table: HostSparseTable, round_to: int = 512, carrier=None,
        prefetch=None,
    ) -> np.ndarray:
        """Two-round exchange; returns THIS host's device slice
        ``[shards_per_host, capacity, width]`` (global row of key =
        global_shard * capacity + rank, exactly the single-process layout).

        With ``carrier`` (a MultiHostCarrier from the previous pass's
        end_pass), the boundary goes delta-only PER HOST: each local
        device splices its surviving shard rows device-locally, departures
        D2H only their slice into the local host table, and only new keys
        upload — then the per-device blocks reassemble into the global
        mesh array without any cross-host traffic (every node keeps its
        HBM cache warm, EndPass parity box_wrapper.cc:627-651). Returns a
        global jax.Array in that case.

        ``prefetch`` is accepted for interface parity with
        PassWorkingSet.finalize and ignored: the dataset's boundary feed
        stage never stages a host prefetch for a distributed pass (owned
        keys are only known after the exchange)."""
        t = self.transport
        with self._lock:
            referenced = merge_unique_keys(
                self._key_chunks,
                int(config.get_flag("boundary_merge_threads")),
            )
            self._key_chunks = []
        self.n_keys = len(referenced)

        # round 1: route referenced keys to their owner hosts. The keys per
        # destination are a masked slice of np.unique output — sorted — so
        # the delta+varint codec applies; the payload's marker byte keeps
        # the format self-describing (a codec-on rank and a raw-ablation
        # rank decode each other's frames identically)
        use_codec = bool(config.get_flag("host_wire_codec"))
        owners = self._owner_host(referenced)
        req_out = []
        for h in range(t.n_ranks):
            req_out.append(
                host_codec.encode_key_stream(referenced[owners == h], use_codec)
            )
        STAT_ADD("wire.ws_req_raw_bytes", int(len(referenced)) * 8)
        STAT_ADD("wire.ws_req_bytes", sum(len(b) for b in req_out))
        req_in = t.alltoall(req_out, f"ws-req:{self.pass_id}@e{self.epoch}")
        # ranks outside the ownership live set contribute b"" placeholder
        # slots (membership-aware alltoall), never decodable payloads
        live = set(self.ownership.live_ranks)
        req_keys = [
            host_codec.decode_key_stream(b) if h in live
            else np.zeros(0, np.uint64)
            for h, b in enumerate(req_in)
        ]

        # owner side: union, per-shard rank assignment (ascending key order)
        owned = (
            np.unique(np.concatenate([k for k in req_keys]))
            if any(len(k) for k in req_keys)
            else np.zeros(0, np.uint64)
        )
        shard_of = key_to_shard(owned, self.n_mesh_shards) - self.shard_lo
        counts = np.bincount(shard_of, minlength=self.shards_per_host)
        local_max = int(counts.max()) + 1 if len(owned) else 1
        cap = t.allreduce_max(local_max, f"ws-cap:{self.pass_id}@e{self.epoch}")
        cap = -(-cap // round_to) * round_to
        self.capacity = cap

        order = np.argsort(shard_of, kind="stable")  # keys sorted => rank order
        rank_in_shard = np.empty(len(owned), dtype=np.int64)
        starts = np.repeat(np.cumsum(counts) - counts, counts)
        rank_in_shard[order] = np.arange(len(owned), dtype=np.int64) - starts
        self.owned_shard_keys = np.split(
            owned[order], np.cumsum(counts)[:-1]
        )
        owned_rows = (
            (key_to_shard(owned, self.n_mesh_shards)) * cap + rank_in_shard
        )

        # build the local device slice: spliced from the carried device
        # table when one is live, else classic pull from the local host
        # table
        self.boundary_stats = None
        same_epoch = carrier is None or (
            getattr(carrier, "ownership_epoch", 0) == self.ownership.epoch
        )
        if carrier is not None and same_epoch and not carrier.flushed and len(owned):
            dev = self._finalize_spliced(table, carrier, cap)
        else:
            if carrier is not None:
                # no splice possible (empty pass, already flushed, or the
                # carrier's shard->host pinning predates this ownership
                # epoch): everything the carrier owes must land before the
                # classic pull reads host rows
                table.drain_pending()
            vals = (
                table.pull_or_create(owned)
                if len(owned)
                else np.zeros((0, table.layout.width), np.float32)
            )
            dev = np.zeros(
                (self.shards_per_host, cap, table.layout.width), np.float32
            )
            if len(owned):
                # guarded: reshape(0, -1) on a zero-width ownership range
                # cannot infer the trailing dim
                local_rows = shard_of * cap + rank_in_shard
                dev.reshape(self.shards_per_host * cap, -1)[local_rows] = vals

        # round 2: reply global rows for each requester's keys (their
        # order). Rows are shard*cap+rank, bounded by n_mesh_shards*cap —
        # the narrow-int codec downcasts to the width that bound needs
        # (uint16/uint32 in practice, never int64) and raises on overflow.
        # Always on, raw ablation included: the width byte self-describes.
        max_row = self.n_mesh_shards * cap - 1
        rep_out = []
        pos_all = np.searchsorted(owned, np.concatenate(req_keys)) if len(owned) else None
        off = 0
        for h in range(t.n_ranks):
            k = req_keys[h]
            if len(k):
                rep_out.append(
                    host_codec.encode_row_ids(
                        owned_rows[pos_all[off : off + len(k)]], max_row
                    )
                )
            else:
                rep_out.append(host_codec.encode_row_ids(np.zeros(0, np.int64), max_row))
            off += len(k)
        STAT_ADD(
            "wire.ws_rep_raw_bytes",
            8 * sum(len(k) for k in req_keys),
        )
        STAT_ADD("wire.ws_rep_bytes", sum(len(b) for b in rep_out))
        rep_in = t.alltoall(rep_out, f"ws-rep:{self.pass_id}@e{self.epoch}")

        # assemble local lookup over referenced keys; non-live slots carry
        # no keys (ownership routing never maps a shard to a dead rank)
        rows = np.empty(len(referenced), dtype=np.int64)
        for h in range(t.n_ranks):
            if h not in live:
                continue
            sel = owners == h
            got = host_codec.decode_row_ids(rep_in[h])
            rows[sel] = got

        # round 3 (gated): hotness bits for the adaptive ICI wire. Each
        # owner reads its LOCAL tier's decayed shows (shows_peek — pure,
        # never perturbs tier state) and replies one bit per requested key
        # in the requester's key order, packed 8 keys/byte. The round only
        # runs when the adaptive wire is engaged, so the ablation's host
        # exchange is byte-identical to the two-round historical one.
        from paddlebox_tpu.ops import wire_quant as _wq  # lazy: import cycle

        if _wq.ici_adaptive_engaged():
            thr = float(config.get_flag("ici_hot_show"))
            owned_hot = (
                (table.shows_peek(owned) >= thr)
                if len(owned)
                else np.zeros(0, bool)
            )
            hot_out = []
            off = 0
            for h in range(t.n_ranks):
                k = req_keys[h]
                bits = (
                    owned_hot[pos_all[off : off + len(k)]]
                    if len(k)
                    else np.zeros(0, bool)
                )
                hot_out.append(np.packbits(bits.astype(np.uint8)).tobytes())
                off += len(k)
            STAT_ADD("wire.ws_hot_bytes", sum(len(b) for b in hot_out))
            hot_in = t.alltoall(hot_out, f"ws-hot:{self.pass_id}@e{self.epoch}")
            hot = np.zeros(self.n_mesh_shards * cap, dtype=bool)
            for h in range(t.n_ranks):
                if h not in live:
                    continue
                sel = owners == h
                nk = int(sel.sum())
                if nk:
                    bits = np.unpackbits(
                        np.frombuffer(hot_in[h], np.uint8), count=nk
                    ).astype(bool)
                    hot[rows[sel]] = bits
            self.hot_rows = hot

        self.sorted_keys = referenced  # np.unique output: sorted
        self.row_of_sorted = rows
        self._finalized = True
        self._table = table
        return dev

    def _finalize_spliced(self, table: HostSparseTable, carrier, cap: int):
        """Per-device delta boundary over the carried shard blocks.

        Each local device splices keys surviving from the previous pass
        out of its own carried block (decay applied on device), pushes its
        departing slice to the LOCAL host table on a background thread,
        and uploads only its genuinely new keys — the multi-host analog of
        PassWorkingSet._finalize_spliced, with every step host-local by
        the stable key->shard->device pinning."""
        import jax
        import jax.numpy as jnp

        from paddlebox_tpu import config as _config
        from paddlebox_tpu.ops.wire_quant import send_rows

        W = table.layout.width
        spd = carrier.shards_per_dev
        stats = {"common": 0, "new": 0, "departed": 0}
        blocks = []
        for di, (dev, part) in enumerate(zip(carrier.devices, carrier.parts)):
            # this device's NEW keys + block-local rows
            ks, rows = [], []
            for j in range(spd):
                k = self.owned_shard_keys[di * spd + j]
                ks.append(k)
                rows.append(j * cap + np.arange(len(k), dtype=np.int64))
            new_keys = np.concatenate(ks) if ks else np.zeros(0, np.uint64)
            new_rows = np.concatenate(rows) if rows else np.zeros(0, np.int64)

            old_keys = part.ws.sorted_keys
            if len(old_keys):
                pos_in_old = np.searchsorted(old_keys, new_keys)
                pos_in_old = np.minimum(pos_in_old, len(old_keys) - 1)
                common = old_keys[pos_in_old] == new_keys
            else:
                pos_in_old = np.zeros(len(new_keys), np.int64)
                common = np.zeros(len(new_keys), bool)
            common_old = pos_in_old[common]
            in_new = np.zeros(len(old_keys), dtype=bool)
            in_new[common_old] = True
            leave_pos = np.nonzero(~in_new)[0]
            if len(leave_pos):
                part.push_departures_async(
                    table, old_keys[leave_pos], leave_pos
                )
            new_mask = ~common
            stats["common"] += int(common.sum())
            stats["new"] += int(new_mask.sum())
            stats["departed"] += len(leave_pos)

            with jax.default_device(dev):
                block = jnp.zeros((spd * cap, W), jnp.float32)
                if new_mask.any():
                    up = send_rows(
                        table.pull_or_create(new_keys[new_mask]),
                        table.layout,
                        str(_config.get_flag("wire_dtype")),
                    )
                    block = block.at[jnp.asarray(new_rows[new_mask])].set(up)
                if common.any():
                    block = block.at[jnp.asarray(new_rows[common])].set(
                        part.rows_for(common_old)
                    )
            blocks.append(block.reshape(spd, cap, W))
        self.boundary_stats = stats
        return jax.make_array_from_single_device_arrays(
            (self.n_mesh_shards, cap, W), carrier.sharding, blocks
        )

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        """Batch keys -> GLOBAL row ids (int32); keys must be in the pass."""
        if len(self.sorted_keys) == 0:
            if len(keys):
                raise KeyError(
                    f"{len(keys)} batch keys but the pass working set is empty"
                )
            return np.zeros(0, np.int32)
        pos = np.searchsorted(self.sorted_keys, keys.astype(np.uint64))
        pos = np.minimum(pos, len(self.sorted_keys) - 1)
        if not np.all(self.sorted_keys[pos] == keys):
            missing = keys[self.sorted_keys[pos] != keys]
            raise KeyError(
                f"{len(missing)} batch keys not in pass working set (e.g. {missing[:5]})"
            )
        return self.row_of_sorted[pos].astype(np.int32)

    @property
    def padding_row(self) -> int:
        return self.capacity - 1

    @property
    def _finalized_ok(self) -> bool:
        return self._finalized

    def writeback(
        self,
        local_slice: np.ndarray,
        cancel: Optional[threading.Event] = None,
    ) -> None:
        """Flush THIS host's trained shard slice into its own host table —
        ownership == device placement, so nothing crosses hosts (EndPass
        parity, box_wrapper.cc:627). ``cancel`` (the overlapped-kick revert
        path) is checked between shard pushes: shards already pushed are
        covered by rollback's partial-writeback contract."""
        if self.owned_shard_keys is None or self.shards_per_host == 0:
            # a zero-width ownership range (uneven map, more ranks than
            # shards) trains nothing and owes the host table nothing
            return
        flat = np.asarray(local_slice).reshape(self.shards_per_host, self.capacity, -1)
        for s, keys in enumerate(self.owned_shard_keys):
            if cancel is not None and cancel.is_set():
                from paddlebox_tpu.table.sparse_table import WritebackCancelled

                raise WritebackCancelled(
                    sum(len(k) for k in self.owned_shard_keys[:s]),
                    sum(len(k) for k in self.owned_shard_keys),
                )
            if len(keys):
                self._table.push(keys, flat[s, : len(keys)])


def hot_shard_loads(table, ownership: OwnershipMap, rank: int) -> np.ndarray:
    """Hotness-weighted per-mesh-shard load of ``rank``'s owned range
    (float64, length ``hi - lo``) — the elastic planner's load vector.

    The same Parallax-style frequency prior the adaptive ICI wire reads:
    each owned key weighs its decayed show count (``shows_peek`` — pure,
    mem-tier only) plus a residency term from the tiered store's
    occupancy split (``tier_stats`` per-host-shard mem/disk rows): a key
    whose host shard is mostly disk-resident is cheaper to move and
    colder to serve, so it weighs half a mem-resident key. Migrating or
    carving by this vector moves *hot* load, not raw key counts — a
    joiner carved at its quantile cuts takes traffic, not tombstone mass.
    Deterministic from the local table state; callers allgather the
    per-rank slices into the global vector."""
    lo, hi = ownership.range_of(int(rank))
    if hi <= lo:
        return np.zeros(0, dtype=np.float64)
    keys = table.keys()
    mesh = key_to_shard(keys, ownership.n_mesh_shards)
    mine = (mesh >= lo) & (mesh < hi)
    keys, mesh = keys[mine], mesh[mine]
    if len(keys) == 0:
        return np.zeros(hi - lo, dtype=np.float64)
    st = table.tier_stats()
    mem = np.asarray(st["per_shard"]["mem_rows"], dtype=np.float64)
    disk = np.asarray(st["per_shard"]["disk_rows"], dtype=np.float64)
    frac_mem = np.where(mem + disk > 0, mem / np.maximum(mem + disk, 1.0), 1.0)
    host = key_to_shard(keys, table.n_shards)
    residency = 0.5 + 0.5 * frac_mem[host]
    w = residency + np.asarray(table.shows_peek(keys), dtype=np.float64)
    return np.bincount(mesh - lo, weights=w, minlength=hi - lo).astype(
        np.float64
    )
