"""Filesystem dispatch: local files vs remote stores behind shell pipes.

Parity with the reference's two IO tiers (SURVEY.md B20/B21):

- open tier (framework/io/fs.{h,cc}): ``fs_open_read``/``fs_open_write``
  dispatch on path prefix — local paths get plain/gzip streams, remote
  (``hdfs:``/``afs:``) paths get a popen'd ``hadoop fs`` pipe — with an
  optional converter command spliced into the pipe either way.
- closed tier (``boxps::PaddleFileMgr``, box_wrapper.h:778-802 + pybind
  box_helper_py.cc:121-140): ls/mkdir/exists/download/upload/remove — here
  ``FileMgr``, implemented over the same dispatch, fully open.

The hadoop binary and flags are configurable (the reference passes an
``fs.default.name``/ugi config string); everything degrades to local-path
behavior in tests where no hadoop exists.
"""

from __future__ import annotations

import glob as _glob
import gzip
import os
import shutil
import subprocess
import time
from contextlib import contextmanager
from typing import IO, Iterator, List, Optional

from paddlebox_tpu import config
from paddlebox_tpu.utils.faultinject import fire as _fault_fire

config.define_flag("hadoop_bin", "hadoop", "hadoop client binary for hdfs:/afs: paths")
config.define_flag("hdfs_retry", 3, "retry count for remote fs commands")
config.define_flag(
    "fs_open_retries", 3, "retry-until-open attempts for data files"
)
config.define_flag(
    "fs_open_backoff_s",
    1.0,
    "base linear backoff (seconds) between retry-until-open attempts; "
    "tests and chaos schedules turn it down to keep injected flakes cheap",
)

_REMOTE_PREFIXES = ("hdfs:", "afs:")


def is_remote(path: str) -> bool:
    return path.startswith(_REMOTE_PREFIXES)


def _hadoop_cmd(extra_conf: Optional[str] = None) -> str:
    cmd = config.get_flag("hadoop_bin") + " fs"
    if extra_conf:
        cmd += " " + extra_conf
    return cmd


class _PipeStream:
    """Text stream over a shell pipeline; raises on nonzero exit at close
    (shell-pipe error propagation, framework/io/shell.cc)."""

    def __init__(self, cmd: str, mode: str = "r", stdin_file: Optional[IO] = None):
        self.cmd = cmd
        writing = "w" in mode
        self.proc = subprocess.Popen(
            cmd,
            shell=True,
            stdin=(subprocess.PIPE if writing else stdin_file),
            stdout=(None if writing else subprocess.PIPE),
            text=True,
        )
        self.stream = self.proc.stdin if writing else self.proc.stdout

    def __iter__(self) -> Iterator[str]:
        return iter(self.stream)

    def read(self, *a) -> str:
        return self.stream.read(*a)

    def write(self, s: str) -> int:
        return self.stream.write(s)

    def close(self) -> None:
        self.stream.close()
        if self.proc.wait() != 0:
            raise RuntimeError(f"pipe command failed ({self.proc.returncode}): {self.cmd}")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if exc[0] is None:
            self.close()
        else:  # error path: don't mask the original exception
            self.proc.kill()
            self.proc.wait()


def _retry_open(fn, retries: Optional[int], backoff_s: Optional[float]):
    """Shared retry-until-open policy: OSError -> linear backoff -> raise
    the last error after ``fs_open_retries`` attempts."""
    n = max(1, retries if retries is not None else config.get_flag("fs_open_retries"))
    if backoff_s is None:
        backoff_s = config.get_flag("fs_open_backoff_s")
    last: Optional[BaseException] = None
    for attempt in range(n):
        try:
            return fn()
        except OSError as e:
            last = e
            if attempt + 1 < n:
                from paddlebox_tpu.utils.monitor import STAT_ADD

                STAT_ADD("fs_open_retries_total")
                time.sleep(backoff_s * (attempt + 1))
    raise last


def fs_open_read_retry(
    path: str,
    converter: Optional[str] = None,
    retries: Optional[int] = None,
    backoff_s: Optional[float] = None,
):
    """Retry-until-open (data_feed.cc:2738-2740 parity): a transiently
    unavailable file — AFS flake, NFS lag, a part file still being
    published — is reopened with linear backoff instead of failing the
    whole pass. Remote paths probe existence first (a hadoop pipe opens
    lazily, so the flake would otherwise only surface mid-stream, where a
    retry could duplicate data; a mid-stream remote failure still fails
    the read)."""

    def attempt():
        if is_remote(path):
            try:
                _run_remote(f"-test -e '{path}' && echo yes")
            except RuntimeError as e:
                rc = getattr(e.__cause__, "returncode", None)
                if rc == 1:  # hadoop -test: path genuinely absent -> retry
                    raise OSError(
                        f"remote path not available yet: {path}"
                    ) from e
                # 127 missing binary / 255 cluster unreachable etc.: NOT a
                # publishing delay — surface it instead of burning retries
                raise RuntimeError(
                    f"remote fs probe failed for {path!r} (hadoop client "
                    "error, not a missing file)"
                ) from e
        return fs_open_read(path, converter)

    return _retry_open(attempt, retries, backoff_s)


def fs_read_bytes_retry(
    path: str, retries: Optional[int] = None, backoff_s: Optional[float] = None
) -> bytes:
    """Whole-file bytes with retry-until-open — LOCAL plain files only (the
    native parser's one-shot fast path; its caller routes remote/gz paths
    through the line-reader tier instead)."""
    if is_remote(path) or path.endswith(".gz"):
        raise ValueError(
            f"fs_read_bytes_retry is local-plain-file only, got {path!r} "
            "(use fs_open_read_retry for remote/gz)"
        )

    def attempt():
        _fault_fire("fs.open_read")
        with open(path, "rb") as f:
            return f.read()

    return _retry_open(attempt, retries, backoff_s)


def fs_open_read(path: str, converter: Optional[str] = None):
    """Readable text stream for ``path`` (fs_open_read parity, io/fs.h:36-88).

    Remote paths stream through ``hadoop fs -cat``; ``.gz`` decompresses
    transparently; ``converter`` (a shell command reading stdin) is spliced
    last, exactly where the reference puts pipe converters.
    """
    _fault_fire("fs.open_read")
    if is_remote(path):
        cmd = f"{_hadoop_cmd()} -cat '{path}'"
        if path.endswith(".gz"):
            cmd += " | zcat"
        if converter:
            cmd += f" | {converter}"
        return _PipeStream(cmd, "r")
    if converter:
        src = open(path, "rb")
        cmd = (f"zcat | {converter}") if path.endswith(".gz") else converter
        stream = _PipeStream(cmd, "r", stdin_file=src)
        src.close()  # child holds its own fd after Popen
        return stream
    if path.endswith(".gz"):
        return gzip.open(path, "rt")
    return open(path, "r")


def fs_open_write_retry(
    path: str,
    converter: Optional[str] = None,
    retries: Optional[int] = None,
    backoff_s: Optional[float] = None,
):
    """Retry-until-open for WRITES: the same policy as
    ``fs_open_read_retry`` (a flaky AFS mount that rejects the first open
    used to fail the whole pass on one OSError). Only the OPEN retries —
    a mid-stream write failure still surfaces, since silently rewriting a
    partially-flushed stream could duplicate data."""

    def attempt():
        return fs_open_write(path, converter)

    return _retry_open(attempt, retries, backoff_s)


def fs_open_write(path: str, converter: Optional[str] = None):
    """Writable text stream; remote goes through ``hadoop fs -put -``; local
    parents are created (fs_open_write parity: reference mkdir -p's first)."""
    _fault_fire("fs.open_write")
    if is_remote(path):
        cmd = f"{_hadoop_cmd()} -put - '{path}'"
        if converter:
            cmd = f"{converter} | " + cmd
        return _PipeStream(cmd, "w")
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    if converter:
        return _PipeStream(f"{converter} > '{path}'", "w")
    if path.endswith(".gz"):
        return gzip.open(path, "wt")
    return open(path, "w")  # pbox-lint: disable=IO004  (the wrapper itself)


@contextmanager
def atomic_write(path: str, mode: str = "w"):
    """Crash-safe local write: stream into ``path + ".tmp"``, publish with
    ``os.replace`` only after the block exits cleanly. A crash anywhere in
    the window leaves the previous ``path`` intact — the torn bytes land in
    the tmp file, which the next successful publish overwrites.

    LOCAL paths only (``os.replace`` has no remote analogue; remote
    durability goes through the manifest/publish protocol in
    train/checkpoint.py). ``mode`` is ``"w"`` or ``"wb"``.

    The fault site fires between write and publish — the narrow window the
    atomicity claim is about — under its own name (``fs.atomic_write``), so
    chaos schedules can target the publish without disturbing the hit
    numbering of ``fs.open_write``.
    """
    if is_remote(path):
        raise ValueError(f"atomic_write is local-only, got {path!r}")
    if mode not in ("w", "wb"):
        raise ValueError(f"atomic_write mode must be 'w' or 'wb', got {mode!r}")
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, mode) as f:  # pbox-lint: disable=IO004  (the wrapper itself)
        yield f
        f.flush()
        os.fsync(f.fileno())
    _fault_fire("fs.atomic_write")
    os.replace(tmp, path)


def _run_remote(args: str) -> str:
    last: Optional[Exception] = None
    for _ in range(max(1, config.get_flag("hdfs_retry"))):
        try:
            return subprocess.check_output(
                f"{_hadoop_cmd()} {args}", shell=True, text=True,
                stderr=subprocess.DEVNULL,
            )
        except subprocess.CalledProcessError as e:  # retry-until-ok pattern
            last = e
    raise RuntimeError(f"remote fs command failed: {args}") from last


def fs_exists(path: str) -> bool:
    if is_remote(path):
        try:
            _run_remote(f"-test -e '{path}' && echo yes")
            return True
        except RuntimeError:
            return False
    return os.path.exists(path)


def fs_mkdir(path: str) -> None:
    if is_remote(path):
        _run_remote(f"-mkdir -p '{path}'")
    else:
        os.makedirs(path, exist_ok=True)


def fs_remove(path: str) -> None:
    if is_remote(path):
        _run_remote(f"-rm -r '{path}'")
    elif os.path.isdir(path):
        shutil.rmtree(path)
    elif os.path.exists(path):
        os.remove(path)


def fs_glob(pattern: str) -> List[str]:
    """File list matching ``pattern`` (ls tier of BoxFileMgr)."""
    if is_remote(pattern):
        out = _run_remote(f"-ls '{pattern}'")
        files = []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) >= 8 and not parts[0].startswith("Found"):
                files.append(parts[-1])
        return files
    return sorted(_glob.glob(pattern))


class FileMgr:
    """The open `BoxFileMgr` (box_wrapper.h:778-802): ls/mkdir/exists/
    upload/download/remove/touch over the fs dispatch above."""

    def ls(self, path: str) -> List[str]:
        pattern = path if any(c in path for c in "*?[") else os.path.join(path, "*")
        return fs_glob(pattern)

    def exists(self, path: str) -> bool:
        return fs_exists(path)

    def mkdir(self, path: str) -> None:
        fs_mkdir(path)

    def remove(self, path: str) -> None:
        fs_remove(path)

    def touch(self, path: str) -> None:
        with fs_open_write(path) as f:
            f.write("")

    def download(self, remote: str, local: str) -> None:
        with fs_open_read(remote) as src, fs_open_write(local) as dst:
            shutil.copyfileobj(src, dst)

    def upload(self, local: str, remote: str) -> None:
        with fs_open_read(local) as src, fs_open_write(remote) as dst:
            shutil.copyfileobj(src, dst)
