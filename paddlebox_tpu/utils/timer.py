"""Stage timers.

Parity with ``platform::Timer`` (platform/timer.h) and the handcrafted stage
timers threaded through the reference's hot paths (per-device pull/push/nccl
timers in DeviceBoxData box_wrapper.h:375-392, reader stage timers
data_feed.h:1731-1736, printed by PrintSyncTimer box_wrapper.cc:1173).
"""

from __future__ import annotations

import threading
import time
from typing import Dict


class Timer:
    """Accumulating start/pause timer (platform::Timer parity)."""

    def __init__(self):
        self._total = 0.0
        self._start: float | None = None
        self._count = 0

    def start(self) -> None:
        self._start = time.perf_counter()

    def pause(self) -> None:
        if self._start is not None:
            self._total += time.perf_counter() - self._start
            self._start = None
            self._count += 1

    def reset(self) -> None:
        self._total = 0.0
        self._start = None
        self._count = 0

    def elapsed_sec(self) -> float:
        run = time.perf_counter() - self._start if self._start is not None else 0.0
        return self._total + run

    def elapsed_ms(self) -> float:
        return self.elapsed_sec() * 1e3

    @property
    def count(self) -> int:
        return self._count


class ScopedTimer:
    """``with ScopedTimer(timer):`` — pause on exit even on error."""

    def __init__(self, timer: Timer):
        self.timer = timer

    def __enter__(self):
        self.timer.start()
        return self.timer

    def __exit__(self, *exc):
        self.timer.pause()


class TimerRegistry:
    """Named stage timers with a one-line report (PrintSyncTimer parity)."""

    def __init__(self):
        self._timers: Dict[str, Timer] = {}
        self._lock = threading.Lock()

    def __getitem__(self, name: str) -> Timer:
        with self._lock:
            t = self._timers.get(name)
            if t is None:
                t = self._timers[name] = Timer()
            return t

    def scope(self, name: str) -> ScopedTimer:
        return ScopedTimer(self[name])

    def report(self) -> str:
        with self._lock:
            items = sorted(self._timers.items())
        return " ".join(
            f"{n}={t.elapsed_sec():.3f}s/{t.count}" for n, t in items
        )

    def as_dict(self) -> Dict[str, float]:
        with self._lock:
            return {n: t.elapsed_sec() for n, t in self._timers.items()}

    def reset(self) -> None:
        with self._lock:
            for t in self._timers.values():
                t.reset()


# global stage timers, mirroring the reference's per-process timer statics
STAGE_TIMERS = TimerRegistry()
