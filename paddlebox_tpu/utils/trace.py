"""Event profiler with chrome-trace export.

Parity with the reference's event profiler (platform/profiler.{h,cc}:
``RecordEvent`` scoped annotations, profiler.h:127) and its chrome-trace
exporter (tools/timeline.py:115-137). On TPU the heavy lifting belongs to
jax.profiler (XLA traces); this host-side layer times the Python/runtime
stages around the device (pack, infeed, pass pipeline) and writes the same
``chrome://tracing`` JSON format.

Telemetry-plane upgrades (docs/OBSERVABILITY.md):

- the event buffer is a bounded ring (flag ``trace_max_events``); when
  full, the oldest data events are dropped and counted in
  ``trace.dropped_events`` instead of growing a soak's RSS without limit;
- tids are stable small per-thread ids (1, 2, ...) with chrome
  ``thread_name`` metadata, and ``set_process(rank)`` stamps pid=rank +
  ``process_name`` so merged multi-rank traces get one labeled process
  row per rank;
- every span/instant also feeds the always-on flight recorder
  (``obs/flight_recorder.py``) — even with tracing disabled — so an
  incident bundle can show the last N spans before a death;
- spans recorded inside an ``obs.trace_span`` context carry
  trace_id/span_id args for cross-rank correlation
  (``tools/obs_report.py --merge-traces``).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Deque, Dict, List, Optional

from paddlebox_tpu import config
from paddlebox_tpu.obs.flight_recorder import FLIGHT_RECORDER
from paddlebox_tpu.obs.trace_context import current_trace
from paddlebox_tpu.utils.monitor import STAT_ADD

config.define_flag(
    "trace_max_events", 200_000,
    "profiler ring capacity per process; once full the oldest data "
    "events are dropped (counted in trace.dropped_events)",
)


def _trace_args() -> Optional[Dict[str, str]]:
    ctx = current_trace()
    return ctx.as_args() if ctx is not None else None


class Profiler:
    def __init__(self, max_events: Optional[int] = None):
        self._lock = threading.Lock()
        self._max_events = max_events  # None -> flag trace_max_events
        # ring state: touched only by the *_locked helpers below, whose
        # callers all hold _lock (THR002 can't see through the helpers)
        self._events: Deque[Dict] = deque()  # synchronized-by: _lock (held by *_locked callers)
        self._thread_meta: List[Dict] = []  # synchronized-by: _lock (held by *_locked callers)
        self._tids: Dict[int, int] = {}  # synchronized-by: _lock (held by *_locked callers)
        self._dropped = 0  # synchronized-by: _lock (held by *_locked callers)
        self._pid = 0  # guarded-by: _lock
        self._process_name = "rank0"  # guarded-by: _lock
        self.enabled = False

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def set_process(self, rank: int, name: Optional[str] = None) -> None:
        """Label this process's rows: pid=rank, a readable process_name.
        Events are stamped with the pid at export, so calling this after
        spans were already recorded still yields one coherent row."""
        with self._lock:
            self._pid = int(rank)
            self._process_name = name or f"rank{int(rank)}"
        FLIGHT_RECORDER.set_rank(int(rank))

    @property
    def dropped_events(self) -> int:
        with self._lock:
            return self._dropped

    # -- recording --------------------------------------------------------
    def _tid_locked(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            tid = len(self._tids) + 1
            self._tids[ident] = tid
            self._thread_meta.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "tid": tid,
                    "args": {"name": threading.current_thread().name},
                }
            )
        return tid

    def _append_locked(self, event: Dict) -> None:
        cap = self._max_events
        if cap is None:
            cap = int(config.get_flag("trace_max_events"))
        while len(self._events) >= max(1, cap):
            self._events.popleft()
            self._dropped += 1
            STAT_ADD("trace.dropped_events")
        self._events.append(event)

    @contextmanager
    def record_event(self, name: str, category: str = "host"):
        """Scoped annotation (platform::RecordEvent parity). Always feeds
        the flight recorder; appends to the trace only when enabled."""
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            t1 = time.perf_counter_ns()
            args = _trace_args()
            FLIGHT_RECORDER.note_span(
                name, category, t0 / 1e3, (t1 - t0) / 1e3, args)
            if self.enabled:
                event = {
                    "name": name,
                    "cat": category,
                    "ph": "X",
                    "ts": t0 / 1e3,  # chrome trace wants microseconds
                    "dur": (t1 - t0) / 1e3,
                }
                if args:
                    event["args"] = args
                with self._lock:
                    event["tid"] = self._tid_locked()
                    self._append_locked(event)

    def instant(self, name: str, args: Optional[Dict] = None,
                category: str = "incident") -> None:
        """Zero-duration structured event (chrome trace "i" phase): the
        supervisor's incident log lands in the same timeline as the pass
        stages it interrupted, with the details in ``args``. Instants feed
        the flight recorder, tracing enabled or not: incident-category
        ones into the incident ring, the rest (transport markers etc.)
        into the span ring as zero-duration entries."""
        merged = dict(args or {})
        tctx = _trace_args()
        if tctx:
            merged.update(tctx)
        if category == "incident":
            FLIGHT_RECORDER.note_incident(name, merged, category)
        else:
            FLIGHT_RECORDER.note_span(
                name, category, time.perf_counter_ns() / 1e3, 0.0, merged)
        if not self.enabled:
            return
        event = {
            "name": name,
            "cat": category,
            "ph": "i",
            "s": "g",  # global scope: draw the incident across rows
            "ts": time.perf_counter_ns() / 1e3,
            "args": merged,
        }
        with self._lock:
            event["tid"] = self._tid_locked()
            self._append_locked(event)

    # -- export -----------------------------------------------------------
    def export_chrome_trace(self, path: str) -> int:
        """Write chrome://tracing JSON (timeline.py parity). Returns the
        number of DATA events written (metadata rows excluded)."""
        from paddlebox_tpu.utils.fs import atomic_write

        with self._lock:
            data = [dict(e) for e in self._events]
            thread_meta = [dict(m) for m in self._thread_meta]
            pid = self._pid
            pname = self._process_name
            dropped = self._dropped
        meta = [
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": pname}},
            {"name": "process_sort_index", "ph": "M", "pid": pid, "tid": 0,
             "args": {"sort_index": pid}},
        ]
        for m in thread_meta:
            m["pid"] = pid
        for e in data:
            e["pid"] = pid
        payload = {
            "traceEvents": meta + thread_meta + data,
            "displayTimeUnit": "ms",
            "otherData": {"rank": pid, "dropped_events": dropped},
        }
        with atomic_write(path) as f:
            json.dump(payload, f)
        return len(data)

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._thread_meta.clear()
            self._tids.clear()
            self._dropped = 0


# process-global profiler, like the reference's g_state
PROFILER = Profiler()


def record_event(name: str, category: str = "host"):
    return PROFILER.record_event(name, category)


@contextmanager
def device_trace(log_dir: Optional[str] = None):
    """Wrap a region with jax.profiler device tracing when available
    (nvprof-hook analog, platform/cuda_profiler.h)."""
    import jax

    if log_dir is None:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
