"""Event profiler with chrome-trace export.

Parity with the reference's event profiler (platform/profiler.{h,cc}:
``RecordEvent`` scoped annotations, profiler.h:127) and its chrome-trace
exporter (tools/timeline.py:115-137). On TPU the heavy lifting belongs to
jax.profiler (XLA traces); this host-side layer times the Python/runtime
stages around the device (pack, infeed, pass pipeline) and writes the same
``chrome://tracing`` JSON format.
"""

from __future__ import annotations

import json
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional


class Profiler:
    def __init__(self):
        self._events: List[Dict] = []
        self._lock = threading.Lock()
        self.enabled = False

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    @contextmanager
    def record_event(self, name: str, category: str = "host"):
        """Scoped annotation (platform::RecordEvent parity)."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            t1 = time.perf_counter_ns()
            with self._lock:
                self._events.append(
                    {
                        "name": name,
                        "cat": category,
                        "ph": "X",
                        "ts": t0 / 1e3,  # chrome trace wants microseconds
                        "dur": (t1 - t0) / 1e3,
                        "pid": 0,
                        "tid": threading.get_ident() % 100000,
                    }
                )

    def instant(self, name: str, args: Optional[Dict] = None,
                category: str = "incident") -> None:
        """Zero-duration structured event (chrome trace "i" phase): the
        supervisor's incident log lands in the same timeline as the pass
        stages it interrupted, with the details in ``args``."""
        if not self.enabled:
            return
        with self._lock:
            self._events.append(
                {
                    "name": name,
                    "cat": category,
                    "ph": "i",
                    "s": "g",  # global scope: draw the incident across rows
                    "ts": time.perf_counter_ns() / 1e3,
                    "pid": 0,
                    "tid": threading.get_ident() % 100000,
                    "args": args or {},
                }
            )

    def export_chrome_trace(self, path: str) -> int:
        """Write chrome://tracing JSON (timeline.py parity). Returns #events."""
        from paddlebox_tpu.utils.fs import atomic_write

        with self._lock:
            events = list(self._events)
        with atomic_write(path) as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
        return len(events)

    def reset(self) -> None:
        with self._lock:
            self._events.clear()


# process-global profiler, like the reference's g_state
PROFILER = Profiler()


def record_event(name: str, category: str = "host"):
    return PROFILER.record_event(name, category)


@contextmanager
def device_trace(log_dir: Optional[str] = None):
    """Wrap a region with jax.profiler device tracing when available
    (nvprof-hook analog, platform/cuda_profiler.h)."""
    import jax

    if log_dir is None:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
