"""Persistent XLA compile cache: kill warmup variance across runs.

``warmup_s`` swung 8-33s across bench rounds because every process paid
full XLA compilation of the same programs (same shapes — the pad-bucket
discipline exists precisely so shapes repeat). jax ships a persistent
compilation cache keyed on the HLO; pointing it at a durable directory
turns warmup into a cold-vs-warm PAIR: the first run compiles and
populates, every later run (or process) with identical programs loads the
compiled executable from disk.

This module is the one place that enables it and counts it:

- :func:`enable` wires ``jax_compilation_cache_dir`` (plus the thresholds
  that would otherwise skip small/fast CPU programs — the tier-1 suite and
  the CPU-fallback bench must be able to verify the machinery without a
  TPU) and registers a ``jax.monitoring`` listener ONCE per process.
- hit/miss counters surface as ``compile_cache.*`` stats and through
  :func:`stats`, which bench.py embeds in its JSON so a cold run
  (hits == 0) and a warm run (hits > 0, lower ``warmup_s``) are
  distinguishable in the artifact record.

Resolution policy (``compile_cache_dir`` flag): "auto" means "under the
durable checkpoint root" — the trainer supervisor resolves it to
``<ckpt_root>/compile_cache`` next to the checkpoints whose job it warms;
entrypoints without a checkpoint root (bench.py) treat "auto" as off
unless an explicit directory is given. "off"/"" disables.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional

from paddlebox_tpu import config
from paddlebox_tpu.utils.monitor import STAT_ADD, STAT_GET

config.define_flag(
    "compile_cache_dir",
    "auto",
    "persistent XLA compile cache directory: 'auto' resolves to "
    "<checkpoint_root>/compile_cache when a supervisor owns a checkpoint "
    "root (and stays off for root-less entrypoints unless set explicitly); "
    "'off' disables; any other value is the cache directory itself",
)

_lock = threading.Lock()
_state = {"dir": None, "listener": False}  # guarded-by: _lock

def _listener(event: str, **kwargs) -> None:
    # jax.monitoring event -> our stat, one literal per branch
    if event == "/jax/compilation_cache/cache_hits":
        STAT_ADD("compile_cache.hits")
    elif event == "/jax/compilation_cache/cache_misses":
        STAT_ADD("compile_cache.misses")
    elif event == "/jax/compilation_cache/compile_requests_use_cache":
        STAT_ADD("compile_cache.requests")


def resolve_dir(flag_value: str, ckpt_root: Optional[str] = None) -> Optional[str]:
    """compile_cache_dir flag -> concrete directory or None (disabled)."""
    v = (flag_value or "").strip()
    if v in ("", "off", "none"):
        return None
    if v == "auto":
        if ckpt_root:
            return os.path.join(ckpt_root, "compile_cache")
        return None
    return v


def enable(cache_dir: str) -> str:
    """Point jax's persistent compilation cache at ``cache_dir``.

    Idempotent; re-pointing at a different directory is allowed (the cache
    is process-global, so the last enable wins — jax reads the config at
    each compile). Returns the directory. Thresholds are dropped to zero so
    CPU-sized programs cache too — without that, the machinery is
    unverifiable anywhere but on a real accelerator.
    """
    import jax

    cache_dir = os.path.abspath(cache_dir)
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    # version-drift probe: the option simply not existing is fine
    # pbox-lint: disable=EXC007
    except Exception:  # pragma: no cover - option absent on older jax
        pass
    try:
        # jax LATCHES cache-unused at the first compile that ran without a
        # cache dir (is_cache_used checks once per task); any entrypoint
        # that compiled anything before calling enable() would silently get
        # no caching at all. reset_cache() clears the latch so the next
        # compile re-evaluates against the directory just configured.
        from jax._src import compilation_cache

        compilation_cache.reset_cache()
    # jax-internals drift probe: a missing reset only re-latches the old
    # behavior, which stats() makes visible as zero hits
    # pbox-lint: disable=EXC007
    except Exception:  # pragma: no cover - internal API drift
        pass
    with _lock:
        _state["dir"] = cache_dir
        if not _state["listener"]:
            try:
                from jax._src import monitoring

                monitoring.register_event_listener(_listener)
                _state["listener"] = True
            except Exception:  # pragma: no cover - counters degrade to 0
                # caching still works without the listener, but every
                # hit/miss counter silently reads 0 — record the
                # degradation once so stats() consumers can tell
                STAT_ADD("compile_cache.listener_errors")
    return cache_dir


def enabled_dir() -> Optional[str]:
    with _lock:
        return _state["dir"]


def disable() -> None:
    """Undo :func:`enable`: detach jax from the cache directory and clear
    the cache-used latch. The cache is process-global state — tests that
    build a supervisor (which enables it under the checkpoint root) use
    this to keep the setting from leaking into every later test."""
    import jax

    jax.config.update("jax_compilation_cache_dir", None)
    with _lock:
        _state["dir"] = None
    try:
        from jax._src import compilation_cache

        compilation_cache.reset_cache()
    # jax-internals drift probe, as in enable()
    # pbox-lint: disable=EXC007
    except Exception:  # pragma: no cover - internal API drift
        pass


def stats() -> Dict:
    """Counters + entry census for artifact embedding (bench JSON,
    tpu_capture artifacts). ``hits``/``misses`` are process-lifetime."""
    d = enabled_dir()
    entries = 0
    if d is not None:
        try:
            entries = sum(1 for n in os.listdir(d) if n.endswith("-cache"))
        # pbox-lint: disable=EXC007 — the -1 label IS the record
        except OSError:
            entries = -1  # dir vanished under us; label, don't crash
    return {
        "enabled": d is not None,
        "dir": d,
        "hits": int(STAT_GET("compile_cache.hits")),
        "misses": int(STAT_GET("compile_cache.misses")),
        "requests": int(STAT_GET("compile_cache.requests")),
        "entries": entries,
    }
