"""Deterministic fault injection for the pass/day robustness loop.

The reference earns its multi-day soak claims through recovery machinery
(Confirm/Revert on the PS tables fleet_wrapper.h:319-321, retry-until-open
on transiently missing inputs data_feed.cc:2738-2740, base+delta publishing
a restarted job resumes from). Those mechanisms are only as trustworthy as
the failure harness that exercises them — so this module gives every
recovery seam a *named injection site* that tests can arm with seeded,
counted triggers and tear down hermetically.

Catalog of wired sites (see docs/ROBUSTNESS.md for the recovery matrix):

    fs.open_read            utils/fs.py  fs_open_read / fs_read_bytes_retry
    fs.open_write           utils/fs.py  fs_open_write
    fs.atomic_write         utils/fs.py  atomic_write: after tmp-file write,
                            before the os.replace publish (its own site so
                            arming it never shifts fs.open_write hit counts)
    pipeline.prefetch_job   data/pipeline.py  each prefetch job execution
    checkpoint.save         train/checkpoint.py  each durability boundary
                            inside save_base/save_delta (multiple fires per
                            save — hit counts select a crash window)
    checkpoint.load         train/checkpoint.py  resume(): before base load
                            and before each delta apply
    step.device             train/trainer.py  before each device-step (or
                            superstep) dispatch
    transport.connect       parallel/transport.py  before each outbound
                            connection attempt (first connect AND every
                            reconnect, so a rule can keep a link down)
    transport.send          parallel/transport.py  before each wire attempt
                            of a data frame — an injected failure exercises
                            the retained-frame reconnect/resend path
    transport.recv_frame    parallel/transport.py  top of each reader-loop
                            frame iteration; a failure drops the connection
                            receiver-side (sender resyncs via heartbeat)
    transport.heartbeat     parallel/transport.py  before each peer beat —
                            suppressing beats starves acks and the peer's
                            failure detector
    wire.host_decode        parallel/transport.py  reader loop, before a
                            codec-framed (PBTX v3) payload is inflated —
                            an injected failure is a corrupt-after-CRC
                            decode: the connection dies pre-delivery and
                            the sender's resync replays the frame
                            exactly once
    boundary.premerge       data/dataset.py  boundary feed stage, before the
                            staged working set's key premerge (pipelined
                            boundary only)
    boundary.stage_pull     data/dataset.py  boundary feed stage, before the
                            host pull_or_create prefetch for the staged
                            next pass
    boundary.writeback      data/dataset.py  top of the end_pass_async
                            worker, before writeback/decay — a failure here
                            exercises the saved-state restore + pass reopen
    parser.parse_line       data/parser.py  top of parse_line, before each
                            text-line parse (the Python tier and the
                            native-fallback re-parse both route through it)
                            — an injected failure is a synthetic corrupt
                            line: quarantined in data_quarantine mode,
                            fatal to the load in strict mode
    data.file_read          data/dataset.py  _read_one, before each part
                            file is opened/read — an injected failure is a
                            synthetic unreadable file (quarantined whole in
                            data_quarantine mode)
    backend.init            utils/backendguard.py  before each subprocess
                            backend-init probe — an injected failure is a
                            simulated wedged TPU runtime, exercising the
                            watchdog + CPU-fallback path without owning a
                            wedgeable chip
    serve.apply_delta       serve/scoring_table.py  commit(): after the next
                            scoring-table version is fully built, before the
                            atomic swap — a failure is a follower crash
                            mid-apply; the served version must remain the
                            previous complete one (no partial delta is ever
                            visible to score requests)
    spill.io                table/sparse_table.py  spill_cold, before the
                            native cap sweep — an injected failure is a
                            disk-tier write error: surfaced as the typed
                            SpillIOError and counted under
                            table.spill_errors (the end_pass worker's
                            failure path then reopens the pass for retry)
    spill.stage_flush       table/sparse_table.py  spill_cold, after spill.io
                            — models the double-buffered stage writer's
                            fwrite handoff dying mid-sweep (native rc -2
                            from the flusher thread) as its own site, so
                            arming it never shifts spill.io hit counts;
                            surfaced as SpillIOError, counted under
                            table.spill_errors
    table.writeback_worker  table/sparse_table.py  push_writeback, before
                            each writer-pool chunk of the end-of-pass
                            writeback — an injected failure is a worker rc
                            error: surfaced as SpillIOError through the
                            chunked writeback, the boundary worker's
                            failure path reopens the pass, and the
                            supervisor's revert restores pre-pass rows
                            bitwise before the retry
    membership.adopt_shard  parallel/membership.py  adopt_dead_shards,
                            after the dead rank's checkpoint shard is
                            resumed but before its keys are pushed into
                            the survivor's table — a failure is a crash
                            mid-adoption; the retry re-runs the same
                            CRC-verified resume and the push is a pure
                            upsert, so the retried adoption lands
                            bitwise-identical
    migrate.transfer        parallel/membership.py  migrate_ranges, on the
                            sender before a shard range is encoded onto
                            the wire — a failure aborts the planned
                            migration; the verdict round then keeps the
                            OLD ownership epoch serving (stale-epoch
                            frames are unreceivable) and the plan is
                            simply retried at the next pass boundary
    wire.ici_pack           data/device_pack.py  _route_sharded, before the
                            hot-first bucket ordering of the adaptive ICI
                            wire (fires only when the working set carries
                            hotness bits) — a failure degrades that batch
                            to the uniform slot order: hot keys ride the
                            int8 region (correct values, just
                            un-prioritized precision), counted under
                            wire.ici_pack_errors
    membership.join_announce  train/supervisor.py  _announce_join, before
                            the joiner knocks on the fleet's sponsors — a
                            failure means the announce never went out;
                            nothing durable moved, the joiner simply
                            knocks again (join_day's retry loop)
    membership.catchup_apply  train/supervisor.py  _catch_up, once per
                            ceding source before its published base+delta
                            chain is applied into the joiner's scratch —
                            a failure folds into the joiner's NO vote on
                            the join verdict: the fleet stays at the OLD
                            ownership epoch bitwise (receivers only
                            staged, nothing committed) and a retried join
                            succeeds (FLT008 recovery contract)
    serve.request_recv      serve/fleet.py  front-end request loop, after a
                            score-request frame is consumed off the wire
                            and before it is decoded/handed to the batcher
                            — an injected failure is a request lost inside
                            the serving host: counted under
                            serve.request_recv_errors, the loop keeps
                            serving, and the CLIENT's bounded-backoff
                            retry (same request id) succeeds
    serve.fleet_stage       serve/fleet.py  FleetStage.stage_once, after a
                            new origin watermark is seen and before any
                            chain link is mirrored into fleet_stage_dir —
                            a failure is a torn host-local stage fetch:
                            the stage watermark never advances (followers
                            keep serving the last staged version; no
                            partial version is ever visible) and the next
                            stage poll retries the same mirror
                            idempotently
    serve.drain             serve/fleet.py  drain-command handling, after
                            a ctl:serve:drain frame is consumed and
                            before the follower flips its drain state —
                            a failure drops the command: counted under
                            serve.drain_errors, the follower stays in its
                            previous state, and the client re-sends until
                            the health gossip confirms (drain/admit are
                            idempotent)
    serve.tier_build        serve/scoring_table.py  build_device_tier, at
                            the start of the device hot-tier build inside
                            commit() — a failure models a follower dying
                            mid-tier-build: the commit aborts before the
                            swap so no partial tier (and no new version)
                            is ever visible, the old version keeps
                            serving bitwise, and the healed retry commits
                            the same version+tier bitwise
                            (tests/test_serve_shard.py pins it)
    stream.tail_read        train/stream.py  DirectoryTailer.poll, before
                            each append-only file's new byte range is read
                            — an injected failure is an unreadable tail
                            chunk: the file's cursor position does not
                            advance (counted under stream.tail_read_errors)
                            and the next poll re-reads the SAME bytes, so
                            a transient read flake never drops a record
    stream.cut_publish      train/stream.py  StreamSupervisor._cut, twice
                            per micro-pass cut (hit counts select a crash
                            window): after the cut intent + spool are
                            durable but before the pass trains/publishes,
                            and after the delta published but before the
                            stream cursor commits — the recovery contract
                            is exactly-once: a restart replays the durable
                            spool when the delta never published, and
                            rolls the cursor forward without retraining
                            when it did (zero records lost or replayed,
                            tests/test_stream.py pins both windows)
    ckpt.compact            train/checkpoint.py  CheckpointManager.compact,
                            three windows (nothing read yet / chain folded
                            into the scratch table but unpublished /
                            compact dir published but cursor stale) — a
                            crash in ANY window leaves the old base+delta
                            chain untouched and fully servable bitwise
                            (the compact dir publishes via the same
                            tmp+rename discipline as every snapshot), and
                            the healed retry folds the same chain bitwise

A site fires via :func:`fire`; when no plan is installed that is a single
global read, so production paths pay nothing. Tests install a
:class:`FaultPlan` through the :func:`inject` context manager:

    with inject(fail_nth("fs.open_read", 1)):          # flake once, heal
        ...

Triggers compose per rule: ``nth`` fails one specific hit, ``prob`` fails
each hit with probability p under a fixed seed, and ``times`` bounds how
many failures a rule deals before going inert (``times=1`` is
fail-once-then-heal). All counters are plan-scoped, so a test's schedule
can never leak into the next test.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional

import numpy as np

# The declared site catalog. fire()/fail_* against a name NOT listed here is
# a silent no-op waiting to happen — pbox-lint REG003 cross-checks every
# literal site string in the package against this tuple.
KNOWN_SITES = (
    "fs.open_read",
    "fs.open_write",
    "fs.atomic_write",
    "pipeline.prefetch_job",
    "checkpoint.save",
    "checkpoint.load",
    "step.device",
    "transport.connect",
    "transport.send",
    "transport.recv_frame",
    "transport.heartbeat",
    "wire.host_decode",
    "boundary.premerge",
    "boundary.stage_pull",
    "boundary.writeback",
    "parser.parse_line",
    "data.file_read",
    "backend.init",
    "serve.apply_delta",
    "spill.io",
    "spill.stage_flush",
    "table.writeback_worker",
    "membership.adopt_shard",
    "migrate.transfer",
    "wire.ici_pack",
    "membership.join_announce",
    "membership.catchup_apply",
    "serve.request_recv",
    "serve.fleet_stage",
    "serve.drain",
    "serve.tier_build",
    "stream.tail_read",
    "stream.cut_publish",
    "ckpt.compact",
)


class InjectedFault(OSError):
    """Deterministic injected failure.

    Subclasses OSError on purpose: the fs retry tier (``_retry_open``)
    treats OSError as transient, so an injected flake exercises exactly
    the production retry path.
    """

    def __init__(self, site: str, hit: int):
        super().__init__(f"injected fault at site {site!r} (hit {hit})")
        self.site = site
        self.hit = hit


@dataclass
class FaultRule:
    """One trigger bound to one site.

    ``nth``    1-based hit index (counted from plan install) that fails.
    ``prob``   iid failure probability per hit, drawn from ``seed``.
    ``times``  failure budget before the rule heals (None = unlimited).
    ``exc``    optional factory ``(site, hit) -> BaseException``.
    """

    site: str
    nth: Optional[int] = None
    prob: float = 0.0
    seed: int = 0
    times: Optional[int] = 1
    exc: Optional[Callable[[str, int], BaseException]] = None
    _rng: np.random.Generator = field(init=False, repr=False)
    _fired: int = field(default=0, init=False, repr=False)

    def __post_init__(self) -> None:
        self._rng = np.random.default_rng(self.seed)

    def should_fail(self, hit: int) -> bool:
        if self.times is not None and self._fired >= self.times:
            return False
        if self.nth is not None and hit == self.nth:
            return True
        # the draw happens on every hit the budget allows, so a schedule's
        # failure positions depend only on (seed, hit sequence)
        if self.prob > 0.0 and self._rng.random() < self.prob:
            return True
        return False

    def make_exc(self, hit: int) -> BaseException:
        self._fired += 1
        if self.exc is not None:
            return self.exc(self.site, hit)
        return InjectedFault(self.site, hit)


class FaultPlan:
    """An installed set of rules + per-site hit/failure counters."""

    def __init__(self, rules: List[FaultRule]):
        self._rules: Dict[str, List[FaultRule]] = {}
        for r in rules:
            self._rules.setdefault(r.site, []).append(r)
        self._hits: Dict[str, int] = {}
        self._failures: Dict[str, int] = {}
        # sites fire from worker threads (prefetch pool, end_pass_async
        # publisher), so counter state must be serialized
        self._lock = threading.Lock()

    def hit(self, site: str) -> None:
        with self._lock:
            n = self._hits.get(site, 0) + 1
            self._hits[site] = n
            for rule in self._rules.get(site, ()):
                if rule.should_fail(n):
                    self._failures[site] = self._failures.get(site, 0) + 1
                    exc = rule.make_exc(n)
                    break
            else:
                return
        from paddlebox_tpu.utils.monitor import STAT_ADD

        STAT_ADD("faults_injected")
        raise exc

    def hits(self, site: str) -> int:
        with self._lock:
            return self._hits.get(site, 0)

    def failures(self, site: str) -> int:
        with self._lock:
            return self._failures.get(site, 0)


_active: Optional[FaultPlan] = None
_install_lock = threading.Lock()


def fire(site: str) -> None:
    """Injection-site hook. No-op (one global read) when nothing is armed."""
    plan = _active
    if plan is not None:
        plan.hit(site)


@contextmanager
def inject(*rules: FaultRule) -> Iterator[FaultPlan]:
    """Install ``rules`` for the dynamic extent of the block (hermetic:
    the previous plan — usually none — is restored on exit, even on
    error). Yields the plan so tests can read hit/failure counters."""
    global _active
    plan = FaultPlan(list(rules))
    with _install_lock:
        prev, _active = _active, plan
    try:
        yield plan
    finally:
        with _install_lock:
            _active = prev


def fail_nth(
    site: str,
    n: int,
    times: Optional[int] = 1,
    exc: Optional[Callable[[str, int], BaseException]] = None,
) -> FaultRule:
    """Fail exactly the ``n``-th hit of ``site`` (1-based, counted from
    plan install)."""
    return FaultRule(site=site, nth=n, times=times, exc=exc)


def fail_once(
    site: str, exc: Optional[Callable[[str, int], BaseException]] = None
) -> FaultRule:
    """Fail the first hit, then heal — the canonical transient flake."""
    return fail_nth(site, 1, times=1, exc=exc)


def fail_always(
    site: str,
    times: Optional[int] = None,
    exc: Optional[Callable[[str, int], BaseException]] = None,
) -> FaultRule:
    """Fail every hit (until ``times`` failures, if set) — a persistent
    outage rather than a flake."""
    return FaultRule(site=site, prob=1.0, times=times, exc=exc)


def fail_prob(
    site: str,
    p: float,
    seed: int = 0,
    times: Optional[int] = None,
    exc: Optional[Callable[[str, int], BaseException]] = None,
) -> FaultRule:
    """Fail each hit with probability ``p`` under a fixed seed; ``times``
    caps the total failures (None = every drawn hit fails)."""
    return FaultRule(site=site, prob=p, seed=seed, times=times, exc=exc)
