"""Host-side utilities: fs/hdfs IO, line readers, timers, stats, dumps, trace.

Reference: paddle/fluid/framework/io/{fs,shell}.*, string/string_helper.h,
platform/{timer,monitor,profiler}.* (SURVEY.md B20/B21 + §5).
"""

from paddlebox_tpu.utils.faultinject import (  # noqa: F401
    InjectedFault,
    fail_always,
    fail_nth,
    fail_once,
    fail_prob,
    inject,
)
from paddlebox_tpu.utils.fs import (  # noqa: F401
    FileMgr,
    fs_exists,
    fs_glob,
    fs_mkdir,
    fs_open_read,
    fs_open_read_retry,
    fs_open_write,
    fs_open_write_retry,
    fs_remove,
)
from paddlebox_tpu.utils.line_reader import (  # noqa: F401
    BufferedLineFileReader,
    LineFileReader,
)
from paddlebox_tpu.utils.monitor import STAT_ADD, STAT_GET, STAT_RESET  # noqa: F401
from paddlebox_tpu.utils.timer import ScopedTimer, Timer, TimerRegistry  # noqa: F401
