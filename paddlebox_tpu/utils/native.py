"""ctypes binding for the native C++ slot parser (csrc/slot_parser.cc).

The reference's data loader is C++ worker threads parsing sample text
(data_feed.cc:2951-3061); this module is that native tier here. The library
is built on demand with g++ (no pybind11 in the image — plain C ABI +
ctypes, per the runtime's binding policy) and cached under csrc/build/.

``parse_buffer(data, schema)`` parses a whole file's bytes in one native
call and wraps the columnar result in per-record numpy VIEWS over two big
copies (one uint64, one float) — no per-line Python work at all. The
records satisfy the same contract as data/parser.py::parse_line, which
remains both the fallback and the semantics oracle (tests assert equality).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

import numpy as np

from paddlebox_tpu.data.slot_record import SlotRecord
from paddlebox_tpu.data.slot_schema import SlotSchema

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "csrc", "slot_parser.cc")
_LIB = os.path.join(_REPO, "csrc", "build", "libpbx_parser.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_u64p = ctypes.POINTER(ctypes.c_uint64)
_u32p = ctypes.POINTER(ctypes.c_uint32)
_i64p = ctypes.POINTER(ctypes.c_int64)
_i32p = ctypes.POINTER(ctypes.c_int32)
_f32p = ctypes.POINTER(ctypes.c_float)


def _build() -> bool:
    os.makedirs(os.path.dirname(_LIB), exist_ok=True)
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", _LIB, _SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB):
            if not (os.path.exists(_SRC) and _build()):
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        lib.pbx_parse_buffer.restype = ctypes.c_void_p
        lib.pbx_parse_buffer.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int,
        ]
        for name in ("pbx_num_records", "pbx_num_skipped", "pbx_num_u64",
                     "pbx_num_f", "pbx_ins_chars"):
            getattr(lib, name).restype = ctypes.c_int64
            getattr(lib, name).argtypes = [ctypes.c_void_p]
        for name, t in (
            ("pbx_u64_values", _u64p), ("pbx_u64_offsets", _u32p),
            ("pbx_u64_base", _i64p), ("pbx_f_values", _f32p),
            ("pbx_f_offsets", _u32p), ("pbx_f_base", _i64p),
            ("pbx_search_ids", _u64p), ("pbx_cmatch", _i32p),
            ("pbx_rank", _i32p), ("pbx_ins_id_off", _i64p),
            ("pbx_ins_id_chars_ptr", ctypes.c_char_p),
        ):
            getattr(lib, name).restype = t
            getattr(lib, name).argtypes = [ctypes.c_void_p]
        lib.pbx_free.restype = None
        lib.pbx_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _copy(ptr, n, dtype):
    if n == 0:
        return np.zeros(0, dtype=dtype)
    return np.ctypeslib.as_array(ptr, shape=(n,)).astype(dtype, copy=True)


def parse_buffer(
    data: bytes, schema: SlotSchema, stats: Optional[dict] = None
) -> List[SlotRecord]:
    """Parse a whole file's bytes natively -> SlotRecords (views over two
    flat arrays). Raises ValueError with the native line diagnostic.
    ``stats["skipped"]`` receives the no-feasign-record count."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native parser unavailable (g++ build failed?)")
    S = len(schema.slots)
    kinds = (ctypes.c_uint8 * S)(*[1 if s.type == "float" else 0 for s in schema.slots])
    dense = (ctypes.c_uint8 * S)(*[1 if s.dense else 0 for s in schema.slots])
    used = (ctypes.c_uint8 * S)(*[1 if s.used else 0 for s in schema.slots])
    errbuf = ctypes.create_string_buffer(512)
    h = lib.pbx_parse_buffer(
        data, len(data), S, kinds, dense, used,
        1 if schema.parse_ins_id else 0,
        1 if schema.parse_logkey else 0,
        errbuf, len(errbuf),
    )
    if not h:
        raise ValueError(f"native slot parse failed: {errbuf.value.decode()}")
    try:
        n = lib.pbx_num_records(h)
        if stats is not None:
            stats["skipped"] = int(lib.pbx_num_skipped(h))
        n_u, n_f = lib.pbx_num_u64(h), lib.pbx_num_f(h)
        u_vals = _copy(lib.pbx_u64_values(h), n_u, np.uint64)
        f_vals = _copy(lib.pbx_f_values(h), n_f, np.float32)
        Su, Sf = schema.num_sparse, schema.num_float
        u_off = _copy(lib.pbx_u64_offsets(h), n * (Su + 1), np.uint32).reshape(n, Su + 1)
        f_off = _copy(lib.pbx_f_offsets(h), n * (Sf + 1), np.uint32).reshape(n, Sf + 1)
        u_base = _copy(lib.pbx_u64_base(h), n, np.int64)
        f_base = _copy(lib.pbx_f_base(h), n, np.int64)
        sids = _copy(lib.pbx_search_ids(h), n, np.uint64)
        cms = _copy(lib.pbx_cmatch(h), n, np.int32)
        rks = _copy(lib.pbx_rank(h), n, np.int32)
        want_ids = schema.parse_ins_id or schema.parse_logkey
        if want_ids and n:
            ioff = _copy(lib.pbx_ins_id_off(h), n + 1, np.int64)
            # offsets are BYTE offsets: slice the raw bytes, decode per id
            chars = ctypes.string_at(
                lib.pbx_ins_id_chars_ptr(h), lib.pbx_ins_chars(h)
            )
        recs: List[SlotRecord] = []
        for r in range(n):
            recs.append(
                SlotRecord(
                    u64_values=u_vals[u_base[r] : u_base[r] + u_off[r, -1]],
                    u64_offsets=u_off[r],
                    f_values=f_vals[f_base[r] : f_base[r] + f_off[r, -1]],
                    f_offsets=f_off[r],
                    ins_id=(
                        chars[ioff[r] : ioff[r + 1]].decode(errors="replace")
                        if want_ids
                        else ""
                    ),
                    search_id=int(sids[r]),
                    cmatch=int(cms[r]),
                    rank=int(rks[r]),
                )
            )
        return recs
    finally:
        lib.pbx_free(h)


def parse_file(
    path: str, schema: SlotSchema, stats: Optional[dict] = None
) -> List[SlotRecord]:
    with open(path, "rb") as f:
        return parse_buffer(f.read(), schema, stats)
