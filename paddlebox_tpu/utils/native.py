"""ctypes binding for the native C++ slot parser (csrc/slot_parser.cc).

The reference's data loader is C++ worker threads parsing sample text
(data_feed.cc:2951-3061); this module is that native tier here. The library
is built on demand with g++ (no pybind11 in the image — plain C ABI +
ctypes, per the runtime's binding policy) and cached under csrc/build/.

``parse_buffer(data, schema)`` parses a whole file's bytes in one native
call and wraps the columnar result in per-record numpy VIEWS over two big
copies (one uint64, one float) — no per-line Python work at all. The
records satisfy the same contract as data/parser.py::parse_line, which
remains both the fallback and the semantics oracle (tests assert equality).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

import numpy as np

from paddlebox_tpu.data.slot_record import SlotRecord
from paddlebox_tpu.data.slot_schema import SlotSchema

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRCS = [
    os.path.join(_REPO, "csrc", "slot_parser.cc"),
    os.path.join(_REPO, "csrc", "batch_packer.cc"),
    os.path.join(_REPO, "csrc", "host_table.cc"),
]
_LIB = os.path.join(_REPO, "csrc", "build", "libpbx_parser.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_u64p = ctypes.POINTER(ctypes.c_uint64)
_u32p = ctypes.POINTER(ctypes.c_uint32)
_i64p = ctypes.POINTER(ctypes.c_int64)
_i32p = ctypes.POINTER(ctypes.c_int32)
_f32p = ctypes.POINTER(ctypes.c_float)

# spill victim-selection policies (mirror csrc/host_table.cc kSpill*)
SPILL_FIFO = 0  # legacy creation-order sweep, untouched rows first
SPILL_FREQ = 1  # coldness-ranked: admission/pin thresholds + (show, epoch)

# column layout of pbx_table_tier_stats (8 int64 slots per shard)
TIER_STAT_FIELDS = (
    "mem_rows", "disk_rows", "spilled_total", "promoted_total",
    "admitted_disk_first", "lazy_shrunk", "dead_records", "spill_bytes",
)

# layout of pbx_table_io_stats (5 cumulative int64 slots): where the
# writeback/spill IO time actually went — the gather-vs-fwrite split of the
# double-buffered spill writers plus the push pre-pass header reads
IO_STAT_FIELDS = (
    "spill_gather_ns", "spill_fwrite_ns", "prepass_read_ns",
    "stage_flushes", "stage_bytes",
)


def _build() -> bool:
    os.makedirs(os.path.dirname(_LIB), exist_ok=True)
    # compile to a tmp path, then atomic-rename: overwriting the .so in
    # place would scribble on pages another live process has dlopen-mapped
    # (and a concurrent builder/loader would see a half-written file);
    # os.replace gives every reader either the old inode or the new one
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp] + _SRCS,
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _LIB)
        return True
    except Exception:
        # every caller silently falls back to the pure-Python paths on
        # False — a 10x parse/pull slowdown nobody asked for must at
        # least leave a counter behind (lazy import: this module stays
        # importable before the package does)
        from paddlebox_tpu.utils.monitor import STAT_ADD

        STAT_ADD("native.build_failures")
        try:
            os.unlink(tmp)
        # pbox-lint: disable=EXC007 — tmp may never have been created
        except OSError:
            pass
        return False


def _stale() -> bool:
    """Rebuild when any source is newer than the cached .so."""
    try:
        t = os.path.getmtime(_LIB)
        return any(os.path.getmtime(s) > t for s in _SRCS)
    # staleness probe: a vanished .so or source answers "rebuild"
    # pbox-lint: disable=EXC007
    except OSError:
        return True


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        # PBOX_NATIVE_LIB points the whole native tier at a prebuilt .so
        # (tools/native_sanitize.py replays the test suite against an
        # ASan+UBSan-instrumented build this way); the override is never
        # rebuilt or staleness-checked — the caller owns its lifecycle
        lib_path = os.environ.get("PBOX_NATIVE_LIB") or _LIB
        if lib_path == _LIB and (not os.path.exists(_LIB) or _stale()):
            if not (all(os.path.exists(s) for s in _SRCS) and _build()):
                return None
        try:
            lib = ctypes.CDLL(lib_path)
        except OSError:
            # a .so that BUILT but won't load (ABI skew, torn file from a
            # pre-atomic-rename writer) is stranger than a missing
            # compiler — count it separately from build failures
            from paddlebox_tpu.utils.monitor import STAT_ADD

            STAT_ADD("native.load_failures")
            return None
        lib.pbx_parse_buffer.restype = ctypes.c_void_p
        lib.pbx_parse_buffer.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int,
        ]
        for name in ("pbx_num_records", "pbx_num_skipped", "pbx_num_u64",
                     "pbx_num_f", "pbx_ins_chars"):
            getattr(lib, name).restype = ctypes.c_int64
            getattr(lib, name).argtypes = [ctypes.c_void_p]
        for name, t in (
            ("pbx_u64_values", _u64p), ("pbx_u64_offsets", _u32p),
            ("pbx_u64_base", _i64p), ("pbx_f_values", _f32p),
            ("pbx_f_offsets", _u32p), ("pbx_f_base", _i64p),
            ("pbx_search_ids", _u64p), ("pbx_cmatch", _i32p),
            ("pbx_rank", _i32p), ("pbx_ins_id_off", _i64p),
            ("pbx_ins_id_chars_ptr", ctypes.c_char_p),
        ):
            getattr(lib, name).restype = t
            getattr(lib, name).argtypes = [ctypes.c_void_p]
        lib.pbx_free.restype = None
        lib.pbx_free.argtypes = [ctypes.c_void_p]
        lib.pbx_packer_create.restype = ctypes.c_void_p
        lib.pbx_packer_create.argtypes = [
            _i32p, _i64p, _u32p, ctypes.c_int64, ctypes.c_int, ctypes.c_int64,
        ]
        lib.pbx_pack_batch.restype = ctypes.c_int64
        lib.pbx_pack_batch.argtypes = [
            ctypes.c_void_p, _i64p, ctypes.c_int64, _i32p, _i32p, _i32p,
        ]
        lib.pbx_packer_free.restype = None
        lib.pbx_packer_free.argtypes = [ctypes.c_void_p]
        lib.pbx_gather_f32_slot.restype = None
        lib.pbx_gather_f32_slot.argtypes = [
            _f32p, _i64p, _u32p, ctypes.c_int, _i64p, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int, _f32p,
        ]
        lib.pbx_block_stats.restype = ctypes.c_int
        lib.pbx_block_stats.argtypes = [
            _i32p, _i64p, _i64p, ctypes.c_int64, _i64p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            _i64p, _i64p,
        ]
        # --- host table store (csrc/host_table.cc) ---
        lib.pbx_table_create.restype = ctypes.c_void_p
        lib.pbx_table_create.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_uint64, _i32p, ctypes.c_int, ctypes.c_float,
            ctypes.c_char_p,
        ]
        lib.pbx_table_free.restype = None
        lib.pbx_table_free.argtypes = [ctypes.c_void_p]
        for name in ("pbx_table_size", "pbx_table_mem_rows", "pbx_table_disk_rows"):
            getattr(lib, name).restype = ctypes.c_int64
            getattr(lib, name).argtypes = [ctypes.c_void_p]
        lib.pbx_table_pull_or_create.restype = ctypes.c_int
        lib.pbx_table_pull_or_create.argtypes = [
            ctypes.c_void_p, _u64p, ctypes.c_int64, _f32p,
        ]
        lib.pbx_table_push.restype = ctypes.c_int
        lib.pbx_table_push.argtypes = [
            ctypes.c_void_p, _u64p, _f32p, ctypes.c_int64,
        ]
        lib.pbx_table_push_mt.restype = ctypes.c_int
        lib.pbx_table_push_mt.argtypes = [
            ctypes.c_void_p, _u64p, _f32p, ctypes.c_int64,
            ctypes.c_int, _i64p,
        ]
        lib.pbx_table_io_stats.restype = None
        lib.pbx_table_io_stats.argtypes = [ctypes.c_void_p, _i64p]
        lib.pbx_table_decay_shrink.restype = ctypes.c_int64
        lib.pbx_table_decay_shrink.argtypes = [
            ctypes.c_void_p, ctypes.c_float, ctypes.c_float,
        ]
        lib.pbx_table_spill_cold.restype = ctypes.c_int64
        lib.pbx_table_spill_cold.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.pbx_table_spill_cold_ex.restype = ctypes.c_int64
        lib.pbx_table_spill_cold_ex.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
            ctypes.c_float, ctypes.c_float,
        ]
        lib.pbx_table_tier_stats.restype = ctypes.c_int64
        lib.pbx_table_tier_stats.argtypes = [ctypes.c_void_p, _i64p]
        lib.pbx_table_compact_spill.restype = ctypes.c_int64
        lib.pbx_table_compact_spill.argtypes = [ctypes.c_void_p]
        lib.pbx_table_spill_stats.restype = None
        lib.pbx_table_spill_stats.argtypes = [
            ctypes.c_void_p, _i64p, _i64p, _i64p,
        ]
        lib.pbx_table_clear_touched.restype = None
        lib.pbx_table_clear_touched.argtypes = [ctypes.c_void_p]
        lib.pbx_table_shard_shows.restype = ctypes.c_int64
        lib.pbx_table_shard_shows.argtypes = [
            ctypes.c_void_p, ctypes.c_int, _f32p, ctypes.c_int64,
        ]
        lib.pbx_table_shard_keys.restype = ctypes.c_int64
        lib.pbx_table_shard_keys.argtypes = [
            ctypes.c_void_p, ctypes.c_int, _u64p, ctypes.c_int64,
        ]
        lib.pbx_table_shows_peek.restype = ctypes.c_int
        lib.pbx_table_shows_peek.argtypes = [
            ctypes.c_void_p, _u64p, ctypes.c_int64, _f32p,
        ]
        lib.pbx_table_snapshot_count.restype = ctypes.c_int64
        lib.pbx_table_snapshot_count.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
        ]
        lib.pbx_table_snapshot.restype = ctypes.c_int64
        lib.pbx_table_snapshot.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            _u64p, _f32p,
        ]
        _lib = lib
        return _lib


def _as_ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def gather_f32_slot(
    f_values: np.ndarray,
    f_base: np.ndarray,
    f_offsets: np.ndarray,
    indices: np.ndarray,
    slot: int,
    dim: int,
) -> np.ndarray:
    """[n, dim] ragged float-slot gather (short rows zero-padded, long rows
    truncated) — native tier for ColumnarRecords.float_slot_matrix."""
    lib = _load()
    f_values = np.ascontiguousarray(f_values, dtype=np.float32)
    f_base = np.ascontiguousarray(f_base, dtype=np.int64)
    f_offsets = np.ascontiguousarray(f_offsets, dtype=np.uint32)
    indices = np.ascontiguousarray(indices, dtype=np.int64)
    out = np.empty((len(indices), dim), np.float32)
    lib.pbx_gather_f32_slot(
        _as_ptr(f_values, ctypes.c_float),
        _as_ptr(f_base, ctypes.c_int64),
        _as_ptr(f_offsets, ctypes.c_uint32),
        f_offsets.shape[1],
        _as_ptr(indices, ctypes.c_int64),
        len(indices),
        slot,
        dim,
        _as_ptr(out, ctypes.c_float),
    )
    return out


def block_stats(
    rows: np.ndarray,
    rec_base: np.ndarray,
    key_counts: np.ndarray,
    blocks: np.ndarray,  # int64 [n_blocks, b] record indices
    cap: int,
    ns: int,
) -> tuple:
    """Per-block (L, max unique rows per shard) over the resolved pass rows
    — the resident feed's pad-freeze sweep, one GIL-released call (the
    counter side of compute_thread_batch_nccl, data_set.cc:2069-2135)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native tier unavailable (g++ build failed?)")
    rows = np.ascontiguousarray(rows, dtype=np.int32)
    rec_base = np.ascontiguousarray(rec_base, dtype=np.int64)
    key_counts = np.ascontiguousarray(key_counts, dtype=np.int64)
    blocks = np.ascontiguousarray(blocks, dtype=np.int64)
    n_blocks, b = blocks.shape
    L_out = np.empty(n_blocks, np.int64)
    bmax_out = np.empty(n_blocks, np.int64)
    rc = lib.pbx_block_stats(
        _as_ptr(rows, ctypes.c_int32),
        _as_ptr(rec_base, ctypes.c_int64),
        _as_ptr(key_counts, ctypes.c_int64),
        len(rec_base),
        _as_ptr(blocks, ctypes.c_int64),
        n_blocks, b, int(cap), int(ns), int(cap) * int(ns),
        _as_ptr(L_out, ctypes.c_int64),
        _as_ptr(bmax_out, ctypes.c_int64),
    )
    if rc != 0:
        raise ValueError("block_stats: record index or row out of range")
    return L_out, bmax_out


class NativePacker:
    """Per-thread handle over one pass's row-resolved columnar records.

    ``pack(indices)`` -> (uniq_rows[U], inverse[L], segments[L]) unpadded;
    the device_pack wrapper buckets/pads. The referenced arrays are pinned
    on the instance so the C++ side's borrowed pointers stay alive.
    """

    def __init__(self, rows: np.ndarray, rec_base: np.ndarray,
                 rec_off: np.ndarray, n_sparse: int, n_table_rows: int):
        lib = _load()
        if lib is None:
            raise RuntimeError("native packer unavailable (g++ build failed?)")
        self._lib = lib
        # keep contiguous copies alive for the borrowed C++ pointers
        self._rows = np.ascontiguousarray(rows, dtype=np.int32)
        self._base = np.ascontiguousarray(rec_base, dtype=np.int64)
        self._off = np.ascontiguousarray(rec_off, dtype=np.uint32)
        self._h = lib.pbx_packer_create(
            _as_ptr(self._rows, ctypes.c_int32),
            _as_ptr(self._base, ctypes.c_int64),
            _as_ptr(self._off, ctypes.c_uint32),
            len(self._base), n_sparse, int(n_table_rows),
        )

    def pack(self, indices: np.ndarray, n_keys: int):
        if not self._h:
            raise RuntimeError("NativePacker used after close()")
        indices = np.ascontiguousarray(indices, dtype=np.int64)
        uniq = np.empty(n_keys, np.int32)
        inv = np.empty(n_keys, np.int32)
        seg = np.empty(n_keys, np.int32)
        U = self._lib.pbx_pack_batch(
            self._h, _as_ptr(indices, ctypes.c_int64), len(indices),
            _as_ptr(uniq, ctypes.c_int32), _as_ptr(inv, ctypes.c_int32),
            _as_ptr(seg, ctypes.c_int32),
        )
        if U < 0:
            raise ValueError("native pack: record index or row out of range")
        return uniq[:U], inv, seg

    def close(self) -> None:
        if self._h:
            self._lib.pbx_packer_free(self._h)
            self._h = None

    def __del__(self):  # best-effort; close() is the real contract
        try:
            self.close()
        # pbox-lint: disable=EXC007 — finalizer; close() is the contract
        except Exception:
            pass


class NativeHostStore:
    """Handle over the C++ sharded key->row store (csrc/host_table.cc).

    The mem+disk host tiers of the sparse table: batch pull_or_create /
    push run natively with the GIL released and thread across shards;
    cold rows spill to per-shard disk files and promote lazily with
    catch-up show/clk decay (LoadSSD2Mem parity, box_wrapper.cc:1325).
    """

    def __init__(
        self,
        n_shards: int,
        width: int,
        show_col: int,
        clk_col: int,
        seed: int,
        init_cols: np.ndarray,
        init_range: float,
        spill_dir: Optional[str] = None,
    ):
        lib = _load()
        if lib is None:
            raise RuntimeError("native host table unavailable (g++ build failed?)")
        self._lib = lib
        self.width = width
        ic = np.ascontiguousarray(init_cols, dtype=np.int32)
        self._h = lib.pbx_table_create(
            n_shards, width, show_col, clk_col,
            ctypes.c_uint64(seed), _as_ptr(ic, ctypes.c_int32), len(ic),
            float(init_range),
            spill_dir.encode() if spill_dir else None,
        )
        self.n_shards = n_shards

    def __len__(self) -> int:
        return int(self._lib.pbx_table_size(self._h))

    @property
    def mem_rows(self) -> int:
        return int(self._lib.pbx_table_mem_rows(self._h))

    @property
    def disk_rows(self) -> int:
        return int(self._lib.pbx_table_disk_rows(self._h))

    def pull_or_create(self, keys: np.ndarray) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        out = np.empty((len(keys), self.width), np.float32)
        rc = self._lib.pbx_table_pull_or_create(
            self._h, _as_ptr(keys, ctypes.c_uint64), len(keys),
            _as_ptr(out, ctypes.c_float),
        )
        if rc != 0:
            raise IOError(f"native table pull failed rc={rc} (spill IO error?)")
        return out

    def push(self, keys: np.ndarray, rows: np.ndarray) -> None:
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        rows = np.ascontiguousarray(rows, dtype=np.float32)
        rc = self._lib.pbx_table_push(
            self._h, _as_ptr(keys, ctypes.c_uint64),
            _as_ptr(rows, ctypes.c_float), len(keys),
        )
        if rc != 0:
            raise IOError(f"native table push failed rc={rc} (spill IO error?)")

    def push_mt(self, keys: np.ndarray, rows: np.ndarray,
                threads: int) -> np.ndarray:
        """Batch push through the explicit writer pool (bitwise-equal to
        ``push`` at every thread count; ``threads <= 0`` = auto heuristic,
        ``1`` = forced serial). Returns per-shard wall seconds (float64
        [n_shards]) — the ``table.writeback.shard_s`` histogram feed.
        Raises the raw IOError on a negative rc; the table layer maps it
        to the typed SpillIOError."""
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        rows = np.ascontiguousarray(rows, dtype=np.float32)
        shard_ns = np.zeros(self.n_shards, np.int64)
        rc = self._lib.pbx_table_push_mt(
            self._h, _as_ptr(keys, ctypes.c_uint64),
            _as_ptr(rows, ctypes.c_float), len(keys), int(threads),
            _as_ptr(shard_ns, ctypes.c_int64),
        )
        if rc != 0:
            raise IOError(f"native table push failed rc={rc} (spill IO error?)")
        return shard_ns.astype(np.float64) / 1e9

    def io_stats(self) -> dict:
        """Cumulative writeback/spill IO telemetry, keyed by
        IO_STAT_FIELDS — the gather-vs-fwrite split of the double-buffered
        spill writers plus push pre-pass header read time."""
        out = np.zeros(len(IO_STAT_FIELDS), np.int64)
        self._lib.pbx_table_io_stats(self._h, _as_ptr(out, ctypes.c_int64))
        return {k: int(v) for k, v in zip(IO_STAT_FIELDS, out)}

    def decay_and_shrink(self, decay: float, threshold: float) -> int:
        return int(self._lib.pbx_table_decay_shrink(self._h, decay, threshold))

    def compact_spill(self) -> int:
        """Rewrite shard spill files keeping only live records; returns the
        live count or the raw negative code (-1 tier disabled, -2 IO
        failure) for the table layer to map to SpillIOError. (spill_cold
        also compacts a shard opportunistically once dead records
        outnumber live.)"""
        return int(self._lib.pbx_table_compact_spill(self._h))

    def spill_stats(self) -> tuple:
        """(live_records, dead_records, file_bytes) of the disk tier."""
        live = ctypes.c_int64()
        dead = ctypes.c_int64()
        nbytes = ctypes.c_int64()
        self._lib.pbx_table_spill_stats(
            self._h, ctypes.byref(live), ctypes.byref(dead), ctypes.byref(nbytes)
        )
        return int(live.value), int(dead.value), int(nbytes.value)

    def spill_cold(
        self,
        max_mem_rows: int,
        policy: int = SPILL_FIFO,
        pin_show: float = 0.0,
        admit_show: float = 0.0,
    ) -> int:
        """Run one cap sweep; returns rows spilled, or the raw NEGATIVE
        native code (-1 tier disabled, -2 IO failure). The table layer maps
        codes to the typed SpillIOError — the raw int never escapes to a
        caller that could read it as "spilled -2 rows"."""
        return int(self._lib.pbx_table_spill_cold_ex(
            self._h, int(max_mem_rows), int(policy),
            float(pin_show), float(admit_show),
        ))

    def tier_stats(self) -> np.ndarray:
        """int64 [n_shards, len(TIER_STAT_FIELDS)] per-shard occupancy and
        cumulative spill/promote counters, rows ordered by shard id."""
        out = np.zeros((self.n_shards, len(TIER_STAT_FIELDS)), np.int64)
        if self.n_shards:
            self._lib.pbx_table_tier_stats(self._h, _as_ptr(out, ctypes.c_int64))
        return out

    def clear_touched(self) -> None:
        self._lib.pbx_table_clear_touched(self._h)

    def shard_shows(self, shard: int) -> np.ndarray:
        """SHOW column of one shard (mem + disk, catch-up decay applied) —
        a column-only export so threshold scans never materialize value
        matrices. The C side clamps to the buffer size, so a concurrent
        push between sizing and export cannot overrun."""
        n = int(self._lib.pbx_table_snapshot_count(self._h, shard, 0))
        out = np.empty(n, np.float32)
        if n:
            got = int(self._lib.pbx_table_shard_shows(
                self._h, shard, _as_ptr(out, ctypes.c_float), n
            ))
            if got < 0:
                raise IOError(f"native shard_shows failed rc={got}")
            out = out[:got]
        return out

    def shows_peek(self, keys: np.ndarray) -> np.ndarray:
        """Decayed shows for a key batch, mem tier only (disk/absent = 0);
        pure read — never creates, promotes or touches a row."""
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        out = np.zeros(len(keys), np.float32)
        if len(keys):
            rc = int(self._lib.pbx_table_shows_peek(
                self._h, _as_ptr(keys, ctypes.c_uint64), len(keys),
                _as_ptr(out, ctypes.c_float),
            ))
            if rc < 0:
                raise IOError(f"native shows_peek failed rc={rc}")
        return out

    def shard_keys(self, shard: int) -> np.ndarray:
        """Keys of one shard straight from the hash (no value copies, no
        disk reads); clamped to the sized buffer like shard_shows."""
        n = int(self._lib.pbx_table_snapshot_count(self._h, shard, 0))
        out = np.empty(n, np.uint64)
        if n:
            got = int(self._lib.pbx_table_shard_keys(
                self._h, shard, _as_ptr(out, ctypes.c_uint64), n
            ))
            out = out[:got]
        return out

    def snapshot_shard(self, shard: int, only_touched: bool, clear_touched: bool):
        n = int(self._lib.pbx_table_snapshot_count(self._h, shard, int(only_touched)))
        keys = np.empty(n, np.uint64)
        vals = np.empty((n, self.width), np.float32)
        if n:
            got = int(self._lib.pbx_table_snapshot(
                self._h, shard, int(only_touched), int(clear_touched),
                _as_ptr(keys, ctypes.c_uint64), _as_ptr(vals, ctypes.c_float),
            ))
            if got < 0:
                raise IOError(f"native table snapshot failed rc={got}")
            keys, vals = keys[:got], vals[:got]
        return keys, vals

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.pbx_table_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        # pbox-lint: disable=EXC007 — finalizer; close() is the contract
        except Exception:
            pass


def available() -> bool:
    return _load() is not None


def _copy(ptr, n, dtype):
    if n == 0:
        return np.zeros(0, dtype=dtype)
    return np.ctypeslib.as_array(ptr, shape=(n,)).astype(dtype, copy=True)


def parse_buffer_columnar(
    data: bytes, schema: SlotSchema, stats: Optional[dict] = None
):
    """Parse a whole file's bytes natively -> ColumnarRecords (one copy per
    array, zero per-record Python work). Raises ValueError with the native
    line diagnostic. ``stats["skipped"]`` receives the no-feasign count."""
    from paddlebox_tpu.data.record_store import ColumnarRecords

    lib = _load()
    if lib is None:
        raise RuntimeError("native parser unavailable (g++ build failed?)")
    S = len(schema.slots)
    kinds = (ctypes.c_uint8 * S)(*[1 if s.type == "float" else 0 for s in schema.slots])
    dense = (ctypes.c_uint8 * S)(*[1 if s.dense else 0 for s in schema.slots])
    used = (ctypes.c_uint8 * S)(*[1 if s.used else 0 for s in schema.slots])
    errbuf = ctypes.create_string_buffer(512)
    h = lib.pbx_parse_buffer(
        data, len(data), S, kinds, dense, used,
        1 if schema.parse_ins_id else 0,
        1 if schema.parse_logkey else 0,
        errbuf, len(errbuf),
    )
    if not h:
        raise ValueError(f"native slot parse failed: {errbuf.value.decode()}")
    try:
        n = lib.pbx_num_records(h)
        if stats is not None:
            stats["skipped"] = int(lib.pbx_num_skipped(h))
        n_u, n_f = lib.pbx_num_u64(h), lib.pbx_num_f(h)
        Su, Sf = schema.num_sparse, schema.num_float
        want_ids = schema.parse_ins_id or schema.parse_logkey
        ins_off = None
        chars = b""
        if want_ids and n:
            ins_off = _copy(lib.pbx_ins_id_off(h), n + 1, np.int64)
            chars = ctypes.string_at(lib.pbx_ins_id_chars_ptr(h), lib.pbx_ins_chars(h))
        return ColumnarRecords(
            _copy(lib.pbx_u64_values(h), n_u, np.uint64),
            _copy(lib.pbx_u64_offsets(h), n * (Su + 1), np.uint32).reshape(n, Su + 1),
            _copy(lib.pbx_u64_base(h), n, np.int64),
            _copy(lib.pbx_f_values(h), n_f, np.float32),
            _copy(lib.pbx_f_offsets(h), n * (Sf + 1), np.uint32).reshape(n, Sf + 1),
            _copy(lib.pbx_f_base(h), n, np.int64),
            search_ids=_copy(lib.pbx_search_ids(h), n, np.uint64),
            cmatch=_copy(lib.pbx_cmatch(h), n, np.int32),
            rank=_copy(lib.pbx_rank(h), n, np.int32),
            ins_id_off=ins_off,
            ins_id_chars=chars,
        )
    finally:
        lib.pbx_free(h)


def parse_buffer(
    data: bytes, schema: SlotSchema, stats: Optional[dict] = None
) -> List[SlotRecord]:
    """Compat wrapper: columnar parse, then materialize SlotRecord views."""
    return parse_buffer_columnar(data, schema, stats).records()


def parse_file(
    path: str, schema: SlotSchema, stats: Optional[dict] = None
) -> List[SlotRecord]:
    with open(path, "rb") as f:
        return parse_buffer(f.read(), schema, stats)


def parse_file_columnar(path: str, schema: SlotSchema, stats: Optional[dict] = None):
    with open(path, "rb") as f:
        return parse_buffer_columnar(f.read(), schema, stats)
