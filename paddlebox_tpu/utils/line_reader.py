"""Line readers over fs streams.

Parity with ``LineFileReader`` (string/string_helper.h:146) and
``BufferedLineFileReader`` (data_feed.cc:57): the buffered variant applies a
line sampling rate — the reference's down-sampling knob for debug/fast runs —
and tracks line counts for stage stats.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from paddlebox_tpu import config
from paddlebox_tpu.utils.fs import fs_open_read_retry


class LineFileReader:
    """Iterate stripped lines of one file (local/remote/gz/converter)."""

    def __init__(self, path: str, converter: Optional[str] = None):
        self.path = path
        self.converter = converter
        self.lines_read = 0

    def __iter__(self) -> Iterator[str]:
        stream = fs_open_read_retry(self.path, self.converter)
        try:
            for line in stream:
                self.lines_read += 1
                yield line.rstrip("\n")
        finally:
            close = getattr(stream, "close", None)
            if close:
                close()


class BufferedLineFileReader:
    """LineFileReader + uniform line sampling (data_feed.cc:57 parity).

    ``sample_rate`` < 1 keeps each line with that probability using a
    per-reader RNG (deterministic given ``seed``), so multi-threaded readers
    stay reproducible.
    """

    def __init__(
        self,
        path: str,
        converter: Optional[str] = None,
        sample_rate: Optional[float] = None,
        seed: int = 0,
    ):
        self.inner = LineFileReader(path, converter)
        self.sample_rate = (
            sample_rate if sample_rate is not None else config.get_flag("sample_rate")
        )
        self._rng = np.random.default_rng(seed)
        self.lines_kept = 0

    @property
    def lines_read(self) -> int:
        return self.inner.lines_read

    def __iter__(self) -> Iterator[str]:
        rate = self.sample_rate
        if rate >= 1.0:
            for line in self.inner:
                self.lines_kept += 1
                yield line
            return
        for line in self.inner:
            if self._rng.random() < rate:
                self.lines_kept += 1
                yield line
