"""Debug dump workers: per-batch field/param dumping to part files.

Parity with the reference's dump machinery (SURVEY.md §5): workers serialize
chosen vars per batch (DeviceWorker::DumpField/DumpParam,
device_worker.cc:98-133, with sampling via dump_mode/dump_interval
device_worker.h:218-219) into a string channel; trainer dump threads drain it
into ``part-NNNNN`` files through fs_open_write + converter
(TrainerBase::DumpWork trainer.cc:55-61, BoxPSTrainer::InitDumpEnv
boxps_trainer.cc:96-108).

Dump modes (trainer_desc dump_mode):
  0 — dump every instance
  1 — sample by hash(ins_id) % interval == 0
  2 — dump batches where step % interval == 0
"""

from __future__ import annotations

import hashlib
import queue
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddlebox_tpu.utils.fs import fs_open_write

_STOP = object()


class DumpWorkerPool:
    """N writer threads draining a string channel into part-NNNNN files."""

    def __init__(
        self,
        dump_path: str,
        n_threads: int = 1,
        converter: Optional[str] = None,
        file_prefix: str = "part",
    ):
        self.dump_path = dump_path.rstrip("/")
        self.converter = converter
        self._q: "queue.Queue" = queue.Queue(maxsize=10000)
        self._threads = [
            threading.Thread(target=self._run, args=(i,), daemon=True)
            for i in range(n_threads)
        ]
        self._prefix = file_prefix
        self._started = False

    def start(self) -> None:
        for t in self._threads:
            t.start()
        self._started = True

    def write(self, line: str) -> None:
        self._q.put(line)

    def _run(self, tid: int) -> None:
        path = f"{self.dump_path}/{self._prefix}-{tid:05d}"
        with fs_open_write(path, self.converter) as f:
            while True:
                item = self._q.get()
                if item is _STOP:
                    return
                f.write(item + "\n")

    def finalize(self) -> None:
        """Flush and join (FinalizeDumpEnv parity)."""
        if not self._started:
            return
        for _ in self._threads:
            self._q.put(_STOP)
        for t in self._threads:
            t.join()
        self._started = False


def _want_ins(mode: int, interval: int, ins_id: str, step: int) -> bool:
    if mode == 0:
        return True
    if mode == 1:
        h = int.from_bytes(
            hashlib.blake2b(ins_id.encode(), digest_size=8).digest(), "little"
        )
        return h % max(1, interval) == 0
    return step % max(1, interval) == 0


def dump_fields(
    pool: DumpWorkerPool,
    ins_ids: Sequence[str],
    fields: Dict[str, np.ndarray],
    step: int = 0,
    dump_mode: int = 0,
    dump_interval: int = 1,
) -> int:
    """Serialize per-instance field rows: ``ins_id\\tname:v0,v1...`` per field
    (DumpField line format parity). Returns instances dumped."""
    n = len(ins_ids)
    rows: List[str] = []
    for i in range(n):
        if not _want_ins(dump_mode, dump_interval, ins_ids[i], step):
            continue
        parts = [ins_ids[i]]
        for name, arr in fields.items():
            vals = np.asarray(arr[i]).reshape(-1)
            parts.append(name + ":" + ",".join(f"{v:.6g}" for v in vals))
        rows.append("\t".join(parts))
    for r in rows:
        pool.write(r)
    return len(rows)


def dump_param(pool: DumpWorkerPool, name: str, value: np.ndarray) -> None:
    """One param per line: ``name\\tv0,v1,...`` (DumpParam parity)."""
    flat = np.asarray(value).reshape(-1)
    pool.write(name + "\t" + ",".join(f"{v:.6g}" for v in flat))
