"""Watchdogged jax backend bring-up with a clean CPU fallback.

The TPU runtime in this environment can wedge FOREVER inside backend init
(``make_c_api_client``; every bench round since r03 recorded it). A hung
import in-process is unkillable — so the first touch of the backend happens
in a SUBPROCESS with a hard watchdog timeout, and only after the probe
reports a live platform does the calling process initialize jax itself.
This generalizes the probe logic that grew inside bench.py /
tools/tpu_capture.py into the one implementation every entrypoint shares
(bench.py, tools/*, and the trainer supervisor).

Contract:

- ``probe_backend``   one subprocess probe under ``backend_init_timeout_s``;
                      the ``backend.init`` fault site lets chaos tests
                      simulate a wedged runtime deterministically.
- ``ensure_backend``  retry loop + decision: returns a :class:`BackendVerdict`
                      whose ``verdict`` is ``"ok"`` (requested backend up) or
                      ``"fallback_cpu"`` (requested backend wedged/absent —
                      the process was switched to the CPU backend so work
                      CONTINUES, labeled, instead of hanging a driver for
                      900s). It never writes any artifact — in particular it
                      can never clobber ``tools/last_good_tpu_capture.json``;
                      recording the verdict is the caller's job.

Probing is skipped (``probe="auto"``) when the backend is already
initialized in-process or the environment pins a non-TPU platform — a CPU
CI run pays zero subprocesses.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from paddlebox_tpu import config
from paddlebox_tpu.utils.faultinject import InjectedFault, fire
from paddlebox_tpu.utils.monitor import STAT_ADD, STAT_SET

config.define_flag(
    "backend_init_timeout_s",
    120.0,
    "watchdog on each subprocess backend-init probe: a TPU runtime that "
    "doesn't come up within this is declared wedged (the probe child is "
    "killed; a hung in-process init would be unkillable)",
)
config.define_flag(
    "backend_init_retries",
    6,
    "backend-init probes before giving up on the requested backend and "
    "falling back to CPU (wedges observed to last hours-but-not-forever; "
    "retrying maximizes the chance of a real measurement)",
)
config.define_flag(
    "backend_init_backoff_s",
    30.0,
    "first sleep between backend-init probes, doubled each retry and "
    "capped at 120s",
)


@dataclass
class BackendVerdict:
    """Outcome of backend bring-up, recorded into bench/capture artifacts."""

    platform: str
    n_devices: int
    verdict: str  # "ok" | "fallback_cpu"
    wedged: bool = False  # the REQUESTED backend never came up
    probed: bool = False  # at least one subprocess probe ran
    error: Optional[str] = None  # last probe failure when wedged
    probe_log: List[Dict] = field(default_factory=list)

    def as_dict(self) -> Dict:
        d = {
            "platform": self.platform,
            "n_devices": self.n_devices,
            "verdict": self.verdict,
            "wedged": self.wedged,
            "probed": self.probed,
        }
        if self.error is not None:
            d["error"] = self.error
        if self.probe_log:
            d["probe_log"] = self.probe_log
        return d


def _initialized_platform() -> Optional[str]:
    """Platform of an already-initialized in-process backend, else None."""
    try:
        from jax._src import xla_bridge

        if xla_bridge.backends_are_initialized():
            import jax

            return jax.default_backend()
    # probe child: None IS the answer (the parent counts/alarms on it)
    # pbox-lint: disable=EXC007
    except Exception:
        return None
    return None


def probe_backend(timeout_s: Optional[float] = None) -> Tuple[Optional[dict], Optional[str]]:
    """Initialize the jax backend in a SUBPROCESS with a hard timeout.

    Returns ``(info, None)`` on success (``info`` = {"platform",
    "n_devices"}) or ``(None, reason)`` on failure — a hung child is killed
    at the watchdog deadline; a hung import in this process would not be.
    The ``backend.init`` fault site fires first so chaos schedules can
    simulate a wedged runtime without owning a wedgeable chip.
    """
    if timeout_s is None:
        timeout_s = float(config.get_flag("backend_init_timeout_s"))
    STAT_ADD("backend.init_probes")
    try:
        fire("backend.init")
    except InjectedFault as e:
        # simulated wedge: the probe "consumed" its slice and saw nothing
        return None, f"backend init wedged (injected: {e})"
    code = (
        "import jax, json; d = jax.devices(); "
        "print(json.dumps({'platform': d[0].platform, 'n_devices': len(d)}))"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return None, f"backend init timed out after {timeout_s:.0f}s (wedged TPU init?)"
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        return None, f"backend init failed rc={proc.returncode}: " + " | ".join(tail)
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1]), None
    except (ValueError, IndexError):
        return None, f"backend probe produced no JSON: {proc.stdout[-200:]!r}"


def probe_backend_with_retries(
    timeout_s: Optional[float] = None,
    retries: Optional[int] = None,
    backoff_s: Optional[float] = None,
    sleep=time.sleep,
) -> Tuple[Optional[dict], List[Dict]]:
    """Probe repeatedly with doubling backoff before giving up.

    Returns ``(info, probe_log)``; ``info`` is None if every probe failed.
    Each log entry is {"ts", "elapsed_s", "ok", "detail"} — the multi-probe
    wedge evidence callers record when the backend never comes up.
    """
    if retries is None:
        retries = max(1, int(config.get_flag("backend_init_retries")))
    if backoff_s is None:
        backoff_s = float(config.get_flag("backend_init_backoff_s"))
    probe_log: List[Dict] = []
    for attempt in range(retries):
        t0 = time.time()
        info, err = probe_backend(timeout_s)
        entry = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(t0)),
            "elapsed_s": round(time.time() - t0, 1),
            "ok": err is None,
            "detail": "ok" if err is None else err,
        }
        probe_log.append(entry)
        # progress to stderr as it happens: a driver with a wall-clock
        # watchdog must see life during the retry budget, or it kills the
        # run before the JSON evidence is ever emitted
        print(
            f"[backendguard] probe {attempt + 1}/{retries}: {entry['detail']}",
            file=sys.stderr,
            flush=True,
        )
        if err is None:
            return info, probe_log
        if attempt + 1 < retries:
            sleep(min(backoff_s, 120.0))
            backoff_s *= 2
    return None, probe_log


def ensure_backend(
    timeout_s: Optional[float] = None,
    retries: Optional[int] = None,
    backoff_s: Optional[float] = None,
    probe: str = "auto",
    sleep=time.sleep,
) -> BackendVerdict:
    """Bring up a usable jax backend, falling back to CPU on a wedge.

    ``probe`` is "auto" (skip the subprocess when the backend is already
    initialized in-process or JAX_PLATFORMS pins a non-TPU platform),
    "always", or "never" (trust in-process init; only for tests).
    Raises only if even the CPU fallback cannot initialize.
    """
    if probe not in ("auto", "always", "never"):
        raise ValueError(f"probe={probe!r} not in ('auto', 'always', 'never')")
    if probe != "always":
        live = _initialized_platform()
        if live is not None:
            import jax

            return BackendVerdict(
                platform=live, n_devices=jax.device_count(), verdict="ok"
            )
        plats = os.environ.get("JAX_PLATFORMS", "")
        if probe == "never" or (plats and "tpu" not in plats.lower()):
            # a pinned non-TPU platform can't wedge the way the TPU
            # runtime does; init in-process without a subprocess
            import jax

            d = jax.devices()
            return BackendVerdict(
                platform=d[0].platform, n_devices=len(d), verdict="ok"
            )

    info, probe_log = probe_backend_with_retries(
        timeout_s, retries, backoff_s, sleep=sleep
    )
    if info is not None:
        return BackendVerdict(
            platform=str(info["platform"]),
            n_devices=int(info["n_devices"]),
            verdict="ok",
            probed=True,
            probe_log=probe_log,
        )

    # Wedged/absent accelerator after the full retry budget: switch THIS
    # process to the CPU backend so the caller still runs end to end —
    # clearly labeled instead of silently degraded or hung.
    STAT_SET("backend.init_wedged", 1)
    err = probe_log[-1]["detail"] if probe_log else "no probe ran"
    # a wedge is a flight-recorder incident: the bundle (when a dump dir
    # is configured) captures the probe log and every stat leading up to
    # the fallback, which is the whole postmortem for "why was this run
    # on CPU"
    from paddlebox_tpu.obs.flight_recorder import FLIGHT_RECORDER

    FLIGHT_RECORDER.note_incident(
        "backend_wedge", {"error": err, "probes": len(probe_log)})
    FLIGHT_RECORDER.dump("backend_wedge", detail=err)
    import jax

    jax.config.update("jax_platforms", "cpu")
    d = jax.devices()  # raises only if even CPU cannot come up
    return BackendVerdict(
        platform=d[0].platform,
        n_devices=len(d),
        verdict="fallback_cpu",
        wedged=True,
        probed=True,
        error=err,
        probe_log=probe_log,
    )
