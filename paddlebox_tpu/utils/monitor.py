"""Process-wide stat registry.

Parity with ``Monitor``/``StatRegistry`` (platform/monitor.h:43-153): named
int/float counters bumped from anywhere via STAT_ADD / read via STAT_GET /
zeroed via STAT_RESET — e.g. the reference's
``STAT_total_feasign_num_in_mem`` (box_wrapper.cc:1282).
"""

from __future__ import annotations

import threading
from typing import Dict, Union

from paddlebox_tpu.obs.histogram import Histogram

Number = Union[int, float]

_lock = threading.Lock()
_stats: Dict[str, Number] = {}  # guarded-by: _lock
_hists: Dict[str, Histogram] = {}  # guarded-by: _lock


def STAT_ADD(name: str, value: Number = 1) -> None:
    with _lock:
        _stats[name] = _stats.get(name, 0) + value


def STAT_SET(name: str, value: Number) -> None:
    with _lock:
        _stats[name] = value


def STAT_GET(name: str) -> Number:
    with _lock:
        return _stats.get(name, 0)


def STAT_OBSERVE(name: str, value: Number) -> None:
    """Record one sample into the named distribution (latency, frame
    size, stage seconds, ...). Same literal-name discipline as STAT_ADD
    (MON005); the histogram itself is log2-bucketed with exact
    count/sum/min/max — see ``obs/histogram.py``."""
    with _lock:
        h = _hists.get(name)
        if h is None:
            h = _hists[name] = Histogram()
    # Histogram carries its own lock; observing outside _lock keeps the
    # registry lock off the hot path.
    h.observe(value)


def STAT_HIST(name: str) -> Histogram | None:
    """The named histogram, or None if nothing was ever observed."""
    with _lock:
        return _hists.get(name)


def STAT_RESET(name: str | None = None) -> None:
    with _lock:
        if name is None:
            _stats.clear()
            _hists.clear()
        else:
            _stats.pop(name, None)
            _hists.pop(name, None)


def all_stats(prefix: str | None = None) -> Dict[str, Number]:
    """Snapshot of the registry; ``prefix`` filters to one dashboard
    namespace (e.g. ``"serve."`` for the serving plane's counters)."""
    with _lock:
        snap = dict(_stats)
    if prefix is None:
        return snap
    return {k: v for k, v in snap.items() if k.startswith(prefix)}


def all_histograms(prefix: str | None = None) -> Dict[str, Histogram]:
    """Snapshot of the distribution registry (live Histogram objects —
    they are individually thread-safe; use ``h.summary()``/``to_dict()``
    for a point-in-time view)."""
    with _lock:
        snap = dict(_hists)
    if prefix is None:
        return snap
    return {k: v for k, v in snap.items() if k.startswith(prefix)}
