"""Pull-only checkpoint follower: tails latest.json, applies delta chains.

The consumer half of the paper's online loop: the trainer publishes
base + per-pass deltas (CheckpointManager / xbox SaveBase+SaveDelta
parity) and a serving replica *pulls* them — no connection back into the
training job, just a shared checkpoint root. Poll cadence is
``serve_poll_interval_s``; each poll:

1. reads the ``latest.json`` watermark (atomic publish, so a read sees a
   whole watermark or the previous one — never a torn save),
2. validates lineage (:func:`validate_watermark` + rewind detection →
   :class:`DeltaLineageError`; a new base/date triggers a full reload),
3. CRC-verifies every snapshot it is about to consume (manifest CRC
   pinned by the watermark, then the full per-file manifest check) — a
   corrupt delta is SKIPPED with an alarm stat and the follower keeps
   serving the last good version,
4. applies verified deltas into a private staging HostSparseTable (the
   same load/apply_delta code the trainer's resume uses, so decay-epoch
   catch-up is bitwise-faithful to the trainer's own table),
5. commits each applied delta to the :class:`ScoringTable` as an atomic
   version swap, and loads the paired dense params for the chain head.

Scores served from the committed version are bitwise-equal to scoring
directly against the trainer's table at the same pass — tests/test_serve.py
and tools/serve_soak.py both pin that gate.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from paddlebox_tpu import config
from paddlebox_tpu.serve.scoring_table import ScoringTable, TableVersion
from paddlebox_tpu.table.sparse_table import HostSparseTable
from paddlebox_tpu.train.checkpoint import (
    DeltaLineageError,
    _file_crc32,
    _manifest_crc,
    read_watermark,
    validate_watermark,
    verify_snapshot,
)
from paddlebox_tpu.utils.monitor import STAT_ADD, STAT_OBSERVE, STAT_SET

logger = logging.getLogger(__name__)


def verify_chain_link(
    root: str, rel: str, want_crc, require_manifest: bool
) -> bool:
    """CRC gate for one published chain link: the snapshot dir's manifest
    must match the watermark's pin AND the manifest's per-file CRCs must
    hold. Shared by the Follower's poll and the elastic joiner's catch-up
    — both consume the SAME verification before trusting a snapshot."""
    snap = os.path.join(root, rel)
    if want_crc is not None and _manifest_crc(snap) != want_crc:
        return False
    return verify_snapshot(snap, require_manifest=require_manifest)


def apply_published_chain(
    root: str, table: HostSparseTable, require_manifest: bool = True
) -> Optional[Dict[str, Any]]:
    """CRC-verified base + delta chain apply into ``table`` — the
    Follower's chain-apply path, shared with the elastic joiner's
    catch-up so a joining rank trusts a published chain under exactly
    the serve-replica rules.

    Reads ``latest.json`` under ``root`` (atomic publish: a read sees a
    whole watermark or the previous one), validates lineage (including
    the mixed-epoch rejection — the trainer base-re-anchors at every
    ownership-epoch flip, so a valid watermark is always single-epoch:
    catching up across a mid-day re-anchor just means reading the
    re-anchored chain), then verifies and applies base + every delta in
    chain order. Returns the chain-head position dict (``date``,
    ``delta_idx``, ``base_crc``, ``ownership_epoch``) or None on a cold
    root; raises :class:`DeltaLineageError` on any CRC-failed link —
    unlike a serving follower, a catch-up consumer has no last-good
    version to keep, so a bad link is fatal to the attempt."""
    wm = read_watermark(root)
    if wm is None:
        return None
    validate_watermark(wm)
    base_crc = wm["base"].get("manifest_crc")
    idx = int(wm["delta_idx"])
    # compact fast path: a published fold of base+delta-0001..covers loads
    # in one verified link (bitwise-equal to replaying the prefix), so a
    # streaming chain costs a joiner O(post-fold tail), not O(minutes-
    # since-base). A torn fold falls back to the full chain — it is an
    # optimization, never the only copy.
    start = 1
    comp = wm.get("compact")
    if comp is not None:
        if verify_chain_link(
            root, comp["path"], comp.get("manifest_crc"), require_manifest
        ):
            table.load(os.path.join(root, comp["path"]))
            STAT_ADD("serve.compact_fastforwards")
            start = int(comp["covers"]) + 1
        else:
            logger.warning(
                "compact snapshot %s failed CRC — falling back to the "
                "full chain", comp["path"],
            )
    if start == 1:
        if not verify_chain_link(
            root, wm["base"]["path"], base_crc, require_manifest
        ):
            raise DeltaLineageError(
                f"base snapshot {wm['base']['path']!r} under {root} failed "
                "CRC verification"
            )
        table.load(os.path.join(root, wm["base"]["path"]))
    for i in range(start, idx + 1):
        entry = wm["deltas"][i - 1]
        if not verify_chain_link(
            root, entry["path"], entry.get("manifest_crc"), require_manifest
        ):
            raise DeltaLineageError(
                f"delta snapshot {entry['path']!r} under {root} failed "
                "CRC verification (chain order is load-bearing)"
            )
        table.apply_delta(os.path.join(root, entry["path"]))
    return {
        "date": wm["date"],
        "delta_idx": idx,
        "base_crc": base_crc,
        "ownership_epoch": int(wm.get("ownership_epoch", 0)),
    }


class Follower:
    """Tail a checkpoint root and maintain an atomically-served ScoringTable.

    ``trainer`` (optional) is a CTRTrainer used purely as the dense-param
    holder/loader — the follower never trains; it calls ``init_params`` to
    build the tree structure and ``load_dense`` per published dense file.
    Threading: ``poll_once``/``run`` mutate follower state from ONE poller
    thread; scorers only touch the immutable versions the ScoringTable
    hands out (plus ``trainer.params``, which dense loads replace with a
    single tuple assignment — readers grab the reference once per batch).
    """

    def __init__(
        self,
        root: str,
        layout,
        sparse_opt,
        n_host_shards: int = 4,
        trainer=None,
        require_manifest: Optional[bool] = None,
    ):
        self.root = root
        self.layout = layout
        self.sparse_opt = sparse_opt
        self.n_host_shards = n_host_shards
        self.trainer = trainer
        self.require_manifest = (
            config.get_flag("serve_require_manifest")
            if require_manifest is None
            else require_manifest
        )
        self.scoring = ScoringTable(layout.width)
        self._staging = self._fresh_staging()
        # last committed chain position; base_crc pins the lineage so a
        # re-published base under the same date forces a full reload
        self._applied: Optional[Dict[str, Any]] = None
        self._dense_loaded: Optional[str] = None
        # health-gossip surface: ``reanchoring`` is True from the moment a
        # mid-day ownership-epoch flip is detected until the re-anchored
        # chain head is fully applied — the fleet view drains (stops
        # querying) a follower for exactly that window. Written by the one
        # poller thread, read by the health-beat thread.
        self.reanchoring = False
        self.epoch_reanchors = 0  # per-instance (serve.epoch_reanchors is global)

    def _fresh_staging(self) -> HostSparseTable:
        # seed is irrelevant: the staging table only ever load()s published
        # rows, it never creates keys
        return HostSparseTable(
            self.layout, self.sparse_opt, n_shards=self.n_host_shards, seed=0
        )

    # ---- public surface --------------------------------------------------

    def version(self) -> TableVersion:
        return self.scoring.version()

    def health_snapshot(self) -> Dict[str, Any]:
        """The follower half of a ctl:serve:health gossip beat: chain
        position, epoch, re-anchor window, and train-to-serve staleness.
        Reads only atomically-swapped references, so any thread may call
        it concurrently with the poller."""
        v = self.version()
        applied = self._applied
        tier = v.device_tier
        return {
            "delta_idx": v.delta_idx,
            "date": v.date,
            "ownership_epoch": 0 if applied is None else int(
                applied.get("ownership_epoch", 0)),
            "reanchoring": bool(self.reanchoring),
            "epoch_reanchors": int(self.epoch_reanchors),
            "warm": v.params is not None,
            "staleness_s": (
                None if v.published_unix is None
                else max(0.0, time.time() - v.published_unix)
            ),
            # per-rank device-tier telemetry: rows the served version holds
            # on-mesh and its lookup hit/miss tally (0/0/0 = host-only)
            "tier_rows": 0 if tier is None else int(tier.n_rows),
            "tier_hits": 0 if tier is None else int(tier.hits),
            "tier_misses": 0 if tier is None else int(tier.misses),
        }

    def poll_once(self) -> bool:
        """One watermark poll; returns True when any new state was applied.

        Raises :class:`DeltaLineageError` on a watermark that conflicts
        with applied history (rewind / malformed chain); propagates
        injected faults from the apply window. ``run`` wraps this with
        alarm-and-keep-serving semantics; tests call it bare.
        """
        STAT_ADD("serve.polls")
        wm = read_watermark(self.root)
        if wm is None:
            return False
        # validate_watermark also rejects mixed-epoch chains (a base and
        # deltas spanning an elastic membership change) with the typed
        # MembershipEpochError — the trainer re-anchors on a fresh base at
        # every ownership-epoch flip, so a mixed chain is always a publish
        # bug, never a state the follower should try to apply
        validate_watermark(wm)
        date, idx = wm["date"], int(wm["delta_idx"])
        base_crc = wm["base"].get("manifest_crc")
        epoch = int(wm.get("ownership_epoch", 0))

        applied = self._applied
        same_lineage = (
            applied is not None
            and applied["date"] == date
            and applied["base_crc"] == base_crc
        )
        if (
            applied is not None
            and applied["date"] == date
            and not same_lineage
            and epoch != applied.get("ownership_epoch", 0)
        ):
            # trainer rank set changed mid-day: the re-anchored base under
            # the new ownership epoch supersedes the old chain wholesale
            STAT_ADD("serve.epoch_reanchors")
            self.epoch_reanchors += 1
            self.reanchoring = True
            logger.info(
                "follower: ownership epoch %s -> %s mid-day (%s) — "
                "reloading from the re-anchored base",
                applied.get("ownership_epoch", 0), epoch, date,
            )
        if same_lineage and idx < applied["delta_idx"]:
            raise DeltaLineageError(
                f"watermark rewound: serving {applied['date']}/delta_idx "
                f"{applied['delta_idx']} but latest.json names delta_idx "
                f"{idx} on the same base — refusing to regress the model"
            )
        advanced = False
        if not same_lineage:
            # new day or re-published base: the old chain's epochs and rows
            # are not comparable — rebuild staging from scratch. A published
            # compact fold fast-forwards the rebuild to delta `covers` in
            # one load (bitwise-equal to replaying the prefix it covers);
            # a torn fold falls back to the classic base walk.
            comp = wm.get("compact")
            anchored = False
            if comp is not None and self._verify(
                comp["path"], comp.get("manifest_crc"), "compact"
            ):
                covers = int(comp["covers"])
                self._staging = self._fresh_staging()
                self._staging.load(os.path.join(self.root, comp["path"]))
                STAT_ADD("serve.compact_fastforwards")
                if covers == idx:
                    self._load_dense(wm)
                self._commit(wm, delta_idx=covers, base_crc=base_crc)
                advanced = anchored = True
            if not anchored:
                if not self._verify(wm["base"]["path"], base_crc, "base"):
                    return False
                self._staging = self._fresh_staging()
                self._staging.load(os.path.join(self.root, wm["base"]["path"]))
                if idx == 0:
                    self._load_dense(wm)
                self._commit(wm, delta_idx=0, base_crc=base_crc)
                advanced = True
        start = self._applied["delta_idx"] + 1
        for i in range(start, idx + 1):
            entry = wm["deltas"][i - 1]
            if not self._verify(entry["path"], entry.get("manifest_crc"), "delta"):
                break  # chain order is load-bearing: stop at the first bad link
            self._staging.apply_delta(os.path.join(self.root, entry["path"]))
            if i == idx:
                # the watermark's dense pairs with the chain HEAD: load it
                # before committing delta idx so any version matching the
                # watermark serves with its exact dense params (mid-chain
                # catch-up versions carry the previous dense)
                self._load_dense(wm)
            self._commit(wm, delta_idx=i, base_crc=base_crc)
            advanced = True
        if self.reanchoring and self._applied["delta_idx"] == idx:
            # re-anchored chain head fully applied: the fleet view may
            # re-admit this follower (a broken link above leaves the flag
            # up — still draining, correctly, until the chain heals)
            self.reanchoring = False
        return advanced

    def run(self, stop: threading.Event, poll_interval_s: Optional[float] = None) -> None:
        """Poll loop with alarm-and-keep-serving semantics: any apply
        failure (corrupt chain, injected crash, lineage conflict) is
        counted and logged, the served version stays the last good one,
        and polling continues — a follower never takes itself out of
        rotation over a bad publish."""
        interval = (
            config.get_flag("serve_poll_interval_s")
            if poll_interval_s is None
            else poll_interval_s
        )
        while not stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — serving must outlive applies
                STAT_ADD("serve.apply_failures")
                logger.error("follower apply failed (still serving last good): %s", e)
            stop.wait(interval)

    # ---- internals -------------------------------------------------------

    def _verify(self, rel: str, want_crc, kind: str) -> bool:
        """Alarm-wrapped :func:`verify_chain_link`: False (+ alarm stats)
        on any mismatch — the caller keeps the last good version
        serving."""
        ok = verify_chain_link(self.root, rel, want_crc, self.require_manifest)
        if not ok:
            STAT_ADD("serve.corrupt_skipped")
            STAT_SET("serve.last_corrupt_unix", time.time())
            logger.error(
                "follower: %s snapshot %s failed CRC verification — "
                "skipping, still serving the last good version", kind, rel,
            )
        return ok

    def _commit(self, wm: Dict[str, Any], delta_idx: int, base_crc) -> None:
        keys = np.sort(self._staging.keys())
        rows = (
            self._staging.pull_or_create(keys)  # all exist: pure read
            if len(keys)
            else np.zeros((0, self.layout.width), dtype=np.float32)
        )
        hotness = None
        if len(keys) and config.get_flag("device_scoring_tier") == "on":
            # decayed-show hotness for the device tier: a pure staging-table
            # peek (the adaptive ICI wire's signal), so opting in cannot
            # perturb the applied state
            hotness = self._staging.shows_peek(keys)
        self.scoring.commit(
            keys,
            rows,
            date=wm["date"],
            delta_idx=delta_idx,
            decay_epoch=self._staging.decay_epochs,
            published_unix=wm.get("published_unix"),
            hotness=hotness,
            # the version carries the dense pair: scorers read params off
            # the version, so sparse+dense swap atomically together
            params=None if self.trainer is None else self.trainer.params,
            opt_state=None if self.trainer is None else self.trainer.opt_state,
        )
        self._applied = {
            "date": wm["date"],
            "delta_idx": delta_idx,
            "base_crc": base_crc,
            "ownership_epoch": int(wm.get("ownership_epoch", 0)),
        }
        STAT_SET("serve.applied_delta_idx", delta_idx)
        STAT_SET("serve.ownership_epoch", int(wm.get("ownership_epoch", 0)))
        STAT_ADD("serve.applies")
        # end-to-end freshness (the streaming-plane SLO): when the trainer
        # is a StreamSupervisor the watermark carries the ingest timestamp
        # of the OLDEST record in the publish; committing the chain head
        # means that record is now servable, so sample event→served
        # latency here. Mid-chain catch-up commits are skipped — they
        # serve older state and would double-count the head's interval.
        stream = wm.get("stream")
        if stream is not None and delta_idx == int(wm["delta_idx"]):
            oldest = stream.get("oldest_unix")
            if oldest is not None:
                STAT_OBSERVE(
                    "serve.freshness_s", max(0.0, time.time() - float(oldest))
                )

    def _load_dense(self, wm: Dict[str, Any]) -> None:
        dense = wm.get("dense")
        if self.trainer is None or dense is None:
            return
        rel = dense["path"]
        if rel == self._dense_loaded:
            return
        path = os.path.join(self.root, rel)
        if not os.path.exists(path):
            STAT_ADD("serve.dense_skipped")
            logger.error("follower: dense file %s missing — keeping previous params", rel)
            return
        want = dense.get("crc32")
        if want is not None and _file_crc32(path) != want:
            STAT_ADD("serve.dense_skipped")
            logger.error("follower: dense file %s failed CRC — keeping previous params", rel)
            return
        if self.trainer.params is None:
            self.trainer.init_params()
        self.trainer.load_dense(path)
        self._dense_loaded = rel
        STAT_ADD("serve.dense_loads")
