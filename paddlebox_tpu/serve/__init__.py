"""Online serving plane: pull-only followers over the published
base+delta checkpoint stream (docs/SERVING.md).

- follower.py       tails latest.json, CRC-verifies, applies delta chains
- scoring_table.py  atomic-swap versions backing the scorers
- server.py         compiled forward-only scoring + batched front-end
- fleet.py          networked fleet: shared staging, health/drain gossip,
                    load-balancing client with retries + hedging
"""

from paddlebox_tpu.serve.fleet import (
    FleetClient,
    FleetFollower,
    FleetStage,
    FleetView,
    ServeRequestError,
)
from paddlebox_tpu.serve.follower import Follower
from paddlebox_tpu.serve.scoring_table import (
    DeviceScoringTier,
    ScoringTable,
    TableVersion,
)
from paddlebox_tpu.serve.server import (
    ScoreServer,
    Scorer,
    ServeOverloadError,
    ServeTimeoutError,
    table_source,
    version_source,
)

__all__ = [
    "DeviceScoringTier",
    "Follower",
    "ScoringTable",
    "TableVersion",
    "Scorer",
    "ScoreServer",
    "FleetClient",
    "FleetFollower",
    "FleetStage",
    "FleetView",
    "ServeOverloadError",
    "ServeRequestError",
    "ServeTimeoutError",
    "table_source",
    "version_source",
]
