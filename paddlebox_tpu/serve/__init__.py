"""Online serving plane: pull-only followers over the published
base+delta checkpoint stream (docs/SERVING.md).

- follower.py       tails latest.json, CRC-verifies, applies delta chains
- scoring_table.py  atomic-swap versions backing the scorers
- server.py         compiled forward-only scoring + batched front-end
"""

from paddlebox_tpu.serve.follower import Follower
from paddlebox_tpu.serve.scoring_table import ScoringTable, TableVersion
from paddlebox_tpu.serve.server import (
    ScoreServer,
    Scorer,
    table_source,
    version_source,
)

__all__ = [
    "Follower",
    "ScoringTable",
    "TableVersion",
    "Scorer",
    "ScoreServer",
    "table_source",
    "version_source",
]
